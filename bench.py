"""Headline benchmark: BM25 top-1000 QPS measured THROUGH THE PRODUCT —
documents indexed via HTTP `_bulk` (full analysis + engine + segments),
queries served via HTTP `_msearch` batches hitting the sort-reduce sparse
kernel (the same scoring path every `_search` request takes).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

vs_baseline: the identical engine+HTTP pipeline run in a subprocess pinned to
the XLA-CPU backend — the documented proxy rung of the baseline ladder
(BASELINE.md: XLA-CPU proxy -> stock ES same corpus -> 10M-doc Wiki).
>1.0 = faster than CPU. Set BENCH_CPU=0 to skip the CPU leg.

Workload shape: BASELINE.json config #1/#2 (match-query BM25 over an
analyzed English-like corpus; default 100k docs, override with BENCH_DOCS),
k=1000 like the north-star metric; solo `_search` p50/p99 (size=10) is
reported alongside.

Secondary leg: `python bench.py --kernel` runs the round-1 pure-kernel
synthetic harness (1M docs, no engine) for kernel-regression tracking.
"""

from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

# make the CPU backend available alongside the accelerator for --kernel
_plat = os.environ.get("JAX_PLATFORMS", "")
if _plat and "cpu" not in _plat.split(","):
    os.environ["JAX_PLATFORMS"] = _plat + ",cpu"

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Wall-clock budget: the harness kills bench.py at ~870s (round 5 hit
# rc=124 and lost the whole headline line). Legs check the budget between
# measurement passes and DEGRADE — the headline JSON always prints from
# whatever completed.
BENCH_T0 = time.monotonic()
BENCH_TIME_BUDGET = float(os.environ.get("BENCH_TIME_BUDGET", "600"))


def _remaining() -> float:
    return BENCH_TIME_BUDGET - (time.monotonic() - BENCH_T0)


def _over_budget(margin: float = 0.0) -> bool:
    return _remaining() <= margin


# The headline line survives EVERYTHING (BENCH_r05 recorded "parsed": null
# at rc=124): legs update _FINAL_LINE as results land, and a SIGTERM/SIGINT
# (the harness timeout's first strike) prints whatever is measured so far
# instead of dying silently. _emit prints at most once.
# tail-latency headline keys (ISSUE 9) default to null at import time so
# a forced timeout/bailout still emits them (the subprocess guard test
# pins this)
_FINAL_LINE: dict = {"value": None, "unit": "qps",
                     "conc_p99_ms": None, "shed_429s": None,
                     "hedged_wins": None,
                     # ANN vector-serving headline keys (ISSUE 10):
                     # seeded null at import so a forced timeout still
                     # emits them (the subprocess guard contract)
                     "knn_nprobe": None, "knn_recall_at_10": None,
                     "ann_dispatches": None,
                     # cluster-wide collectives data plane (ISSUE 11):
                     # seeded null at import so a forced timeout still
                     # emits them (the subprocess guard contract)
                     "cluster_host_reduce_qps": None,
                     "mesh_agg_dispatches": None,
                     # quantized ANN tier (ISSUE 12): seeded null at
                     # import so a forced timeout still emits them
                     "knn_int8_qps": None, "knn_pq_qps": None,
                     "pq_recall_at_10": None,
                     "vector_stack_bytes_f32": None,
                     "vector_stack_bytes_quantized": None,
                     # chaos harness (ISSUE 14): seeded null at import so
                     # a forced timeout still emits them
                     "chaos_rounds": None, "chaos_parity_checks": None,
                     "chaos_invariant_violations": None,
                     # rebalance-under-load (ISSUE 15): seeded null at
                     # import so a forced timeout still emits them
                     "rebalance_p99_ms": None, "rebalance_move_s": None,
                     "recovery_throttle_bytes_per_sec": None,
                     "decider_vetoes": None,
                     # device telemetry flight recorder (ISSUE 16): seeded
                     # null at import so a forced timeout still emits them
                     "xla_compile_ms_total": None, "hbm_peak_bytes": None,
                     "lane_decision_counts": None, "flight": None,
                     # log-analytics observability tier (ISSUE 17):
                     # seeded null at import so a forced timeout still
                     # emits them
                     "sorted_mesh_qps": None, "sorted_fanout_qps": None,
                     "subagg_mesh_qps": None,
                     "monitoring_overview_p50_ms": None,
                     # reverse search + script compiler (ISSUE 18):
                     # seeded null at import so a forced timeout still
                     # emits them
                     "percolate_qps": None, "percolate_matrix_qps": None,
                     "percolate_vs_loop": None,
                     "script_score_qps": None, "script_vs_decline": None,
                     # pod-scale serving (ISSUE 19): seeded null at
                     # import so a forced timeout still emits them
                     "pod_qps": None, "single_pool_qps": None,
                     "pod_vs_single": None, "dcn_hops_per_query": None,
                     "exec_lock_waits": None,
                     # watcher alerting tier (ISSUE 20): seeded null at
                     # import so a forced timeout still emits them
                     "watcher_evals_per_sec": None,
                     "watcher_fire_p50_ms": None,
                     "watcher_percolate_rides": None,
                     "composite_page_qps": None}
_LINE_PRINTED = False


def _emit(line: dict) -> None:
    global _LINE_PRINTED
    if not _LINE_PRINTED:
        _LINE_PRINTED = True
        print(json.dumps(line), flush=True)


class _BudgetExceeded(Exception):
    """Raised INTO a running leg by the SIGALRM handler while budget
    remains: the per-leg try/except degrades that leg and the run
    continues. Past the budget, SIGALRM emits the line and exits instead
    — the r05 failure mode (rc=124, "parsed": null) can't recur as long
    as the interpreter is executing Python bytecode at all."""


_ALARM_MARGIN = float(os.environ.get("BENCH_ALARM_MARGIN", "45"))


def _install_bailout() -> None:
    """Arm the always-emit guards. MUST run before the first leg (module
    import time): round 5 hung during a leg on an experimental platform
    ('axon') with no handler armed and the harness's rc=124 erased the
    whole headline line."""
    import signal

    def bail(signum, frame):  # noqa: ANN001 — signal handler signature
        _FINAL_LINE.setdefault("error", f"terminated by signal {signum} "
                               f"({_remaining():.0f}s of budget left)")
        _emit(_FINAL_LINE)
        os._exit(0)

    def alarm(signum, frame):  # noqa: ANN001 — signal handler signature
        if _remaining() <= 5.0:
            # the whole budget is gone: print whatever landed and stop
            _FINAL_LINE.setdefault(
                "error", "wall-clock budget exhausted (SIGALRM)")
            _emit(_FINAL_LINE)
            os._exit(0)
        # a LEG overran its slice while budget remains: re-arm the hard
        # stop at the budget edge and interrupt the leg so it degrades
        signal.alarm(max(int(_remaining()), 1))
        raise _BudgetExceeded(
            f"leg alarm fired with {_remaining():.0f}s of budget left")

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, bail)
        except (ValueError, OSError):      # non-main thread / restricted env
            pass
    try:
        signal.signal(signal.SIGALRM, alarm)
        signal.alarm(max(int(BENCH_TIME_BUDGET + _ALARM_MARGIN), 1))
    except (ValueError, OSError, AttributeError):
        pass


_FLIGHT_PREV: dict = {"invocations": 0, "device_ms": 0.0,
                      "compile_ms": 0.0, "compiles": 0, "lanes": {}}


def _flight_snapshot(leg: str) -> None:
    """Flight recorder (ISSUE 16): after every leg, fold that leg's
    device-stats DELTAS (program dispatches, compile time, lane
    decisions, HBM high-water) into _FINAL_LINE["flight"]. The sidecar
    updates incrementally, so a SIGALRM/rc=124 mid-leg still emits every
    leg that finished before the kill — the same always-emit contract as
    the headline keys."""
    try:
        from elasticsearch_tpu.common import device_stats
        snap = device_stats.registry_snapshot(top_n=0, with_cost=False)
        lanes = device_stats.lane_decisions_snapshot()
        prev = _FLIGHT_PREV
        entry = {
            "invocations": snap["invocations_total"] - prev["invocations"],
            "device_ms": round(
                snap["device_time_in_millis"] - prev["device_ms"], 3),
            "compile_ms": round(
                snap["compile_time_in_millis"] - prev["compile_ms"], 3),
            "compiles": snap["compiles_total"] - prev["compiles"],
            "lane_decisions": {k: n - prev["lanes"].get(k, 0)
                               for k, n in lanes.items()
                               if n - prev["lanes"].get(k, 0)},
            "hbm_peak_bytes": device_stats.hbm_peak_bytes()}
        _FLIGHT_PREV.update(
            {"invocations": snap["invocations_total"],
             "device_ms": snap["device_time_in_millis"],
             "compile_ms": snap["compile_time_in_millis"],
             "compiles": snap["compiles_total"], "lanes": lanes})
        flight = _FINAL_LINE.get("flight") or {}
        flight[leg] = entry
        flight["program_count"] = snap["program_count"]
        _FINAL_LINE["flight"] = flight
        _FINAL_LINE["xla_compile_ms_total"] = round(
            device_stats.compile_ms_total(), 3)
        _FINAL_LINE["hbm_peak_bytes"] = device_stats.hbm_peak_bytes()
        _FINAL_LINE["lane_decision_counts"] = lanes
    except Exception as e:  # noqa: BLE001 — telemetry never fails the run
        print(f"flight snapshot ({leg}) failed: {e}", file=sys.stderr)


def _arm_leg_alarm(reserve: float) -> None:
    """Per-leg wall-clock enforcement by elapsed-time subtraction: the
    leg about to run may consume at most what is LEFT of the budget minus
    `reserve` (held back for later legs + the final print). A leg that
    hangs gets a _BudgetExceeded raised into it and degrades instead of
    erasing the run."""
    try:
        import signal
        signal.alarm(max(int(_remaining() - reserve), 1))
    except (ValueError, OSError, AttributeError):
        pass


def _arm_hard_alarm() -> None:
    """Measurement done: keep only the budget-edge emit guard armed."""
    try:
        import signal
        signal.alarm(max(int(_remaining() + _ALARM_MARGIN), 5))
    except (ValueError, OSError, AttributeError):
        pass


# armed at import — before the first leg, in every mode (main process,
# the BENCH_LEG=cpu subprocess, --kernel)
_install_bailout()

if os.environ.get("BENCH_SELFTEST_HANG"):
    # test seam: simulate the r05 hang (a leg stuck before any result
    # lands). The guards above must still print the one-line JSON.
    _FINAL_LINE.setdefault("metric", "selftest_hang")
    time.sleep(3600)


N_DOCS = int(os.environ.get("BENCH_DOCS", str(100_000)))
VOCAB = 30_000
AVG_DL = 20
Q_BATCH = 256             # queries per _msearch request (device batch)
N_BATCHES = 4             # distinct msearch payloads
REPS = 3
K = 1000                  # top-1000 (headline metric)
T = 4                     # terms per query
LATENCY_N = 50            # solo _search latency probes

# config #3: terms + date_histogram analytics over a log-event corpus
AGG_DOCS = int(os.environ.get("BENCH_AGG_DOCS", str(4_000_000)))
AGG_Q = 128               # agg requests per msearch batch
AGG_BATCHES = 4
# configs #4/#5: stored-vector cosine + BM25->dense hybrid rescore
VEC_DOCS = int(os.environ.get("BENCH_VEC_DOCS", str(100_000)))
VEC_DIMS = 768
VEC_Q = 128
VEC_BATCHES = 4
# IVF-clustered ANN (ISSUE 10): clusters + probes for the vector legs —
# nprobe/nlist = 1/16 of the corpus scanned per query
VEC_NLIST = int(os.environ.get("BENCH_VEC_NLIST", "256"))
VEC_NPROBE = int(os.environ.get("BENCH_VEC_NPROBE", "16"))
# recall-sensitive leg: pin f32 matmuls (`index.knn.precision`) — the
# recall@10 bar is measured against an f32 numpy oracle, and bf16's
# ~1e-3 relative error alone costs ~0.03 recall on near-tie neighbor
# sets (see README Vector search); on CPU runners f32 is also native
VEC_PRECISION = os.environ.get("BENCH_VEC_PRECISION", "f32")
# quantized ANN tier (ISSUE 12): PQ subquantizers (768/48 = 16-dim
# subspaces, 48 B/vec = 1/64 of f32) and the full-precision rescore
# window the int8/pq scans rank through before answering
VEC_PQ_M = int(os.environ.get("BENCH_VEC_PQ_M", "48"))
VEC_RESCORE = int(os.environ.get("BENCH_VEC_RESCORE", "64"))


def make_corpus(n_docs: int, seed: int = 7):
    """Zipf-distributed synthetic English-like corpus, built as strings so
    every doc passes the real analysis chain."""
    rng = np.random.default_rng(seed)
    words = np.array([f"term{i:05d}" for i in range(VOCAB)])
    lens = np.maximum(rng.poisson(AVG_DL, n_docs), 3)
    ranks = np.minimum(rng.zipf(1.3, size=int(lens.sum())), VOCAB) - 1
    docs = []
    pos = 0
    for L in lens:
        docs.append(" ".join(words[ranks[pos:pos + L]]))
        pos += L
    return docs


def make_queries(n: int, seed: int = 42) -> list[str]:
    rng = np.random.default_rng(seed)
    tids = rng.integers(64, 8192, size=(n, T))
    return [" ".join(f"term{t:05d}" for t in row) for row in tids]


def http(port: int, method: str, path: str, body: bytes | str = b"",
         timeout: float = 600.0) -> dict:
    import urllib.request
    if isinstance(body, str):
        body = body.encode()
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 data=body or None, method=method)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def run_agg_leg(tag: str) -> dict:
    """BASELINE config #3: terms + date_histogram aggregations over an
    AGG_DOCS log-event index, through HTTP — the device-side masked
    bincount / affine-histogram collect path (ops/aggs.py)."""
    import shutil
    import tempfile
    from elasticsearch_tpu.node import NodeService
    from elasticsearch_tpu.rest import HttpServer

    workdir = tempfile.mkdtemp(prefix=f"bench-agg-{tag}-")
    node = NodeService(os.path.join(workdir, "node"))
    server = HttpServer(node, port=0).start()
    port = server.port
    try:
        rng = np.random.default_rng(11)
        tags = [f"svc{i:02d}" for i in range(20)]
        t0 = time.perf_counter()
        http(port, "PUT", "/logs", json.dumps(
            {"settings": {"number_of_shards": 1},
             "mappings": {"_doc": {"properties": {
                 "tag": {"type": "string", "index": "not_analyzed"},
                 "ts": {"type": "date"},
                 "value": {"type": "long"}}}}}))
        base_ms = 1_700_000_000_000
        batch = 10_000
        tag_ids = rng.integers(0, len(tags), AGG_DOCS)
        ts = base_ms + rng.integers(0, 30 * 86_400_000, AGG_DOCS)
        vals = rng.integers(0, 10_000, AGG_DOCS)
        for i in range(0, AGG_DOCS, batch):
            lines = []
            for j in range(i, min(i + batch, AGG_DOCS)):
                lines.append('{"index":{"_id":"%d"}}' % j)
                lines.append('{"tag":"%s","ts":%d,"value":%d}'
                             % (tags[tag_ids[j]], ts[j], vals[j]))
            http(port, "POST", "/logs/_bulk", "\n".join(lines) + "\n")
        http(port, "POST", "/logs/_refresh")
        http(port, "POST", "/logs/_optimize")
        index_secs = time.perf_counter() - t0

        payloads = []
        for bi in range(AGG_BATCHES):
            lines = []
            for qi in range(AGG_Q):
                tag = tags[(bi * AGG_Q + qi) % len(tags)]
                lines.append('{"index":"logs"}')
                lines.append(json.dumps({
                    "size": 0,
                    "query": {"term": {"tag": tag}},
                    "aggs": {
                        "per_day": {"date_histogram": {"field": "ts",
                                                       "interval": "1d"}},
                        "by_tag": {"terms": {"field": "tag"}},
                        "val_stats": {"stats": {"field": "value"}}}}))
            payloads.append("\n".join(lines) + "\n")
        http(port, "POST", "/_msearch", payloads[0])     # warm compile
        t1 = time.perf_counter()
        n = 0
        for _ in range(REPS):
            for pl in payloads:
                out = http(port, "POST", "/_msearch", pl)
                n += len(out["responses"])
            if _over_budget():
                break          # a slow leg degrades the number, not erases it
        res = {"agg_qps": n / (time.perf_counter() - t1),
               "agg_index_secs": index_secs,
               "agg_docs_per_sec": AGG_DOCS / index_secs}

        # request-cache serving leg (ISSUE 3): the dashboard workload —
        # one heavy size=0 aggregation repeated verbatim. The first call
        # fills the shared request cache; repeats are O(1) lookups. The
        # uncached probes rotate a range filter so every body is novel —
        # the latency gap IS the cache win, measured through HTTP.
        solo = json.dumps({
            "size": 0, "query": {"term": {"tag": tags[0]}},
            "aggs": {"per_day": {"date_histogram": {"field": "ts",
                                                    "interval": "1d"}},
                     "val_stats": {"stats": {"field": "value"}}}})
        http(port, "POST", "/logs/_search", solo)        # fill (miss)
        cached_lat = []
        for _ in range(25):
            t2 = time.perf_counter()
            http(port, "POST", "/logs/_search", solo)
            cached_lat.append((time.perf_counter() - t2) * 1000)
        uncached_lat = []
        for i in range(10):
            body = json.dumps({
                "size": 0, "query": {"bool": {
                    "must": [{"term": {"tag": tags[0]}}],
                    "filter": [{"range": {"value": {"gte": i}}}]}},
                "aggs": {"per_day": {"date_histogram": {
                    "field": "ts", "interval": "1d"}},
                    "val_stats": {"stats": {"field": "value"}}}})
            t2 = time.perf_counter()
            http(port, "POST", "/logs/_search", body)
            uncached_lat.append((time.perf_counter() - t2) * 1000)
        cached_lat.sort()
        uncached_lat.sort()
        st = http(port, "GET", "/logs/_stats")
        rc = st["indices"]["logs"]["total"].get("request_cache", {})
        lookups = rc.get("hit_count", 0) + rc.get("miss_count", 0)
        res.update({
            "request_cache_hit_ratio":
                rc.get("hit_count", 0) / lookups if lookups else None,
            "request_cache_mem_bytes": rc.get("memory_size_in_bytes"),
            "agg_cached_p50_ms": cached_lat[len(cached_lat) // 2],
            "agg_uncached_p50_ms": uncached_lat[len(uncached_lat) // 2]})
        return res
    finally:
        server.stop()
        node.close()
        shutil.rmtree(workdir, ignore_errors=True)


def run_multiseg_leg(tag: str) -> dict:
    """ISSUE 4: the live-index (never force-merged, ~8 segments/shard)
    dense workload. Two identical indices — one on the segment-stacked
    dense lane, one pinned to the per-segment loop
    (`index.search.stacked.enable: false`) — serve the same dense
    unsorted query mix; the p50 gap is the stacked win, and the
    device-fetch counter delta is the fetches-per-query proof."""
    import shutil
    import tempfile
    from elasticsearch_tpu.node import NodeService
    from elasticsearch_tpu.rest import HttpServer
    from elasticsearch_tpu.common.metrics import transfer_snapshot

    n_docs = int(os.environ.get("BENCH_MS_DOCS", "40000"))
    n_segments = int(os.environ.get("BENCH_MS_SEGMENTS", "8"))
    reps = int(os.environ.get("BENCH_MS_REPS", "60"))
    workdir = tempfile.mkdtemp(prefix=f"bench-ms-{tag}-")
    node = NodeService(os.path.join(workdir, "node"))
    server = HttpServer(node, port=0).start()
    port = server.port
    try:
        rng = np.random.default_rng(23)
        words = [f"w{i:03d}" for i in range(300)]
        mapping = {"mappings": {"_doc": {"properties": {
            "body": {"type": "string"},
            "n": {"type": "long"}}}}}
        for name, extra in (("live", {}),
                            ("live_loop",
                             {"index.search.stacked.enable": False})):
            http(port, "PUT", f"/{name}", json.dumps(
                {**mapping,
                 "settings": {"number_of_shards": 1, **extra}}))
        word_ids = rng.integers(0, len(words), (n_docs, 6))
        # two size tiers (half big, half small segments) — the realistic
        # live-index shape, and no single tier fills the engine's
        # 8-segment merge trigger, so all ~8 segments survive refresh
        big = n_segments // 2
        small_sz = max(n_docs // 100, 8)
        big_sz = (n_docs - small_sz * (n_segments - big)) // big
        sizes = [big_sz] * big + [small_sz] * (n_segments - big)
        for name in ("live", "live_loop"):
            j = 0
            for sz in sizes:
                if _over_budget(margin=45.0):
                    # indexing alone ate the slice: degrade to absent
                    # keys — the headline line still prints (r05 fix)
                    return {}
                lines = []
                for _ in range(sz):
                    lines.append('{"index":{"_id":"%d"}}' % j)
                    lines.append(json.dumps({
                        "body": " ".join(words[w] for w in word_ids[j]),
                        "n": int(j)}))
                    j += 1
                http(port, "POST", f"/{name}/_bulk",
                     "\n".join(lines) + "\n")
                # refresh per batch -> one segment per round, NO force
                # merge: this leg measures the live-index shape
                http(port, "POST", f"/{name}/_refresh")

        def body_of(i: int) -> str:
            # should-scoring keeps the query off the sparse/packed lanes:
            # this is the dense tree the stacked lane serves
            a, b = words[i % len(words)], words[(i * 7 + 3) % len(words)]
            return json.dumps({"size": 10, "query": {"bool": {
                "should": [{"match": {"body": a}}, {"match": {"body": b}}],
                "filter": [{"range": {"n": {"gte": (i * 13) % 1000}}}]}}})

        out: dict = {}
        seg_counts = {
            name: http(port, "GET", f"/{name}/_stats")["indices"][name]
            ["total"]["segments"]["count"]
            for name in ("live", "live_loop")}
        for name, key in (("live", "stacked"), ("live_loop", "per_segment")):
            http(port, "POST", f"/{name}/_search", body_of(0))   # warm
            f0 = transfer_snapshot()["device_fetches_total"]
            lat = []
            served = 0
            for i in range(reps):
                t0 = time.perf_counter()
                http(port, "POST", f"/{name}/_search", body_of(i))
                lat.append((time.perf_counter() - t0) * 1000)
                served += 1
                if _over_budget():
                    break
            f1 = transfer_snapshot()["device_fetches_total"]
            lat.sort()
            out[f"{key}_p50_ms"] = lat[len(lat) // 2]
            out[f"{key}_fetches_per_query"] = (f1 - f0) / max(served, 1)
        out["multiseg_segments"] = seg_counts.get("live", n_segments)
        if out.get("per_segment_p50_ms"):
            out["multiseg_speedup"] = (out["per_segment_p50_ms"]
                                       / out["stacked_p50_ms"])

        # mesh lane (ISSUE 6): the ≥4-shard config — one shard_map program
        # with an on-device cross-shard reduce vs the thread-pool fan-out
        # over per-shard stacked programs. Skipped when the host lacks the
        # devices to seat the shards (the production fallback, measured
        # honestly as absent keys rather than a fake number).
        import jax as _jax
        n_mesh_shards = int(os.environ.get("BENCH_MS_SHARDS", "4"))
        if len(_jax.devices()) >= n_mesh_shards \
                and not _over_budget(margin=60.0):
            for name, extra in (("live_mesh", {}),
                                ("live_fanout",
                                 {"index.search.mesh.enable": False})):
                http(port, "PUT", f"/{name}", json.dumps(
                    {**mapping, "settings": {
                        "number_of_shards": n_mesh_shards, **extra}}))
            for name in ("live_mesh", "live_fanout"):
                j = 0
                for sz in sizes:
                    if _over_budget(margin=45.0):
                        return out       # keep the 1-shard numbers
                    lines = []
                    for _ in range(sz):
                        lines.append('{"index":{"_id":"%d"}}' % j)
                        lines.append(json.dumps({
                            "body": " ".join(words[w] for w in word_ids[j]),
                            "n": int(j)}))
                        j += 1
                    http(port, "POST", f"/{name}/_bulk",
                         "\n".join(lines) + "\n")
                    http(port, "POST", f"/{name}/_refresh")
            for name, key in (("live_mesh", "mesh"),
                              ("live_fanout", "fanout")):
                http(port, "POST", f"/{name}/_search", body_of(0))   # warm
                f0 = transfer_snapshot()["device_fetches_total"]
                lat = []
                served = 0
                for i in range(reps):
                    t0 = time.perf_counter()
                    http(port, "POST", f"/{name}/_search", body_of(i))
                    lat.append((time.perf_counter() - t0) * 1000)
                    served += 1
                    if _over_budget():
                        break
                f1 = transfer_snapshot()["device_fetches_total"]
                lat.sort()
                out[f"{key}_p50_ms"] = lat[len(lat) // 2]
                out[f"{key}_fetches_per_query"] = \
                    (f1 - f0) / max(served, 1)
            out["mesh_shards"] = n_mesh_shards
            if out.get("fanout_p50_ms") and out.get("mesh_p50_ms"):
                out["mesh_speedup"] = (out["fanout_p50_ms"]
                                       / out["mesh_p50_ms"])

            # aggs through the mesh program (ISSUE 11): terms/histogram/
            # stats partials collect INSIDE the collective and ride the
            # same single fetch — count the dispatches that actually
            # took the lane
            agg_body = json.dumps({
                "size": 0, "query": {"match": {"body": words[0]}},
                "aggs": {"h": {"histogram": {"field": "n",
                                             "interval": 64}},
                         "s": {"stats": {"field": "n"}}}})
            agg_reps = min(reps, 30)
            http(port, "POST", "/live_mesh/_search?request_cache=false",
                 agg_body)                                   # warm
            t0 = time.perf_counter()
            agg_served = 0
            for _ in range(agg_reps):
                http(port, "POST",
                     "/live_mesh/_search?request_cache=false", agg_body)
                agg_served += 1
                if _over_budget(margin=30.0):
                    break
            if agg_served:
                out["mesh_agg_qps"] = agg_served / max(
                    time.perf_counter() - t0, 1e-9)
            out["mesh_agg_dispatches"] = node.indices["live_mesh"] \
                .search_stats.get("mesh_agg_dispatches", 0)

            # sorted + 2-level sub-agg tree through the dense lanes
            # (ISSUE 17): the log-analytics shape — newest-first sort
            # and a histogram -> metrics tree — through the mesh
            # program vs the thread-pool fan-out over per-shard sorted
            # stacked programs, on the same corpus
            sorted_body = json.dumps({
                "size": 10, "query": {"match": {"body": words[0]}},
                "sort": [{"n": "desc"}]})
            subagg_body = json.dumps({
                "size": 0, "query": {"match": {"body": words[0]}},
                "aggs": {"h": {
                    "histogram": {"field": "n", "interval": 64},
                    "aggs": {"mx": {"max": {"field": "n"}},
                             "c": {"value_count": {"field": "n"}}}}}})
            s_reps = min(reps, 40)

            def observability_qps(name: str, body: str):
                http(port, "POST",
                     f"/{name}/_search?request_cache=false", body)  # warm
                t0 = time.perf_counter()
                served = 0
                for _ in range(s_reps):
                    http(port, "POST",
                         f"/{name}/_search?request_cache=false", body)
                    served += 1
                    if _over_budget(margin=30.0):
                        break
                return served / max(time.perf_counter() - t0, 1e-9)

            if not _over_budget(margin=45.0):
                out["sorted_mesh_qps"] = observability_qps(
                    "live_mesh", sorted_body)
                out["sorted_fanout_qps"] = observability_qps(
                    "live_fanout", sorted_body)
                out["subagg_mesh_qps"] = observability_qps(
                    "live_mesh", subagg_body)
                out["subagg_fanout_qps"] = observability_qps(
                    "live_fanout", subagg_body)
                out["mesh_sorted_dispatches"] = node.indices["live_mesh"] \
                    .search_stats.get("mesh_sorted_dispatches", 0)
                if out.get("sorted_fanout_qps"):
                    out["sorted_mesh_speedup"] = (out["sorted_mesh_qps"]
                                                  / out["sorted_fanout_qps"])
                if out.get("subagg_fanout_qps"):
                    out["subagg_mesh_speedup"] = (out["subagg_mesh_qps"]
                                                  / out["subagg_fanout_qps"])

            # the self-monitoring overview end to end (ISSUE 17
            # tentpole (c)): sampler snapshots drain into
            # .monitoring-es-* via the bulk lane, and GET
            # /_monitoring/overview answers with the sorted + 2-level
            # sub-agg body through the device lanes
            if not _over_budget(margin=40.0):
                from elasticsearch_tpu.common.monitoring import \
                    MonitoringCollector
                node.monitoring = MonitoringCollector(node, interval_s=0)
                for _ in range(24):
                    node.sampler.sample()
                node.monitoring.collect_once()
                http(port, "GET", "/_monitoring/overview")       # warm
                lat = []
                for _ in range(min(reps, 20)):
                    t0 = time.perf_counter()
                    http(port, "GET", "/_monitoring/overview")
                    lat.append((time.perf_counter() - t0) * 1000)
                    if _over_budget(margin=30.0):
                        break
                lat.sort()
                out["monitoring_overview_p50_ms"] = lat[len(lat) // 2]
        return out
    finally:
        server.stop()
        node.close()
        shutil.rmtree(workdir, ignore_errors=True)


def run_cluster_leg(tag: str) -> dict:
    """Cluster-wide collectives data plane (ISSUE 11): a 2-node cluster
    co-hosting a 4-shard index serves the same match-query workload
    through the node-local mesh reduce (ONE A_QUERY_HOST + one device
    program per host per query) vs the per-shard transport fan-out —
    `cluster_host_reduce_qps` vs `cluster_fanout_qps` on the same corpus
    is the flat-vs-linear reduce the device wins (ROADMAP item 1)."""
    import shutil
    import tempfile
    from elasticsearch_tpu.cluster import TestCluster

    n_docs = int(os.environ.get("BENCH_CLUSTER_DOCS", "100000"))
    n_shards = int(os.environ.get("BENCH_CLUSTER_SHARDS", "8"))
    reps = int(os.environ.get("BENCH_CLUSTER_REPS", "150"))
    n_q = 64
    tmp = tempfile.mkdtemp(prefix=f"bench-cluster-{tag}-")
    out: dict = {}
    cluster = TestCluster(2, tmp)
    try:
        client = cluster.client()
        # 2 nodes x (n_shards/2) co-hosted shards each — the ISSUE 11
        # acceptance config: each host reduces its 4 co-hosted shards in
        # ONE device program per query
        client.create_index("cdocs", {"number_of_shards": n_shards,
                                      "number_of_replicas": 0})
        cluster.ensure_green()
        docs = make_corpus(n_docs, seed=11)
        ops = []
        for i, body in enumerate(docs):
            ops.append(("index", {"_index": "cdocs", "_id": str(i)},
                        {"body": body}))
            if len(ops) >= 4000:
                client.bulk(ops)
                ops = []
            if _over_budget(margin=60.0):
                return {}        # indexing ate the slice: absent keys
        if ops:
            client.bulk(ops)
        client.refresh("cdocs")
        queries = make_queries(n_q, seed=13)

        def set_setting(val):
            master = cluster.master_node()

            def task(cur):
                st = cur.mutate()
                st.data.setdefault("settings", {})[
                    "cluster.search.host_reduce.enable"] = val
                return st
            master.cluster.submit_task("bench-host-reduce", task)

        def body_of(i: int) -> dict:
            # dense bool-should shape: the workload the collective reduce
            # serves (match-only bodies ride the per-shard sparse kernel
            # on the fan-out, a different lane entirely)
            terms = queries[i % n_q].split()
            return {"size": 10, "query": {"bool": {
                "should": [{"match": {"body": terms[0]}},
                           {"match": {"body": terms[1]}}]}}}

        def measure():
            for i in range(n_q):         # warm every pow2 shape bucket
                client.search("cdocs", json.loads(json.dumps(body_of(i))))
                if _over_budget(margin=45.0):
                    return None
            t0 = time.perf_counter()
            served = 0
            for i in range(reps):
                client.search("cdocs", json.loads(json.dumps(body_of(i))))
                served += 1
                if _over_budget(margin=30.0):
                    break
            return served / max(time.perf_counter() - t0, 1e-9)

        set_setting(True)
        d0 = sum(n.host_reduce_stats["dispatches"]
                 for n in cluster.nodes.values())
        out["cluster_host_reduce_qps"] = measure()
        out["cluster_host_reduce_dispatches"] = sum(
            n.host_reduce_stats["dispatches"]
            for n in cluster.nodes.values()) - d0
        set_setting(False)
        out["cluster_fanout_qps"] = measure()
        out["cluster_shards"] = n_shards
        if out.get("cluster_fanout_qps") and out.get(
                "cluster_host_reduce_qps"):
            out["cluster_host_speedup"] = (out["cluster_host_reduce_qps"]
                                           / out["cluster_fanout_qps"])
        return {k: v for k, v in out.items() if v is not None}
    finally:
        cluster.close()
        shutil.rmtree(tmp, ignore_errors=True)


def _pod_leg_measure(tag: str) -> dict:
    """Pod-scale serving (ISSUE 19): 2 simulated pools — each node OWNS
    half the devices and is its own host — vs the single shared-pool
    cluster on the SAME corpus and workload, both driven by TWO
    concurrent coordinators (the regime where per-pool dispatch locks
    beat the process-wide EXEC_LOCK). `pod_vs_single` is the acceptance
    ratio; `exec_lock_waits` must stay 0 on the per-node path;
    `dcn_hops_per_query` counts the pre-reduced cross-host hops."""
    import shutil
    import tempfile
    import threading
    from elasticsearch_tpu.cluster import TestCluster
    from elasticsearch_tpu.parallel.mesh_exec import (exec_lock_stats,
                                                      reset_exec_lock_stats)

    n_docs = int(os.environ.get("BENCH_POD_DOCS", "40000"))
    n_shards = int(os.environ.get("BENCH_POD_SHARDS", "8"))
    reps = int(os.environ.get("BENCH_POD_REPS", "120"))
    n_q = 32
    docs = make_corpus(n_docs, seed=17)
    queries = make_queries(n_q, seed=19)

    def body_of(i: int) -> dict:
        terms = queries[i % n_q].split()
        return {"size": 10, "query": {"bool": {
            "should": [{"match": {"body": terms[0]}},
                       {"match": {"body": terms[1]}}]}}}

    def build(pods: int):
        tmp = tempfile.mkdtemp(prefix=f"bench-pod-{tag}-{pods}-")
        cluster = TestCluster(2, tmp, pods=pods)
        client = cluster.client()
        client.create_index("pdocs", {"number_of_shards": n_shards,
                                      "number_of_replicas": 0})
        cluster.ensure_green()
        ops = []
        for i, body in enumerate(docs):
            ops.append(("index", {"_index": "pdocs", "_id": str(i)},
                        {"body": body}))
            if len(ops) >= 4000:
                client.bulk(ops)
                ops = []
            if _over_budget(margin=60.0):
                break
        if ops:
            client.bulk(ops)
        client.refresh("pdocs")
        return cluster, tmp

    def measure(cluster):
        # one coordinator thread per node, dispatching simultaneously
        nodes = [cluster.nodes[nid] for nid in sorted(cluster.nodes)]
        for i in range(n_q):             # warm every pow2 shape bucket
            nodes[0].search("pdocs", json.loads(json.dumps(body_of(i))))
            if _over_budget(margin=45.0):
                return None, 0
        served = [0] * len(nodes)

        def go(ci: int, node) -> None:
            for i in range(reps):
                node.search("pdocs",
                            json.loads(json.dumps(body_of(i + ci))))
                served[ci] += 1
                if _over_budget(margin=30.0):
                    break
        threads = [threading.Thread(target=go, args=(ci, n), daemon=True)
                   for ci, n in enumerate(nodes)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = max(time.perf_counter() - t0, 1e-9)
        total = sum(served)
        return (total / dt if total else None), total

    out: dict = {}
    cluster, tmp = build(2)
    try:
        reset_exec_lock_stats()
        # count the PRE-REDUCED query hops (one A_QUERY_HOST per remote
        # node), not every cross-host send — fetches/pings ride the dcn
        # transport class too but are not the reduce's hop budget
        d0 = sum(n.host_reduce_stats["dcn_hops"]
                 for n in cluster.nodes.values())
        out["pod_qps"], total = measure(cluster)
        if total:
            hops = sum(n.host_reduce_stats["dcn_hops"]
                       for n in cluster.nodes.values()) - d0
            out["dcn_hops_per_query"] = round(hops / total, 3)
        st = exec_lock_stats()
        out["exec_lock_waits"] = st["shared_waits"] \
            + st["shared_acquisitions"]
        out["pod_reduce_dispatches"] = sum(
            n.host_reduce_stats["pod_dispatches"]
            for n in cluster.nodes.values())
    finally:
        cluster.close()
        shutil.rmtree(tmp, ignore_errors=True)
    if _over_budget(margin=60.0):
        return {k: v for k, v in out.items() if v is not None}
    cluster, tmp = build(0)
    try:
        out["single_pool_qps"], _ = measure(cluster)
    finally:
        cluster.close()
        shutil.rmtree(tmp, ignore_errors=True)
    if out.get("pod_qps") and out.get("single_pool_qps"):
        out["pod_vs_single"] = out["pod_qps"] / out["single_pool_qps"]
    return {k: v for k, v in out.items() if v is not None}


def run_pod_leg(tag: str) -> dict:
    """Two owned pools need >= 4 devices; on smaller hosts (CPU dev
    runs) re-exec in a child with 8 virtual host devices — the same
    mechanism the test conftest uses — and adopt its one-line JSON."""
    import jax
    if len(jax.devices()) >= 4:
        return _pod_leg_measure(tag)
    import subprocess
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    env["BENCH_POD_CHILD"] = "1"
    env["BENCH_TIME_BUDGET"] = str(max(30.0, _remaining() - 30.0))
    try:
        child = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True,
            timeout=max(30.0, _remaining() - 15.0))
        for ln in child.stdout.splitlines():
            if ln.startswith("{"):
                return json.loads(ln)
        print(f"pod child produced no result (rc={child.returncode}): "
              f"{child.stderr[-500:]}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — the pod leg is best-effort
        print(f"pod child failed: {e}", file=sys.stderr)
    return {}


def run_vector_leg(tag: str) -> dict:
    """BASELINE configs #4/#5: function_score cosine over stored 768-d
    vectors (exact kNN through the product) and BM25->dense hybrid rescore,
    with recall@10 against a numpy brute-force oracle."""
    import shutil
    import tempfile
    from elasticsearch_tpu.node import NodeService
    from elasticsearch_tpu.rest import HttpServer

    workdir = tempfile.mkdtemp(prefix=f"bench-vec-{tag}-")
    # the latency-EWMA shed signal is off for THIS leg only: the quantized
    # tier's first query per mode pays a one-off train+compile measured in
    # tens of seconds, which would spike the EWMA past the 5s ceiling and
    # 429 the whole remaining leg (one sequential client — queue/breaker
    # admission stays on; the QoS contract has its own leg)
    from elasticsearch_tpu.common.settings import Settings
    node = NodeService(os.path.join(workdir, "node"),
                       settings=Settings(
                           {"node.search.qos.shed_latency_ms": 0}))
    server = HttpServer(node, port=0).start()
    port = server.port
    try:
        # clustered corpus: text and vectors CORRELATE (each doc belongs to
        # a topic; its text contains the topic token, its vector sits near
        # the topic centroid). The BM25 gate then retrieves the right
        # cluster and hybrid recall@10 vs the GLOBAL kNN oracle measures
        # the pipeline honestly — with random text/vectors it would only
        # measure the (meaningless) overlap of two unrelated top-k sets.
        # Within each topic, docs cluster around PROTOTYPES (~16 near-
        # duplicates each) so a query's true top-10 sits at a real margin
        # above the rest — the regime ANN retrieval serves. The previous
        # corpus's ranks 2-10 were pure-noise ties (margins far below any
        # quantizer's error), which made recall@10 measure tie-ranking
        # luck instead of neighbor retrieval (ISSUE 12).
        rng = np.random.default_rng(23)
        n_topics = 64
        group = 16                         # docs per prototype
        centers = rng.normal(0, 1, (n_topics, VEC_DIMS)).astype(np.float32)
        centers /= np.linalg.norm(centers, axis=1, keepdims=True)
        sigma = 0.35 / np.sqrt(VEC_DIMS)   # noise NORM ~0.35 vs unit center
        sigma_dup = 0.12 / np.sqrt(VEC_DIMS)   # near-duplicate radius
        n_protos = max(VEC_DOCS // group, 1)
        proto_topic = rng.integers(0, n_topics, n_protos)
        protos = centers[proto_topic] \
            + sigma * rng.normal(0, 1, (n_protos, VEC_DIMS)).astype(
                np.float32)
        proto_of = np.repeat(np.arange(n_protos), group)[:VEC_DOCS]
        if len(proto_of) < VEC_DOCS:
            proto_of = np.resize(proto_of, VEC_DOCS)
        topic_of = proto_topic[proto_of]
        vecs = protos[proto_of] \
            + sigma_dup * rng.normal(0, 1, (VEC_DOCS, VEC_DIMS)).astype(
                np.float32)
        vecs = vecs.astype(np.float32)
        vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
        base_docs = make_corpus(VEC_DOCS, seed=29)
        docs = [f"topic{topic_of[j]:03d} " + base_docs[j]
                for j in range(VEC_DOCS)]
        t0 = time.perf_counter()
        http(port, "PUT", "/vec", json.dumps(
            {"settings": {"number_of_shards": 1,
                          "index.knn.ivf.nlist": VEC_NLIST,
                          "index.knn.ivf.nprobe": VEC_NPROBE,
                          "index.knn.precision": VEC_PRECISION,
                          "index.knn.pq.m": VEC_PQ_M,
                          "index.knn.rescore_window": VEC_RESCORE},
             "mappings": {"_doc": {"properties": {
                 "body": {"type": "string"},
                 "emb": {"type": "dense_vector",
                         "dims": VEC_DIMS}}}}}))
        batch = 500
        for i in range(0, VEC_DOCS, batch):
            lines = []
            for j in range(i, min(i + batch, VEC_DOCS)):
                lines.append('{"index":{"_id":"%d"}}' % j)
                emb = ",".join("%.3f" % x for x in vecs[j])
                lines.append('{"body":%s,"emb":[%s]}'
                             % (json.dumps(docs[j]), emb))
            http(port, "POST", "/vec/_bulk", "\n".join(lines) + "\n")
        http(port, "POST", "/vec/_refresh")
        http(port, "POST", "/vec/_optimize")
        index_secs = time.perf_counter() - t0

        nq = VEC_Q * VEC_BATCHES
        q_proto = rng.integers(0, n_protos, nq)
        q_topic = proto_topic[q_proto]
        qv = protos[q_proto] \
            + sigma_dup * rng.normal(0, 1, (nq, VEC_DIMS)).astype(
                np.float32)
        qv = qv.astype(np.float32)
        qv /= np.linalg.norm(qv, axis=1, keepdims=True)
        # brute-force oracle top-10 by cosine (global — the honest bar)
        oracle = np.argsort(-(qv @ vecs.T), axis=1)[:, :10]
        queries = [f"topic{q_topic[i]:03d}" for i in range(nq)]

        def measure(body_of, oracle_of=None):
            payloads = []
            for bi in range(VEC_BATCHES):
                lines = []
                for qi in range(VEC_Q):
                    gi = bi * VEC_Q + qi
                    lines.append('{"index":"vec"}')
                    lines.append(json.dumps(body_of(gi)))
                payloads.append("\n".join(lines) + "\n")
            first = http(port, "POST", "/_msearch", payloads[0])  # warm
            recall = None
            if oracle_of is not None:
                hits_total = 0
                match_total = 0
                for bi, pl in enumerate(payloads):
                    out = first if bi == 0 \
                        else http(port, "POST", "/_msearch", pl)
                    for qi, resp in enumerate(out["responses"]):
                        gi = bi * VEC_Q + qi
                        want = oracle_of(gi)
                        got = {int(h["_id"])
                               for h in resp["hits"]["hits"][:len(want)]}
                        match_total += len(got & want)
                        hits_total += len(want)
                recall = match_total / max(hits_total, 1)
            t1 = time.perf_counter()
            n = 0
            for _ in range(REPS):
                for pl in payloads:
                    out = http(port, "POST", "/_msearch", pl)
                    n += len(out["responses"])
                if _over_budget():
                    break
            return n / (time.perf_counter() - t1), recall

        # config #4 (ISSUE 10): kNN through the product — the IVF lane
        # (centroid route + gathered cluster scan) is the index default;
        # the exact [Q, N] matmul runs as the control at the same corpus
        knn_qps, knn_recall = measure(
            lambda gi: {"knn": {"field": "emb",
                                "query_vector": [round(float(x), 3)
                                                 for x in qv[gi]],
                                "k": 10},
                        "size": 10, "_source": False},
            oracle_of=lambda gi: set(oracle[gi]))
        ann_dispatches = node.indices["vec"].search_stats.get(
            "ann_dispatches", 0)
        knn_exact_qps = None
        if not _over_budget(margin=30.0):
            knn_exact_qps, _ = measure(
                lambda gi: {"knn": {"field": "emb",
                                    "query_vector": [round(float(x), 3)
                                                     for x in qv[gi]],
                                    "k": 10, "exact": True},
                            "size": 10, "_source": False})

        # quantized tier (ISSUE 12): int8 + PQ scans on the SAME corpus
        # via the per-request override — no reindex, same nprobe, same
        # oracle. The TRAIN phase (the first query builds codes /
        # codebooks, sample-capped at ops/ann.TRAIN_SAMPLE_CAP) and the
        # SCAN phase are budget-checked separately so a slow build is
        # skipped-and-reported instead of eating the remaining legs
        # (the r05 rc=124 lesson).
        quant_res: dict = {}
        qcache = node.caches.ann_indexes
        for mode in ("int8", "pq"):
            if _over_budget(margin=45.0):
                print(f"quantized [{mode}] skipped: "
                      f"{_remaining():.0f}s of budget left",
                      file=sys.stderr)
                break
            b0 = qcache.quant_code_bytes + qcache.quant_book_bytes

            def qbody(gi, _mode=mode):
                return {"knn": {"field": "emb",
                                "query_vector": [round(float(x), 3)
                                                 for x in qv[gi]],
                                "k": 10, "quantization": _mode},
                        "size": 10, "_source": False}
            http(port, "POST", "/vec/_search", json.dumps(qbody(0)))
            quant_res[f"vector_stack_bytes_{mode}"] = \
                qcache.quant_code_bytes + qcache.quant_book_bytes - b0
            if _over_budget(margin=45.0):
                print(f"quantized [{mode}] trained but scan skipped: "
                      f"{_remaining():.0f}s of budget left",
                      file=sys.stderr)
                break
            qps, rec = measure(qbody,
                               oracle_of=lambda gi: set(oracle[gi]))
            quant_res[f"knn_{mode}_qps"] = qps
            quant_res[f"{mode}_recall"] = rec
        # the f32 column bytes the quantized tier replaces in the scan —
        # measured from the live segments, not assumed
        searcher = next(iter(node.indices["vec"].searchers()), None)
        if searcher is not None:
            quant_res["vector_stack_bytes_f32"] = sum(
                int(seg.vectors["emb"].vecs.size) * 4
                for _i, seg in searcher.live_segments
                if "emb" in seg.vectors)

        # config #5: hybrid — BM25 top-1000 then dense rescore to top-10
        hybrid_qps, hybrid_recall = measure(
            lambda gi: {"query": {"match": {"body": queries[gi]}},
                        "size": 10,
                        "rescore": {"window_size": K, "query": {
                            "rescore_query": {"function_score": {
                                "query": {"match_all": {}},
                                "cosine": {"field": "emb",
                                           "query_vectors": [
                                               [round(float(x), 3)
                                                for x in qv[gi]]]},
                                "boost_mode": "replace"}},
                            "query_weight": 0.0,
                            "rescore_query_weight": 1.0,
                            "score_mode": "total"}},
                        "_source": False},
            oracle_of=lambda gi: set(oracle[gi]))
        # first-class hybrid fusion (the body's "rank" section): BM25
        # and the IVF vector list fuse via RRF at the coordinator
        hybrid_rrf_qps = hybrid_rrf_recall = None
        if not _over_budget(margin=30.0):
            hybrid_rrf_qps, hybrid_rrf_recall = measure(
                lambda gi: {"query": {"match": {"body": queries[gi]}},
                            "knn": {"field": "emb",
                                    "query_vector": [round(float(x), 3)
                                                     for x in qv[gi]],
                                    "k": 100},
                            "rank": {"rrf": {"window_size": 100}},
                            "size": 10, "_source": False},
                oracle_of=lambda gi: set(oracle[gi]))
        return {"knn_qps": knn_qps, "knn_recall": knn_recall,
                "knn_exact_qps": knn_exact_qps,
                "knn_nprobe": VEC_NPROBE,
                "ann_dispatches": ann_dispatches,
                "hybrid_qps": hybrid_qps, "hybrid_recall": hybrid_recall,
                "hybrid_rrf_qps": hybrid_rrf_qps,
                "hybrid_rrf_recall": hybrid_rrf_recall,
                "vec_index_secs": index_secs,
                "vec_docs_per_sec": VEC_DOCS / index_secs,
                **quant_res}
    finally:
        server.stop()
        node.close()
        shutil.rmtree(workdir, ignore_errors=True)


def run_scale_leg(tag: str) -> dict:
    """ISSUE 8 scale leg (opt-in: BENCH_SCALE=1): the BASELINE 10M-doc
    tier's shapes at bench scale — config #3 aggs at BENCH_SCALE_AGG_DOCS
    (default 4M) and config #4 vectors at BENCH_SCALE_VEC_DOCS (default
    1M) — under the per-leg wall-clock budget. The streaming blockwise
    dense lane keeps peak device score memory O(Q × block); the leg
    reports peak RSS and the process-peak score-matrix gauge so the bound
    is visible in the one-line JSON (the materializing path either trips
    the request breaker or blows the budget at these sizes)."""
    global AGG_DOCS, VEC_DOCS
    import resource
    from elasticsearch_tpu.common.metrics import peak_score_matrix_bytes
    out: dict = {}
    save_agg, save_vec = AGG_DOCS, VEC_DOCS
    AGG_DOCS = int(os.environ.get("BENCH_SCALE_AGG_DOCS", str(4_000_000)))
    VEC_DOCS = int(os.environ.get("BENCH_SCALE_VEC_DOCS", str(1_000_000)))
    try:
        try:
            r = run_agg_leg(tag + "-scale")
            out.update({"scale_agg_qps": r["agg_qps"],
                        "scale_agg_docs": AGG_DOCS,
                        "scale_agg_index_secs": r["agg_index_secs"]})
        except Exception as e:  # noqa: BLE001 — legs are best-effort
            print(f"BENCH_SCALE agg leg failed: {e}", file=sys.stderr)
        if not _over_budget(margin=90.0):
            _arm_leg_alarm(reserve=60.0)
            try:
                r = run_vector_leg(tag + "-scale")
                out.update({"scale_knn_qps": r["knn_qps"],
                            "scale_knn_recall": r["knn_recall"],
                            "scale_knn_exact_qps": r.get("knn_exact_qps"),
                            "scale_ann_dispatches": r.get("ann_dispatches"),
                            "scale_vec_docs": VEC_DOCS,
                            "scale_vec_index_secs": r["vec_index_secs"],
                            # quantized tier at the scale corpus
                            # (ISSUE 12): the 10M-config crossover proof
                            "scale_knn_int8_qps": r.get("knn_int8_qps"),
                            "scale_knn_pq_qps": r.get("knn_pq_qps"),
                            "scale_pq_recall": r.get("pq_recall"),
                            "scale_vector_stack_bytes_f32":
                                r.get("vector_stack_bytes_f32"),
                            "scale_vector_stack_bytes_pq":
                                r.get("vector_stack_bytes_pq")})
            except Exception as e:  # noqa: BLE001
                print(f"BENCH_SCALE vec leg failed: {e}", file=sys.stderr)
        out["scale_peak_rss_bytes"] = resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss * 1024
        out["scale_peak_score_matrix_bytes"] = peak_score_matrix_bytes()
    finally:
        AGG_DOCS, VEC_DOCS = save_agg, save_vec
    return out


def run_engine_leg(tag: str) -> dict:
    """Full product pipeline: index via _bulk, serve via _msearch/_search."""
    import shutil
    import tempfile
    from elasticsearch_tpu.node import NodeService
    from elasticsearch_tpu.rest import HttpServer

    workdir = tempfile.mkdtemp(prefix=f"bench-{tag}-")
    node = NodeService(os.path.join(workdir, "node"))
    server = HttpServer(node, port=0).start()
    port = server.port
    try:
        docs = make_corpus(N_DOCS)
        t0 = time.perf_counter()          # after corpus gen: index cost only
        http(port, "PUT", "/bench", json.dumps(
            {"settings": {"number_of_shards": 1},
             "mappings": {"_doc": {"properties": {
                 "body": {"type": "string"},
                 "price": {"type": "long"}}}}}))
        # 4000 docs/bulk (~600KB) sits inside the reference's recommended
        # 5-15MB window and halves the per-request HTTP/ack overhead the
        # 2000-doc batches paid
        batch = 4000
        for i in range(0, len(docs), batch):
            lines = []
            for j, d in enumerate(docs[i:i + batch]):
                # corpus terms are plain ASCII — interpolation is exact
                # JSON and keeps client-side encoding out of index_secs
                # (the agg leg builds its lines the same way)
                lines.append('{"index":{"_id":"%d"}}' % (i + j))
                lines.append('{"body":"%s","price":%d}' % (d, (i + j) % 1000))
            http(port, "POST", "/bench/_bulk", "\n".join(lines) + "\n")
        http(port, "POST", "/bench/_refresh")
        http(port, "POST", "/bench/_optimize")
        index_secs = time.perf_counter() - t0

        queries = make_queries(Q_BATCH * N_BATCHES)

        def msearch_payloads(body_of):
            out = []
            for bi in range(N_BATCHES):
                lines = []
                for q in queries[bi * Q_BATCH:(bi + 1) * Q_BATCH]:
                    lines.append(json.dumps({"index": "bench"}))
                    lines.append(json.dumps(body_of(q)))
                out.append("\n".join(lines) + "\n")
            return out

        def measure_msearch(payloads):
            http(port, "POST", "/_msearch", payloads[0])   # warm compile
            t1 = time.perf_counter()
            n = 0
            for _ in range(REPS):
                for pl in payloads:
                    out = http(port, "POST", "/_msearch", pl)
                    n += len(out["responses"])
                if _over_budget():
                    break
            return n / (time.perf_counter() - t1)

        # config #1: match query, top-K
        qps = measure_msearch(msearch_payloads(
            lambda q: {"query": {"match": {"body": q}}, "size": K,
                       "_source": False}))
        # config #2: bool{match + range filter}, top-K — the packed
        # kernel's filter slots serve this
        lo = 100
        qps_filter = measure_msearch(msearch_payloads(
            lambda q: {"query": {"bool": {
                "must": [{"match": {"body": q}}],
                "filter": [{"range": {"price": {"gte": lo,
                                                "lte": lo + 500}}}]}},
                "size": K, "_source": False}))

        # solo _search latency, size=10 (BASELINE config #1 shape)
        lat = []
        solo = json.dumps({"query": {"match": {"body": queries[0]}},
                           "size": 10, "_source": False})
        http(port, "POST", "/bench/_search", solo)
        for q in queries[:LATENCY_N]:
            body = json.dumps({"query": {"match": {"body": q}},
                               "size": 10, "_source": False})
            t1 = time.perf_counter()
            http(port, "POST", "/bench/_search", body)
            lat.append((time.perf_counter() - t1) * 1000)
        lat.sort()

        def serving_counters():
            # batcher + admission counters ride the payload so the bench
            # trajectory captures serving EFFICIENCY (how much coalescing
            # and rejection happened), not just latency
            bst = node._batcher.stats()
            return {"batches": bst["batches"],
                    "batched_requests": bst["batched_requests"],
                    "search_rejected":
                        node.thread_pool.stats()["search"]["rejected"]}

        # concurrent solo clients (NOT pre-batched msearch): the dynamic
        # batcher coalesces these into shared device programs. Skipped
        # cleanly when the wall-clock budget is spent.
        if _over_budget(margin=30.0):
            return {"qps": qps, "qps_filter": qps_filter,
                    "p50_ms": lat[len(lat) // 2],
                    "p99_ms": lat[min(len(lat) - 1, int(len(lat) * 0.99))],
                    "conc_qps": None, "conc_p50_ms": None,
                    "conc_p99_ms": None, "shed_429s": None,
                    "hedged_wins": None,
                    "conc_clients": 0, "index_secs": index_secs,
                    "docs_per_sec": N_DOCS / index_secs,
                    **serving_counters()}
        import threading
        import urllib.error
        # BENCH_CONC_CLIENTS (ISSUE 9) is the canonical fan-in override;
        # BENCH_CONC stays honored for older harness configs
        CONC = int(os.environ.get("BENCH_CONC_CLIENTS",
                                  os.environ.get("BENCH_CONC", "32")))
        PER = 8
        conc_lat: list[float] = []
        shed_429s = [0]
        conc_lock = threading.Lock()

        def client(ci: int):
            for qi in range(PER):
                q = queries[(ci * PER + qi) % len(queries)]
                body = json.dumps({"query": {"match": {"body": q}},
                                   "size": 10, "_source": False})
                t2 = time.perf_counter()
                try:
                    http(port, "POST", "/bench/_search", body)
                except urllib.error.HTTPError as e:
                    # load shedding IS the contract under overload: a 429
                    # is counted, anything else still fails the leg
                    if e.code != 429:
                        raise
                    with conc_lock:
                        shed_429s[0] += 1
                    continue
                dt = (time.perf_counter() - t2) * 1000
                with conc_lock:
                    conc_lat.append(dt)

        # unmeasured warm round: the batcher compiles one program per
        # coalesced Q-shape bucket; steady-state is what we measure
        warm_threads = [threading.Thread(target=client, args=(ci,))
                        for ci in range(CONC)]
        for t in warm_threads:
            t.start()
        for t in warm_threads:
            t.join()
        conc_lat.clear()
        shed_429s[0] = 0
        threads = [threading.Thread(target=client, args=(ci,))
                   for ci in range(CONC)]
        t1 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        conc_dt = time.perf_counter() - t1
        conc_lat.sort()
        from elasticsearch_tpu.serving.qos import hedge_snapshot
        return {"qps": qps,
                "qps_filter": qps_filter,
                "p50_ms": lat[len(lat) // 2],
                "p99_ms": lat[min(len(lat) - 1, int(len(lat) * 0.99))],
                "conc_qps": len(conc_lat) / conc_dt,
                "conc_p50_ms": conc_lat[len(conc_lat) // 2]
                if conc_lat else None,
                "conc_p99_ms": conc_lat[min(len(conc_lat) - 1,
                                            int(len(conc_lat) * 0.99))]
                if conc_lat else None,
                "shed_429s": shed_429s[0],
                "hedged_wins": hedge_snapshot()["win_backup"],
                "conc_clients": CONC,
                "index_secs": index_secs,
                "docs_per_sec": N_DOCS / index_secs,
                **serving_counters()}
    finally:
        server.stop()
        node.close()
        shutil.rmtree(workdir, ignore_errors=True)


def run_chaos_leg(tag: str) -> dict:
    """Chaos harness leg (ISSUE 14): one seeded round of the cross-lane
    parity oracle + leak detectors in the cheap single-node mode
    (cluster_nodes=0 — the multi-node disruption rounds live in tier-1's
    chaos smoke; the bench leg proves the oracle runs clean on THIS
    build and reports the counts). BENCH_CHAOS_SEED / BENCH_CHAOS_ROUNDS
    override; a mismatch degrades to a non-zero count in the line, never
    a failed run."""
    import shutil
    import tempfile
    from elasticsearch_tpu.testing.chaos import ChaosOptions, ChaosRunner
    seed = int(os.environ.get("BENCH_CHAOS_SEED", "1234"))
    rounds = int(os.environ.get("BENCH_CHAOS_ROUNDS", "1"))
    workdir = tempfile.mkdtemp(prefix=f"bench-chaos-{tag}-")
    try:
        report = ChaosRunner(workdir, ChaosOptions(
            seed=seed, rounds=rounds, cluster_nodes=0,
            raise_on_failure=False)).run()
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    return {"chaos_seed": report.seed,
            "chaos_rounds": report.rounds,
            "chaos_parity_checks": report.parity_checks,
            "chaos_mismatches": len(report.mismatches),
            "chaos_invariant_violations":
                len(report.invariant_violations)}


def run_percolate_leg(tag: str) -> dict:
    """Reverse search (ISSUE 18): register BENCH_PERCOLATE_QUERIES dense-
    eligible queries (match / term / range / bool — the four channel
    families of the doc×query grid), then percolate doc batches through
    the ONE-program dense executor vs the per-doc loop rung measured on a
    small doc subsample and extrapolated. Also times a compilable
    script_score riding the fused device lane vs the SAME expression
    forced onto the host evaluator (an `if true else` wrapper declines the
    compiler but evaluates identically) — the compiled-vs-decline ratio."""
    import shutil
    import tempfile
    from elasticsearch_tpu.common.metrics import transfer_snapshot
    from elasticsearch_tpu.node import NodeService
    from elasticsearch_tpu.search import percolator as perc_mod
    from elasticsearch_tpu.search.percolate_exec import percolate_batch

    nq = int(os.environ.get("BENCH_PERCOLATE_QUERIES", "50000"))
    batch_docs = int(os.environ.get("BENCH_PERCOLATE_BATCH", "64"))
    reps = int(os.environ.get("BENCH_PERCOLATE_REPS", "6"))
    loop_docs = int(os.environ.get("BENCH_PERCOLATE_LOOP_DOCS", "2"))
    s_docs = int(os.environ.get("BENCH_SCRIPT_DOCS", "5000"))
    s_reps = int(os.environ.get("BENCH_SCRIPT_REPS", "30"))
    workdir = tempfile.mkdtemp(prefix=f"bench-perc-{tag}-")
    node = NodeService(os.path.join(workdir, "node"))
    out: dict = {}
    try:
        node.create_index("perc", settings={"number_of_shards": 1},
                          mappings={"_doc": {"properties": {
                              "body": {"type": "string"},
                              "tag": {"type": "string",
                                      "index": "not_analyzed"},
                              "n": {"type": "long"}}}})
        tags = [f"t{i}" for i in range(16)]

        def qbody(i: int) -> dict:
            w = f"term{64 + (i * 131) % 8000:05d}"
            kind = i % 4
            if kind == 0:
                return {"match": {"body": w}}
            if kind == 1:
                return {"term": {"tag": tags[i % len(tags)]}}
            if kind == 2:
                lo = (i * 37) % 5000
                return {"range": {"n": {"gte": lo, "lt": lo + 200}}}
            return {"bool": {"must": [{"match": {"body": w}}],
                             "must_not": [{"term": {
                                 "tag": tags[(i + 7) % len(tags)]}}]}}

        registered = 0
        for i in range(0, nq, 4000):
            ops = [("index", {"_index": "perc", "_id": f"pq-{j}",
                              "_type": ".percolator"},
                    {"query": qbody(j)})
                   for j in range(i, min(i + 4000, nq))]
            node.bulk(ops)
            registered += len(ops)
            if _over_budget(margin=120.0):
                break              # partial registry: ratio still holds
        node.refresh("perc")
        svc = node.indices["perc"]
        rng = np.random.default_rng(29)
        docs = [{"body": " ".join(
                     f"term{t:05d}" for t in rng.integers(64, 8192, size=6)),
                 "tag": tags[int(rng.integers(len(tags)))],
                 "n": int(rng.integers(0, 5200))}
                for _ in range(batch_docs)]
        pairs = [(d, "_doc") for d in docs]
        percolate_batch(svc, "perc", pairs, caches=node.caches)   # warm
        f0 = transfer_snapshot()["device_fetches_total"]
        t0 = time.perf_counter()
        dense_n = batches = 0
        for _ in range(reps):
            percolate_batch(svc, "perc", pairs, caches=node.caches)
            dense_n += len(pairs)
            batches += 1
            if _over_budget(margin=90.0):
                break
        dense_s = time.perf_counter() - t0
        fetches = transfer_snapshot()["device_fetches_total"] - f0
        out.update({
            "percolate_queries": registered,
            "percolate_qps": dense_n / max(dense_s, 1e-9),
            "percolate_matrix_qps":
                dense_n * registered / max(dense_s, 1e-9),
            "percolate_fetches_per_batch": fetches / max(batches, 1)})
        # loop rung on a doc SUBSAMPLE, extrapolated — per-doc it re-plans
        # and re-dispatches the whole registry, which is the point
        registry = perc_mod.parsed_registry(svc)
        t0 = time.perf_counter()
        loop_n = 0
        for doc in docs[:loop_docs]:
            _, seg, root = perc_mod.build_doc_segment(svc, doc)
            perc_mod.loop_match(registry, seg, root)
            loop_n += 1
            if _over_budget(margin=60.0):
                break
        loop_s = time.perf_counter() - t0
        if loop_n:
            loop_qps = loop_n / max(loop_s, 1e-9)
            out["percolate_loop_qps"] = loop_qps
            out["percolate_vs_loop"] = \
                out["percolate_qps"] / max(loop_qps, 1e-9)

        # -- script_score: compiled device lane vs forced host decline
        node.create_index("sdocs", settings={"number_of_shards": 1},
                          mappings={"_doc": {"properties": {
                              "body": {"type": "string"},
                              "n": {"type": "long"},
                              "price": {"type": "double"}}}})
        bodies = make_corpus(s_docs, seed=31)
        for i in range(0, s_docs, 4000):
            node.bulk([("index", {"_index": "sdocs", "_id": str(j)},
                        {"body": bodies[j], "n": j,
                         "price": float((j * 7) % 1000) / 10.0})
                       for j in range(i, min(i + 4000, s_docs))])
        node.refresh("sdocs")
        expr = ("doc['n'].value * 2.0"
                " + Math.min(doc['price'].value, params.c)")

        def sbody(src: str, i: int) -> dict:
            return {"size": 10, "query": {"function_score": {
                "query": {"match": {"body": f"term{64 + i % 512:05d}"}},
                "script_score": {"script": src, "params": {"c": 50.0}},
                "boost_mode": "replace"}}}

        def measure_script(src: str, max_reps: int) -> float | None:
            node.search("sdocs", sbody(src, 0))        # warm compile
            t0 = time.perf_counter()
            n = 0
            for i in range(max_reps):
                node.search("sdocs", sbody(src, i + 1))
                n += 1
                if _over_budget(margin=45.0):
                    break
            return n / max(time.perf_counter() - t0, 1e-9) if n else None

        comp = measure_script(expr, s_reps)
        # the wrapper declines compilation (IfExp is outside the grammar)
        # but the host evaluator computes the identical expression
        host = measure_script(f"({expr}) if true else 0.0",
                              max(s_reps // 6, 2))
        if comp:
            out["script_score_qps"] = comp
        if comp and host:
            out["script_host_qps"] = host
            out["script_vs_decline"] = comp / host
        return out
    finally:
        node.close()
        shutil.rmtree(workdir, ignore_errors=True)


def run_watcher_leg(tag: str) -> dict:
    """Watcher alerting tier (ISSUE 20): register BENCH_WATCHER_WATCHES
    watches (mixed percolate/agg conditions), drive the monitoring
    collector so document watches ride its dense percolate batch, tick
    the scheduler over the agg watches, and page a composite agg through
    `after`-key cursors — evals/sec, per-fire latency, ride count, and
    composite pages/sec."""
    import shutil
    import tempfile
    from elasticsearch_tpu.common.settings import Settings
    from elasticsearch_tpu.node import NodeService

    n_watches = int(os.environ.get("BENCH_WATCHER_WATCHES", "1000"))
    n_agg = max(1, int(os.environ.get("BENCH_WATCHER_AGG", "50")))
    rounds = int(os.environ.get("BENCH_WATCHER_ROUNDS", "3"))
    fire_reps = int(os.environ.get("BENCH_WATCHER_FIRE_REPS", "20"))
    comp_docs = int(os.environ.get("BENCH_COMPOSITE_DOCS", "20000"))
    comp_secs = float(os.environ.get("BENCH_COMPOSITE_SECS", "5"))
    workdir = tempfile.mkdtemp(prefix=f"bench-watch-{tag}-")
    node = NodeService(os.path.join(workdir, "node"), Settings({
        "node.monitoring.enable": True,
        "node.monitoring.interval": 0,      # manual collector ticks
        "node.sampler.interval": 0,
        "watcher.interval": 0,              # manual scheduler ticks
        "watcher.throttle_period": "0s"}))
    out: dict = {}
    try:
        ws = node.watcher_service
        agg_body = {"size": 0, "aggs": {"over_time": {
            "date_histogram": {"field": "@timestamp", "interval": "1s"},
            "aggs": {"rate": {"derivative": {"buckets_path": "_count"}}},
        }}}
        stride = max(1, n_watches // n_agg)
        for i in range(n_watches):
            if i % stride != 0 or i // stride >= n_agg:
                # document watch: one more column of the dense matrix
                ws.put_watch(f"doc-{i}", {"input": {"percolate": {
                    "query": {"term": {"kind": "node_stats"}}
                    if i % 2 else
                    {"range": {"heap_used_bytes": {"gte": i % 97}}}}}})
            else:
                ws.put_watch(f"agg-{i}", {
                    "trigger": {"schedule": {"interval": "1s"}},
                    "input": {"search": {"request": {
                        "index": ".monitoring-es-*", "body": agg_body}}},
                    "condition": {"compare": {
                        "ctx.payload.hits.total": {"gte": 0}}}})
            if _over_budget(margin=120.0):
                break              # partial registry: rates still hold
        out["watcher_watches"] = len(ws.watches)

        # collector ticks: every bulk percolates ALL document watches in
        # one dense matrix program (the dogfood ride)
        e0 = ws.stats["evaluations_total"]
        t0 = time.perf_counter()
        for _ in range(3):
            for _ in range(4):
                node.sampler.sample()
                time.sleep(0.002)
            node.monitoring.collect_once()
            if _over_budget(margin=90.0):
                break
        # scheduler rounds over the agg watches (now_ms advances past
        # every 1s trigger so each round evaluates the full agg set)
        base_ms = int(time.time() * 1000)
        for r in range(rounds):
            ws.run_due(now_ms=base_ms + (r + 1) * 2000)
            if _over_budget(margin=90.0):
                break
        eval_s = time.perf_counter() - t0
        evals = ws.stats["evaluations_total"] - e0
        out["watcher_evals_per_sec"] = evals / max(eval_s, 1e-9)
        out["watcher_percolate_rides"] = ws.stats["percolate_rides_total"]
        out["watcher_fires"] = ws.stats["fires_total"]

        # per-fire latency: one always-firing watch, throttle 0 — each
        # execute runs search + condition + alert bulk + registry persist
        ws.put_watch("fire-probe", {
            "input": {"search": {"request": {
                "index": ".monitoring-es-*",
                "body": {"size": 0, "query": {"match_all": {}}}}}},
            "condition": {"always": {}}, "throttle_period": "0s"})
        lat = []
        for _ in range(fire_reps):
            t0 = time.perf_counter()
            res = ws.execute_watch("fire-probe")
            lat.append((time.perf_counter() - t0) * 1000.0)
            if not res.get("fired"):
                break
            if _over_budget(margin=60.0):
                break
        if lat:
            lat.sort()
            out["watcher_fire_p50_ms"] = lat[len(lat) // 2]

        # composite after-key pagination: full disjoint cover of a
        # keyword×histogram bucket space, pages/sec
        node.create_index("comp", settings={"number_of_shards": 1},
                          mappings={"_doc": {"properties": {
                              "tag": {"type": "string",
                                      "index": "not_analyzed"},
                              "n": {"type": "long"}}}})
        for i in range(0, comp_docs, 4000):
            node.bulk([("index", {"_index": "comp", "_id": str(j)},
                        {"tag": f"t{j % 40:02d}", "n": j % 500})
                       for j in range(i, min(i + 4000, comp_docs))])
        node.refresh("comp")

        def comp_body(after):
            b = {"size": 0, "aggs": {"pages": {"composite": {
                "size": 50,
                "sources": [{"tag": {"terms": {"field": "tag"}}},
                            {"bin": {"histogram": {"field": "n",
                                                   "interval": 100}}}]},
            }}}
            if after is not None:
                b["aggs"]["pages"]["composite"]["after"] = after
            return b

        pages = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < comp_secs:
            after = None
            while True:
                resp = node.search("comp", comp_body(after))
                comp = resp["aggregations"]["pages"]
                pages += 1
                after = comp.get("after_key")
                if after is None or not comp["buckets"]:
                    break
            if _over_budget(margin=60.0):
                break
        comp_s = time.perf_counter() - t0
        out["composite_page_qps"] = pages / max(comp_s, 1e-9)
        out["composite_pages"] = pages
        return out
    finally:
        node.close()
        shutil.rmtree(workdir, ignore_errors=True)


def run_rebalance_leg(tag: str) -> dict:
    """Multi-tenant elasticity (ISSUE 15): drain one node of a live
    3-node cluster via an `exclude._id` filter update WHILE 32 client
    threads keep querying it — the relocations stream through the
    `indices.recovery.max_bytes_per_sec` token bucket and hedged reads
    cover the moving copies. Reports the under-move p50/p99 (the SLO
    pair: p99 must hold <= 5x p50), the drain wall time, the measured
    recovery byte rate vs the configured throttle, and the decider veto
    count the drain produced."""
    import shutil
    import tempfile
    import threading
    from elasticsearch_tpu.cluster import TestCluster
    from elasticsearch_tpu.cluster.recovery import parse_bytes
    from elasticsearch_tpu.cluster.recovery import snapshot as rec_snapshot
    from elasticsearch_tpu.cluster.state import (INITIALIZING, RELOCATING,
                                                 UNASSIGNED)

    n_docs = int(os.environ.get("BENCH_REBAL_DOCS", "12000"))
    n_shards = int(os.environ.get("BENCH_REBAL_SHARDS", "4"))
    rate = os.environ.get("BENCH_REBAL_RATE", "4mb")
    conc = int(os.environ.get("BENCH_CONC_CLIENTS",
                              os.environ.get("BENCH_CONC", "32")))
    tmp = tempfile.mkdtemp(prefix=f"bench-rebal-{tag}-")
    cluster = TestCluster(3, tmp)
    try:
        client = cluster.client()
        client.create_index("rdocs", {"number_of_shards": n_shards,
                                      "number_of_replicas": 1})
        cluster.ensure_green()
        ops = []
        for i, body in enumerate(make_corpus(n_docs, seed=17)):
            ops.append(("index", {"_index": "rdocs", "_id": str(i)},
                        {"body": body}))
            if len(ops) >= 4000:
                client.bulk(ops)
                ops = []
            if _over_budget(margin=60.0):
                return {}        # indexing ate the slice: absent keys
        if ops:
            client.bulk(ops)
        client.refresh("rdocs")
        client.update_cluster_settings(
            {"indices.recovery.max_bytes_per_sec": rate})
        queries = make_queries(32, seed=19)

        def body_of(i: int) -> dict:
            return {"size": 10, "query": {
                "match": {"body": queries[i % len(queries)]}}}

        for i in range(16):        # warm the shape buckets
            client.search("rdocs", body_of(i))
        lats: list[float] = []
        errors = [0]
        lock = threading.Lock()
        stop = threading.Event()

        def qos_client(ci: int) -> None:
            qi = 0
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    client.search("rdocs", body_of(ci * 7 + qi))
                except Exception:  # noqa: BLE001 — shed/transient under move
                    with lock:
                        errors[0] += 1
                    continue
                dt = (time.perf_counter() - t0) * 1000
                with lock:
                    lats.append(dt)
                qi += 1

        threads = [threading.Thread(target=qos_client, args=(ci,))
                   for ci in range(conc)]
        for t in threads:
            t.start()
        time.sleep(0.5)            # steady-state before the move starts
        victim = sorted(cluster.nodes)[-1]
        r0 = dict(rec_snapshot())
        v0 = sum(n.deciders.veto_total() for n in cluster.nodes.values())
        with lock:
            lats.clear()           # measure latency UNDER the move only
        t_move = time.perf_counter()
        client.update_cluster_settings(
            {"cluster.routing.allocation.exclude._id": victim})
        deadline = time.monotonic() + max(min(_remaining() - 60.0, 120.0),
                                          5.0)
        moved = False
        while time.monotonic() < deadline:
            st = cluster.master_node().cluster.current()
            copies = [c for cs in st.routing.get("rdocs", []) for c in cs]
            busy = any(c["state"] in (RELOCATING, INITIALIZING)
                       or c.get("relocation") for c in copies)
            holds = any(c["node"] == victim and c["state"] != UNASSIGNED
                        for c in copies)
            if not busy and not holds:
                moved = True
                break
            time.sleep(0.05)
        move_s = time.perf_counter() - t_move
        stop.set()
        for t in threads:
            t.join()
        r1 = dict(rec_snapshot())
        lats.sort()
        rec_bytes = r1["bytes_total"] - r0["bytes_total"]
        out = {
            "rebalance_moved": moved,
            "rebalance_move_s": move_s,
            "rebalance_p50_ms": lats[len(lats) // 2] if lats else None,
            "rebalance_p99_ms": lats[min(len(lats) - 1,
                                         int(len(lats) * 0.99))]
            if lats else None,
            "rebalance_queries": len(lats),
            "rebalance_errors": errors[0],
            "rebalance_recovered_bytes": rec_bytes,
            "recovery_throttle_bytes_per_sec":
                rec_bytes / max(move_s, 1e-9),
            "recovery_throttle_limit_bytes_per_sec": parse_bytes(rate),
            "recovery_throttle_waits":
                r1["throttle_waits_total"] - r0["throttle_waits_total"],
            "decider_vetoes":
                sum(n.deciders.veto_total()
                    for n in cluster.nodes.values()) - v0,
            "hedged_moving": sum(n.hedge_stats.get("moving", 0)
                                 for n in cluster.nodes.values())}
        return {k: v for k, v in out.items() if v is not None}
    finally:
        cluster.close()
        shutil.rmtree(tmp, ignore_errors=True)


def _run_all_legs(tag: str) -> dict:
    _arm_leg_alarm(reserve=120.0)
    res = run_engine_leg(tag)
    _flight_snapshot("engine")
    if tag == "main":
        # results land in the emergency line the moment they exist, so a
        # kill during a LATER leg still reports the measured headline
        _FINAL_LINE.update({k: res[k] for k in
                            ("qps", "qps_filter", "p50_ms", "p99_ms",
                             "conc_qps", "conc_p50_ms", "conc_p99_ms",
                             "shed_429s", "hedged_wins",
                             "batches", "batched_requests",
                             "search_rejected") if k in res})
        _FINAL_LINE["value"] = res.get("qps")
    # optional legs run only while the budget allows AND degrade to
    # absent keys on failure — the headline line always prints. The
    # vector leg runs FIRST among them (ISSUE 12): the quantized-tier
    # crossover is the acceptance measurement, so a squeezed budget
    # degrades analytics keys, not the vector ones.
    legs = [("BENCH_VEC", "1", run_vector_leg),
            ("BENCH_AGG", "1", run_agg_leg),
            ("BENCH_MULTISEG", "1", run_multiseg_leg),
            # cluster host-reduce leg (ISSUE 11): skipped on the CPU
            # baseline subprocess — both lanes run the same device code,
            # so the ratio is measured once, in the main process
            ("BENCH_CLUSTER", "1" if tag == "main" else "0",
             run_cluster_leg),
            # pod-scale serving (ISSUE 19): a concurrency ratio between
            # two clusters in the same process — measured once, in the
            # main process
            ("BENCH_POD", "1" if tag == "main" else "0", run_pod_leg),
            # chaos parity oracle (ISSUE 14): correctness counts, not a
            # perf ratio — measured once, in the main process
            ("BENCH_CHAOS", "1" if tag == "main" else "0",
             run_chaos_leg),
            # rebalance-under-load SLO (ISSUE 15): wall-clock + SLO
            # ratio, not a device-perf ratio — measured once, in the
            # main process
            # reverse-search dense-vs-loop + compiled-vs-host script
            # ratios (ISSUE 18): both lanes run in the same process, so
            # the ratio is measured once, in the main process
            ("BENCH_PERCOLATE", "1" if tag == "main" else "0",
             run_percolate_leg),
            # watcher alerting tier (ISSUE 20): scheduler/ride/pagination
            # rates over a single self-monitoring node — measured once,
            # in the main process
            ("BENCH_WATCHER", "1" if tag == "main" else "0",
             run_watcher_leg),
            ("BENCH_REBAL", "1" if tag == "main" else "0",
             run_rebalance_leg),
            # 4M-doc aggs + 1M-doc vectors: opt-in —
            # the scale tier only fits a long budget
            ("BENCH_SCALE", "0", run_scale_leg)]
    for li, (flag, default, leg) in enumerate(legs):
        if os.environ.get(flag, default) == "0":
            continue
        if _over_budget(margin=90.0):
            print(f"{flag} leg skipped: {_remaining():.0f}s of "
                  f"BENCH_TIME_BUDGET left", file=sys.stderr)
            continue
        # tightened per-leg slices (BENCH_r05 rc=124 hardening): each leg
        # may consume only what's left MINUS a hold-back for every leg
        # still queued (45s each) plus the final-print headroom — a slow
        # leg gets _BudgetExceeded raised into it and is skipped-and-
        # reported, it can no longer starve the legs behind it
        later = sum(1 for f, d, _fn in legs[li + 1:]
                    if os.environ.get(f, d) != "0")
        _arm_leg_alarm(reserve=45.0 * later + 45.0)
        try:
            res.update(leg(tag))
        except _BudgetExceeded as e:
            print(f"{flag} leg over its slice, skipped: {e}",
                  file=sys.stderr)
        except Exception as e:  # noqa: BLE001 — legs are best-effort
            print(f"{flag} leg failed: {e}", file=sys.stderr)
        finally:
            _flight_snapshot(flag.removeprefix("BENCH_").lower())
    _arm_hard_alarm()
    return res


def main_engine():
    import subprocess
    _FINAL_LINE["metric"] = \
        f"http_msearch_bm25_top{K}_qps_{N_DOCS // 1000}k_docs"
    res: dict = {}
    err = None
    try:
        res = _run_all_legs("main")
    except Exception as e:  # noqa: BLE001 — a failed leg degrades the
        err = f"{type(e).__name__}: {e}"    # number, never erases the line
    ratios: dict = {}
    plat = "unknown"
    try:
        import jax
        plat = jax.devices()[0].platform
    except Exception:  # noqa: BLE001
        pass
    ratio_keys = ["qps", "qps_filter", "conc_qps", "agg_qps", "knn_qps",
                  "hybrid_qps", "scale_agg_qps", "scale_knn_qps"]
    if plat == "cpu":
        ratios = {k: 1.0 for k in ratio_keys if k in res}
    elif os.environ.get("BENCH_CPU", "1") != "0" and not _over_budget(60.0) \
            and res:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["BENCH_LEG"] = "cpu"
        # the CPU leg gets what's LEFT of the budget (minus headroom to
        # print): a timeout here degrades vs_baseline to null, it no
        # longer erases the headline line (BENCH_r05 rc=124)
        env["BENCH_TIME_BUDGET"] = str(max(30.0, _remaining() - 30.0))
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, capture_output=True, text=True,
                timeout=max(30.0, _remaining() - 15.0))
            for ln in out.stdout.splitlines():
                if ln.startswith("{"):
                    cpu = json.loads(ln)
                    for k in ratio_keys:
                        if res.get(k) and cpu.get(k):
                            ratios[k] = res[k] / cpu[k]
                    break
            if not ratios:
                print(f"cpu leg produced no result (rc={out.returncode}): "
                      f"{out.stderr[-500:]}", file=sys.stderr)
        except Exception as e:  # noqa: BLE001 — baseline leg is best-effort
            print(f"cpu leg failed: {e}", file=sys.stderr)
    rnd = lambda x: round(x, 3) if x is not None else None  # noqa: E731
    r2 = lambda x: round(x, 2) \
        if isinstance(x, (int, float)) else None  # noqa: E731
    line = {
        "metric": f"http_msearch_bm25_top{K}_qps_{N_DOCS // 1000}k_docs",
        "value": r2(res.get("qps")), "unit": "qps",
        "vs_baseline": rnd(ratios.get("qps")),
        "qps_filter": r2(res.get("qps_filter")),
        "vs_baseline_filter": rnd(ratios.get("qps_filter")),
        "conc_qps": r2(res.get("conc_qps")),
        "vs_baseline_concurrent": rnd(ratios.get("conc_qps")),
        "conc_p50_ms": r2(res.get("conc_p50_ms")),
        # tail latency as a headline (ISSUE 9): the p99 under concurrent
        # fan-in plus the QoS counters that explain it
        "conc_p99_ms": r2(res.get("conc_p99_ms")),
        "shed_429s": res.get("shed_429s"),
        "hedged_wins": res.get("hedged_wins"),
        "conc_clients": res.get("conc_clients", 0),
        "p50_ms": r2(res.get("p50_ms")),
        "p99_ms": r2(res.get("p99_ms")),
        "index_secs": r2(res.get("index_secs")),
        # ingest throughput headline (ISSUE 7): ≥20k docs/s through the
        # vectorized bulk lane is the write-path acceptance bar
        "docs_per_sec": r2(res.get("docs_per_sec")),
        "batches": res.get("batches"),
        "batched_requests": res.get("batched_requests"),
        "search_rejected": res.get("search_rejected"),
        "budget_secs_left": round(_remaining(), 1),
        "platform": plat,
        # device telemetry flight recorder (ISSUE 16): the per-leg
        # sidecar + rollups already landed in _FINAL_LINE after each leg
        "xla_compile_ms_total": _FINAL_LINE.get("xla_compile_ms_total"),
        "hbm_peak_bytes": _FINAL_LINE.get("hbm_peak_bytes"),
        "lane_decision_counts": _FINAL_LINE.get("lane_decision_counts"),
        "flight": _FINAL_LINE.get("flight")}
    if err is not None:
        line["error"] = err
    if "agg_qps" in res:
        line.update({
            "agg_qps": round(res["agg_qps"], 2),
            "vs_baseline_agg": rnd(ratios.get("agg_qps")),
            "agg_docs": AGG_DOCS,
            "agg_index_secs": round(res["agg_index_secs"], 1),
            "agg_docs_per_sec": r2(res.get("agg_docs_per_sec")),
            # request-cache leg: hit ratio + resident bytes + the
            # cached-vs-uncached p50 gap (the cache's latency win)
            "request_cache_hit_ratio": rnd(
                res.get("request_cache_hit_ratio")),
            "request_cache_mem_bytes": res.get("request_cache_mem_bytes"),
            "agg_cached_p50_ms": r2(res.get("agg_cached_p50_ms")),
            "agg_uncached_p50_ms": r2(res.get("agg_uncached_p50_ms"))})
    if "stacked_p50_ms" in res:
        # multiseg leg (ISSUE 4) — the keys were computed but never made
        # it into the emitted line before ISSUE 5
        line.update({
            "stacked_p50_ms": r2(res.get("stacked_p50_ms")),
            "per_segment_p50_ms": r2(res.get("per_segment_p50_ms")),
            "multiseg_speedup": rnd(res.get("multiseg_speedup")),
            "stacked_fetches_per_query":
                r2(res.get("stacked_fetches_per_query")),
            "per_segment_fetches_per_query":
                r2(res.get("per_segment_fetches_per_query")),
            "multiseg_segments": res.get("multiseg_segments")})
        if "mesh_p50_ms" in res:
            # mesh lane (ISSUE 6): one collective program vs the
            # thread-pool fan-out on the multi-shard config
            line.update({
                "mesh_p50_ms": r2(res.get("mesh_p50_ms")),
                "fanout_p50_ms": r2(res.get("fanout_p50_ms")),
                "mesh_speedup": rnd(res.get("mesh_speedup")),
                "mesh_fetches_per_query":
                    r2(res.get("mesh_fetches_per_query")),
                "fanout_fetches_per_query":
                    r2(res.get("fanout_fetches_per_query")),
                "mesh_shards": res.get("mesh_shards"),
                # aggs through the mesh program (ISSUE 11)
                "mesh_agg_qps": r2(res.get("mesh_agg_qps")),
                "mesh_agg_dispatches": res.get("mesh_agg_dispatches")})
    if "cluster_host_reduce_qps" in res:
        # cluster-wide collectives data plane (ISSUE 11): one device
        # program per HOST vs one transport round-trip per shard
        line.update({
            "cluster_host_reduce_qps": r2(res.get("cluster_host_reduce_qps")),
            "cluster_fanout_qps": r2(res.get("cluster_fanout_qps")),
            "cluster_host_speedup": rnd(res.get("cluster_host_speedup")),
            "cluster_shards": res.get("cluster_shards"),
            "cluster_host_reduce_dispatches":
                res.get("cluster_host_reduce_dispatches")})
    if "pod_qps" in res:
        # pod-scale serving (ISSUE 19): concurrent per-pool collectives
        # vs the shared-pool EXEC_LOCK serialization, with the DCN hop
        # count and the shared-lock contention evidence
        line.update({
            "pod_qps": r2(res.get("pod_qps")),
            "single_pool_qps": r2(res.get("single_pool_qps")),
            "pod_vs_single": rnd(res.get("pod_vs_single")),
            "dcn_hops_per_query": rnd(res.get("dcn_hops_per_query")),
            "exec_lock_waits": res.get("exec_lock_waits"),
            "pod_reduce_dispatches": res.get("pod_reduce_dispatches")})
    if "chaos_rounds" in res:
        # chaos harness (ISSUE 14): zero mismatches / zero violations is
        # the acceptance signal; the seed makes any non-zero reproducible
        line.update({
            "chaos_seed": res.get("chaos_seed"),
            "chaos_rounds": res.get("chaos_rounds"),
            "chaos_parity_checks": res.get("chaos_parity_checks"),
            "chaos_mismatches": res.get("chaos_mismatches"),
            "chaos_invariant_violations":
                res.get("chaos_invariant_violations")})
    if "percolate_qps" in res:
        # reverse search + script compiler (ISSUE 18): the dense-vs-loop
        # percolate ratio at the registered-query count, the matrix cell
        # rate, and the compiled-vs-host script_score ratio
        line.update({
            "percolate_queries": res.get("percolate_queries"),
            "percolate_qps": r2(res.get("percolate_qps")),
            "percolate_matrix_qps": r2(res.get("percolate_matrix_qps")),
            "percolate_loop_qps": rnd(res.get("percolate_loop_qps")),
            "percolate_vs_loop": rnd(res.get("percolate_vs_loop")),
            "percolate_fetches_per_batch":
                r2(res.get("percolate_fetches_per_batch")),
            "script_score_qps": r2(res.get("script_score_qps")),
            "script_host_qps": r2(res.get("script_host_qps")),
            "script_vs_decline": rnd(res.get("script_vs_decline"))})
    if "watcher_evals_per_sec" in res:
        # watcher alerting tier (ISSUE 20): evaluation throughput,
        # per-fire latency (search + condition + alert bulk + persist),
        # the collector percolate-ride count, and composite pages/sec
        line.update({
            "watcher_watches": res.get("watcher_watches"),
            "watcher_evals_per_sec": r2(res.get("watcher_evals_per_sec")),
            "watcher_fire_p50_ms": r2(res.get("watcher_fire_p50_ms")),
            "watcher_percolate_rides": res.get("watcher_percolate_rides"),
            "watcher_fires": res.get("watcher_fires"),
            "composite_page_qps": r2(res.get("composite_page_qps")),
            "composite_pages": res.get("composite_pages")})
    if "rebalance_move_s" in res:
        # rebalance-under-load (ISSUE 15): the SLO pair under a live
        # shard move + the throttle-compliance evidence
        line.update({
            "rebalance_moved": res.get("rebalance_moved"),
            "rebalance_move_s": r2(res.get("rebalance_move_s")),
            "rebalance_p50_ms": r2(res.get("rebalance_p50_ms")),
            "rebalance_p99_ms": r2(res.get("rebalance_p99_ms")),
            "rebalance_queries": res.get("rebalance_queries"),
            "rebalance_errors": res.get("rebalance_errors"),
            "rebalance_recovered_bytes": res.get(
                "rebalance_recovered_bytes"),
            "recovery_throttle_bytes_per_sec": r2(res.get(
                "recovery_throttle_bytes_per_sec")),
            "recovery_throttle_limit_bytes_per_sec": res.get(
                "recovery_throttle_limit_bytes_per_sec"),
            "recovery_throttle_waits": res.get("recovery_throttle_waits"),
            "decider_vetoes": res.get("decider_vetoes"),
            "hedged_moving": res.get("hedged_moving")})
    if "scale_peak_rss_bytes" in res:
        # BENCH_SCALE leg (ISSUE 8): the 10M-doc-tier shapes, served by
        # the blockwise lane; peak RSS + peak score-matrix residency show
        # the O(Q × block) bound holding at 4M-doc aggs / 1M-doc vectors
        line.update({
            "scale_agg_qps": r2(res.get("scale_agg_qps")),
            "vs_baseline_scale_agg": rnd(ratios.get("scale_agg_qps")),
            "scale_agg_docs": res.get("scale_agg_docs"),
            "scale_agg_index_secs": r2(res.get("scale_agg_index_secs")),
            "scale_knn_qps": r2(res.get("scale_knn_qps")),
            "vs_baseline_scale_knn": rnd(ratios.get("scale_knn_qps")),
            "scale_knn_recall_at_10": rnd(res.get("scale_knn_recall")),
            "scale_knn_int8_qps": r2(res.get("scale_knn_int8_qps")),
            "scale_knn_pq_qps": r2(res.get("scale_knn_pq_qps")),
            "scale_pq_recall_at_10": rnd(res.get("scale_pq_recall")),
            "scale_vector_stack_bytes_f32":
                res.get("scale_vector_stack_bytes_f32"),
            "scale_vector_stack_bytes_pq":
                res.get("scale_vector_stack_bytes_pq"),
            "scale_vec_docs": res.get("scale_vec_docs"),
            "scale_vec_index_secs": r2(res.get("scale_vec_index_secs")),
            "scale_peak_rss_bytes": res.get("scale_peak_rss_bytes"),
            "scale_peak_score_matrix_bytes":
                res.get("scale_peak_score_matrix_bytes")})
    if "knn_qps" in res:
        exact = res.get("knn_exact_qps")
        line.update({
            "knn_qps": round(res["knn_qps"], 2),
            "vs_baseline_knn": rnd(ratios.get("knn_qps")),
            "knn_recall_at_10": round(res["knn_recall"], 4),
            # ANN lane (ISSUE 10): probes, adoption and the in-corpus
            # IVF-vs-exact speedup (the acceptance ratio)
            "knn_nprobe": res.get("knn_nprobe"),
            "ann_dispatches": res.get("ann_dispatches"),
            "knn_exact_qps": r2(exact),
            "ivf_speedup": rnd(res["knn_qps"] / exact) if exact else None,
            "hybrid_qps": round(res["hybrid_qps"], 2),
            "vs_baseline_hybrid": rnd(ratios.get("hybrid_qps")),
            "hybrid_recall_at_10": round(res["hybrid_recall"], 4),
            "hybrid_rrf_qps": r2(res.get("hybrid_rrf_qps")),
            "hybrid_rrf_recall_at_10": rnd(res.get("hybrid_rrf_recall")),
            "vec_docs": VEC_DOCS, "vec_dims": VEC_DIMS,
            "vec_index_secs": r2(res.get("vec_index_secs")),
            "vec_docs_per_sec": r2(res.get("vec_docs_per_sec"))})
        # quantized ANN tier (ISSUE 12): int8/PQ scan QPS vs the f32 IVF
        # lane on the same corpus + the measured byte reduction of the
        # quantized vector stack (codes + codebooks vs the f32 column)
        ivf = res.get("knn_qps")
        i8 = res.get("knn_int8_qps")
        pq = res.get("knn_pq_qps")
        qbytes = [res.get("vector_stack_bytes_int8"),
                  res.get("vector_stack_bytes_pq")]
        qbytes = [b for b in qbytes if b]
        line.update({
            "knn_int8_qps": r2(i8),
            "int8_recall_at_10": rnd(res.get("int8_recall")),
            "int8_vs_ivf": rnd(i8 / ivf) if i8 and ivf else None,
            "knn_pq_qps": r2(pq),
            "pq_recall_at_10": rnd(res.get("pq_recall")),
            "pq_vs_ivf": rnd(pq / ivf) if pq and ivf else None,
            "knn_pq_m": VEC_PQ_M, "knn_rescore_window": VEC_RESCORE,
            "vector_stack_bytes_f32": res.get("vector_stack_bytes_f32"),
            "vector_stack_bytes_int8":
                res.get("vector_stack_bytes_int8"),
            "vector_stack_bytes_pq": res.get("vector_stack_bytes_pq"),
            "vector_stack_bytes_quantized":
                min(qbytes) if qbytes else None})
    _FINAL_LINE.update(line)
    _emit(line)


# ---------------------------------------------------------------------------
# --kernel: round-1 synthetic kernel harness (kernel regression tracking)
# ---------------------------------------------------------------------------

KN_DOCS = 1 << 20
KVOCAB = 1 << 17
KAVG_DL = 64
KQ = 64
KNB = 8


def build_chained(Wt: int):
    import jax
    import jax.numpy as jnp
    from elasticsearch_tpu.ops.bm25_sparse import bm25_topk_sparse
    kern = partial(bm25_topk_sparse, Wt=Wt, k=K, n_docs=KN_DOCS)

    @jax.jit
    def chained(doc_ids, tf, dl, qs, ql, w):
        def body(carry, batch):
            s, ln, ww = batch
            top, docs, hits = kern(doc_ids, tf, dl, s, ln, ww,
                                   jnp.float32(1.2), jnp.float32(0.75),
                                   jnp.float32(KAVG_DL))
            return carry + top[:, 0].sum() + docs[:, 0].sum() + hits.sum(), None
        acc, _ = jax.lax.scan(body, jnp.float32(0.0), (qs, ql, w))
        return acc
    return chained


def run_on(device, postings, batches, Wt):
    import jax
    args = [jax.device_put(a, device) for a in postings + batches]
    chained = build_chained(Wt)
    float(chained(*args))                      # compile + first exec
    t0 = time.perf_counter()
    for _ in range(REPS):
        float(chained(*args))                  # host fetch = true sync
    dt = (time.perf_counter() - t0) / REPS
    return KNB * KQ / dt


def main_kernel():
    import jax
    from __graft_entry__ import _synthetic_segment
    doc_ids, tf, doc_len, term_starts, term_lens = _synthetic_segment(
        KN_DOCS, KVOCAB, KAVG_DL, seed=7)
    dl = doc_len[doc_ids].astype(np.float32)

    rng = np.random.default_rng(42)
    tids = rng.integers(64, 8192, size=(KNB, KQ, T))
    qs = term_starts[tids].astype(np.int32)
    ql = term_lens[tids].astype(np.int32)
    w = np.abs(rng.normal(2.0, 0.5, (KNB, KQ, T))).astype(np.float32)
    Wt = 1 << int(np.ceil(np.log2(max(8, ql.max()))))

    pad = lambda a, fill: np.concatenate(   # noqa: E731
        [a, np.full(Wt, fill, a.dtype)])
    postings = [pad(doc_ids, KN_DOCS), pad(tf, 0), pad(dl, 1)]
    batches = [qs, ql, w]

    main_dev = jax.devices()[0]
    qps = run_on(main_dev, postings, batches, Wt)
    vs = 1.0
    if main_dev.platform != "cpu":
        try:
            cpu = jax.devices("cpu")[0]
            vs = qps / run_on(cpu, postings, batches, Wt)
        except Exception as e:  # noqa: BLE001
            print(f"cpu baseline unavailable: {e}", file=sys.stderr)
    print(json.dumps({"metric": "kernel_bm25_top1000_qps_1M_docs",
                      "value": round(qps, 2), "unit": "qps",
                      "vs_baseline": round(vs, 3)}))


if __name__ == "__main__":
    if "--kernel" in sys.argv:
        main_kernel()
    elif os.environ.get("BENCH_POD_CHILD"):
        # pod-leg child (ISSUE 19): 8 virtual host devices forced via
        # XLA_FLAGS by run_pod_leg; print the leg's one-line JSON
        print(json.dumps(_pod_leg_measure("pod-child")))
    elif os.environ.get("BENCH_LEG") == "cpu":
        res = _run_all_legs("cpu")
        out = {"metric": "cpu_leg", "unit": "qps"}
        for k, v in res.items():
            if isinstance(v, (int, float)):
                out[k] = round(v, 3)
        print(json.dumps(out))
    else:
        main_engine()
