"""Headline benchmark: batched BM25 top-1000 QPS (BASELINE.json config #1/#5
workload shape: match-query scoring over a ~1M-doc corpus, k=1000) using the
sort-reduce sparse kernel (ops/bm25_sparse.py).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Timing method: NB query batches are chained inside ONE jitted lax.scan and
synchronized by fetching the result to host — device-queue semantics under
the hosted TPU tunnel make per-step block_until_ready unreliable, and the
host fetch also amortizes the ~100ms tunnel round-trip across all NB steps.

vs_baseline is measured in-process: the identical XLA program on the host CPU
backend (the stand-in for the reference's CPU scoring path until a stock-ES
side-by-side exists; BASELINE.md documents the ladder). >1.0 = faster than
CPU.
"""

from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

# make the CPU backend available alongside the accelerator for the baseline leg
_plat = os.environ.get("JAX_PLATFORMS", "")
if _plat and "cpu" not in _plat.split(","):
    os.environ["JAX_PLATFORMS"] = _plat + ",cpu"

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from __graft_entry__ import _synthetic_segment  # noqa: E402
from elasticsearch_tpu.ops.bm25_sparse import bm25_topk_sparse  # noqa: E402

N_DOCS = 1 << 20          # ~1M docs
VOCAB = 1 << 17
AVG_DL = 64
Q = 64                    # query batch per step
K = 1000                  # top-1000 (headline metric)
T = 4                     # terms per query
NB = 8                    # steps chained per timed call
REPS = 3


def build_chained(Wt: int):
    kern = partial(bm25_topk_sparse, Wt=Wt, k=K, n_docs=N_DOCS)

    @jax.jit
    def chained(doc_ids, tf, dl, qs, ql, w):
        def body(carry, batch):
            s, ln, ww = batch
            top, docs, hits = kern(doc_ids, tf, dl, s, ln, ww,
                                   jnp.float32(1.2), jnp.float32(0.75),
                                   jnp.float32(AVG_DL))
            # fold outputs into a tiny carry so nothing is dead-code-eliminated
            return carry + top[:, 0].sum() + docs[:, 0].sum() + hits.sum(), None
        acc, _ = jax.lax.scan(body, jnp.float32(0.0), (qs, ql, w))
        return acc
    return chained


def run_on(device, postings, batches, Wt):
    args = [jax.device_put(a, device) for a in postings + batches]
    chained = build_chained(Wt)
    float(chained(*args))                      # compile + first exec
    t0 = time.perf_counter()
    for _ in range(REPS):
        float(chained(*args))                  # host fetch = true sync
    dt = (time.perf_counter() - t0) / REPS
    return NB * Q / dt


def main():
    doc_ids, tf, doc_len, term_starts, term_lens = _synthetic_segment(
        N_DOCS, VOCAB, AVG_DL, seed=7)
    dl = doc_len[doc_ids].astype(np.float32)   # per-posting doc length

    rng = np.random.default_rng(42)
    tids = rng.integers(64, 8192, size=(NB, Q, T))
    qs = term_starts[tids].astype(np.int32)
    ql = term_lens[tids].astype(np.int32)
    w = np.abs(rng.normal(2.0, 0.5, (NB, Q, T))).astype(np.float32)
    Wt = 1 << int(np.ceil(np.log2(max(8, ql.max()))))

    pad = lambda a, fill: np.concatenate(   # noqa: E731
        [a, np.full(Wt, fill, a.dtype)])
    postings = [pad(doc_ids, N_DOCS), pad(tf, 0), pad(dl, 1)]
    batches = [qs, ql, w]

    main_dev = jax.devices()[0]
    qps = run_on(main_dev, postings, batches, Wt)

    vs = 1.0
    if main_dev.platform != "cpu":
        try:
            cpu = jax.devices("cpu")[0]
            cpu_qps = run_on(cpu, postings, batches, Wt)
            vs = qps / cpu_qps
        except Exception as e:  # noqa: BLE001 — baseline leg is best-effort
            print(f"cpu baseline unavailable: {e}", file=sys.stderr)

    print(json.dumps({"metric": "bm25_top1000_qps_1M_docs",
                      "value": round(qps, 2), "unit": "qps",
                      "vs_baseline": round(vs, 3)}))


if __name__ == "__main__":
    main()
