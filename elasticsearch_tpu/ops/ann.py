"""IVF-clustered approximate nearest neighbor — two-stage device kernels.

Exact kNN (ops/knn.py) pays a full [Q, N] similarity matmul per query —
fine at 100k docs, fatal at the BASELINE 1M+ vector tier. This module is
the canonical inverted-file (IVF) shape from the FAISS/ScaNN lineage,
mapped onto this engine's device idioms:

  train  : k-means over a deterministic sample of the segment's vectors —
           Lloyd iterations are ONE assignment matmul + one segment_sum
           per round, all on device (`train_centroids`).
  layout : cluster -> doc-id CSR built with ONE composite-key argsort on
           host (`build_ivf` in index/segment.py) — exactly the postings
           layout text fields already use, with clusters as "terms".
  query  : stage 1 routes each query to `nprobe` clusters with one
           [Q, nlist] matmul; stage 2 maps the probed clusters' CSR runs
           onto a fixed gather-slot budget W (ops/bm25.postings_slots —
           clusters ARE terms) and scans the candidates in pow2 doc
           blocks under a running on-device top-k (ops/topk.
           merge_running_topk, the blockwise-lane carry) — peak score
           memory O(Q × block), never O(Q × N). Both stages + the
           liveness mask fuse into ONE jitted program per shape bucket:
           one dispatch, one fetch, zero mid-query host syncs.

bf16 matmuls with f32 accumulation by default (`index.knn.precision`,
~1e-3 relative error); `nprobe >= nlist` routes to the exact kernel
upstream (search/shard_searcher.py) so full-coverage requests are
bitwise-identical to `knn_topk`.

The hybrid fusion kernels at the bottom (`rrf_fuse`, `weighted_fuse`)
combine a BM25 top-k list and a vector top-k list on device — the
first-class `"rank"` search section (search/controller.fuse_hybrid).
"""

from __future__ import annotations

import functools
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from . import bm25 as bm25_ops
from .topk import merge_running_topk

# candidate-gather budget per scan step: Q * block * dims elements
# (bf16/f32). 16M elements ≈ 32-64 MB resident — the O(Q × block) analog
# of search/blockwise.py's score-memory bound, applied to gathered vectors
_GATHER_BUDGET_ELEMS = 1 << 24
_ASSIGN_BLOCK = 1 << 16          # full-corpus assignment scan block (docs)
DEFAULT_ITERS = 4
# training-sample ceiling for BOTH the Lloyd loop and the PQ codebooks:
# k-means cost is O(sample × nlist × D) per iteration, so an uncapped 10M-
# vector corpus would spend the whole bench budget training (the r05
# rc=124 lesson) — 64k vectors is plenty for 256-4096 clusters
TRAIN_SAMPLE_CAP = 1 << 16
PQ_CODES = 256                   # codes per subquantizer (one u8 per code)
DEFAULT_PQ_M = 16                # subquantizers (index.knn.pq.m)


def next_pow2(n: int, floor: int = 8) -> int:
    n = max(int(n), floor)
    return 1 << (n - 1).bit_length()


def auto_nlist(n_docs: int) -> int:
    """~sqrt(N), pow2-bucketed (the FAISS guidance), clamped so clusters
    keep enough members to be worth routing to."""
    return min(next_pow2(int(math.sqrt(max(n_docs, 1))), floor=8),
               max(next_pow2(n_docs, floor=8) // 8, 8))


def auto_nprobe(nlist: int) -> int:
    """Default probe width: 1/8 of the clusters — ~12.5% of the corpus
    scanned, comfortably past recall@10 ≥ 0.95 on clustered corpora."""
    return max(1, nlist // 8)


def _cast(x, precision: str):
    return x.astype(jnp.bfloat16) if precision == "bf16" \
        else x.astype(jnp.float32)


# ---------------------------------------------------------------------------
# training: device Lloyd iterations over a sample
# ---------------------------------------------------------------------------

def _lloyd(sample: jax.Array, init: jax.Array, *,
           nlist: int, iters: int) -> jax.Array:
    """The Lloyd-iteration core (traced, not jitted): shared between
    `train_centroids` and the vmapped-over-subspaces PQ codebook
    trainer."""

    def step(cents, _):
        cn2 = jnp.sum(cents * cents, axis=1)                 # [nlist]
        scores = 2.0 * lax.dot_general(
            sample, cents, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) - cn2[None, :]
        assign = jnp.argmax(scores, axis=1)                  # [S]
        sums = jax.ops.segment_sum(sample, assign, num_segments=nlist)
        counts = jax.ops.segment_sum(jnp.ones((sample.shape[0],),
                                              jnp.float32),
                                     assign, num_segments=nlist)
        new = sums / jnp.maximum(counts[:, None], 1.0)
        cents = jnp.where(counts[:, None] > 0, new, cents)
        return cents, None

    cents, _ = lax.scan(step, init.astype(jnp.float32), None, length=iters)
    return cents


@functools.partial(jax.jit, static_argnames=("nlist", "iters"))
def train_centroids(sample: jax.Array, init: jax.Array, *,
                    nlist: int, iters: int) -> jax.Array:
    """Lloyd k-means on device: sample f32[S, D], init f32[nlist, D].
    Each iteration is one [S, nlist] assignment matmul (l2, via the
    ||x||²-free argmin identity) + one segment_sum update; empty clusters
    keep their previous centroid. Returns centroids f32[nlist, D]."""
    return _lloyd(sample, init, nlist=nlist, iters=iters)


@functools.partial(jax.jit, static_argnames=("block",))
def assign_clusters(vecs: jax.Array, cents: jax.Array, *,
                    block: int) -> jax.Array:
    """Full-corpus cluster assignment, scanned in `block`-doc chunks so the
    [N, nlist] score matrix never materializes: vecs f32[N_pad, D]
    (N_pad a multiple of block) -> i32[N_pad]."""
    n_pad, d = vecs.shape
    cn2 = jnp.sum(cents * cents, axis=1)

    def body(_, vb):
        scores = 2.0 * lax.dot_general(
            vb, cents, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) - cn2[None, :]
        return _, jnp.argmax(scores, axis=1).astype(jnp.int32)

    _, out = lax.scan(body, None, vecs.reshape(n_pad // block, block, d))
    return out.reshape(n_pad)


def assign_block_size(n_pad: int) -> int:
    return min(next_pow2(n_pad, floor=8), _ASSIGN_BLOCK)


# ---------------------------------------------------------------------------
# quantized storage tier (ISSUE 12): int8 scalar + IVF-PQ residual codes
# ---------------------------------------------------------------------------

@jax.jit
def train_int8_scales(vecs: jax.Array) -> jax.Array:
    """Per-dimension symmetric affine scales: s_d = max|x_d| / 127 over
    the whole column (padding rows are zero — they never win the max).
    One reduction over an already-resident tensor, no extra residency."""
    return jnp.maximum(jnp.max(jnp.abs(vecs), axis=0), 1e-12) / 127.0


@functools.partial(jax.jit, static_argnames=("block",))
def quantize_int8(vecs: jax.Array, scales: jax.Array, *,
                  block: int) -> jax.Array:
    """f32[N_pad, D] -> i8[N_pad, D], scanned in `block`-doc chunks so the
    f32 intermediate never exceeds O(block × D)."""
    n_pad, d = vecs.shape

    def body(_, vb):
        q = jnp.clip(jnp.round(vb / scales[None, :]), -127.0, 127.0)
        return _, q.astype(jnp.int8)

    _, out = lax.scan(body, None, vecs.reshape(n_pad // block, block, d))
    return out.reshape(n_pad, d)


@functools.partial(jax.jit, static_argnames=("iters",))
def train_pq_codebooks(samples: jax.Array, inits: jax.Array, *,
                       iters: int) -> jax.Array:
    """PQ codebooks: the Lloyd core vmapped over the m subspaces.
    samples f32[m, S, dsub] are residuals against each sample's ROUTED
    centroid (the FAISS IVFPQ shape: codebooks are shared across
    clusters, trained on residuals); inits f32[m, 256, dsub].
    Returns f32[m, 256, dsub]."""
    return jax.vmap(
        lambda s, i: _lloyd(s, i, nlist=PQ_CODES, iters=iters))(samples,
                                                                inits)


@functools.partial(jax.jit, static_argnames=("block",))
def encode_pq(vecs: jax.Array, assign: jax.Array, centroids: jax.Array,
              codebooks: jax.Array, *, block: int) -> jax.Array:
    """Encode the whole column: residual against the assigned centroid,
    per-subspace argmin against the codebook (the ||c||²-free identity),
    scanned in blocks. vecs f32[N_pad, D], assign i32[N_pad] (clamped to
    a real cluster), codebooks f32[m, 256, dsub] -> u8[N_pad, m]."""
    n_pad, d = vecs.shape
    m = codebooks.shape[0]
    dsub = d // m
    cn2 = jnp.sum(codebooks * codebooks, axis=2)             # [m, 256]

    def body(_, x):
        vb, ab = x
        r = vb - centroids[ab]                               # [B, D]
        rsub = r.reshape(vb.shape[0], m, dsub)
        sc = 2.0 * jnp.einsum("bmd,mjd->bmj", rsub, codebooks,
                              preferred_element_type=jnp.float32) \
            - cn2[None, :, :]
        return _, jnp.argmax(sc, axis=2).astype(jnp.uint8)

    _, out = lax.scan(body, None,
                      (vecs.reshape(n_pad // block, block, d),
                       assign.reshape(n_pad // block, block)))
    return out.reshape(n_pad, m)


def quant_scan_block_size(Q: int, dims: int, mode: str, m: int,
                          W: int) -> int:
    """Scan block for the quantized lanes: the PQ scan gathers m code
    bytes per candidate instead of D vector elements, so its block can
    be D/m larger under the same gather budget (fewer scan steps). The
    int8 scan keeps the f32 sizing — its gathered element count matches
    the f32 lane's."""
    if mode == "pq":
        return scan_block_size(Q, max(m, 1), W)
    return scan_block_size(Q, dims, W)


def rescore_width(k: int, setting: int, W: int) -> int:
    """Full-precision rescore window (static program shape): the index's
    `index.knn.rescore_window` when set, else 4×k (the quantize-the-scan-
    never-the-final-ranking default), clamped into [k, W]. rw == k means
    the rescore reorders but cannot change the retrieved SET — the
    measurable no-rescore baseline."""
    rw = int(setting) if int(setting) > 0 else 4 * k
    return max(min(max(rw, k), W), 1)


def quant_nbytes(n_pad: int, dims: int, mode: str,
                 m: int) -> tuple[int, int]:
    """(codes_bytes, codebook_bytes) the quantized tier is accounted at —
    the true 1/4 (int8) or ~1/(4·D/m) (PQ) of the f32 column."""
    if mode == "int8":
        return n_pad * dims, dims * 4
    return n_pad * m, PQ_CODES * dims * 4


# ---------------------------------------------------------------------------
# query: route + gathered blockwise scan, one program
# ---------------------------------------------------------------------------

def scan_block_size(Q: int, dims: int, W: int) -> int:
    """Static scan block: the largest pow2 candidate window whose gathered
    [Q, block, D] tensor stays inside the gather budget."""
    per_slot = max(Q * dims, 1)
    blk = _GATHER_BUDGET_ELEMS // per_slot
    blk = 1 << max(int(blk).bit_length() - 1, 7)     # floor pow2, >= 128
    return min(blk, W)


@functools.partial(jax.jit, static_argnames=(
    "k", "metric", "precision", "nprobe", "W", "block", "per_query_live"))
def ivf_search(vecs: jax.Array, centroids: jax.Array, starts: jax.Array,
               sizes: jax.Array, slot_docs: jax.Array, norms: jax.Array,
               live, qv: jax.Array, *, k: int, metric: str,
               precision: str, nprobe: int, W: int, block: int,
               per_query_live: bool):
    """Two-stage IVF query, one program:

    stage 1 — [Q, nlist] centroid similarity -> top-`nprobe` clusters per
    query, kept in routing order (best first — deterministic, and any
    W-truncated tail is the least-promising clusters).
    stage 2 — probed clusters' CSR runs map onto W gather slots
    (bm25.postings_slots: clusters are terms), then a lax.scan over
    pow2 candidate blocks gathers [Q, block, D] vectors, scores them
    (bf16/f32 matmul, f32 accum), masks dead/filtered/padding slots and
    merges a running top-k.

    vecs f32[N_pad, D]; centroids f32[nlist, D]; starts/sizes i32[nlist];
    slot_docs i32[N_pad] (docs sorted by (cluster, doc)); norms f32[N_pad]
    (L2 norms, cosine); live bool[N_pad] or bool[Q, N_pad] (when
    per_query_live — filter masks). Returns (top f32[Q,k], idx i32[Q,k]).
    """
    n_pad = vecs.shape[0]
    Q = qv.shape[0]
    qc = _cast(qv, precision)
    cc = _cast(centroids, precision)
    route = lax.dot_general(qc, cc, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # [Q, nlist]
    if metric == "cosine":
        cn = jnp.linalg.norm(centroids, axis=1)
        qn = jnp.linalg.norm(qv, axis=1, keepdims=True)
        route = route / jnp.maximum(qn * cn[None, :], 1e-12)
    elif metric == "l2":
        cn2 = jnp.sum(centroids * centroids, axis=1)
        route = 2.0 * route - cn2[None, :]
    # probes stay in ROUTING order (best cluster first): the gather-slot
    # budget W may be tighter than the worst-case probed total (see
    # slot_budget), and postings_slots enumerates clusters in the order
    # given — so any truncated tail is the LEAST-promising clusters
    _, probe = lax.top_k(route, nprobe)                          # [Q, nprobe]

    t_starts = starts[probe]                                     # [Q, nprobe]
    t_lens = sizes[probe]
    idx, _t, valid = bm25_ops.postings_slots(t_starts, t_lens, W)
    idx = jnp.clip(idx, 0, n_pad - 1)
    docs = slot_docs[idx]                                        # [Q, W] i32
    docs = jnp.where(valid, docs, n_pad - 1)

    qn_cos = jnp.linalg.norm(qv, axis=1, keepdims=True)          # [Q, 1]
    qn2 = jnp.sum(qv * qv, axis=1, keepdims=True)

    nb = W // block
    docs_s = docs.reshape(Q, nb, block).transpose(1, 0, 2)       # [nb, Q, B]
    valid_s = valid.reshape(Q, nb, block).transpose(1, 0, 2)

    def body(carry, x):
        top_s, top_i = carry
        d_blk, v_blk = x                                         # [Q, B]
        cand = _cast(vecs[d_blk], precision)                     # [Q, B, D]
        sims = jnp.einsum("qd,qbd->qb", qc, cand,
                          preferred_element_type=jnp.float32)
        if metric == "cosine":
            cn = norms[d_blk]
            sims = sims / jnp.maximum(qn_cos * cn, 1e-12)
        elif metric == "l2":
            xn2 = jnp.square(norms[d_blk])
            sims = -(qn2 + xn2 - 2.0 * sims)
        if per_query_live:
            ok = v_blk & jnp.take_along_axis(live, d_blk, axis=1)
        else:
            ok = v_blk & live[d_blk]
        sims = jnp.where(ok, sims, -jnp.inf)
        top_s, top_i = merge_running_topk(top_s, top_i, sims, d_blk, k=k)
        return (top_s, top_i), None

    carry = (jnp.full((Q, k), -jnp.inf, jnp.float32),
             jnp.full((Q, k), -1, jnp.int32))
    (top_s, top_i), _ = lax.scan(body, carry, (docs_s, valid_s))
    top_i = jnp.where(jnp.isfinite(top_s), top_i, -1)
    return top_s, top_i


# ---------------------------------------------------------------------------
# quantized query kernels: int8 GEMM / PQ ADC scan + full-precision rescore
# ---------------------------------------------------------------------------

def quantize_query_int8(qv: jax.Array, scales: jax.Array):
    """Fold the storage tier's per-dimension scales into the query, then
    quantize with ONE per-query scalar:

        dot(q, x) ≈ Σ_d q_d · (s_d · c_d) = Σ_d (q_d s_d) c_d
                  ≈ sq · Σ_d q8_d c_d          (pure int8×int8, i32 accum)

    The per-dim scales live entirely on the query side, so the doc-side
    GEMM stays a plain integer contraction. Returns (q8 i8[Q, D],
    sq f32[Q, 1])."""
    qf = qv * scales[None, :]
    sq = jnp.maximum(jnp.max(jnp.abs(qf), axis=1, keepdims=True),
                     1e-12) / 127.0
    q8 = jnp.clip(jnp.round(qf / sq), -127.0, 127.0).astype(jnp.int8)
    return q8, sq


def rescore_topk(vecs, norms, qv, top_s, top_i, *, k: int, metric: str,
                 precision: str):
    """Full-precision rescore of the scan's survivors (traced, not
    jitted — the tail of the quantized kernels and the mesh program):
    gather the top-rw candidates' f32 vectors, score them EXACTLY like
    the f32 IVF scan body (`index.knn.precision` matmuls, exact stored
    norms), and keep the top k. The quantized approximation ranks the
    scan; it never ranks the response."""
    safe = jnp.maximum(top_i, 0)
    cand = _cast(vecs[safe], precision)                      # [Q, rw, D]
    qc = _cast(qv, precision)
    sims = jnp.einsum("qd,qrd->qr", qc, cand,
                      preferred_element_type=jnp.float32)
    if metric == "cosine":
        qn = jnp.linalg.norm(qv, axis=1, keepdims=True)
        sims = sims / jnp.maximum(qn * norms[safe], 1e-12)
    elif metric == "l2":
        qn2 = jnp.sum(qv * qv, axis=1, keepdims=True)
        sims = -(qn2 + jnp.square(norms[safe]) - 2.0 * sims)
    sims = jnp.where(top_i >= 0, sims, -jnp.inf)
    top, pos = lax.top_k(sims, min(k, sims.shape[1]))
    idx = jnp.take_along_axis(top_i, pos, axis=1)
    return top, jnp.where(jnp.isfinite(top), idx, -1)


@functools.partial(jax.jit, static_argnames=(
    "k", "metric", "precision", "nprobe", "W", "block", "rw",
    "per_query_live"))
def ivf_search_int8(vecs: jax.Array, codes: jax.Array, scales: jax.Array,
                    centroids: jax.Array, starts: jax.Array,
                    sizes: jax.Array, slot_docs: jax.Array,
                    norms: jax.Array, live, qv: jax.Array, *, k: int,
                    metric: str, precision: str, nprobe: int, W: int,
                    block: int, rw: int, per_query_live: bool):
    """ivf_search with the cluster scan on int8: stage 1 routes at full
    precision (centroids are tiny), stage 2 gathers i8 codes — 1/4 the
    HBM traffic of the f32 scan — and scores them with an int8×int8 GEMM
    accumulating in i32 (exact integer arithmetic; the only rounding is
    the quantization itself), then the top `rw` survivors rescore at
    full precision (rescore_topk). codes i8[N_pad, D], scales f32[D]."""
    n_pad = vecs.shape[0]
    Q = qv.shape[0]
    qc = _cast(qv, precision)
    cc = _cast(centroids, precision)
    route = lax.dot_general(qc, cc, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if metric == "cosine":
        cn = jnp.linalg.norm(centroids, axis=1)
        qn = jnp.linalg.norm(qv, axis=1, keepdims=True)
        route = route / jnp.maximum(qn * cn[None, :], 1e-12)
    elif metric == "l2":
        cn2 = jnp.sum(centroids * centroids, axis=1)
        route = 2.0 * route - cn2[None, :]
    _, probe = lax.top_k(route, nprobe)                      # [Q, nprobe]

    t_starts = starts[probe]
    t_lens = sizes[probe]
    idx, _t, valid = bm25_ops.postings_slots(t_starts, t_lens, W)
    idx = jnp.clip(idx, 0, n_pad - 1)
    docs = slot_docs[idx]
    docs = jnp.where(valid, docs, n_pad - 1)

    q8, sq = quantize_query_int8(qv, scales)
    qn_cos = jnp.linalg.norm(qv, axis=1, keepdims=True)
    qn2 = jnp.sum(qv * qv, axis=1, keepdims=True)

    nb = W // block
    docs_s = docs.reshape(Q, nb, block).transpose(1, 0, 2)
    valid_s = valid.reshape(Q, nb, block).transpose(1, 0, 2)

    def body(carry, x):
        top_s, top_i = carry
        d_blk, v_blk = x                                     # [Q, B]
        cand = codes[d_blk]                                  # [Q, B, D] i8
        idot = jnp.einsum("qd,qbd->qb", q8, cand,
                          preferred_element_type=jnp.int32)
        sims = sq * idot.astype(jnp.float32)
        if metric == "cosine":
            sims = sims / jnp.maximum(qn_cos * norms[d_blk], 1e-12)
        elif metric == "l2":
            sims = -(qn2 + jnp.square(norms[d_blk]) - 2.0 * sims)
        if per_query_live:
            ok = v_blk & jnp.take_along_axis(live, d_blk, axis=1)
        else:
            ok = v_blk & live[d_blk]
        sims = jnp.where(ok, sims, -jnp.inf)
        top_s, top_i = merge_running_topk(top_s, top_i, sims, d_blk, k=rw)
        return (top_s, top_i), None

    carry = (jnp.full((Q, rw), -jnp.inf, jnp.float32),
             jnp.full((Q, rw), -1, jnp.int32))
    (top_s, top_i), _ = lax.scan(body, carry, (docs_s, valid_s))
    top_i = jnp.where(jnp.isfinite(top_s), top_i, -1)
    return rescore_topk(vecs, norms, qv, top_s, top_i, k=k,
                        metric=metric, precision=precision)


@functools.partial(jax.jit, static_argnames=(
    "k", "metric", "precision", "nprobe", "W", "block", "rw",
    "per_query_live"))
def ivf_search_pq(vecs: jax.Array, codes: jax.Array, codebooks: jax.Array,
                  centroids: jax.Array, starts: jax.Array,
                  sizes: jax.Array, slot_docs: jax.Array,
                  norms: jax.Array, live, qv: jax.Array, *, k: int,
                  metric: str, precision: str, nprobe: int, W: int,
                  block: int, rw: int, per_query_live: bool):
    """IVF-PQ asymmetric-distance scan, one program:

        dot(q, x) = dot(q, c_routed) + dot(q, residual)
                  ≈ route_dot[q, cluster] + Σ_m LUT[q, m, code_m(x)]

    The LUT ([Q, m, 256] = one einsum of the query's subvectors against
    the shared codebooks) is cluster-INDEPENDENT because codebooks train
    on residuals with the centroid dot folded out — so the per-candidate
    work is m u8 gathers + adds instead of D MACs. cosine/l2 derive from
    the same dot approximation plus the EXACT stored norms (the seam all
    three lanes share). codes u8[N_pad, m], codebooks f32[m, 256, dsub].
    Top `rw` survivors rescore at full precision."""
    n_pad = vecs.shape[0]
    Q = qv.shape[0]
    d = qv.shape[1]
    m = codebooks.shape[0]
    dsub = d // m
    qc = _cast(qv, precision)
    cc = _cast(centroids, precision)
    r_dot = lax.dot_general(qc, cc, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if metric == "cosine":
        cn = jnp.linalg.norm(centroids, axis=1)
        qn = jnp.linalg.norm(qv, axis=1, keepdims=True)
        route = r_dot / jnp.maximum(qn * cn[None, :], 1e-12)
    elif metric == "l2":
        cn2 = jnp.sum(centroids * centroids, axis=1)
        route = 2.0 * r_dot - cn2[None, :]
    else:
        route = r_dot
    _, probe = lax.top_k(route, nprobe)                      # [Q, nprobe]

    t_starts = starts[probe]
    t_lens = sizes[probe]
    idx, t_slot, valid = bm25_ops.postings_slots(t_starts, t_lens, W)
    idx = jnp.clip(idx, 0, n_pad - 1)
    docs = slot_docs[idx]
    docs = jnp.where(valid, docs, n_pad - 1)
    # which probed cluster each slot belongs to -> that cluster's RAW
    # centroid dot (the ADC base term; invalid slots are masked below)
    cl = jnp.take_along_axis(probe, jnp.clip(t_slot, 0, nprobe - 1),
                             axis=1)                         # [Q, W]
    c_dot = jnp.take_along_axis(r_dot, cl, axis=1)           # [Q, W]

    qsub = _cast(qv.reshape(Q, m, dsub), precision)
    lut = jnp.einsum("qmd,mjd->qmj", qsub, _cast(codebooks, precision),
                     preferred_element_type=jnp.float32)     # [Q, m, 256]

    qn_cos = jnp.linalg.norm(qv, axis=1, keepdims=True)
    qn2 = jnp.sum(qv * qv, axis=1, keepdims=True)

    nb = W // block
    docs_s = docs.reshape(Q, nb, block).transpose(1, 0, 2)
    valid_s = valid.reshape(Q, nb, block).transpose(1, 0, 2)
    cdot_s = c_dot.reshape(Q, nb, block).transpose(1, 0, 2)

    def body(carry, x):
        top_s, top_i = carry
        d_blk, v_blk, cd_blk = x                             # [Q, B]
        cb = codes[d_blk]                                    # [Q, B, m] u8
        cmb = jnp.moveaxis(cb, 2, 1).astype(jnp.int32)       # [Q, m, B]
        vals = jnp.take_along_axis(lut, cmb, axis=2)         # [Q, m, B]
        sims = cd_blk + jnp.sum(vals, axis=1)
        if metric == "cosine":
            sims = sims / jnp.maximum(qn_cos * norms[d_blk], 1e-12)
        elif metric == "l2":
            sims = -(qn2 + jnp.square(norms[d_blk]) - 2.0 * sims)
        if per_query_live:
            ok = v_blk & jnp.take_along_axis(live, d_blk, axis=1)
        else:
            ok = v_blk & live[d_blk]
        sims = jnp.where(ok, sims, -jnp.inf)
        top_s, top_i = merge_running_topk(top_s, top_i, sims, d_blk, k=rw)
        return (top_s, top_i), None

    carry = (jnp.full((Q, rw), -jnp.inf, jnp.float32),
             jnp.full((Q, rw), -1, jnp.int32))
    (top_s, top_i), _ = lax.scan(body, carry, (docs_s, valid_s, cdot_s))
    top_i = jnp.where(jnp.isfinite(top_s), top_i, -1)
    return rescore_topk(vecs, norms, qv, top_s, top_i, k=k,
                        metric=metric, precision=precision)


# ---------------------------------------------------------------------------
# hybrid fusion: BM25 list x vector list, on device
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("k",))
def rrf_fuse(keys_a: jax.Array, keys_b: jax.Array, weights: jax.Array,
             rank_constant: jax.Array, *, k: int):
    """Reciprocal-rank fusion of two per-query top-k id lists
    (ref. Cormack et al.; the `"rank": {"rrf": ...}` search section):
    score(d) = Σ_list w_list / (rank_constant + rank_list(d)).

    keys_*: i64[Q, Ka]/[Q, Kb], rank = slot position + 1, -1 = empty.
    weights f32[2] (text, vector). A doc in both lists scores once with
    both contributions (matched via the pairwise-equality plane); the
    duplicate b-side slot is suppressed. Returns
    (scores f32[Q, k], keys i64[Q, k]) sorted by fused score desc."""
    Ka, Kb = keys_a.shape[1], keys_b.shape[1]
    ra = 1.0 / (rank_constant + jnp.arange(1, Ka + 1, dtype=jnp.float32))
    rb = 1.0 / (rank_constant + jnp.arange(1, Kb + 1, dtype=jnp.float32))
    va = keys_a >= 0
    vb = keys_b >= 0
    eq = (keys_a[:, :, None] == keys_b[:, None, :]) \
        & va[:, :, None] & vb[:, None, :]                   # [Q, Ka, Kb]
    sa = weights[0] * ra[None, :] \
        + weights[1] * jnp.einsum("qab,b->qa", eq.astype(jnp.float32), rb)
    sa = jnp.where(va, sa, -jnp.inf)
    dup_b = eq.any(axis=1)                                  # [Q, Kb]
    sb = jnp.where(vb & ~dup_b, weights[1] * rb[None, :], -jnp.inf)
    cand_s = jnp.concatenate([sa, sb], axis=1)
    cand_k = jnp.concatenate([keys_a, keys_b], axis=1)
    top, pos = lax.top_k(cand_s, min(k, Ka + Kb))
    return top, jnp.take_along_axis(cand_k, pos, axis=-1)


@functools.partial(jax.jit, static_argnames=("k", "normalize"))
def weighted_fuse(keys_a: jax.Array, scores_a: jax.Array,
                  keys_b: jax.Array, scores_b: jax.Array,
                  weights: jax.Array, *, k: int,
                  normalize: str = "minmax"):
    """Weighted-sum fusion: each list's scores are min-max normalized to
    [0, 1] per query (normalize="none" keeps raw scores), then
    fused(d) = w_text * n_text(d) + w_vec * n_vec(d); a doc missing from
    one list contributes 0 from that side. Same pairwise-match plane and
    duplicate suppression as rrf_fuse."""
    Ka, Kb = keys_a.shape[1], keys_b.shape[1]
    va = keys_a >= 0
    vb = keys_b >= 0

    def norm(s, v):
        if normalize == "none":
            return jnp.where(v, s, 0.0)
        s = jnp.where(v, s, jnp.nan)
        mn = jnp.nanmin(s, axis=1, keepdims=True)
        mx = jnp.nanmax(s, axis=1, keepdims=True)
        rng = jnp.maximum(mx - mn, 1e-12)
        return jnp.where(v, (jnp.nan_to_num(s) - mn) / rng, 0.0)

    na = norm(scores_a, va)
    nb = norm(scores_b, vb)
    eq = (keys_a[:, :, None] == keys_b[:, None, :]) \
        & va[:, :, None] & vb[:, None, :]
    sa = weights[0] * na + weights[1] * jnp.einsum(
        "qab,qb->qa", eq.astype(jnp.float32), nb)
    sa = jnp.where(va, sa, -jnp.inf)
    dup_b = eq.any(axis=1)
    sb = jnp.where(vb & ~dup_b, weights[1] * nb, -jnp.inf)
    cand_s = jnp.concatenate([sa, sb], axis=1)
    cand_k = jnp.concatenate([keys_a, keys_b], axis=1)
    top, pos = lax.top_k(cand_s, min(k, Ka + Kb))
    return top, jnp.take_along_axis(cand_k, pos, axis=-1)


# ---------------------------------------------------------------------------
# host-side sizing helpers
# ---------------------------------------------------------------------------

def slot_budget(sizes_desc_cum: np.ndarray, nprobe: int,
                n_docs: int, nlist: int) -> int:
    """Gather-slot budget W for a given nprobe, pow2-bucketed so
    refresh→query cycles inside a bucket reuse the compiled program.

    The worst case (the `nprobe` LARGEST clusters probed together) is
    capped at ~1.25x the AVERAGE probed total: k-means on clustered
    corpora is imbalanced enough that the worst case pays 2-4x the
    typical query's work in padding. Queries whose probed clusters
    overflow W lose the tail — and because probes arrive in routing
    order (ivf_search), the dropped docs belong to the least-promising
    probed clusters, so the measured recall cost is ~zero while the
    scan cost halves."""
    n = min(max(nprobe, 1), len(sizes_desc_cum))
    worst = int(sizes_desc_cum[n - 1])
    typical = int(1.25 * n * max(n_docs // max(nlist, 1), 1)) + 1
    return next_pow2(min(worst, typical), floor=8)


def ivf_nbytes(n_pad: int, nlist: int, dims: int) -> int:
    """Device residency estimate: centroids + CSR + norms (the cache tier's
    breaker charge)."""
    return nlist * dims * 4 + n_pad * 4 + nlist * 8 + n_pad * 4


# dispatch accounting for the serving kernels (common/device_stats);
# training kernels run once per build and are traced via pq_train spans
from ..common.device_stats import instrument as _instrument  # noqa: E402

ivf_search = _instrument("ops:ivf_search", ivf_search)
ivf_search_int8 = _instrument("ops:ivf_search_int8", ivf_search_int8)
ivf_search_pq = _instrument("ops:ivf_search_pq", ivf_search_pq)
rrf_fuse = _instrument("ops:rrf_fuse", rrf_fuse)
weighted_fuse = _instrument("ops:weighted_fuse", weighted_fuse)
