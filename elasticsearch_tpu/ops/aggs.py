"""Device aggregation kernels: masked bincount + fused numeric stats.

The collect step of the aggregation framework (search/aggs/aggregators.py)
runs these on device when the query mask is already device-resident (the
sparse/packed serving lanes produce it there): one fused XLA program per
(segment, agg) pair returning a SMALL psum-able partial — counts [V] or a
5-scalar stats vector — instead of downloading a bool[N] mask per segment
and reducing on host.

ref search/aggregations/bucket/terms/TermsAggregator (collect loop) and
metrics/stats/StatsAggregator — here the whole collect is one reduction,
not a per-doc callback.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


# bincounts with small bin counts lower to ONE-HOT MATMULS, not scatters:
# counts[b] = Σ_n mask[n]·(ids[n]==b) is a [1..Q, N] x [N, B] contraction —
# MXU work with exact f32 accumulation (0/1 inputs), where jnp.bincount's
# scatter-add serializes (13s per 64x1M batch measured on both backends).
# Large B falls back to bincount (the one-hot would not fit).
_MATMUL_BINS = 256   # one-hot is [N, B] bf16 — cap its footprint


# Above this many docs the [N, n_bins] one-hot is chunked along the doc
# axis inside a lax.scan: bucket state accumulates PER BLOCK (the blockwise
# lane's ring-attention discipline applied to agg collect), so a 4M+ doc
# terms/date_histogram materializes [block, n_bins] instead of the 2 GB
# full one-hot. Per-block counts are exact integers <= block < 2^24, so the
# i32 accumulation is exact and results match the one-shot matmul bitwise.
_ONEHOT_BLOCK = 65536


def _onehot_block(ids, v2, n_bins: int):
    oh = (ids[:, None] == jnp.arange(n_bins, dtype=ids.dtype)[None, :])
    return jax.lax.dot_general(
        v2.astype(jnp.bfloat16), oh.astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _onehot_counts(ids, valid, n_bins: int):
    """ids i32[N], valid bool[..., N] -> f32[..., n_bins] exact counts."""
    v2 = valid[None, :] if valid.ndim == 1 else valid
    N = ids.shape[0]
    if N > _ONEHOT_BLOCK and N % _ONEHOT_BLOCK == 0:
        nb = N // _ONEHOT_BLOCK
        Q = v2.shape[0]
        ids_b = ids.reshape(nb, _ONEHOT_BLOCK)
        v_b = jnp.moveaxis(v2.reshape(Q, nb, _ONEHOT_BLOCK), 1, 0)

        def body(acc, x):
            i_blk, vb = x
            return acc + _onehot_block(i_blk, vb, n_bins).astype(jnp.int32), None
        acc0 = jnp.zeros((Q, n_bins), jnp.int32)
        out, _ = jax.lax.scan(body, acc0, (ids_b, v_b))
        out = out.astype(jnp.float32)
    else:
        out = _onehot_block(ids, v2, n_bins)
    return out[0] if valid.ndim == 1 else out


@partial(jax.jit, static_argnames=("n_bins",))
def masked_bincount(ords, mask, *, n_bins: int):
    """Counts per ordinal among masked docs. ords i32[N] (-1 = missing),
    mask bool[N] -> i32[n_bins]. Missing/unmasked docs fall into a spill
    bin that is sliced off."""
    if n_bins <= _MATMUL_BINS:
        valid = mask & (ords >= 0)
        return _onehot_counts(ords, valid, n_bins).astype(jnp.int32)
    idx = jnp.where(mask & (ords >= 0), ords, n_bins)
    return jnp.bincount(idx, length=n_bins + 1)[:n_bins]


@jax.jit
def masked_stats(vals, missing, mask):
    """Fused (count, sum, sum_sq, min, max) over masked present docs.
    vals f64[N]/i64[N], missing bool[N], mask bool[N] -> f64[5]."""
    sel = mask & ~missing
    v = vals.astype(jnp.float64)
    vz = jnp.where(sel, v, 0.0)
    cnt = sel.sum().astype(jnp.float64)
    s = vz.sum()
    ss = (vz * vz).sum()
    mn = jnp.where(sel, v, jnp.inf).min()
    mx = jnp.where(sel, v, -jnp.inf).max()
    return jnp.stack([cnt, s, ss, mn, mx])


@jax.jit
def count_mask(mask):
    return mask.sum()


@partial(jax.jit, static_argnames=("n_bins",))
def masked_histogram(vals, missing, mask, base, interval, *, n_bins: int):
    """Histogram/date_histogram collect: bucket id is an affine transform
    of the numeric column (floor((v - base)/interval)); counting is a
    one-hot matmul (see _onehot_counts). vals [N] -> i32[n_bins]."""
    sel = mask & ~missing
    idx = jnp.floor((vals.astype(jnp.float64) - base)
                    / interval).astype(jnp.int32)
    ok = sel & (idx >= 0) & (idx < n_bins)
    if n_bins <= _MATMUL_BINS:
        return _onehot_counts(idx, ok, n_bins).astype(jnp.int32)
    idx = jnp.where(ok, idx, n_bins)
    return jnp.bincount(idx, length=n_bins + 1)[:n_bins]


@jax.jit
def masked_ranges(vals, missing, mask, los, his):
    """range/date_range collect: counts per [lo, hi) interval, all ranges
    in one program. los/his f64[R] (±inf for open ends) -> i64[R]."""
    sel = (mask & ~missing)[None, :]
    v = vals.astype(jnp.float64)[None, :]
    inr = sel & (v >= los[:, None]) & (v < his[:, None])
    return inr.sum(axis=1)


# -- row-batched variants: one device call serves a WHOLE msearch batch
# (mask [Q, N]); on a tunneled chip per-row launches would pay Q RTTs ------

@partial(jax.jit, static_argnames=("n_bins",))
def masked_bincount_q(ords, mask, *, n_bins: int):
    """mask bool[Q, N] -> counts i32[Q, n_bins] (one-hot matmul)."""
    if n_bins <= _MATMUL_BINS:
        valid = mask & (ords >= 0)[None, :]
        return _onehot_counts(ords, valid, n_bins).astype(jnp.int32)
    idx = jnp.where(mask & (ords >= 0)[None, :], ords[None, :], n_bins)
    return jax.vmap(lambda ix: jnp.bincount(ix, length=n_bins + 1))(
        idx)[:, :n_bins]


@partial(jax.jit, static_argnames=("n_bins",))
def masked_histogram_q(vals, missing, mask, base, interval, *, n_bins: int):
    """mask bool[Q, N] -> counts i32[Q, n_bins] (one-hot matmul)."""
    idx = jnp.floor((vals.astype(jnp.float64) - base)
                    / interval).astype(jnp.int32)
    ok = (~missing) & (idx >= 0) & (idx < n_bins)
    if n_bins <= _MATMUL_BINS:
        return _onehot_counts(idx, mask & ok[None, :],
                              n_bins).astype(jnp.int32)
    idx = jnp.where(mask & ok[None, :], idx[None, :], n_bins)
    return jax.vmap(lambda ix: jnp.bincount(ix, length=n_bins + 1))(
        idx)[:, :n_bins]


@jax.jit
def masked_stats_q(vals, missing, mask):
    """mask bool[Q, N] -> f64[Q, 5] (count, sum, sum_sq, min, max)."""
    sel = mask & ~missing[None, :]
    v = vals.astype(jnp.float64)[None, :]
    vz = jnp.where(sel, v, 0.0)
    cnt = sel.sum(axis=1).astype(jnp.float64)
    s = vz.sum(axis=1)
    ss = (vz * vz).sum(axis=1)
    mn = jnp.where(sel, v, jnp.inf).min(axis=1)
    mx = jnp.where(sel, v, -jnp.inf).max(axis=1)
    return jnp.stack([cnt, s, ss, mn, mx], axis=1)


@jax.jit
def masked_ranges_q(vals, missing, mask, los, his):
    """mask bool[Q, N] -> i64[Q, R]."""
    ok = ~missing
    v = vals.astype(jnp.float64)
    inr = ok[None, :] & (v[None, :] >= los[:, None]) \
        & (v[None, :] < his[:, None])              # [R, N]
    return (mask[:, None, :] & inr[None, :, :]).sum(axis=2)


@jax.jit
def col_minmax(vals, missing):
    """(min, max) over present values — cached per immutable segment so
    histogram bucket counts can be sized without downloading the column."""
    v = vals.astype(jnp.float64)
    mn = jnp.where(missing, jnp.inf, v).min()
    mx = jnp.where(missing, -jnp.inf, v).max()
    return jnp.stack([mn, mx])
