"""Device aggregation kernels: masked bincount + fused numeric stats.

The collect step of the aggregation framework (search/aggs/aggregators.py)
runs these on device when the query mask is already device-resident (the
sparse/packed serving lanes produce it there): one fused XLA program per
(segment, agg) pair returning a SMALL psum-able partial — counts [V] or a
5-scalar stats vector — instead of downloading a bool[N] mask per segment
and reducing on host.

ref search/aggregations/bucket/terms/TermsAggregator (collect loop) and
metrics/stats/StatsAggregator — here the whole collect is one reduction,
not a per-doc callback.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("n_bins",))
def masked_bincount(ords, mask, *, n_bins: int):
    """Counts per ordinal among masked docs. ords i32[N] (-1 = missing),
    mask bool[N] -> i32[n_bins]. Missing/unmasked docs fall into a spill
    bin that is sliced off."""
    idx = jnp.where(mask & (ords >= 0), ords, n_bins)
    return jnp.bincount(idx, length=n_bins + 1)[:n_bins]


@jax.jit
def masked_stats(vals, missing, mask):
    """Fused (count, sum, sum_sq, min, max) over masked present docs.
    vals f64[N]/i64[N], missing bool[N], mask bool[N] -> f64[5]."""
    sel = mask & ~missing
    v = vals.astype(jnp.float64)
    vz = jnp.where(sel, v, 0.0)
    cnt = sel.sum().astype(jnp.float64)
    s = vz.sum()
    ss = (vz * vz).sum()
    mn = jnp.where(sel, v, jnp.inf).min()
    mx = jnp.where(sel, v, -jnp.inf).max()
    return jnp.stack([cnt, s, ss, mn, mx])


@jax.jit
def count_mask(mask):
    return mask.sum()
