"""Sort-reduce BM25 top-k: the gather/scatter-free TPU hot kernel.

Why not the dense formulation (ops/bm25.py)? On TPU, arbitrary gathers
(doc_ids[idx]) and scatter-adds into a [Q, N] score matrix serialize into
dynamic-slice loops — measured ~25x slower than this kernel at 1M docs.
This kernel touches postings ONLY through contiguous `dynamic_slice` DMAs
and never materializes per-doc state:

  1. slice    — each (query, term) loads its postings block [Wt] with three
                contiguous slices (doc ids, tf, per-posting dl). Per-posting
                dl (denormalized at segment build) kills the doc_len[doc]
                gather entirely.
  2. score    — elementwise BM25 impact × per-term weight (idf*(k1+1)*boost),
                matching Lucene's BM25Similarity term-at-a-time contribution
                (ref /root/reference/src/main/java/org/elasticsearch/index/
                similarity/BM25SimilarityProvider.java; QueryPhase hot loop
                search/query/QueryPhase.java:144-154).
  3. sort     — lax.sort the (doc, contrib) pairs per query: same-doc
                contributions become adjacent runs. Postings are doc-sorted
                per term, so a run's length is at most T (one entry per
                query term).
  4. reduce   — windowed segment-sum: run length <= T means per-doc totals
                need only T-1 shifted compare-adds — no segment_sum scatter.
  5. top-k    — lax.top_k over the W = T*Wt slots (slots, not the N-doc
                space): the "never materialize the full score vector" move
                (SURVEY.md §5.7), with doc-id-ascending tie-break like
                Lucene's priority queue.

The per-term slot budget Wt is a static pow2 bucket >= the largest df among
the query batch's terms; compile cache stays small, padding is masked.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def required_padding(n_postings: int, max_df: int) -> int:
    """Physical postings padding so any term slice start+Wt stays in bounds
    (dynamic_slice clamps out-of-range starts, which would silently read a
    neighboring term's postings). THE single source of this invariant —
    segment build and shard packing must both use it, together with
    `slot_budget` for Wt, or slices can clamp."""
    from ..index.segment import next_pow2
    return next_pow2(n_postings + next_pow2(max_df, floor=8), floor=8)


def _sorted_runs(doc_ids, tf, dl, term_starts, term_lens, weights,
                 k1, b, avgdl, *, Wt: int, n_docs: int, with_count: bool):
    """Stages 1-4 of the pipeline, shared by both kernels: slice postings,
    score, sort, windowed segment-sum. Returns (d i32[Q,W] sorted doc ids,
    total f32[Q,W] per-run score on each run's last slot, count f32[Q,W]
    per-run distinct-term count or None, ends bool[Q,W] run-end markers)."""
    Q, T = term_starts.shape
    PAD = jnp.int32(n_docs)

    def slice_term(s, ln):
        d = jax.lax.dynamic_slice(doc_ids, (s,), (Wt,))
        t = jax.lax.dynamic_slice(tf, (s,), (Wt,))
        l = jax.lax.dynamic_slice(dl, (s,), (Wt,))
        valid = jnp.arange(Wt, dtype=jnp.int32) < ln
        return jnp.where(valid, d, PAD), t, l, valid

    d, t, l, valid = jax.vmap(jax.vmap(slice_term))(term_starts, term_lens)

    norm = k1 * (1.0 - b + b * l / avgdl)
    impact = t / (t + norm)
    contrib = jnp.where(valid, weights[:, :, None] * impact, 0.0)

    W = T * Wt
    d = d.reshape(Q, W)
    contrib = contrib.reshape(Q, W).astype(jnp.float32)
    if with_count:
        cnt = valid.astype(jnp.float32).reshape(Q, W)
        d, contrib, cnt = jax.lax.sort((d, contrib, cnt),
                                       dimension=1, num_keys=1)
    else:
        cnt = None
        d, contrib = jax.lax.sort((d, contrib), dimension=1, num_keys=1)

    # windowed segment-sum: totals land on each run's last slot (runs are at
    # most T long: postings are doc-sorted per term, one entry per query term)
    total = contrib
    count = cnt
    for j in range(1, T):
        same = d == jnp.roll(d, j, axis=1)
        same = same.at[:, :j].set(False)
        total = total + jnp.where(same, jnp.roll(contrib, j, axis=1), 0.0)
        if with_count:
            count = count + jnp.where(same, jnp.roll(cnt, j, axis=1), 0.0)

    is_real = d < PAD
    ends = jnp.concatenate([d[:, :-1] != d[:, 1:], jnp.ones((Q, 1), bool)],
                           axis=1) & is_real
    return d, total, count, ends


@functools.partial(jax.jit,
                   static_argnames=("Wt", "k", "n_docs", "with_positions"))
def bm25_topk_sparse(doc_ids: jax.Array, tf: jax.Array, dl: jax.Array,
                     term_starts: jax.Array, term_lens: jax.Array,
                     weights: jax.Array, k1, b, avgdl, *,
                     Wt: int, k: int, n_docs: int,
                     with_positions: bool = False):
    """Batched BM25 top-k over one postings block.

    doc_ids i32[P], tf f32[P], dl f32[P]: postings (P >= max start + Wt —
    use `required_padding`). term_starts/term_lens i32[Q,T]; weights f32[Q,T].
    Returns (top_scores f32[Q,k], top_docs i32[Q,k], total_hits i32[Q]).
    Empty slots: score -inf, doc == n_docs.
    """
    PAD = jnp.int32(n_docs)
    d, total, _, ends = _sorted_runs(
        doc_ids, tf, dl, term_starts, term_lens, weights, k1, b, avgdl,
        Wt=Wt, n_docs=n_docs, with_count=False)
    W = d.shape[1]
    masked = jnp.where(ends, total, -jnp.inf)
    top, pos = jax.lax.top_k(masked, min(k, W))
    top_docs = jnp.where(top > -jnp.inf,
                         jnp.take_along_axis(d, pos, axis=1), PAD)
    total_hits = jnp.sum(ends, axis=1, dtype=jnp.int32)
    return top, top_docs, total_hits


def slot_budget(term_lens) -> int:
    """Static per-term slot budget for a query batch: pow2 >= max df."""
    import numpy as np
    from ..index.segment import next_pow2
    return next_pow2(int(np.asarray(term_lens).max()), floor=8)


@functools.partial(jax.jit,
                   static_argnames=("S", "CHUNK", "R", "k", "FR", "FT", "TV"))
def bm25_serve_packed_filtered(packed_q: jax.Array, doc_ids: jax.Array,
                               tf: jax.Array, dl: jax.Array, live: jax.Array,
                               pad_doc: jax.Array, k1, b, avgdl, const,
                               fcols: jax.Array,
                               fr_col: jax.Array, fr_lo: jax.Array,
                               fr_hi: jax.Array, fr_neg: jax.Array,
                               ft_col: jax.Array, ft_targets: jax.Array,
                               ft_neg: jax.Array, *,
                               S: int, CHUNK: int, R: int, k: int,
                               FR: int, FT: int, TV: int) -> jax.Array:
    """bm25_serve_packed + per-query COLUMNAR FILTERS evaluated on device at
    the candidate positions (the filter analog of Lucene's filtered query
    inside QueryPhase — BASELINE config #2's bool{match + filter} shape).

    fcols f64[NC, Npad]: the filter columns this batch touches, packed over
        the global doc space — numeric values (NaN = missing) or keyword
        ordinals in the view's union vocabulary (-1 = missing).
    Range slots (AND-ed): fr_col i32[Q, FR] (index into fcols; -1 = slot
        unused, -2 = active but the field has no column: matches nothing),
        fr_lo/fr_hi f64[Q, FR] INCLUSIVE bounds, fr_neg i32[Q, FR].
    Term slots (AND-ed; OR within a slot's TV targets): ft_col i32[Q, FT],
        ft_targets f64[Q, FT, TV] (NaN = unused target), ft_neg i32[Q, FT].

    Filters gate `keep` exactly like liveness, so total_hits and top-k
    honor them in the same single program — still 1 upload + 1 download.
    """
    return _serve_packed_impl(
        packed_q, doc_ids, tf, dl, live, pad_doc, k1, b, avgdl, const,
        S=S, CHUNK=CHUNK, R=R, k=k,
        filters=(fcols, fr_col, fr_lo, fr_hi, fr_neg,
                 ft_col, ft_targets, ft_neg, FR, FT, TV))


@functools.partial(jax.jit, static_argnames=("S", "CHUNK", "R", "k"))
def bm25_serve_packed(packed_q: jax.Array, doc_ids: jax.Array, tf: jax.Array,
                      dl: jax.Array, live: jax.Array, pad_doc: jax.Array,
                      k1, b, avgdl, const, *,
                      S: int, CHUNK: int, R: int, k: int) -> jax.Array:
    """The tunnel-aware serving kernel: ONE device program for a whole
    request batch over ALL shards/segments of an index, ONE packed input
    upload, ONE packed output download.

    Motivation (measured on this TPU): every host<->device interaction costs
    ~20-115 ms of tunnel round-trip latency regardless of size, so the
    per-segment kernel + 3 separate result fetches of the round-2 serving
    path paid ~6+ RTTs per request. This kernel serves the entire request in
    a single dispatch. It also replaces the per-batch `Wt = max df` slot
    budget with FIXED-SIZE postings chunks: a (query, term, segment) postings
    slice of length L becomes ceil(L/CHUNK) slots of exactly CHUNK postings,
    so the compile-cache key no longer depends on the data's df distribution
    — shapes are (Q, S) buckets only, and a single huge term can't blow the
    slot budget for the whole batch.

    packed_q i32[Q, 3S+1]: per-query slot table, one H2D transfer —
        [:, 0:S)    slot postings start
        [:, S:2S)   slot length (<= CHUNK; 0 = unused slot)
        [:, 2S:3S)  slot weight, f32 bitcast to i32
                    (idf * (k1+1) * per-query boost — slots of one term all
                    carry the same weight)
        [:, 3S]     per-query minimum distinct matching terms
    doc_ids i32[P], tf f32[P], dl f32[P]: postings packed across ALL
        segments (doc ids rebased to the global packed doc space), padded
        with >= CHUNK sentinel entries so any in-range slice stays in bounds.
    live bool[Npad]: global liveness; index `pad_doc` (and any padding row)
        MUST be False.
    pad_doc i32 scalar: the PAD sentinel doc id — dynamic, so doc-space
        growth does not recompile (only pow2 bucket changes do).
    R: max distinct query terms — the run-length bound of the windowed
        segment-sum. A doc appears at most once per term (chunks of one term
        are disjoint doc ranges), so runs are <= R regardless of S.

    Returns ONE i32[Q, 2k+1]: [scores f32-bitcast | top docs | total_hits]
    — a single D2H transfer; host splits and bitcasts back.

    ref: replaces the reference's per-segment BulkScorer loop
    (search/query/QueryPhase.java:91-168) with one batched program; the
    2-phase contract (ids only, fetch later) is unchanged.
    """
    return _serve_packed_impl(packed_q, doc_ids, tf, dl, live, pad_doc,
                              k1, b, avgdl, const,
                              S=S, CHUNK=CHUNK, R=R, k=k, filters=None)


def _serve_packed_impl(packed_q, doc_ids, tf, dl, live, pad_doc,
                       k1, b, avgdl, const, *, S, CHUNK, R, k, filters):
    Q = packed_q.shape[0]
    starts = packed_q[:, :S]
    lens = packed_q[:, S:2 * S]
    weights = jax.lax.bitcast_convert_type(packed_q[:, 2 * S:3 * S],
                                           jnp.float32)
    min_match = packed_q[:, 3 * S]
    PAD = pad_doc.astype(jnp.int32)

    def slice_slot(s, ln):
        d = jax.lax.dynamic_slice(doc_ids, (s,), (CHUNK,))
        t = jax.lax.dynamic_slice(tf, (s,), (CHUNK,))
        l = jax.lax.dynamic_slice(dl, (s,), (CHUNK,))
        valid = jnp.arange(CHUNK, dtype=jnp.int32) < ln
        return jnp.where(valid, d, PAD), t, l, valid

    d, t, l, valid = jax.vmap(jax.vmap(slice_slot))(starts, lens)

    norm = k1 * (1.0 - b + b * l / avgdl)
    impact = t / (t + norm)
    contrib = jnp.where(valid, weights[:, :, None] * impact, 0.0)

    W = S * CHUNK
    d = d.reshape(Q, W)
    contrib = contrib.reshape(Q, W).astype(jnp.float32)
    cnt = valid.astype(jnp.float32).reshape(Q, W)
    d, contrib, cnt = jax.lax.sort((d, contrib, cnt), dimension=1, num_keys=1)

    total = contrib
    count = cnt
    for j in range(1, R):
        same = d == jnp.roll(d, j, axis=1)
        same = same.at[:, :j].set(False)
        total = total + jnp.where(same, jnp.roll(contrib, j, axis=1), 0.0)
        count = count + jnp.where(same, jnp.roll(cnt, j, axis=1), 0.0)

    is_real = d != PAD
    ends = jnp.concatenate([d[:, :-1] != d[:, 1:], jnp.ones((Q, 1), bool)],
                           axis=1) & is_real
    accepted = live.take(d, mode="clip")
    keep = ends & accepted & (count >= min_match[:, None].astype(jnp.float32))

    if filters is not None:
        (fcols, fr_col, fr_lo, fr_hi, fr_neg,
         ft_col, ft_targets, ft_neg, FR, FT, TV) = filters

        def eval_one(dq, fr_c, fr_l, fr_h, fr_n, ft_c, ft_t, ft_n):
            ok = jnp.ones(dq.shape, bool)
            for fi in range(FR):
                col = jnp.take(fcols, jnp.maximum(fr_c[fi], 0), axis=0)
                v = col.take(dq, mode="clip")
                m = (v >= fr_l[fi]) & (v <= fr_h[fi])
                m = jnp.where(fr_c[fi] == -2, False, m)  # absent column
                m = jnp.where(fr_n[fi] > 0, ~m, m)
                ok = ok & jnp.where(fr_c[fi] != -1, m, True)
            for fi in range(FT):
                col = jnp.take(fcols, jnp.maximum(ft_c[fi], 0), axis=0)
                v = col.take(dq, mode="clip")
                m = (v[None, :] == ft_t[fi][:, None]).any(axis=0)
                m = jnp.where(ft_c[fi] == -2, False, m)
                m = jnp.where(ft_n[fi] > 0, ~m, m)
                ok = ok & jnp.where(ft_c[fi] != -1, m, True)
            return ok

        keep = keep & jax.vmap(eval_one)(
            d, fr_col, fr_lo, fr_hi, fr_neg, ft_col, ft_targets, ft_neg)

    masked = jnp.where(keep, total + const, -jnp.inf)

    top, pos = jax.lax.top_k(masked, min(k, W))
    top_docs = jnp.where(top > -jnp.inf,
                         jnp.take_along_axis(d, pos, axis=1), PAD)
    if k > W:   # degenerate tiny-index case: pad out to the contract shape
        fill = ((Q, k - W))
        top = jnp.concatenate(
            [top, jnp.full(fill, -jnp.inf, top.dtype)], axis=1)
        top_docs = jnp.concatenate(
            [top_docs, jnp.broadcast_to(PAD, fill).astype(jnp.int32)], axis=1)
    total_hits = jnp.sum(keep, axis=1, dtype=jnp.int32)
    return jnp.concatenate(
        [jax.lax.bitcast_convert_type(top, jnp.int32), top_docs,
         total_hits[:, None]], axis=1)


@functools.partial(jax.jit, static_argnames=("Wt", "k", "n_docs"))
def bm25_topk_sparse_masked(doc_ids: jax.Array, tf: jax.Array, dl: jax.Array,
                            term_starts: jax.Array, term_lens: jax.Array,
                            weights: jax.Array, min_match: jax.Array,
                            doc_mask: jax.Array, k1, b, avgdl, *,
                            Wt: int, k: int, n_docs: int):
    """The served-search variant of `bm25_topk_sparse`: same sort-reduce
    pipeline, plus the two things a real request needs —

      * `min_match` i32[Q]: per-query minimum distinct matching terms
        (1 = operator "or", T = operator "and", otherwise
        minimum_should_match). Counted with a second windowed segment-sum
        over the validity indicator — reuses the same rolls as the score
        reduce, so "and" costs no extra sort.
      * `doc_mask` bool[M, n_docs+1] with M in {1, Q}: per-doc acceptance
        (tombstone liveness AND any filter/must_not context). Gathered only
        at the W candidate slots — a [Q, W] gather, never a [Q, N] one —
        so filters stay columnar and the scoring stays scatter-free.
        Index n_docs is the PAD sentinel row and MUST be False.

    Returns (top_scores f32[Q,k], top_docs i32[Q,k], total_hits i32[Q]).
    ref: the reference applies filters as Lucene FilteredQuery inside the
    same per-segment hot loop (search/query/QueryPhase.java:144-154).
    """
    PAD = jnp.int32(n_docs)
    d, total, count, ends = _sorted_runs(
        doc_ids, tf, dl, term_starts, term_lens, weights, k1, b, avgdl,
        Wt=Wt, n_docs=n_docs, with_count=True)
    W = d.shape[1]
    accepted = (doc_mask[0].take(d) if doc_mask.shape[0] == 1
                else jnp.take_along_axis(doc_mask, d, axis=1))
    keep = ends & accepted & (count >= min_match[:, None].astype(jnp.float32))
    masked = jnp.where(keep, total, -jnp.inf)

    top, pos = jax.lax.top_k(masked, min(k, W))
    top_docs = jnp.where(top > -jnp.inf,
                         jnp.take_along_axis(d, pos, axis=1), PAD)
    total_hits = jnp.sum(keep, axis=1, dtype=jnp.int32)
    return top, top_docs, total_hits


# dispatch accounting: rebind the serving entry points so host-level calls
# enter the device_stats registry (in-trace calls pass straight through)
from ..common.device_stats import instrument as _instrument  # noqa: E402

bm25_topk_sparse = _instrument("ops:bm25_topk_sparse", bm25_topk_sparse)
bm25_topk_sparse_masked = _instrument(
    "ops:bm25_topk_sparse_masked", bm25_topk_sparse_masked)
bm25_serve_packed = _instrument("ops:bm25_serve_packed", bm25_serve_packed)
bm25_serve_packed_filtered = _instrument(
    "ops:bm25_serve_packed_filtered", bm25_serve_packed_filtered)
