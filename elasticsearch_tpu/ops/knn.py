"""Exact kNN + vector rescoring kernels — pure MXU work.

The reference has no native vector search (ES 2.0 predates it; plugins did
script-score loops over stored fields, one doc at a time). Here vectors are
first-class [N, D] device matrices (index/segment.py VectorColumn) and every
similarity is a batched matmul, which is exactly what the TPU's systolic
array is built for:

  dot      : scores = Q · Xᵀ                       [Q,D]x[D,N]
  cosine   : normalized dot (doc norms precomputed at segment build)
  l2       : ||q||² + ||x||² - 2 q·x  (matmul + two row norms)

bf16 matmuls with f32 accumulation by default: half the HBM traffic, MXU-
native, and ~1e-3 relative error — far below ranking noise for kNN.

The rescore kernel gathers only the candidate window's vectors ([Q,W,D],
W = rescore window ≤ 1000) so the hybrid BM25→dense pipeline
(BASELINE config #5) never touches the full matrix.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _sim(qv: jax.Array, vecs: jax.Array, metric: str,
         vec_norms: jax.Array | None = None,
         precision: str = "bf16") -> jax.Array:
    """[Q,D] x [N,D] -> [Q,N] similarity (higher = closer).

    precision: "bf16" (default — half the HBM traffic, MXU-native, ~1e-3
    relative error) or "f32" (exact-parity matmuls for recall-sensitive
    users; `index.knn.precision`)."""
    dt = jnp.bfloat16 if precision == "bf16" else jnp.float32
    qb = qv.astype(dt)
    xb = vecs.astype(dt)
    dots = jax.lax.dot_general(
        qb, xb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)              # [Q,N] f32 accum
    if metric == "dot":
        return dots
    if metric == "cosine":
        qn = jnp.linalg.norm(qv, axis=1, keepdims=True)
        xn = vec_norms if vec_norms is not None \
            else jnp.linalg.norm(vecs, axis=1)
        return dots / jnp.maximum(qn * xn[None, :], 1e-12)
    if metric == "l2":
        qn2 = jnp.sum(qv * qv, axis=1, keepdims=True)
        xn2 = jnp.sum(vecs * vecs, axis=1)
        # negative squared distance so that higher = closer
        return -(qn2 + xn2[None, :] - 2.0 * dots)
    raise ValueError(f"unknown metric [{metric}]")


@functools.partial(jax.jit, static_argnames=("k", "metric", "precision"))
def knn_topk(vecs: jax.Array, qv: jax.Array, live: jax.Array, *,
             k: int, metric: str = "cosine", precision: str = "bf16"):
    """Exact kNN: [N,D] docs x [Q,D] queries -> (scores f32[Q,k], idx i32[Q,k]).
    Tombstoned docs (live False) are excluded."""
    sims = _sim(qv, vecs, metric, precision=precision)
    sims = jnp.where(live[None, :], sims, -jnp.inf)
    top, idx = jax.lax.top_k(sims, k)
    return top, idx.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("precision",))
def rescore_window(vecs: jax.Array, qv: jax.Array,
                   cand_idx: jax.Array, *,
                   precision: str = "bf16") -> jax.Array:
    """Vector similarity for a candidate window only.
    vecs [N,D], qv [Q,D], cand_idx i32[Q,W] (negative = empty slot)
    -> sims f32[Q,W] (empty slots -inf). Cosine metric."""
    dt = jnp.bfloat16 if precision == "bf16" else jnp.float32
    safe = jnp.maximum(cand_idx, 0)
    cand = vecs[safe]                                    # [Q,W,D]
    dots = jnp.einsum("qd,qwd->qw", qv.astype(dt), cand.astype(dt),
                      preferred_element_type=jnp.float32)
    qn = jnp.linalg.norm(qv, axis=1, keepdims=True)
    cn = jnp.linalg.norm(cand, axis=2)
    sims = dots / jnp.maximum(qn * cn, 1e-12)
    return jnp.where(cand_idx >= 0, sims, -jnp.inf)


def combine_scores(primary: jax.Array, secondary: jax.Array,
                   mode: str, query_weight: float = 1.0,
                   rescore_weight: float = 1.0) -> jax.Array:
    """Rescore combine modes (ref search/rescore/QueryRescorer.java
    score_mode: total/multiply/avg/max/min + query/rescore weights)."""
    p = primary * query_weight
    s = secondary * rescore_weight
    if mode in ("total", "sum"):
        return p + s
    if mode == "multiply":
        return p * s
    if mode == "avg":
        return (p + s) / 2.0
    if mode == "max":
        return jnp.maximum(p, s)
    if mode == "min":
        return jnp.minimum(p, s)
    if mode == "replace":
        return s
    raise ValueError(f"unknown score mode [{mode}]")


# dispatch accounting: the module attrs callers import ARE the instrumented
# wrappers (common/device_stats registry; in-trace calls pass through)
from ..common.device_stats import instrument as _instrument  # noqa: E402

knn_topk = _instrument("ops:knn_topk", knn_topk)
rescore_window = _instrument("ops:rescore_window", rescore_window)
