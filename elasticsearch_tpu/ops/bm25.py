"""Batched BM25 scoring over CSR postings tensors — the device hot loop.

This replaces the reference's per-segment Lucene scoring loop
(/root/reference/src/main/java/org/elasticsearch/search/query/QueryPhase.java:144-154
— IndexSearcher.search driving BulkScorer + priority-queue top-k, one doc at a
time, one query at a time) with a *batched* dense-tensor program: Q queries ×
one segment's postings are scored in a single XLA computation.

Layout (per text field per segment, built in index/segment.py):
    doc_ids : i32[P]  postings doc ids, CSR-concatenated per term, sorted per term
    tf      : f32[P]  term frequency per posting
    doc_len : f32[N]  field length per doc (Lucene norm analog)

Query batch (host-prepared per segment, see search/query phase):
    term_starts : i32[Q, T]  CSR start of each query term's postings
    term_lens   : i32[Q, T]  postings length per term (0 = absent/padding)
    weights     : f32[Q, T]  idf * boost per term (idf computed host-side from
                             df like Lucene's TermStatistics; DFS mode feeds
                             cross-shard stats here, ref search/dfs/DfsPhase.java:57)

The variable-length postings problem (SURVEY.md §7 hard part (a)) is solved by
flattening each query's postings work into a fixed budget W of gather slots:
slot p maps to (term t, offset within t) via a row-wise searchsorted over the
cumulative term lengths — all static shapes, fully vectorized, no host loop.

BM25: score(q,d) = Σ_t w(t) * tf/(tf + k1*(1-b + b*dl/avgdl))
with w(t) = idf(t) * (k1+1) * boost, matching Lucene's BM25Similarity
(ref index/similarity/BM25SimilarityProvider.java; defaults k1=1.2, b=0.75).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = jnp.float32(-jnp.inf)


def postings_slots(term_starts: jax.Array, term_lens: jax.Array, W: int):
    """Map a flat work budget [0, W) to per-(query, slot) postings indices.

    Returns (idx i32[Q,W] into the postings arrays, t_idx i32[Q,W] which query
    term each slot belongs to, valid bool[Q,W]).
    """
    Q, T = term_starts.shape
    cum = jnp.cumsum(term_lens, axis=1)                      # [Q,T]
    total = cum[:, -1:]                                      # [Q,1]
    p = jnp.arange(W, dtype=jnp.int32)
    t_idx = jax.vmap(lambda c: jnp.searchsorted(c, p, side="right"))(cum)  # [Q,W]
    t_idx = jnp.minimum(t_idx, T - 1).astype(jnp.int32)
    prev = jnp.where(t_idx > 0,
                     jnp.take_along_axis(cum, jnp.maximum(t_idx - 1, 0), axis=1), 0)
    starts = jnp.take_along_axis(term_starts, t_idx, axis=1)
    idx = starts + (p[None, :] - prev)
    valid = p[None, :] < total
    return idx, t_idx, valid


def bm25_impact(tf: jax.Array, dl: jax.Array, k1: float, b: float, avgdl) -> jax.Array:
    """Per-posting BM25 impact (everything except idf*(k1+1))."""
    norm = k1 * (1.0 - b + b * dl / avgdl)
    return tf / (tf + norm)


@functools.partial(jax.jit, static_argnames=("W", "n_pad"))
def bm25_score_batch(doc_ids: jax.Array, tf: jax.Array, doc_len: jax.Array,
                     term_starts: jax.Array, term_lens: jax.Array,
                     weights: jax.Array, k1: jax.Array, b: jax.Array,
                     avgdl: jax.Array, *, W: int, n_pad: int) -> jax.Array:
    """Score Q queries against one segment: returns scores f32[Q, n_pad].

    Unmatched docs score exactly 0; callers derive the match mask as
    scores > 0 (valid because BM25 weights and impacts are strictly positive
    for any present term).
    """
    Q = term_starts.shape[0]
    P = doc_ids.shape[0]
    idx, t_idx, valid = postings_slots(term_starts, term_lens, W)
    idx = jnp.clip(idx, 0, P - 1)
    doc = doc_ids[idx]                                       # [Q,W]
    tfv = tf[idx]
    dl = doc_len[doc]
    impact = bm25_impact(tfv, dl, k1, b, avgdl)
    w = jnp.take_along_axis(weights, t_idx, axis=1)
    contrib = jnp.where(valid, w * impact, 0.0).astype(jnp.float32)
    doc = jnp.where(valid, doc, n_pad - 1)                   # park padding on last slot
    scores = jnp.zeros((Q, n_pad), jnp.float32)
    scores = scores.at[jnp.arange(Q, dtype=jnp.int32)[:, None], doc].add(
        contrib, mode="drop", unique_indices=False)
    return scores


@functools.partial(jax.jit, static_argnames=("W", "n_pad"))
def classic_score_batch(doc_ids: jax.Array, tf: jax.Array,
                        doc_len: jax.Array, term_starts: jax.Array,
                        term_lens: jax.Array, weights: jax.Array, *,
                        W: int, n_pad: int) -> jax.Array:
    """Lucene ClassicSimilarity (TF-IDF) scoring: per-posting contribution
    is weight * sqrt(tf) / sqrt(dl), where the caller bakes idf^2 * boost
    into `weights` (ref org.apache.lucene.search.similarities.
    ClassicSimilarity: tf=sqrt, lengthNorm=1/sqrt(dl), idf squared via
    weight*idf at both query and doc ends)."""
    Q = term_starts.shape[0]
    P = doc_ids.shape[0]
    idx, t_idx, valid = postings_slots(term_starts, term_lens, W)
    idx = jnp.clip(idx, 0, P - 1)
    doc = doc_ids[idx]
    tfv = tf[idx]
    dl = doc_len[doc]
    impact = jnp.sqrt(tfv) / jnp.sqrt(jnp.maximum(dl, 1.0))
    w = jnp.take_along_axis(weights, t_idx, axis=1)
    contrib = jnp.where(valid, w * impact, 0.0).astype(jnp.float32)
    doc = jnp.where(valid, doc, n_pad - 1)
    scores = jnp.zeros((Q, n_pad), jnp.float32)
    scores = scores.at[jnp.arange(Q, dtype=jnp.int32)[:, None], doc].add(
        contrib, mode="drop", unique_indices=False)
    return scores


@functools.partial(jax.jit, static_argnames=("W", "n_pad"))
def lm_dirichlet_score_batch(doc_ids: jax.Array, tf: jax.Array,
                             doc_len: jax.Array, term_starts: jax.Array,
                             term_lens: jax.Array, boosts: jax.Array,
                             pcoll: jax.Array, mu: jax.Array, *,
                             W: int, n_pad: int) -> jax.Array:
    """LM with Dirichlet smoothing (ref org.apache.lucene.search.
    similarities.LMDirichletSimilarity): per present term,
        score_t(d) = boost * max(log(1 + tf/(mu*p(t|C))) + log(mu/(dl+mu)), 0)
    with p(t|C) the collection probability ((ttf+1)/(sumTotalTermFreq+1)),
    precomputed host-side into `pcoll` f32[Q, T] — the same per-term
    weight seam the BM25/classic kernels use. Lucene clamps each term's
    contribution at 0 so common-term penalties never outrank absence;
    callers derive the match mask from term PRESENCE (term_match_mask),
    not from scores > 0."""
    Q = term_starts.shape[0]
    P = doc_ids.shape[0]
    idx, t_idx, valid = postings_slots(term_starts, term_lens, W)
    idx = jnp.clip(idx, 0, P - 1)
    doc = doc_ids[idx]
    tfv = tf[idx]
    dl = doc_len[doc]
    pc = jnp.take_along_axis(pcoll, t_idx, axis=1)
    raw = jnp.log1p(tfv / jnp.maximum(mu * pc, 1e-12)) \
        + jnp.log(mu / (dl + mu))
    w = jnp.take_along_axis(boosts, t_idx, axis=1)
    contrib = jnp.where(valid, w * jnp.maximum(raw, 0.0),
                        0.0).astype(jnp.float32)
    doc = jnp.where(valid, doc, n_pad - 1)
    scores = jnp.zeros((Q, n_pad), jnp.float32)
    scores = scores.at[jnp.arange(Q, dtype=jnp.int32)[:, None], doc].add(
        contrib, mode="drop", unique_indices=False)
    return scores


@functools.partial(jax.jit, static_argnames=("W", "n_pad"))
def lm_jm_score_batch(doc_ids: jax.Array, tf: jax.Array,
                      doc_len: jax.Array, term_starts: jax.Array,
                      term_lens: jax.Array, boosts: jax.Array,
                      pcoll: jax.Array, lam: jax.Array, *,
                      W: int, n_pad: int) -> jax.Array:
    """LM with Jelinek-Mercer smoothing (ref LMJelinekMercerSimilarity):
        score_t(d) = boost * log(1 + ((1-λ) * tf/dl) / (λ * p(t|C)))
    — strictly positive for any present term, so scores > 0 remains a
    valid match derivation for the "or" case."""
    Q = term_starts.shape[0]
    P = doc_ids.shape[0]
    idx, t_idx, valid = postings_slots(term_starts, term_lens, W)
    idx = jnp.clip(idx, 0, P - 1)
    doc = doc_ids[idx]
    tfv = tf[idx]
    dl = doc_len[doc]
    pc = jnp.take_along_axis(pcoll, t_idx, axis=1)
    raw = jnp.log1p(((1.0 - lam) * tfv / jnp.maximum(dl, 1.0))
                    / jnp.maximum(lam * pc, 1e-12))
    w = jnp.take_along_axis(boosts, t_idx, axis=1)
    contrib = jnp.where(valid, w * raw, 0.0).astype(jnp.float32)
    doc = jnp.where(valid, doc, n_pad - 1)
    scores = jnp.zeros((Q, n_pad), jnp.float32)
    scores = scores.at[jnp.arange(Q, dtype=jnp.int32)[:, None], doc].add(
        contrib, mode="drop", unique_indices=False)
    return scores


@functools.partial(jax.jit, static_argnames=("W", "n_pad"))
def term_match_mask(doc_ids: jax.Array, term_starts: jax.Array,
                    term_lens: jax.Array, W: int, n_pad: int) -> jax.Array:
    """Boolean [Q, n_pad]: does doc contain ANY of the given terms.

    Used for pure-filter term matching on text fields (no scoring).
    """
    Q = term_starts.shape[0]
    P = doc_ids.shape[0]
    idx, _, valid = postings_slots(term_starts, term_lens, W)
    idx = jnp.clip(idx, 0, P - 1)
    doc = jnp.where(valid, doc_ids[idx], n_pad - 1)
    hits = jnp.zeros((Q, n_pad), jnp.float32)
    hits = hits.at[jnp.arange(Q, dtype=jnp.int32)[:, None], doc].add(
        jnp.where(valid, 1.0, 0.0), mode="drop")
    return hits > 0


# -- blockwise variants (search/blockwise.py): same math, scatter into a
# `block`-wide doc window starting at `base`. NOT jitted here — they trace
# inside the blockwise lax.scan body, so the scan is one program. Per-block
# CSR pointers guarantee every valid slot's doc lies inside the window, so
# the per-doc contribution sequence is exactly the full kernel's (bitwise-
# identical scores); padding slots add an exact 0.0 parked on the window's
# last slot, the full kernel's own convention. ------------------------------

def bm25_score_block(doc_ids: jax.Array, tf: jax.Array, doc_len: jax.Array,
                     term_starts: jax.Array, term_lens: jax.Array,
                     weights: jax.Array, k1, b, avgdl, base, *,
                     W: int, block: int) -> jax.Array:
    """Score one doc block: returns scores f32[Q, block] for docs
    [base, base+block). term_starts/lens are PER-BLOCK CSR slices; doc_len
    stays the full [N] column (global gather — it is already resident)."""
    Q = term_starts.shape[0]
    P = doc_ids.shape[0]
    idx, t_idx, valid = postings_slots(term_starts, term_lens, W)
    idx = jnp.clip(idx, 0, P - 1)
    doc = doc_ids[idx]                                       # [Q,W] global
    tfv = tf[idx]
    dl = doc_len[doc]
    impact = bm25_impact(tfv, dl, k1, b, avgdl)
    w = jnp.take_along_axis(weights, t_idx, axis=1)
    contrib = jnp.where(valid, w * impact, 0.0).astype(jnp.float32)
    loc = jnp.where(valid, doc - base, block - 1)            # window-local
    scores = jnp.zeros((Q, block), jnp.float32)
    scores = scores.at[jnp.arange(Q, dtype=jnp.int32)[:, None], loc].add(
        contrib, mode="drop", unique_indices=False)
    return scores


def classic_score_block(doc_ids: jax.Array, tf: jax.Array,
                        doc_len: jax.Array, term_starts: jax.Array,
                        term_lens: jax.Array, weights: jax.Array, base, *,
                        W: int, block: int) -> jax.Array:
    """classic_score_batch over one doc block (see bm25_score_block)."""
    Q = term_starts.shape[0]
    P = doc_ids.shape[0]
    idx, t_idx, valid = postings_slots(term_starts, term_lens, W)
    idx = jnp.clip(idx, 0, P - 1)
    doc = doc_ids[idx]
    tfv = tf[idx]
    dl = doc_len[doc]
    impact = jnp.sqrt(tfv) / jnp.sqrt(jnp.maximum(dl, 1.0))
    w = jnp.take_along_axis(weights, t_idx, axis=1)
    contrib = jnp.where(valid, w * impact, 0.0).astype(jnp.float32)
    loc = jnp.where(valid, doc - base, block - 1)
    scores = jnp.zeros((Q, block), jnp.float32)
    scores = scores.at[jnp.arange(Q, dtype=jnp.int32)[:, None], loc].add(
        contrib, mode="drop", unique_indices=False)
    return scores


def term_match_mask_block(doc_ids: jax.Array, term_starts: jax.Array,
                          term_lens: jax.Array, base, *,
                          W: int, block: int) -> jax.Array:
    """term_match_mask over one doc block (per-block CSR pointers)."""
    Q = term_starts.shape[0]
    P = doc_ids.shape[0]
    idx, _, valid = postings_slots(term_starts, term_lens, W)
    idx = jnp.clip(idx, 0, P - 1)
    doc = doc_ids[idx]
    loc = jnp.where(valid, doc - base, block - 1)
    hits = jnp.zeros((Q, block), jnp.float32)
    hits = hits.at[jnp.arange(Q, dtype=jnp.int32)[:, None], loc].add(
        jnp.where(valid, 1.0, 0.0), mode="drop")
    return hits > 0


def idf(doc_freq, doc_count) -> jax.Array:
    """Lucene BM25 idf: log(1 + (N - df + 0.5) / (df + 0.5))."""
    df = jnp.asarray(doc_freq, jnp.float32)
    n = jnp.asarray(doc_count, jnp.float32)
    return jnp.log(1.0 + (n - df + 0.5) / (df + 0.5))


# dispatch accounting (common/device_stats): these are the loop-lane scoring
# kernels query_dsl dispatches per segment; in-trace calls pass through
from ..common.device_stats import instrument as _instrument  # noqa: E402

bm25_score_batch = _instrument("ops:bm25_score_batch", bm25_score_batch)
classic_score_batch = _instrument(
    "ops:classic_score_batch", classic_score_batch)
lm_dirichlet_score_batch = _instrument(
    "ops:lm_dirichlet_score_batch", lm_dirichlet_score_batch)
lm_jm_score_batch = _instrument("ops:lm_jm_score_batch", lm_jm_score_batch)
