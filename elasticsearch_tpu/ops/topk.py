"""Top-k selection and merge — the tensor analog of Lucene's priority queues
and the coordinator's TopDocs.merge.

ref: /root/reference/src/main/java/org/elasticsearch/search/controller/SearchPhaseController.java:147,233
(coordinator-side merge of per-shard top-k) — here both the per-segment top-k
and the cross-segment/cross-shard merge are `lax.top_k` programs so they can
run on device and, across chips, over ICI collectives
(see parallel/distributed_search.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("k",))
def topk_scores(scores: jax.Array, mask: jax.Array, *, k: int):
    """Per-query top-k over one segment.

    scores: f32[Q, N]; mask: bool[Q, N] (live & filter & match).
    Returns (top_scores f32[Q,k], top_idx i32[Q,k]); masked-out entries come
    back with score -inf.
    """
    masked = jnp.where(mask, scores, -jnp.inf)
    top, idx = jax.lax.top_k(masked, k)
    return top, idx.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("k",))
def merge_topk(scores_a, ids_a, scores_b, ids_b, *, k: int):
    """Merge two per-query candidate sets (running top-k across segments)."""
    s = jnp.concatenate([scores_a, scores_b], axis=-1)
    i = jnp.concatenate([ids_a, ids_b], axis=-1)
    top, pos = jax.lax.top_k(s, k)
    return top, jnp.take_along_axis(i, pos, axis=-1)


@functools.partial(jax.jit, static_argnames=("k",))
def topk_concat(all_scores: jax.Array, all_ids: jax.Array, *, k: int):
    """Top-k over concatenated candidates [Q, M] -> ([Q,k], [Q,k])."""
    top, pos = jax.lax.top_k(all_scores, k)
    return top, jnp.take_along_axis(all_ids, pos, axis=-1)


def merge_running_topk(top_s: jax.Array, top_i: jax.Array,
                       blk_s: jax.Array, blk_i: jax.Array, *, k: int):
    """One step of a running top-k: merge the carried winner list with a
    new block's candidates (search/blockwise.py scan body). NOT jitted —
    traces inside the blockwise scan. Candidate order [carry, block] plus
    lax.top_k's keep-earlier-on-ties makes the running merge reproduce a
    full-axis top_k's exact tie order when blocks arrive in doc order."""
    s = jnp.concatenate([top_s, blk_s], axis=-1)
    i = jnp.concatenate([top_i, blk_i], axis=-1)
    top, pos = jax.lax.top_k(s, k)
    return top, jnp.take_along_axis(i, pos, axis=-1)


@jax.jit
def count_matches(mask: jax.Array) -> jax.Array:
    """total_hits per query: sum of the match mask (i64 to be exact)."""
    return jnp.sum(mask, axis=-1, dtype=jnp.int64)
