"""Snapshot / restore over the binary segment store.

Analog of the reference's snapshot machinery
(/root/reference/src/main/java/org/elasticsearch/snapshots/SnapshotsService.java
+ repositories/blobstore/BlobStoreRepository.java): a filesystem repository
holds content-addressed copies of the write-once segment files; snapshots
are manifests referencing blobs by checksum, so a second snapshot of a
mostly-unchanged index copies only the new segments (incremental by
construction — the same dedupe the reference gets from Lucene's immutable
segment files).
"""

from .service import (RepositoryException, SnapshotException,
                      SnapshotMissingException, SnapshotsService)

__all__ = ["SnapshotsService", "SnapshotException",
           "SnapshotMissingException", "RepositoryException"]
