"""Filesystem snapshot repository + snapshot/restore service.

Repository layout (ref BlobStoreRepository's blob-per-file model):

    <location>/index.json             snapshot registry for the repo
    <location>/blobs/<crc>_<size>     content-addressed segment files
    <location>/snap_<name>.json       one manifest per snapshot

A blob is keyed by (crc32, size) of the source file; identical segment
files across snapshots share one blob — the incremental property. Restore
copies blobs back into a fresh index directory, writes the shard commit
points and index _meta, and boots an IndexService over them (recovery is
the store's checksum-verified load; no re-analysis).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import time

from ..common.settings import Settings
from ..index.store import MANIFEST, _crc


class RepositoryException(Exception):
    pass


class SnapshotException(Exception):
    pass


class SnapshotMissingException(Exception):
    def __init__(self, repo: str, snap: str):
        super().__init__(f"[{repo}:{snap}] snapshot is missing")


class SnapshotsService:
    """Registered repositories + snapshot lifecycle for one node."""

    def __init__(self, node):
        self.node = node
        self._registry = os.path.join(node.data_path, "_repositories.json")
        self.repos: dict[str, dict] = {}
        if os.path.exists(self._registry):
            with open(self._registry) as f:
                self.repos = json.load(f)

    # -- repositories ------------------------------------------------------

    def put_repository(self, name: str, body: dict) -> dict:
        rtype = (body or {}).get("type")
        if rtype == "url":
            # read-only URL repository (ref repositories/uri/URLRepository):
            # registry metadata only — no local blob store to create
            url = (body.get("settings") or {}).get("url")
            if not url:
                raise RepositoryException("missing url setting")
            self.repos[name] = {"type": "url", "settings": {"url": url}}
            self._write_json(self._registry, self.repos)
            return {"acknowledged": True}
        if rtype != "fs":
            raise RepositoryException(
                f"repository type [{rtype}] not supported (only [fs, url])")
        location = (body.get("settings") or {}).get("location")
        if not location:
            raise RepositoryException("missing location setting")
        os.makedirs(os.path.join(location, "blobs"), exist_ok=True)
        idx = os.path.join(location, "index.json")
        if not os.path.exists(idx):
            self._write_json(idx, {"snapshots": []})
        self.repos[name] = {"type": "fs", "settings": {"location": location}}
        self._write_json(self._registry, self.repos)
        return {"acknowledged": True}

    def get_repository(self, name: str) -> dict:
        if name not in self.repos:
            raise RepositoryException(f"[{name}] missing repository")
        return {name: self.repos[name]}

    def _location(self, repo: str) -> str:
        if repo not in self.repos:
            raise RepositoryException(f"[{repo}] missing repository")
        meta = self.repos[repo]
        if meta.get("type") != "fs" or "location" not in meta["settings"]:
            raise RepositoryException(
                f"[{repo}] repository type [{meta.get('type')}] is "
                f"read-only; snapshot operations require an [fs] repository")
        return meta["settings"]["location"]

    # -- snapshot creation -------------------------------------------------

    def create_snapshot(self, repo: str, snapshot: str,
                        body: dict | None = None) -> dict:
        loc = self._location(repo)
        registry = self._read_json(os.path.join(loc, "index.json"))
        if snapshot in registry["snapshots"]:
            raise SnapshotException(
                f"[{repo}:{snapshot}] snapshot already exists")
        indices_expr = (body or {}).get("indices", "_all")
        names = self.node._resolve(indices_expr)
        if not names:
            raise SnapshotException(f"no indices match [{indices_expr}]")

        manifest = {"snapshot": snapshot, "state": "SUCCESS",
                    "start_time": time.time(), "indices": {}}
        copied = 0
        shared = 0
        for name in names:
            svc = self.node.indices[name]
            svc.flush()     # segments + commit point durable on disk
            shards = []
            for eng in svc.shards:
                with eng._lock:
                    entries = []
                    for seg in eng.segments:
                        eng.store.write_segment(seg)
                        crc, docs_crc = eng.store.persisted[seg.seg_id]
                        npz = os.path.join(eng.path,
                                           f"seg_{seg.seg_id}.npz")
                        # the store knows which stored-fields filename is
                        # actually on disk (pre-compression segments keep
                        # their plain .jsonl name)
                        docs = os.path.join(
                            eng.path, eng.store.docs_name(seg.seg_id))
                        blob, was_new = self._blobize(loc, npz, crc)
                        copied += was_new
                        shared += (not was_new)
                        docs_blob, was_new = self._blobize(loc, docs,
                                                           docs_crc)
                        copied += was_new
                        shared += (not was_new)
                        entries.append({
                            "seg_id": seg.seg_id, "blob": blob,
                            "docs_blob": docs_blob, "crc": crc,
                            "docs_crc": docs_crc,
                            "dead": [int(i) for i in range(seg.n_docs)
                                     if not seg.live_host[i]]})
                    tombstones = {k: v[0] for k, v in eng.versions.items()
                                  if v[1]}
                    shards.append({"segments": entries,
                                   "tombstones": tombstones})
            manifest["indices"][name] = {
                "settings": dict(svc.settings),
                "mappings": svc.mappings_dict(),
                "aliases": dict(sorted(svc.aliases.items())),
                "shards": shards,
            }
        manifest["end_time"] = time.time()
        self._write_json(os.path.join(loc, f"snap_{snapshot}.json"), manifest)
        registry["snapshots"].append(snapshot)
        self._write_json(os.path.join(loc, "index.json"), registry)
        return {"snapshot": {"snapshot": snapshot, "state": "SUCCESS",
                             "indices": sorted(manifest["indices"]),
                             "blobs_copied": copied,
                             "blobs_shared": shared}}

    def _blobize(self, loc: str, path: str, crc: int) -> tuple[str, bool]:
        """Copy-by-checksum: blob key = crc+size; existing blobs are shared
        (the incremental dedupe; ref BlobStoreRepository generation reuse)."""
        size = os.path.getsize(path)
        key = f"{crc:08x}_{size}"
        dest = os.path.join(loc, "blobs", key)
        if os.path.exists(dest):
            return key, False
        tmp = dest + ".tmp"
        shutil.copyfile(path, tmp)
        if _crc(tmp) != crc:
            os.remove(tmp)
            raise SnapshotException(f"checksum changed while copying {path}")
        os.replace(tmp, dest)
        return key, True

    # -- introspection / deletion ------------------------------------------

    def get_snapshots(self, repo: str, snapshot: str = "_all") -> dict:
        loc = self._location(repo)
        registry = self._read_json(os.path.join(loc, "index.json"))
        names = registry["snapshots"] if snapshot in ("_all", "*") \
            else [snapshot]
        out = []
        for n in names:
            p = os.path.join(loc, f"snap_{n}.json")
            if not os.path.exists(p):
                raise SnapshotMissingException(repo, n)
            m = self._read_json(p)
            out.append({"snapshot": n, "state": m["state"],
                        "indices": sorted(m["indices"])})
        return {"snapshots": out}

    def delete_snapshot(self, repo: str, snapshot: str) -> dict:
        loc = self._location(repo)
        registry = self._read_json(os.path.join(loc, "index.json"))
        if snapshot not in registry["snapshots"]:
            raise SnapshotMissingException(repo, snapshot)
        registry["snapshots"].remove(snapshot)
        os.remove(os.path.join(loc, f"snap_{snapshot}.json"))
        self._write_json(os.path.join(loc, "index.json"), registry)
        self._gc_blobs(loc, registry["snapshots"])
        return {"acknowledged": True}

    def _gc_blobs(self, loc: str, snapshots: list[str]) -> None:
        live: set[str] = set()
        for n in snapshots:
            m = self._read_json(os.path.join(loc, f"snap_{n}.json"))
            for imeta in m["indices"].values():
                for shard in imeta["shards"]:
                    for e in shard["segments"]:
                        live.add(e["blob"])
                        live.add(e["docs_blob"])
        bdir = os.path.join(loc, "blobs")
        for fn in os.listdir(bdir):
            if fn not in live and not fn.endswith(".tmp"):
                os.remove(os.path.join(bdir, fn))

    # -- restore -----------------------------------------------------------

    def restore_snapshot(self, repo: str, snapshot: str,
                         body: dict | None = None) -> dict:
        from ..index.index_service import IndexService
        from ..index.store import FORMAT

        body = body or {}
        loc = self._location(repo)
        p = os.path.join(loc, f"snap_{snapshot}.json")
        if not os.path.exists(p):
            raise SnapshotMissingException(repo, snapshot)
        manifest = self._read_json(p)
        wanted = body.get("indices")
        if wanted:
            names = [n for n in manifest["indices"]
                     if n in (wanted if isinstance(wanted, list)
                              else wanted.split(","))]
        else:
            names = list(manifest["indices"])
        pat = body.get("rename_pattern")
        repl = body.get("rename_replacement")

        restored = []
        for name in names:
            dest = re.sub(pat, repl, name) if pat and repl else name
            if dest in self.node.indices:
                raise SnapshotException(
                    f"cannot restore [{name}] to [{dest}]: index exists "
                    f"(close/delete it or use rename_pattern)")
            imeta = manifest["indices"][name]
            dest_path = os.path.join(self.node.data_path, dest)
            for si, shard in enumerate(imeta["shards"]):
                sp = os.path.join(dest_path, str(si))
                os.makedirs(sp, exist_ok=True)
                commit = {"format": FORMAT, "segments": [],
                          "tombstones": shard["tombstones"]}
                for e in shard["segments"]:
                    # a docs blob from a pre-compression snapshot is plain
                    # jsonl — sniff the gzip magic so the restored file
                    # gets the name load() will decode it under
                    docs_src = os.path.join(loc, "blobs", e["docs_blob"])
                    with open(docs_src, "rb") as bf:
                        is_gz = bf.read(2) == b"\x1f\x8b"
                    docs_name = f"seg_{e['seg_id']}.docs.jsonl" \
                        + (".gz" if is_gz else "")
                    for blob_key, fname, crc_key in (
                            (e["blob"], f"seg_{e['seg_id']}.npz", "crc"),
                            (e["docs_blob"], docs_name, "docs_crc")):
                        src = os.path.join(loc, "blobs", blob_key)
                        dst = os.path.join(sp, fname)
                        shutil.copyfile(src, dst)
                        if _crc(dst) != e[crc_key]:
                            raise SnapshotException(
                                f"blob {blob_key} failed verification")
                    commit["segments"].append({
                        "seg_id": e["seg_id"],
                        "file": f"seg_{e['seg_id']}.npz",
                        "docs_file": docs_name,
                        "crc": e["crc"], "docs_crc": e["docs_crc"],
                        "dead": e["dead"]})
                self._write_json(os.path.join(sp, MANIFEST), commit)
            svc = IndexService(dest, dest_path,
                               Settings(imeta["settings"]),
                               imeta["mappings"],
                               breakers=getattr(self.node, "breakers", None),
                               caches=getattr(self.node, "caches", None))
            from ..node import alias_dict
            svc.aliases = alias_dict(imeta.get("aliases", []))
            self.node.indices[dest] = svc
            self.node._persist_index_meta(svc)
            restored.append(dest)
        return {"snapshot": {"snapshot": snapshot, "indices": restored,
                             "shards": {"failed": 0}}}

    # -- io ----------------------------------------------------------------

    @staticmethod
    def _write_json(path: str, obj) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(obj, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    @staticmethod
    def _read_json(path: str):
        with open(path) as f:
            return json.load(f)
