"""Span-based request tracing: a Dapper-style per-request span tree.

PR 4 made shard execution concurrent (`_ShardJob` fan-out) and collapsed
segment loops into single stacked dispatches, so a query's wall clock is
the max over parallel subtrees — flat per-phase timers can no longer say
where a SPECIFIC slow request's time went (queue-wait vs run, cache miss
vs stack build, jit compile vs device fetch). This module is the answer
modern serving stacks converged on (Dapper, as adopted by the OTel
ecosystem): one trace tree per request, sampled, retained in-process in a
bounded ring, exportable to standard viewers.

  * `Tracer.request(...)` roots a trace at the trace id the task layer
    already generates/echoes (common/tasks.py); `span(name, **attrs)` is
    the in-request instrumentation primitive — a context manager that is
    a near-free no-op when no trace is active, so the hot path pays one
    contextvar read when tracing is off or the request wasn't opened.
  * Propagation is contextvars-native: the coordinator's `_ShardJob`
    fan-out copies the request context onto the search pool, so shard
    subtrees parent correctly with no plumbing; `wire_header()` /
    `Tracer.remote(...)` carry (trace id, parent span id) across the
    cluster transport as the `_trace` header next to `_task`.
  * Completed traces land in a ring (`node.tracing.retention`, default
    256 traces); retention is decided at COMPLETION: `?trace=true`
    forces, a slowlog hit forces (the request proved itself interesting),
    otherwise `node.tracing.sample_rate` draws. `node.tracing.enabled:
    false` removes every span allocation.
  * Export: the stored trace renders as a nested tree
    (`GET /_traces/{id}`), Chrome trace-event JSON (`?format=chrome`,
    loadable in chrome://tracing or Perfetto) and OTLP-shaped span JSON
    (`?format=otlp`).

Spans carry monotonic-ns timestamps (duration-exact); a wall-clock anchor
captured at trace start converts to unix nanos for OTLP export.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from collections import deque
from contextvars import ContextVar

# (trace, current span) of the running request; copied into shard jobs by
# the fan-out's contextvars.copy_context() and into transport handlers by
# Tracer.remote()
_ACTIVE: ContextVar["tuple[Trace, Span] | None"] = \
    ContextVar("es_active_trace", default=None)


def now_ns() -> int:
    return time.monotonic_ns()


def current_trace() -> "Trace | None":
    active = _ACTIVE.get()
    return active[0] if active is not None else None


class Span:
    __slots__ = ("span_id", "parent_id", "name", "start_ns", "end_ns",
                 "attrs", "thread")

    def __init__(self, span_id: int, parent_id: int | None, name: str,
                 start_ns: int, attrs: dict):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start_ns = start_ns
        self.end_ns = start_ns
        self.attrs = attrs
        self.thread = threading.get_ident()


class Trace:
    """One in-flight request's span set (flat, parent-linked; the tree is
    built at render time). Span appends cross threads (the shard fan-out),
    so they serialize on a lock; device counters accumulate here so the
    stored trace carries its own device section."""

    __slots__ = ("trace_id", "root", "spans", "max_spans", "dropped_spans",
                 "forced", "slowlogged", "remote_parent", "opaque_id",
                 "fetches", "d2h_bytes", "h2d_bytes", "_jit0",
                 "_wall_anchor_ns", "_mono_anchor_ns", "_seq", "_lock")

    def __init__(self, trace_id: str, max_spans: int = 512):
        self.trace_id = trace_id
        self.root: Span | None = None
        self.spans: list[Span] = []
        self.max_spans = max_spans
        self.dropped_spans = 0
        self.forced = False
        self.slowlogged = False
        self.remote_parent: int | None = None
        self.opaque_id: str | None = None
        self.fetches = 0
        self.d2h_bytes = 0
        self.h2d_bytes = 0
        from .metrics import device_events_snapshot
        self._jit0 = device_events_snapshot()
        self._wall_anchor_ns = time.time_ns()
        self._mono_anchor_ns = time.monotonic_ns()
        self._seq = 0
        self._lock = threading.Lock()

    def new_span(self, name: str, parent_id: int | None, start_ns: int,
                 attrs: dict) -> Span | None:
        with self._lock:
            if len(self.spans) >= self.max_spans:
                self.dropped_spans += 1
                return None
            self._seq += 1
            span = Span(self._seq, parent_id, name, start_ns, attrs)
            self.spans.append(span)
            return span

    def note_fetch(self, nbytes: int) -> None:
        with self._lock:
            self.fetches += 1
            self.d2h_bytes += int(nbytes)

    def note_h2d(self, nbytes: int) -> None:
        with self._lock:
            self.h2d_bytes += int(nbytes)

    def device_section(self) -> dict:
        from .metrics import device_events_snapshot
        compiles, compile_ms = device_events_snapshot()
        return {"device_fetches": self.fetches,
                "bytes_device_to_host": self.d2h_bytes,
                "bytes_host_to_device": self.h2d_bytes,
                "jit_compiles": compiles - self._jit0[0],
                "jit_compile_time_in_millis": round(
                    compile_ms - self._jit0[1], 3)}

    def render(self) -> dict:
        """The stored (ring) form: plain JSON-safe dict, offsets in µs
        from the root start so every export derives from one snapshot."""
        root = self.root
        t0 = root.start_ns if root is not None else self._mono_anchor_ns
        spans = []
        with self._lock:
            snap = list(self.spans)
        for s in snap:
            entry = {"id": s.span_id, "parent_id": s.parent_id,
                     "name": s.name,
                     "start_us": round((s.start_ns - t0) / 1e3, 3),
                     "duration_us": round(
                         max(s.end_ns - s.start_ns, 0) / 1e3, 3),
                     "thread": s.thread}
            if s.attrs:
                entry["attributes"] = dict(s.attrs)
            spans.append(entry)
        out = {"trace_id": self.trace_id,
               "root": root.name if root is not None else "",
               "start_time_in_millis": self._wall_anchor_ns // 1_000_000,
               "start_time_unix_nanos": self._wall_anchor_ns
               + (t0 - self._mono_anchor_ns),
               "duration_in_millis": round(
                   max(root.end_ns - root.start_ns, 0) / 1e6, 3)
               if root is not None else 0.0,
               "span_count": len(spans),
               "dropped_spans": self.dropped_spans,
               "slowlog": self.slowlogged,
               "forced": self.forced,
               "device": self.device_section(),
               "spans": spans}
        if self.remote_parent is not None:
            out["remote_parent_span"] = self.remote_parent
        if self.opaque_id is not None:
            out["x_opaque_id"] = self.opaque_id
        return out


# ---------------------------------------------------------------------------
# in-request instrumentation primitives (module-level: call sites never
# need a Tracer reference, and every one is a no-op without an active trace)
# ---------------------------------------------------------------------------

class _SpanCtx:
    """`with span("name", k=v) as sp:` — class-based (not
    contextlib.contextmanager) to keep the inactive path allocation-light
    on seams that run on every request."""

    __slots__ = ("name", "attrs", "start_ns", "_span", "_tok")

    def __init__(self, name: str, start_ns: int | None, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.start_ns = start_ns
        self._span = None
        self._tok = None

    def __enter__(self) -> Span | None:
        active = _ACTIVE.get()
        if active is None:
            return None
        trace, parent = active
        span = trace.new_span(
            self.name, parent.span_id if parent is not None else None,
            self.start_ns if self.start_ns is not None
            else time.monotonic_ns(), self.attrs)
        if span is None:            # per-trace span cap: dropped, counted
            return None
        self._span = span
        self._tok = _ACTIVE.set((trace, span))
        return span

    def __exit__(self, *exc) -> bool:
        if self._span is not None:
            self._span.end_ns = time.monotonic_ns()
            _ACTIVE.reset(self._tok)
        return False


def span(name: str, start_ns: int | None = None, **attrs) -> _SpanCtx:
    """Open a child span of the current span for the block. `start_ns`
    backdates the start (the shard-span-covers-queue-wait case)."""
    return _SpanCtx(name, start_ns, attrs)


def add_span(name: str, start_ns: int, end_ns: int, **attrs) -> None:
    """Record a completed child span with explicit timestamps (phases the
    caller already timed — queue_wait, parse — need no second timer)."""
    active = _ACTIVE.get()
    if active is None:
        return
    trace, parent = active
    sp = trace.new_span(name,
                        parent.span_id if parent is not None else None,
                        int(start_ns), attrs)
    if sp is not None:
        sp.end_ns = int(end_ns)


def add_event(name: str, **attrs) -> None:
    """Zero-duration marker span (cache evictions, ...)."""
    t = time.monotonic_ns()
    add_span(name, t, t, **attrs)


def mark_slowlog() -> None:
    """The request crossed a slowlog threshold: force trace retention so
    the slowlog entry's trace id always resolves in `GET /_traces`."""
    trace = current_trace()
    if trace is not None:
        trace.slowlogged = True


def note_fetch_start() -> int | None:
    """ns timestamp when a trace is active, else None — the device_fetch
    seam's cheap gate."""
    return time.monotonic_ns() if _ACTIVE.get() is not None else None


def note_fetch_end(start_ns: int, nbytes: int) -> None:
    active = _ACTIVE.get()
    if active is None:
        return
    active[0].note_fetch(nbytes)
    add_span("device_fetch", start_ns, time.monotonic_ns(), bytes=nbytes)


def note_h2d(nbytes: int) -> None:
    trace = current_trace()
    if trace is not None:
        trace.note_h2d(nbytes)


def wire_header() -> dict | None:
    """The `_trace` transport header: (trace id, parent span id) — None
    when nothing is being traced, so untraced requests add zero bytes."""
    active = _ACTIVE.get()
    if active is None:
        return None
    trace, span_ = active
    return {"trace_id": trace.trace_id,
            "span": span_.span_id if span_ is not None else None}


# ---------------------------------------------------------------------------
# the tracer: per-node roots, sampling, the bounded ring, exports
# ---------------------------------------------------------------------------

def _as_bool(v, default: bool) -> bool:
    if v is None:
        return default
    if isinstance(v, str):
        return v.strip().lower() not in ("false", "0", "no", "off")
    return bool(v)


class Tracer:
    """Node-level trace store. Settings (all live at node boot):

      node.tracing.enabled      default true — false removes every span
      node.tracing.sample_rate  default 1.0 — retention probability for
                                traces that neither forced nor slowlogged
      node.tracing.retention    default 256 — finished-trace ring size
      node.tracing.max_spans    default 512 — per-trace span cap; beyond
                                it spans drop (counted), the trace survives
    """

    def __init__(self, settings=None, rng=None):
        get = settings.get if settings is not None else \
            (lambda k, d=None: d)
        self.enabled = _as_bool(get("node.tracing.enabled"), True)
        try:
            self.sample_rate = float(get("node.tracing.sample_rate", 1.0))
        except (TypeError, ValueError):
            self.sample_rate = 1.0
        try:
            retention = int(get("node.tracing.retention", 256))
        except (TypeError, ValueError):
            retention = 256
        try:
            self.max_spans = int(get("node.tracing.max_spans", 512))
        except (TypeError, ValueError):
            self.max_spans = 512
        self._rng = rng or random.random
        self._ring: deque = deque(maxlen=max(retention, 1))
        self._lock = threading.Lock()
        self.active = 0
        self.traces_started = 0
        self.traces_retained = 0
        self.traces_sampled_out = 0
        self.dropped_traces = 0        # ring evictions (oldest pushed out)
        self.dropped_spans = 0
        self.spans_total = 0

    # -- roots -------------------------------------------------------------

    @contextlib.contextmanager
    def request(self, name: str, trace_id: str | None = None,
                force: bool = False, opaque_id: str | None = None,
                attrs: dict | None = None):
        """Root a trace for the request (nested roots — warmers,
        percolate-inner-search — join the surrounding trace as plain
        spans instead of starting a second one)."""
        if not self.enabled:
            yield None
            return
        if _ACTIVE.get() is not None:
            with span(name, **(attrs or {})):
                yield None
            return
        import uuid
        trace = Trace(trace_id or uuid.uuid4().hex[:16],
                      max_spans=self.max_spans)
        trace.forced = bool(force)
        trace.opaque_id = opaque_id
        trace.root = trace.new_span(name, None, time.monotonic_ns(),
                                    dict(attrs or {}))
        with self._lock:
            self.active += 1
            self.traces_started += 1
        tok = _ACTIVE.set((trace, trace.root))
        try:
            yield trace
        finally:
            trace.root.end_ns = time.monotonic_ns()
            _ACTIVE.reset(tok)
            self._finalize(trace)

    @contextlib.contextmanager
    def remote(self, header: dict | None, name: str,
               attrs: dict | None = None):
        """Continue a trace that crossed the cluster transport: the local
        subtree roots at the coordinator's (trace id, span id) from the
        `_trace` wire header and lands in THIS node's ring as a partial
        trace — `GET /_traces/{id}` on the copy-holder shows its side."""
        if not self.enabled or not header or not header.get("trace_id"):
            yield None
            return
        trace = Trace(str(header["trace_id"]), max_spans=self.max_spans)
        trace.forced = True        # explicitly propagated => keep it
        rp = header.get("span")
        trace.remote_parent = int(rp) if rp is not None else None
        trace.root = trace.new_span(name, None, time.monotonic_ns(),
                                    dict(attrs or {}))
        with self._lock:
            self.active += 1
            self.traces_started += 1
        tok = _ACTIVE.set((trace, trace.root))
        try:
            yield trace
        finally:
            trace.root.end_ns = time.monotonic_ns()
            _ACTIVE.reset(tok)
            self._finalize(trace)

    def _finalize(self, trace: Trace) -> None:
        retain = trace.forced or trace.slowlogged \
            or self.sample_rate >= 1.0 or self._rng() < self.sample_rate
        with self._lock:
            self.active -= 1
            self.spans_total += len(trace.spans)
            self.dropped_spans += trace.dropped_spans
            if not retain:
                self.traces_sampled_out += 1
                return
            if len(self._ring) == self._ring.maxlen:
                self.dropped_traces += 1
            self._ring.append(trace.render())
            self.traces_retained += 1

    # -- the REST surface --------------------------------------------------

    def list(self) -> list[dict]:
        """Newest-first summaries: the `GET /_traces` body."""
        with self._lock:
            snap = list(self._ring)
        return [{"trace_id": t["trace_id"], "root": t["root"],
                 "start_time_in_millis": t["start_time_in_millis"],
                 "duration_in_millis": t["duration_in_millis"],
                 "span_count": t["span_count"],
                 "slowlog": t["slowlog"]}
                for t in reversed(snap)]

    def get(self, trace_id: str) -> dict | None:
        with self._lock:
            snap = list(self._ring)
        for t in reversed(snap):
            if t["trace_id"] == trace_id:
                return t
        return None

    def stats(self) -> dict:
        with self._lock:
            return {"traces_started_total": self.traces_started,
                    "traces_retained_total": self.traces_retained,
                    "traces_sampled_out_total": self.traces_sampled_out,
                    "dropped_traces_total": self.dropped_traces,
                    "dropped_spans_total": self.dropped_spans,
                    "spans_total": self.spans_total,
                    "active_traces": self.active,
                    "retained_traces": len(self._ring)}


# ---------------------------------------------------------------------------
# exports: nested tree, Chrome trace-event JSON, OTLP span JSON
# ---------------------------------------------------------------------------

def span_tree(trace: dict) -> dict:
    """Stored trace -> nested tree (`GET /_traces/{id}` default body)."""
    by_id: dict[int, dict] = {}
    for s in trace["spans"]:
        by_id[s["id"]] = {**s, "children": []}
    root = None
    orphans = []
    for s in trace["spans"]:
        node = by_id[s["id"]]
        pid = s.get("parent_id")
        if pid is None:
            if root is None:
                root = node
            else:
                orphans.append(node)
        elif pid in by_id:
            by_id[pid]["children"].append(node)
        else:
            orphans.append(node)
    if root is None:
        root = {"id": 0, "name": trace.get("root", ""), "children": orphans}
    else:
        root["children"] = root.get("children", []) + orphans
    out = {k: v for k, v in trace.items() if k != "spans"}
    out["tree"] = root
    return out


def chrome_trace(trace: dict) -> dict:
    """Chrome trace-event JSON (the `?format=chrome` body): complete (X)
    events with µs timestamps, one tid lane per recording thread —
    loadable in chrome://tracing and Perfetto as-is."""
    tid_of: dict[int, int] = {}
    events: list[dict] = []
    for s in trace["spans"]:
        thread = s.get("thread", 0)
        tid = tid_of.setdefault(thread, len(tid_of) + 1)
        args = {k: v for k, v in (s.get("attributes") or {}).items()}
        args["span_id"] = s["id"]
        if s.get("parent_id") is not None:
            args["parent_span_id"] = s["parent_id"]
        events.append({"name": s["name"], "cat": "es", "ph": "X",
                       "ts": s["start_us"], "dur": s["duration_us"],
                       "pid": 1, "tid": tid, "args": args})
    for thread, tid in tid_of.items():
        events.append({"name": "thread_name", "ph": "M", "pid": 1,
                       "tid": tid,
                       "args": {"name": f"thread-{tid}"}})
    return {"displayTimeUnit": "ms",
            "otherData": {"trace_id": trace["trace_id"],
                          "root": trace["root"]},
            "traceEvents": events}


def _otlp_value(v) -> dict:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def otlp_trace(trace: dict) -> dict:
    """OTLP-shaped span JSON (the `?format=otlp` body): resourceSpans →
    scopeSpans → spans with hex ids and unix-nano timestamps."""
    tid32 = (trace["trace_id"].replace("-", "") + "0" * 32)[:32]
    anchor = int(trace.get("start_time_unix_nanos",
                           trace["start_time_in_millis"] * 1_000_000))
    spans = []
    for s in trace["spans"]:
        start = anchor + int(s["start_us"] * 1000)
        parent = s.get("parent_id")
        if parent is None and trace.get("remote_parent_span") is not None:
            parent = trace["remote_parent_span"]
        entry = {"traceId": tid32,
                 "spanId": "%016x" % s["id"],
                 "name": s["name"], "kind": 1,
                 "startTimeUnixNano": str(start),
                 "endTimeUnixNano": str(
                     start + int(s["duration_us"] * 1000)),
                 "attributes": [
                     {"key": k, "value": _otlp_value(v)}
                     for k, v in (s.get("attributes") or {}).items()]}
        if parent is not None:
            entry["parentSpanId"] = "%016x" % parent
        spans.append(entry)
    return {"resourceSpans": [{
        "resource": {"attributes": [
            {"key": "service.name",
             "value": {"stringValue": "elasticsearch-tpu"}}]},
        "scopeSpans": [{"scope": {"name": "elasticsearch_tpu.tracing"},
                        "spans": spans}]}]}
