"""Self-monitoring pipeline (ISSUE 17 tentpole (c)): the node observes
itself through the exact lanes this repo builds.

A `node.monitoring.enable` collector drains the StatsSampler ring
(common/monitor.py) into rolling `.monitoring-es-YYYY.MM.DD` internal
indices on a cadence — every snapshot becomes one document through the
VECTORIZED bulk lane (`NodeService.bulk`, index/bulk_ingest.py), so
monitoring ingest rides the same batched-analysis columnar path as user
traffic. ILM-lite: the target index rolls daily (UTC) and indices older
than `node.monitoring.retention_days` are deleted on the same tick.

`overview()` serves `GET /_monitoring/overview` by issuing a REAL sorted
+ two-level sub-agg search body (`sort: @timestamp desc` +
`date_histogram -> terms -> avg/max`) against the newest monitoring
index — the query that exercises the ISSUE 17 sorted and sub-agg-tree
device lanes end to end (the index is created with 2 shards so the mesh
lane is eligible). The response carries the lane the search actually
took via the same search_stats counters the lane recorder feeds.

Leak hygiene (tier-1 contract): the collector thread is a daemon, joins
on `close()`, and every index it creates goes through the ordinary
IndexService lifecycle — breaker ledgers and caches drain on delete, so
the suite-wide `leak_report()` teardown stays clean.
"""

from __future__ import annotations

import threading
import time

INDEX_PREFIX = ".monitoring-es-"
ENABLE_SETTING = "node.monitoring.enable"
INTERVAL_SETTING = "node.monitoring.interval"
RETENTION_SETTING = "node.monitoring.retention_days"

# 2 shards: the overview's sorted + sub-agg body needs >1 searcher for
# the mesh gate; snapshots are tiny, so the split costs nothing
MONITORING_SETTINGS = {"number_of_shards": 2, "number_of_replicas": 0}
MONITORING_MAPPING = {"_doc": {"properties": {
    "@timestamp": {"type": "date"},
    "node": {"type": "string", "index": "not_analyzed"},
    "kind": {"type": "string", "index": "not_analyzed"},
}}}


def _enabled(settings) -> bool:
    v = settings.get(ENABLE_SETTING, False)
    if isinstance(v, str):
        return v.strip().lower() in ("true", "1", "yes", "on")
    return bool(v)


class MonitoringCollector:
    """Rolling-index writer over the sampler ring + the overview query.

    `clock` injects deterministic time for tests (same convention as
    StatsSampler); `interval_s <= 0` skips the thread — tests drive
    `collect_once()` directly."""

    def __init__(self, node, interval_s: float = 10.0,
                 retention_days: int = 3, clock=None):
        self.node = node
        self.interval_s = float(interval_s)
        self.retention_days = int(retention_days)
        self._clock = clock or time.time
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_ts = 0
        self.stats = {"collections_total": 0, "docs_indexed_total": 0,
                      "rollovers_total": 0, "retention_deletes_total": 0,
                      "errors_total": 0}
        self.current_index: str | None = None

    @classmethod
    def from_settings(cls, node):
        """None unless `node.monitoring.enable` is set — monitoring is
        opt-in so plain test nodes never grow internal indices."""
        if not _enabled(node.settings):
            return None
        try:
            interval = float(node.settings.get(INTERVAL_SETTING, 10))
        except (TypeError, ValueError):
            interval = 10.0
        try:
            retention = int(node.settings.get(RETENTION_SETTING, 3))
        except (TypeError, ValueError):
            retention = 3
        return cls(node, interval_s=interval, retention_days=retention)

    # -- naming / rollover --------------------------------------------------

    def index_for(self, ts_ms: int) -> str:
        day = time.gmtime(ts_ms / 1000.0)
        return f"{INDEX_PREFIX}{day.tm_year:04d}." \
               f"{day.tm_mon:02d}.{day.tm_mday:02d}"

    def _day_of(self, name: str):
        try:
            y, m, d = name[len(INDEX_PREFIX):].split(".")
            return (int(y), int(m), int(d))
        except (ValueError, IndexError):
            return None

    # -- the collection tick ------------------------------------------------

    def collect_once(self) -> int:
        """Drain sampler entries newer than the last tick into today's
        index via ONE bulk, refresh it (the overview reads its own
        writes), roll/retire daily indices. Returns docs indexed."""
        node = self.node
        samples = node.sampler.history().get("samples", [])
        fresh = [s for s in samples if s["timestamp"] > self._last_ts]
        self.stats["collections_total"] += 1
        if not fresh:
            self._apply_retention()
            return 0
        name = self.index_for(fresh[-1]["timestamp"])
        if name not in node.indices:
            from ..node import IndexAlreadyExistsException
            try:
                node.create_index(name, dict(MONITORING_SETTINGS),
                                  {k: dict(v) for k, v in
                                   MONITORING_MAPPING.items()})
            except IndexAlreadyExistsException:
                pass
        if self.current_index is not None and name != self.current_index:
            self.stats["rollovers_total"] += 1
        self.current_index = name
        watcher = getattr(node, "watcher_service", None)
        if watcher is not None:
            # document watches compile into THIS index's percolator
            # registry (re-armed across daily rollover) so the batch
            # below percolates them in one dense matrix program
            watcher.ensure_percolator_registrations(name)
        node_name = getattr(node, "node_name", "tpu-node-0")
        ops = []
        for s in fresh:
            doc = {"@timestamp": int(s["timestamp"]),
                   "node": node_name, "kind": "node_stats"}
            doc.update(s.get("metrics") or {})
            ops.append(("index",
                        {"_index": name,
                         "_id": f"{node_name}-{s['timestamp']}"},
                        doc))
        node.bulk(ops)
        node.indices[name].refresh()
        if watcher is not None:
            # the ISSUE 20 dogfood ride: the tick's own docs, percolated
            # against every document watch as ONE doc×query matrix
            watcher.percolate_collector_batch(name, [op[2] for op in ops])
        self._last_ts = fresh[-1]["timestamp"]
        self.stats["docs_indexed_total"] += len(ops)
        self._apply_retention()
        return len(ops)

    def _apply_retention(self) -> None:
        """Delete monitoring indices whose UTC day is older than
        `retention_days` days before today (daily granularity — the
        ILM-lite delete phase)."""
        import datetime
        today = datetime.datetime.utcfromtimestamp(self._clock()).date()
        cutoff = today - datetime.timedelta(days=self.retention_days)
        for name in sorted(self.node.indices):
            if not name.startswith(INDEX_PREFIX):
                continue
            day = self._day_of(name)
            if day is None:
                continue
            try:
                when = datetime.date(*day)
            except ValueError:
                continue
            if when < cutoff:
                self.node.delete_index(name)
                self.stats["retention_deletes_total"] += 1

    # -- thread lifecycle ---------------------------------------------------

    def start(self) -> None:
        if self._thread is not None or self.interval_s <= 0:
            return

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.collect_once()
                except Exception:  # noqa: BLE001 — never break serving
                    self.stats["errors_total"] += 1
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="es[monitoring_collector]")
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    # -- GET /_monitoring/overview ------------------------------------------

    OVERVIEW_METRICS = ("heap_used_bytes", "hbm_bytes_in_use")

    def overview_body(self, size: int = 10,
                      interval: str = "1m") -> dict:
        """The canned sorted + 2-level sub-agg body: newest samples
        first, a `date_histogram -> terms(node) -> avg/max` tree over
        the gauges an incident inspection reads first."""
        return {
            "size": size,
            "query": {"match_all": {}},
            "sort": [{"@timestamp": "desc"}],
            "aggs": {"over_time": {
                "date_histogram": {"field": "@timestamp",
                                   "interval": interval},
                "aggs": {
                    "by_node": {
                        "terms": {"field": "node"},
                        "aggs": {
                            "avg_heap": {"avg":
                                         {"field": "heap_used_bytes"}},
                            "max_hbm": {"max":
                                        {"field": "hbm_bytes_in_use"}},
                        }},
                    # sample-rate column through the new pipeline-agg
                    # path (ISSUE 20 dogfood): Δcount per bucket,
                    # applied host-side at render over the same
                    # bitwise device partials
                    "doc_rate": {"derivative":
                                 {"buckets_path": "_count"}},
                }}},
        }

    def overview(self, size: int = 10, interval: str = "1m") -> dict:
        node = self.node
        names = sorted(n for n in node.indices
                       if n.startswith(INDEX_PREFIX)
                       and self._day_of(n) is not None)
        meta = {"enabled": True, "interval_s": self.interval_s,
                "retention_days": self.retention_days,
                "indices": names, "collector": dict(self.stats)}
        # watcher/alert-index visibility (ISSUE 20 satellite): the
        # overview answers "what is watching this stream and what has
        # it filed" next to the dispatch deltas it already reports
        watcher = getattr(node, "watcher_service", None)
        if watcher is not None:
            from ..watcher.service import ALERTS_PREFIX
            meta["watcher"] = {
                "watch_count": len(watcher.watches),
                "execution": dict(watcher.stats),
                "alert_indices": sorted(
                    n for n in node.indices
                    if n.startswith(ALERTS_PREFIX)),
                "alerts_docs": sum(
                    node.indices[n].doc_count()
                    for n in node.indices
                    if n.startswith(ALERTS_PREFIX)),
            }
        if not names:
            return {"monitoring": meta, "hits": {"total": 0,
                                                 "max_score": None,
                                                 "hits": []},
                    "aggregations": {}}
        target = names[-1]          # newest day: one index, mesh-eligible
        svc = node.indices[target]
        before = {k: svc.search_stats.get(k, 0)
                  for k in ("mesh_sorted_dispatches",
                            "mesh_agg_dispatches")}
        resp = node.search(target, self.overview_body(size=size,
                                                      interval=interval))
        meta["index"] = target
        meta["lanes"] = {k: svc.search_stats.get(k, 0) - before[k]
                         for k in before}
        resp["monitoring"] = meta
        return resp
