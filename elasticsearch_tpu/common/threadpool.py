"""Named bounded thread pools.

The analog of the reference's ThreadPool
(/root/reference/src/main/java/org/elasticsearch/threadpool/ThreadPool.java:116
— named executors per operation class: search = 3×cores queue 1000,
index/bulk = cores queue 50/200, get, management, snapshot, refresh, generic
— each with a bounded queue whose overflow is a *rejection*, not unbounded
buffering; EsRejectedExecutionException surfaces to the client as 429).

TPU-first note: device programs serialize on the chip anyway, so pools here
bound *host-side* concurrency (parse/pack/render, IO, management) and give
rejection a well-defined point before any HBM is charged — the same
admission-control role the reference's search pool plays in front of Lucene.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable

import os as _os

_CORES = _os.cpu_count() or 4

DEFAULT_POOLS = {
    # name: (threads, queue_size)  — queue_size None = unbounded (scaling
    # pools in the reference: management/generic/snapshot). The reference
    # sizes search at 3×cores (ThreadPool.java:116-129) because its search
    # threads BURN cpu in Lucene; ours mostly WAIT on a device program, so
    # the search pool floors at 32 — narrower would strangle the dynamic
    # batcher, whose whole point is coalescing many concurrent waiters
    # into one device launch (serving/batcher.py).
    "search": (max(32, 3 * _CORES), 1000),
    "index": (max(4, _CORES), 200),
    "bulk": (max(4, _CORES), 50),
    "get": (max(8, _CORES), 1000),
    "management": (2, None),
    "generic": (4, None),
    "snapshot": (1, None),
    "refresh": (2, None),
}


class EsRejectedExecutionException(Exception):
    """Bounded queue overflow — maps to HTTP 429 (ref
    common/util/concurrent/EsRejectedExecutionException.java)."""


class _Pool:
    def __init__(self, name: str, threads: int, queue_size: int | None):
        self.name = name
        self.size = threads
        self.queue_size = queue_size
        self._q: queue.Queue = (queue.Queue(queue_size)
                                if queue_size else queue.Queue())
        self.active = 0
        self.completed = 0
        self.rejected = 0
        self.largest_queue = 0
        # windowed throughput (EWMA, common/metrics.Meter): the trajectory
        # the raw `completed` counter can't show between two stats calls
        from .metrics import Meter
        self.meter = Meter()
        self._lock = threading.Lock()
        self._shutdown = False
        # workers spawn LAZILY on demand up to `threads` (the reference's
        # executors do the same) — a NodeService that never serves traffic
        # costs zero threads
        self._started = 0
        self._idle = 0

    def _worker(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, args = item
            with self._lock:
                self._idle -= 1
                self.active += 1
            try:
                fn(*args)
            except Exception:  # noqa: BLE001 — tasks carry their own futures
                pass
            finally:
                with self._lock:
                    self.active -= 1
                    self.completed += 1
                    self._idle += 1
                self.meter.mark()

    def execute(self, fn: Callable, *args) -> None:
        if self._shutdown:
            raise EsRejectedExecutionException(f"pool [{self.name}] shut down")
        try:
            self._q.put_nowait((fn, args))
        except queue.Full:
            with self._lock:
                self.rejected += 1
            raise EsRejectedExecutionException(
                f"rejected execution on pool [{self.name}] "
                f"(queue capacity {self.queue_size})") from None
        with self._lock:
            self.largest_queue = max(self.largest_queue, self._q.qsize())
            if self._idle == 0 and self._started < self.size:
                self._started += 1
                self._idle += 1
                threading.Thread(
                    target=self._worker, daemon=True,
                    name=f"es[{self.name}][{self._started - 1}]").start()

    def submit(self, fn: Callable, *args):
        """-> a waitable holder; .result() re-raises task exceptions."""
        done = threading.Event()
        box: dict[str, Any] = {}

        def run():
            try:
                box["value"] = fn(*args)
            except BaseException as e:  # noqa: BLE001
                box["error"] = e
            finally:
                done.set()
        self.execute(run)

        class _F:
            def result(self, timeout: float | None = None):
                if not done.wait(timeout):
                    raise TimeoutError(f"task on [{_pool.name}] timed out")
                if "error" in box:
                    raise box["error"]
                return box.get("value")
        _pool = self
        return _F()

    def stats(self) -> dict:
        with self._lock:
            out = {"threads": self.size, "queue": self._q.qsize(),
                   "queue_size": self.queue_size or -1,
                   "active": self.active, "rejected": self.rejected,
                   "largest": self.largest_queue,
                   "completed": self.completed}
        out["completed_rate_1m"] = round(self.meter.rate(60), 4)
        return out

    def shutdown(self) -> None:
        self._shutdown = True
        with self._lock:
            n = self._started
        for _ in range(n):
            self._q.put(None)


class ThreadPool:
    """The per-node pool registry (ref ThreadPool.java — `executor(name)`).

    Settings may override sizes: `threadpool.<name>.size` /
    `threadpool.<name>.queue_size` (the reference's dynamic threadpool
    settings; here applied at construction)."""

    def __init__(self, settings: dict | None = None):
        self.pools: dict[str, _Pool] = {}
        settings = settings or {}
        for name, (threads, qsize) in DEFAULT_POOLS.items():
            threads = int(settings.get(f"threadpool.{name}.size", threads))
            q = settings.get(f"threadpool.{name}.queue_size", qsize)
            q = None if q in (None, -1, "-1") else int(q)
            self.pools[name] = _Pool(name, threads, q)

    def executor(self, name: str) -> _Pool:
        return self.pools[name]

    def execute(self, name: str, fn: Callable, *args) -> None:
        self.pools[name].execute(fn, *args)

    def submit(self, name: str, fn: Callable, *args):
        return self.pools[name].submit(fn, *args)

    def stats(self) -> dict:
        return {name: p.stats() for name, p in sorted(self.pools.items())}

    def shutdown(self) -> None:
        for p in self.pools.values():
            p.shutdown()
