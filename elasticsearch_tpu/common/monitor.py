"""Host monitoring: os / process / fs sampling + hot_threads.

The analog of the reference's monitor module
(/root/reference/src/main/java/org/elasticsearch/monitor/ — os/OsService,
process/ProcessService, fs/FsService sample sigar-or-/proc sources on a
cadence; jvm/HotThreads.java:36,83 samples thread stacks N times and ranks
them by busyness). Python host: /proc + os.getloadavg + shutil.disk_usage
+ sys._current_frames give the same observability surface; the "jvm"
section reports the Python runtime + gc the way the reference reports heap
+ collectors.
"""

from __future__ import annotations

import collections
import os
import sys
import threading
import time
import traceback


def os_stats() -> dict:
    out: dict = {"timestamp": int(time.time() * 1000)}
    try:
        la = os.getloadavg()
        out["load_average"] = [round(x, 2) for x in la]
    except OSError:
        out["load_average"] = [0.0, 0.0, 0.0]
    out["cpu"] = {"percent": _cpu_percent()}
    mem: dict = {}
    try:
        with open("/proc/meminfo") as f:
            info = {}
            for line in f:
                parts = line.split()
                if len(parts) >= 2:
                    info[parts[0].rstrip(":")] = int(parts[1]) * 1024
        total = info.get("MemTotal", 0)
        free = info.get("MemAvailable", info.get("MemFree", 0))
        mem = {"total_in_bytes": total, "free_in_bytes": free,
               "used_in_bytes": total - free,
               "free_percent": int(100 * free / total) if total else 0,
               "used_percent": int(100 * (total - free) / total)
               if total else 0}
        out["swap"] = {"total_in_bytes": info.get("SwapTotal", 0),
                       "free_in_bytes": info.get("SwapFree", 0),
                       "used_in_bytes": info.get("SwapTotal", 0)
                       - info.get("SwapFree", 0)}
    except OSError:
        pass
    out["mem"] = mem
    return out


_last_cpu: list = []


def _cpu_percent() -> int:
    """Whole-host cpu busy %, from consecutive /proc/stat samples."""
    try:
        with open("/proc/stat") as f:
            parts = f.readline().split()[1:]
        vals = [int(x) for x in parts[:8]]
    except (OSError, ValueError):
        return 0
    idle = vals[3] + (vals[4] if len(vals) > 4 else 0)
    total = sum(vals)
    if _last_cpu:
        dt = total - _last_cpu[0]
        di = idle - _last_cpu[1]
        pct = int(100 * (dt - di) / dt) if dt > 0 else 0
    else:
        pct = 0
    _last_cpu[:] = [total, idle]
    return max(0, min(100, pct))


def process_stats() -> dict:
    out: dict = {"timestamp": int(time.time() * 1000),
                 "id": os.getpid()}
    try:
        with open("/proc/self/status") as f:
            info = {}
            for line in f:
                parts = line.split()
                if parts and parts[0].rstrip(":") in (
                        "VmRSS", "VmSize", "Threads", "FDSize"):
                    info[parts[0].rstrip(":")] = int(parts[1])
        out["mem"] = {
            "resident_in_bytes": info.get("VmRSS", 0) * 1024,
            "total_virtual_in_bytes": info.get("VmSize", 0) * 1024}
        out["threads"] = info.get("Threads", threading.active_count())
    except (OSError, ValueError):
        out["mem"] = {}
        out["threads"] = threading.active_count()
    try:
        out["open_file_descriptors"] = len(os.listdir("/proc/self/fd"))
    except OSError:
        out["open_file_descriptors"] = -1
    try:
        t = os.times()
        out["cpu"] = {"total_in_millis": int((t.user + t.system) * 1000)}
    except OSError:
        pass
    return out


def fs_stats(paths: list[str]) -> dict:
    import shutil
    data = []
    total = {"total_in_bytes": 0, "free_in_bytes": 0,
             "available_in_bytes": 0}
    for p in paths:
        try:
            du = shutil.disk_usage(p)
        except OSError:
            continue
        data.append({"path": p, "total_in_bytes": du.total,
                     "free_in_bytes": du.free,
                     "available_in_bytes": du.free})
        total["total_in_bytes"] += du.total
        total["free_in_bytes"] += du.free
        total["available_in_bytes"] += du.free
    return {"timestamp": int(time.time() * 1000), "total": total,
            "data": data}


def runtime_stats() -> dict:
    """Python runtime stats — the reference's jvm section (heap + gc)."""
    import gc
    counts = gc.get_count()
    stats = gc.get_stats() if hasattr(gc, "get_stats") else []
    collected = sum(s.get("collected", 0) for s in stats)
    collections_n = sum(s.get("collections", 0) for s in stats)
    return {
        "timestamp": int(time.time() * 1000),
        "uptime_in_millis": int(
            (time.monotonic() - _START_MONO) * 1000),
        "version": sys.version.split()[0],
        "mem": {"heap_used_in_bytes": _rss(),
                "heap_max_in_bytes": 0},
        "gc": {"collectors": {"python": {
            "collection_count": collections_n,
            "collected": collected,
            "pending": sum(counts)}}},
        "threads": {"count": threading.active_count()},
    }


_START_MONO = time.monotonic()


def _rss() -> int:
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


class StatsSampler:
    """Periodic node-gauge history: a bounded ring of flat snapshots (the
    reference's monitor services sample os/process/fs on a cadence —
    OsService/ProcessService refresh intervals; this keeps the SAMPLES, so
    a spike between two manual stats calls is still inspectable post-hoc
    via `GET /_nodes/stats/history` without an external TSDB).

    `snapshot_fn() -> {gauge: number}` decouples the ring from what is
    sampled; tests drive `sample()` directly (no wall-clock sleeps) and
    inject `clock` for deterministic timestamps."""

    def __init__(self, snapshot_fn, interval_s: float = 10.0,
                 maxlen: int = 360, clock=None):
        self._snapshot_fn = snapshot_fn
        self.interval_s = float(interval_s)
        self._clock = clock or time.time
        self._ring: collections.deque = collections.deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- sampling ----------------------------------------------------------

    def sample(self) -> dict:
        """Take ONE snapshot and append it to the ring (the background loop
        calls this on the cadence; tests call it directly)."""
        try:
            metrics = self._snapshot_fn()
        except Exception:  # noqa: BLE001 — sampling must never break serving
            metrics = {}
        entry = {"timestamp": int(self._clock() * 1000),
                 "metrics": {k: v for k, v in metrics.items()
                             if isinstance(v, (int, float))
                             and not isinstance(v, bool) and v == v}}
        with self._lock:
            self._ring.append(entry)
        return entry

    def start(self) -> None:
        if self._thread is not None or self.interval_s <= 0:
            return

        def loop():
            self.sample()          # boot sample: history is never empty
            while not self._stop.wait(self.interval_s):
                self.sample()
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="es[stats_sampler]")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    # -- the `GET /_nodes/stats/history` body -------------------------------

    def history(self, metrics: list[str] | None = None) -> dict:
        """Samples plus per-gauge min/max/avg/last rollups; `metrics` is an
        optional list of gauge-name patterns (`*` wildcards, the stats
        ?metric= convention)."""
        import fnmatch
        with self._lock:
            samples = [dict(s, metrics=dict(s["metrics"]))
                       for s in self._ring]
        if metrics:
            for s in samples:
                s["metrics"] = {
                    k: v for k, v in s["metrics"].items()
                    if any(fnmatch.fnmatch(k, pat) for pat in metrics)}
        rollups: dict[str, dict] = {}
        for s in samples:
            for k, v in s["metrics"].items():
                r = rollups.get(k)
                if r is None:
                    rollups[k] = {"min": v, "max": v, "sum": v,
                                  "count": 1, "last": v}
                else:
                    r["min"] = min(r["min"], v)
                    r["max"] = max(r["max"], v)
                    r["sum"] += v
                    r["count"] += 1
                    r["last"] = v
        for r in rollups.values():
            r["avg"] = round(r.pop("sum") / r["count"], 4)
        return {"interval_in_seconds": self.interval_s,
                "sample_count": len(samples),
                "samples": samples,
                "rollups": rollups}


def hot_threads(threads: int = 3, snapshots: int = 10,
                interval_ms: float = 50.0) -> str:
    """Sample every thread's stack `snapshots` times; rank stacks by how
    often they appear (ref monitor/jvm/HotThreads.java:83 — N samples at
    an interval, grouped by identical stack, top-N rendered as text)."""
    samples: collections.Counter = collections.Counter()
    names = {t.ident: t.name for t in threading.enumerate()}
    me = threading.get_ident()
    for i in range(snapshots):
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            stack = "".join(traceback.format_stack(frame, limit=12))
            samples[(tid, stack)] += 1
        if i < snapshots - 1:
            time.sleep(interval_ms / 1000.0)
    lines = [f"::: {{tpu-node-0}}{{local}}\n   Hot threads at "
             f"{time.strftime('%Y-%m-%dT%H:%M:%S')}, interval="
             f"{interval_ms}ms, busiestThreads={threads}:\n"]
    for (tid, stack), n in samples.most_common(threads):
        pct = 100.0 * n / snapshots
        lines.append(
            f"   {pct:.1f}% ({n}/{snapshots} snapshots) cpu usage by "
            f"thread '{names.get(tid, tid)}'\n"
            + "".join(f"     {ln}\n" for ln in stack.splitlines()[-6:]))
    return "".join(lines)
