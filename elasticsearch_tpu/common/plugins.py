"""PluginsService: discovery + lifecycle hooks for node plugins.

The analog of /root/reference/src/main/java/org/elasticsearch/plugins/
(PluginsService.java:91 — scan the plugins dir, read each plugin's
descriptor, instantiate, surface in nodes-info; plugins can register REST
handlers and lifecycle hooks).

Python shape: `<data>/plugins/<name>/plugin.json` holds
{"name", "version", "description", "module"?}. When "module" names a
python file inside the plugin dir, it is imported and its optional
`init(node)` hook runs at node boot; an optional
`register_routes(controller, node)` hook adds REST endpoints.
"""

from __future__ import annotations

import json
import os


class PluginInfo:
    __slots__ = ("name", "version", "description", "path", "module")

    def __init__(self, name, version, description, path, module=None):
        self.name = name
        self.version = version
        self.description = description
        self.path = path
        self.module = module

    def to_dict(self) -> dict:
        return {"name": self.name, "version": self.version,
                "description": self.description, "jvm": False,
                "site": False}


class PluginsService:
    def __init__(self, plugins_dir: str):
        self.plugins_dir = plugins_dir
        self.plugins: list[PluginInfo] = []
        self.load_errors: list[str] = []
        self._scan()

    def _scan(self) -> None:
        if not os.path.isdir(self.plugins_dir):
            return
        for name in sorted(os.listdir(self.plugins_dir)):
            pdir = os.path.join(self.plugins_dir, name)
            desc = os.path.join(pdir, "plugin.json")
            if not os.path.isfile(desc):
                continue
            try:
                with open(desc) as f:
                    meta = json.load(f)
            except (OSError, ValueError) as e:
                self.load_errors.append(f"{name}: bad descriptor: {e}")
                continue
            info = PluginInfo(meta.get("name", name),
                              str(meta.get("version", "0")),
                              meta.get("description", ""), pdir)
            mod_file = meta.get("module")
            if mod_file:
                try:
                    info.module = self._load_module(
                        f"es_tpu_plugin_{name}",
                        os.path.join(pdir, mod_file))
                except Exception as e:  # noqa: BLE001 — a broken plugin
                    self.load_errors.append(f"{name}: {e}")
                    continue            # must not take the node down
            self.plugins.append(info)

    @staticmethod
    def _load_module(modname: str, path: str):
        import importlib.util
        spec = importlib.util.spec_from_file_location(modname, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def on_node_start(self, node) -> None:
        for p in self.plugins:
            hook = getattr(p.module, "init", None)
            if callable(hook):
                try:
                    hook(node)
                except Exception as e:  # noqa: BLE001
                    self.load_errors.append(f"{p.name}: init failed: {e}")

    def register_routes(self, controller, node) -> None:
        for p in self.plugins:
            hook = getattr(p.module, "register_routes", None)
            if callable(hook):
                try:
                    hook(controller, node)
                except Exception as e:  # noqa: BLE001
                    self.load_errors.append(f"{p.name}: routes failed: {e}")

    def infos(self) -> list[dict]:
        return [p.to_dict() for p in self.plugins]
