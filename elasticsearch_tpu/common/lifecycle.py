"""Component lifecycle state machine.

The analog of /root/reference/src/main/java/org/elasticsearch/common/
component/Lifecycle.java (INITIALIZED -> STARTED -> STOPPED -> CLOSED with
guarded transitions) + AbstractLifecycleComponent's moveToStarted/Stopped/
Closed discipline. Components embed one of these and gate their work on
`started`; illegal transitions raise instead of corrupting state.
"""

from __future__ import annotations

import threading

INITIALIZED = "INITIALIZED"
STARTED = "STARTED"
STOPPED = "STOPPED"
CLOSED = "CLOSED"


class IllegalStateTransition(Exception):
    pass


class Lifecycle:
    _ALLOWED = {
        INITIALIZED: {STARTED, CLOSED},
        STARTED: {STOPPED},
        STOPPED: {STARTED, CLOSED},
        CLOSED: set(),
    }

    def __init__(self):
        self.state = INITIALIZED
        self._lock = threading.Lock()

    def _move(self, to: str) -> bool:
        with self._lock:
            if self.state == to:
                return False           # idempotent re-entry
            if to not in self._ALLOWED[self.state]:
                raise IllegalStateTransition(
                    f"cannot move from [{self.state}] to [{to}]")
            self.state = to
            return True

    def move_to_started(self) -> bool:
        return self._move(STARTED)

    def move_to_stopped(self) -> bool:
        return self._move(STOPPED)

    def move_to_closed(self) -> bool:
        # closing from STARTED implies a stop first (the reference's
        # close() calls stop() when started)
        with self._lock:
            if self.state == CLOSED:
                return False
            if self.state == STARTED:
                self.state = STOPPED
            if CLOSED not in self._ALLOWED[self.state]:
                raise IllegalStateTransition(
                    f"cannot close from [{self.state}]")
            self.state = CLOSED
            return True

    @property
    def started(self) -> bool:
        return self.state == STARTED

    @property
    def closed(self) -> bool:
        return self.state == CLOSED
