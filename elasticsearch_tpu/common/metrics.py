"""Per-phase search timers, histogram metrics, request profiling + slowlog.

The observability floor (SURVEY §5.1/§5.5; VERDICT r4 #10):
  * PhaseTimers — parse / device(query) / fetch / render wall-time
    accumulators, surfaced through `_nodes/stats` and `_stats`. This is
    the TPU analog of the reference's per-phase stats (SearchStats
    queryTime/fetchTime) — here the interesting split is host parse vs
    device program vs response render, because host overhead is where
    TPU serving loses its speedup.
  * MetricsRegistry — histogram-capable named timers (count/sum/min/max/
    p50/p99 from a bounded reservoir), the `profiling` section of
    `_nodes/stats`.
  * RequestProfiler — the per-request timing tree behind `"profile": true`
    on `_search` (ref search/profile/ Profilers + InternalProfiler in
    later reference versions). The TPU twist the reference never had: jit
    retraces and host↔device transfers silently dominate tail latency, so
    the profiler also diffs process-wide compile events (jax.monitoring)
    and counts bytes crossing the device boundary per request.
  * SlowLog — per-index query slowlog with live-updatable thresholds
    (ref index/search/slowlog/ShardSlowLogSearchService.java: warn/info/
    debug/trace thresholds from index settings, applied per request),
    stamped with the request's trace/opaque ids so one id correlates the
    slowlog, the task listing and the profile output.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import math
import re as _re
import threading
import time
import uuid
from collections import deque


class PhaseTimers:
    """Lock-cheap accumulators: {phase: (count, total_ms, max_ms)}."""

    PHASES = ("parse", "device", "fetch", "render", "total")

    def __init__(self):
        self._lock = threading.Lock()
        self._acc: dict[str, list] = {p: [0, 0.0, 0.0] for p in self.PHASES}

    def record(self, phase: str, ms: float) -> None:
        with self._lock:
            a = self._acc.setdefault(phase, [0, 0.0, 0.0])
            a[0] += 1
            a[1] += ms
            a[2] = max(a[2], ms)

    def stats(self) -> dict:
        with self._lock:
            return {p: {"count": a[0],
                        "time_in_millis": round(a[1], 3),
                        "max_millis": round(a[2], 3)}
                    for p, a in self._acc.items() if a[0]}


class MetricsRegistry:
    """Named wall-time histograms: count/sum/min/max plus p50/p99 computed
    from a bounded sample reservoir (the reference keeps count+sum only;
    tail percentiles are what a latency SLO actually needs)."""

    def __init__(self, reservoir: int = 512):
        self._lock = threading.Lock()
        self._reservoir = reservoir
        self._timers: dict[str, dict] = {}

    def record(self, name: str, ms: float) -> None:
        with self._lock:
            t = self._timers.get(name)
            if t is None:
                t = self._timers[name] = {
                    "count": 0, "sum": 0.0,
                    "min": float("inf"), "max": 0.0,
                    "samples": deque(maxlen=self._reservoir)}
            t["count"] += 1
            t["sum"] += ms
            t["min"] = min(t["min"], ms)
            t["max"] = max(t["max"], ms)
            t["samples"].append(ms)

    @contextlib.contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, (time.perf_counter() - t0) * 1000)

    def stats(self) -> dict:
        with self._lock:
            snap = {n: (t["count"], t["sum"], t["min"], t["max"],
                        sorted(t["samples"]))
                    for n, t in self._timers.items()}
        out = {}
        for name, (count, total, mn, mx, samples) in snap.items():
            entry = {"count": count,
                     "time_in_millis": round(total, 3),
                     "min_millis": round(mn, 3),
                     "max_millis": round(mx, 3)}
            if samples:
                entry["p50_millis"] = round(
                    samples[len(samples) // 2], 3)
                entry["p99_millis"] = round(
                    samples[min(len(samples) - 1,
                                int(len(samples) * 0.99))], 3)
            out[name] = entry
        return out


class Meter:
    """Exponentially-weighted moving-average rate meter (the codahale
    Meter the reference exposes through its stats APIs): 1m/5m/15m rates
    ticked on a fixed 5s interval, plus a lifetime mean. The clock is
    injectable so tests drive exact tick sequences with no sleeping —
    rates are then a pure function of (marks, tick times)."""

    TICK_S = 5.0
    WINDOWS = (60, 300, 900)

    def __init__(self, clock=None):
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self.count = 0
        self._uncounted = 0
        self._start = self._last_tick = self._clock()
        # EWMA per window; None until the first tick initializes it to the
        # first interval's instant rate (the codahale bootstrap)
        self._ewma: dict[int, float | None] = {w: None for w in self.WINDOWS}

    def _tick(self, now: float) -> None:
        # caller holds the lock
        intervals = int((now - self._last_tick) / self.TICK_S)
        if intervals <= 0:
            return
        instant = self._uncounted / self.TICK_S
        self._uncounted = 0
        self._last_tick += intervals * self.TICK_S
        for w in self.WINDOWS:
            alpha = 1.0 - math.exp(-self.TICK_S / w)
            r = self._ewma[w]
            if r is None:
                r = instant
                intervals_left = intervals - 1
            else:
                r += alpha * (instant - r)
                intervals_left = intervals - 1
            # idle intervals after the first decay toward zero
            for _ in range(intervals_left):
                r += alpha * (0.0 - r)
            self._ewma[w] = r

    def mark(self, n: int = 1) -> None:
        with self._lock:
            self._tick(self._clock())
            self.count += n
            self._uncounted += n

    def rate(self, window: int = 60) -> float:
        """Events/second over the EWMA window (0.0 before the first tick)."""
        with self._lock:
            self._tick(self._clock())
            r = self._ewma[window]
            return r if r is not None else 0.0

    def mean_rate(self) -> float:
        with self._lock:
            elapsed = self._clock() - self._start
            return self.count / elapsed if elapsed > 0 else 0.0

    def stats(self) -> dict:
        with self._lock:
            self._tick(self._clock())
            out = {"count": self.count,
                   "mean_rate": round(self.mean_rate_locked(), 4)}
            for w, label in zip(self.WINDOWS, ("1m", "5m", "15m")):
                r = self._ewma[w]
                out[f"rate_{label}"] = round(r, 4) if r is not None else 0.0
            return out

    def mean_rate_locked(self) -> float:
        elapsed = self._clock() - self._start
        return self.count / elapsed if elapsed > 0 else 0.0


# ---------------------------------------------------------------------------
# Device-level counters: jit compiles (retraces) via jax.monitoring, bytes
# crossing the host↔device boundary via the device_fetch/note_h2d seams.
# Process-wide accumulators; RequestProfiler diffs them around a request.
# ---------------------------------------------------------------------------

_DEVICE_EVENTS = {"compiles": 0, "compile_ms": 0.0,
                  "h2d_bytes": 0, "d2h_bytes": 0, "fetches": 0}
_DEVICE_LOCK = threading.Lock()
_LISTENER_INSTALLED = False


def _install_compile_listener() -> None:
    """Register a jax.monitoring duration listener (idempotent). Compile
    events fire only on an actual retrace+compile, never on a cache-hit
    dispatch — exactly the signal the no-retrace tripwire needs. Degrades
    to zeros on jax builds without the monitoring API."""
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return
    _LISTENER_INSTALLED = True
    try:
        import jax

        def _on_duration(name, secs, **kw):  # noqa: ANN001 — jax callback
            if "/jax/core/compile/" not in name:
                return
            with _DEVICE_LOCK:
                if name.endswith("backend_compile_duration"):
                    _DEVICE_EVENTS["compiles"] += 1
                _DEVICE_EVENTS["compile_ms"] += secs * 1000.0

        jax.monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:  # noqa: BLE001 — observability must never break serving
        pass


def device_events_snapshot() -> tuple[int, float]:
    with _DEVICE_LOCK:
        return _DEVICE_EVENTS["compiles"], _DEVICE_EVENTS["compile_ms"]


_FETCH_HIST: dict[int, int] = {}


def record_shard_fetches(n: int) -> None:
    """One shard query phase performed `n` device_fetch round-trips —
    bucket counts for the fetches-per-shard-query histogram on the
    `/_metrics` scrape (the stacked dense lane's whole point is n == 1)."""
    with _DEVICE_LOCK:
        _FETCH_HIST[int(n)] = _FETCH_HIST.get(int(n), 0) + 1


def shard_fetch_histogram() -> dict[int, int]:
    """{device_fetches_per_shard_query: occurrences} snapshot."""
    with _DEVICE_LOCK:
        return dict(_FETCH_HIST)


# peak per-query score-matrix residency (ISSUE 8): what one dense query
# phase materializes on device at most — O(Q × block) on the blockwise
# lane vs O(Q × n_pad) on the materializing executor. A gauge, not a
# counter: the scrape reads the process high-water mark.
_SCORE_MATRIX_PEAK = [0]


def record_score_matrix_bytes(n: int) -> None:
    """One dense execution is about to materialize `n` bytes of score +
    match state (the lane-accurate request-breaker charge)."""
    with _DEVICE_LOCK:
        if n > _SCORE_MATRIX_PEAK[0]:
            _SCORE_MATRIX_PEAK[0] = int(n)


def peak_score_matrix_bytes() -> int:
    with _DEVICE_LOCK:
        return _SCORE_MATRIX_PEAK[0]


_HOST_MERGES = [0]


def record_host_merge() -> None:
    """One host-side cross-shard merge ran (controller.sort_docs). The
    mesh-sharded query lane's whole point is replacing these with one
    on-device collective reduce — tests tripwire on the delta staying 0."""
    with _DEVICE_LOCK:
        _HOST_MERGES[0] += 1


def host_merge_count() -> int:
    with _DEVICE_LOCK:
        return _HOST_MERGES[0]


# bulk-ingest lane counters (ISSUE 7): how many `_bulk` requests rode the
# vectorized batch lane vs fell back to the per-doc path, how many docs each
# carried, and a docs-per-bulk pow2 histogram — es_indexing_* on the scrape
_BULK_INGEST = {"vectorized_bulks": 0, "fallback_bulks": 0,
                "vectorized_docs": 0, "fallback_docs": 0}
_BULK_DOCS_HIST: dict[int, int] = {}


def record_bulk_ingest(docs: int, vectorized: bool) -> None:
    """One `_bulk` request finished: `docs` ops, fully vectorized or not
    (a request with ANY per-doc-lane op counts as fallback — mixed
    requests are what the fallback ladder is for)."""
    with _DEVICE_LOCK:
        if vectorized:
            _BULK_INGEST["vectorized_bulks"] += 1
            _BULK_INGEST["vectorized_docs"] += docs
        else:
            _BULK_INGEST["fallback_bulks"] += 1
            _BULK_INGEST["fallback_docs"] += docs
        bucket = 1 << max(int(docs) - 1, 0).bit_length() if docs else 0
        _BULK_DOCS_HIST[bucket] = _BULK_DOCS_HIST.get(bucket, 0) + 1


def bulk_ingest_snapshot() -> dict:
    with _DEVICE_LOCK:
        return {"vectorized_bulks_total": _BULK_INGEST["vectorized_bulks"],
                "fallback_bulks_total": _BULK_INGEST["fallback_bulks"],
                "vectorized_docs_total": _BULK_INGEST["vectorized_docs"],
                "fallback_docs_total": _BULK_INGEST["fallback_docs"]}


def bulk_docs_histogram() -> dict[int, int]:
    """{pow2 docs-per-bulk bucket: request count} snapshot."""
    with _DEVICE_LOCK:
        return dict(_BULK_DOCS_HIST)


def transfer_snapshot() -> dict:
    """Process-wide host↔device transfer counters (every device_fetch /
    note_h2d call accounts here, profiler active or not) — the scrape's
    `es_transfer_*` series."""
    with _DEVICE_LOCK:
        return {"bytes_to_device_total": _DEVICE_EVENTS["h2d_bytes"],
                "bytes_from_device_total": _DEVICE_EVENTS["d2h_bytes"],
                "device_fetches_total": _DEVICE_EVENTS["fetches"]}


def note_h2d(nbytes: int) -> None:
    """Account host→device bytes: always process-wide, and into the active
    RequestProfiler when one is installed. Hot paths call this at their
    upload points so the scrape sees every transfer, not just profiled
    requests."""
    from . import tracing
    n = int(nbytes)
    with _DEVICE_LOCK:
        _DEVICE_EVENTS["h2d_bytes"] += n
    prof = _PROFILER.get()
    if prof is not None:
        prof.note_h2d(n)
    tracing.note_h2d(n)


def _nbytes(x) -> int:
    if isinstance(x, dict):
        return sum(_nbytes(v) for v in x.values())
    if isinstance(x, (list, tuple)):
        return sum(_nbytes(v) for v in x)
    return int(getattr(x, "nbytes", 0))


def device_fetch(x):
    """jax.device_get with per-request accounting: when a profiler is
    active, the fetch counts as one device round-trip and its payload as
    device→host bytes. The hot paths call this INSTEAD of jax.device_get,
    so `"profile": true` sees every transfer without touching the kernels.
    An active trace additionally gets a timed `device_fetch` span and its
    bytes in the trace's device section (common/tracing.py)."""
    import jax
    from . import tracing
    t0 = tracing.note_fetch_start()
    out = jax.device_get(x)
    nb = _nbytes(out)
    with _DEVICE_LOCK:
        _DEVICE_EVENTS["d2h_bytes"] += nb
        _DEVICE_EVENTS["fetches"] += 1
    prof = _PROFILER.get()
    if prof is not None:
        prof.note_dispatch()
        prof.note_d2h(nb)
    if t0 is not None:
        tracing.note_fetch_end(t0, nb)
    return out


_PROFILER: contextvars.ContextVar["RequestProfiler | None"] = \
    contextvars.ContextVar("es_request_profiler", default=None)


def current_profiler() -> "RequestProfiler | None":
    return _PROFILER.get()


@contextlib.contextmanager
def use_profiler(prof: "RequestProfiler"):
    tok = _PROFILER.set(prof)
    try:
        yield prof
    finally:
        _PROFILER.reset(tok)


class RequestProfiler:
    """Per-request timing tree: coordinator phases, per-shard query
    execution with per-DSL-node score/match wall time (non-jit-visible
    timers around the jitted calls — query_dsl.Node instruments itself
    against the active profiler), plus the device section (jit cache
    hit/miss, compile time when a retrace fired, host↔device bytes)."""

    def __init__(self, trace_id: str | None = None):
        _install_compile_listener()
        self.trace_id = trace_id or uuid.uuid4().hex[:16]
        self.phases: dict[str, float] = {}
        self.shards: list[dict] = []
        # per-THREAD shard stack: shard phases fan out concurrently onto
        # the search pool, and each worker must attribute node timings to
        # its own shard entry, not whichever shard another thread opened
        self._local = threading.local()
        self._lock = threading.Lock()
        self.dispatches = 0
        self.d2h_bytes = 0
        self.h2d_bytes = 0
        self.paths: dict[str, int] = {}   # device path -> shard query count
        # per-request program activity (common/device_stats.py wrapper):
        # site name -> {invocations, device time} for THIS request only
        self.programs: dict[str, dict] = {}
        self._jit0 = device_events_snapshot()

    @property
    def _shard_stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    # -- coordinator phases ------------------------------------------------

    def record_phase(self, name: str, ms: float) -> None:
        with self._lock:
            self.phases[name] = self.phases.get(name, 0.0) + ms

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record_phase(name, (time.perf_counter() - t0) * 1000)

    # -- per-shard tree ----------------------------------------------------

    @contextlib.contextmanager
    def shard(self, index: str, shard_id: int):
        entry = {"index": index, "shard_id": shard_id,
                 "time_in_millis": 0.0, "query": {}}
        with self._lock:
            self.shards.append(entry)
            self._shard_stack.append(entry)
        t0 = time.perf_counter()
        try:
            yield entry
        finally:
            entry["time_in_millis"] = round(
                (time.perf_counter() - t0) * 1000, 3)
            with self._lock:
                self._shard_stack.pop()

    def record_node(self, node_type: str, op: str, ms: float) -> None:
        """One DSL-node execution (op: score|match) — aggregated per node
        type inside the current shard, or under a synthetic 'coordinator'
        shard when node execution happens outside a shard scope."""
        with self._lock:
            if self._shard_stack:
                tree = self._shard_stack[-1]["query"]
            else:
                if not self.shards or self.shards[-1].get("index") != "_coordinator":
                    self.shards.append({"index": "_coordinator",
                                        "shard_id": -1,
                                        "time_in_millis": 0.0, "query": {}})
                tree = self.shards[-1]["query"]
            b = tree.setdefault(node_type, {
                "score_count": 0, "score_time_in_millis": 0.0,
                "match_count": 0, "match_time_in_millis": 0.0})
            b[f"{op}_count"] += 1
            b[f"{op}_time_in_millis"] = round(
                b[f"{op}_time_in_millis"] + ms, 3)

    # -- device counters ---------------------------------------------------

    def note_dispatch(self, n: int = 1) -> None:
        with self._lock:
            self.dispatches += n

    def note_d2h(self, nbytes: int) -> None:
        with self._lock:
            self.d2h_bytes += int(nbytes)

    def note_h2d(self, nbytes: int) -> None:
        with self._lock:
            self.h2d_bytes += int(nbytes)

    def note_path(self, path: str) -> None:
        """One shard query phase served by `path` (sparse / stacked /
        dense / packed) — the _path_stats view scoped to THIS request."""
        with self._lock:
            self.paths[path] = self.paths.get(path, 0) + 1

    def note_program(self, name: str, ms: float) -> None:
        """One instrumented-program dispatch attributed to this request
        (device_stats.InstrumentedProgram calls in)."""
        with self._lock:
            b = self.programs.setdefault(
                name, {"invocations": 0, "device_time_in_millis": 0.0})
            b["invocations"] += 1
            b["device_time_in_millis"] = round(
                b["device_time_in_millis"] + ms, 3)

    def device_section(self) -> dict:
        compiles, compile_ms = device_events_snapshot()
        misses = compiles - self._jit0[0]
        return {"jit_cache_misses": misses,
                "jit_cache_hits": max(self.dispatches - misses, 0),
                "compile_time_in_millis": round(
                    compile_ms - self._jit0[1], 3),
                "bytes_device_to_host": self.d2h_bytes,
                "bytes_host_to_device": self.h2d_bytes,
                "query_paths": dict(self.paths),
                "programs": {k: dict(v)
                             for k, v in self.programs.items()}}

    def render(self, opaque_id: str | None = None) -> dict:
        out = {"trace_id": self.trace_id,
               "phases": {k: round(v, 3) for k, v in self.phases.items()},
               "shards": [{"id": f"[{s['index']}][{s['shard_id']}]", **s}
                          for s in self.shards],
               "device": self.device_section()}
        if opaque_id is not None:
            out["x_opaque_id"] = opaque_id
        return out


def _threshold_ms(settings, level: str,
                  kind: str = "search.slowlog.threshold.query") -> float | None:
    """index.<kind>.<level> -> ms (live: read per request, so a settings
    update applies immediately)."""
    for key in (f"index.{kind}.{level}", f"{kind}.{level}"):
        v = settings.get(key)
        if v is not None:
            from ..mapping.mapper import parse_ttl_ms
            try:
                return float(parse_ttl_ms(v))
            except Exception:  # noqa: BLE001
                return None
    return None


class SlowLog:
    """Query slowlog: threshold-gated log lines + a bounded in-memory tail
    (the reference writes log files; the tail makes it assertable and
    REST-visible). Subclasses set KIND (the settings-key prefix) and
    PAYLOAD_FIELD (what the log line carries)."""

    KIND = "search.slowlog.threshold.query"
    PAYLOAD_FIELD = "source"
    LOGGER_NAME = "elasticsearch_tpu.index.search.slowlog.query"

    def __init__(self, maxlen: int = 128):
        self.logger = logging.getLogger(self.LOGGER_NAME)
        self.tail: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def snapshot(self) -> list:
        """Race-free copy for REST rendering (the HTTP server is threaded
        and searches append concurrently)."""
        with self._lock:
            return list(self.tail)

    def maybe_log(self, settings, index: str, took_ms: float,
                  body, trace_id: str | None = None,
                  opaque_id: str | None = None) -> str | None:
        """Returns the level logged at, or None. trace_id/opaque_id stamp
        the tail entry so a slow request correlates with its task listing
        and profile output (the X-Opaque-Id contract)."""
        for level, log_fn in (("warn", self.logger.warning),
                              ("info", self.logger.info),
                              ("debug", self.logger.debug),
                              ("trace", self.logger.debug)):
            thr = _threshold_ms(settings, level, kind=self.KIND)
            if thr is not None and took_ms >= thr:
                import json
                payload = json.dumps(body)[:512] \
                    if isinstance(body, (dict, list)) else str(body)[:128]
                entry = {"level": level, "index": index,
                         "took_millis": round(took_ms, 2),
                         self.PAYLOAD_FIELD: payload}
                if trace_id is not None:
                    entry["trace_id"] = trace_id
                if opaque_id is not None:
                    entry["x_opaque_id"] = opaque_id
                with self._lock:
                    self.tail.append(entry)
                log_fn("[%s] took[%sms], %s[%s]", index,
                       entry["took_millis"], self.PAYLOAD_FIELD, payload)
                return level
        return None


class IndexingSlowLog(SlowLog):
    """Indexing slowlog (ref index/indexing/slowlog/
    ShardSlowLogIndexingService.java — index.indexing.slowlog.threshold.
    index.<level> thresholds applied per write)."""

    KIND = "indexing.slowlog.threshold.index"
    PAYLOAD_FIELD = "id"
    LOGGER_NAME = "elasticsearch_tpu.index.indexing.slowlog.index"


# ---------------------------------------------------------------------------
# OpenMetrics exposition (`GET /_metrics`): every stats registry renders as
# one scrapeable text document. The walk is generic over *sections* — a
# section is either a flat dict of leaves or a {entry: leaves} registry
# labeled by pool/breaker/timer/index/... — so a NEW registry joins the
# scrape by adding one entry to NodeService.metric_sections(), and the
# strict-parser test fails if a stats source forgets to.
# ---------------------------------------------------------------------------

# leaf keys that are MONOTONE counters in the existing stats dicts (the
# scrape renames them to the OpenMetrics `_total` convention); any curated
# leaf already ending in `_total` is a counter by construction
_COUNTER_LEAVES = frozenset({
    "count", "completed", "rejected", "tripped", "time_in_millis",
    "batches", "batched_requests", "compiles", "total_started",
    "index_total", "delete_total", "query_total", "collection_count",
    "collected",
})

_NAME_SANITIZE = _re.compile(r"[^a-zA-Z0-9_]")


def _metric_leaf(key: str) -> tuple[str, str]:
    """(leaf name, type): byte/milli renames + counter `_total` suffixing."""
    leaf = key
    if leaf.endswith("_in_bytes"):
        leaf = leaf[: -len("_in_bytes")] + "_bytes"
    if leaf.endswith("_in_millis"):
        leaf = leaf[: -len("_in_millis")] + "_millis"
    if leaf == "total_started":
        leaf = "started"          # -> *_started_total, not *_total_started_*
    if key == "total":
        # a leaf literally named "total" is a counter whose family name
        # already carries the suffix (es_search_hedged_total{outcome=})
        return "total", "counter"
    if key in _COUNTER_LEAVES or key.endswith("_total") \
            or key.endswith("time_in_millis"):
        if not leaf.endswith("_total"):
            leaf += "_total"
        return leaf, "counter"
    return leaf, "gauge"


class _Family:
    __slots__ = ("name", "mtype", "help", "samples")

    def __init__(self, name: str, mtype: str, help_text: str):
        self.name = name
        self.mtype = mtype
        self.help = help_text
        self.samples: list[tuple[dict, float]] = []


def _flatten(prefix: str, payload: dict, out: list) -> None:
    for k, v in payload.items():
        key = f"{prefix}_{k}" if prefix else str(k)
        if isinstance(v, dict):
            _flatten(key, v, out)
        elif isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        elif v == float("inf") or v != v:
            continue
        else:
            out.append((key, v))


def openmetrics_families(sections: dict, node: str,
                         families: dict | None = None) -> dict:
    """sections: {section: (label_name | None, payload)}. Labeled payloads
    are registries ({entry: {leaf: num}}); unlabeled ones flatten directly.
    Merging several nodes into one `families` dict is the cluster fan-out
    (`/_cluster/_metrics`) — same family, one sample per node."""
    fams = families if families is not None else {}

    def emit(section, labels, key, value):
        leaf, mtype = _metric_leaf(key)
        name = _NAME_SANITIZE.sub("_", f"es_{section}_{leaf}")
        fam = fams.get(name)
        if fam is None:
            fam = fams[name] = _Family(
                name, mtype, f"{section} {key} ({mtype})")
        elif fam.mtype != mtype:
            raise ValueError(
                f"metric family [{name}] registered as {fam.mtype} "
                f"and {mtype}")
        fam.samples.append((labels, float(value)))

    for section, (label_name, payload) in sections.items():
        if not isinstance(payload, dict):
            continue
        if label_name is None:
            leaves: list = []
            _flatten("", payload, leaves)
            for key, v in leaves:
                emit(section, {"node": node}, key, v)
        else:
            for entry, sub in payload.items():
                if not isinstance(sub, dict):
                    continue
                leaves = []
                _flatten("", sub, leaves)
                if isinstance(label_name, tuple):
                    # multi-label registry: entry keys are value tuples
                    # aligned with the label-name tuple
                    # (es_search_lane_decisions_total{lane=,reason=})
                    labels = {"node": node,
                              **{ln: str(lv) for ln, lv in
                                 zip(label_name, entry)}}
                else:
                    labels = {"node": node, label_name: str(entry)}
                for key, v in leaves:
                    emit(section, labels, key, v)
    return fams


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def render_families(families: dict, comments: list[str] | None = None) -> str:
    out: list[str] = []
    for name in sorted(families):
        fam = families[name]
        out.append(f"# HELP {name} {fam.help}\n")
        out.append(f"# TYPE {name} {fam.mtype}\n")
        for labels, value in fam.samples:
            lbl = ",".join(f'{k}="{_escape_label(str(v))}"'
                           for k, v in sorted(labels.items()))
            out.append(f"{name}{{{lbl}}} {_fmt_value(value)}\n")
    for c in comments or ():
        out.append(f"# {c}\n")
    out.append("# EOF\n")
    return "".join(out)


def render_openmetrics(sections: dict, node: str = "tpu-node-0") -> str:
    """One node's full exposition: `GET /_metrics`."""
    return render_families(openmetrics_families(sections, node))
