"""Per-phase search timers + search slowlog.

The observability floor (SURVEY §5.1/§5.5; VERDICT r4 #10):
  * PhaseTimers — parse / device(query) / fetch / render wall-time
    accumulators, surfaced through `_nodes/stats` and `_stats`. This is
    the TPU analog of the reference's per-phase stats (SearchStats
    queryTime/fetchTime) — here the interesting split is host parse vs
    device program vs response render, because host overhead is where
    TPU serving loses its speedup.
  * SlowLog — per-index query slowlog with live-updatable thresholds
    (ref index/search/slowlog/ShardSlowLogSearchService.java: warn/info/
    debug/trace thresholds from index settings, applied per request).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque


class PhaseTimers:
    """Lock-cheap accumulators: {phase: (count, total_ms, max_ms)}."""

    PHASES = ("parse", "device", "fetch", "render", "total")

    def __init__(self):
        self._lock = threading.Lock()
        self._acc: dict[str, list] = {p: [0, 0.0, 0.0] for p in self.PHASES}

    def record(self, phase: str, ms: float) -> None:
        with self._lock:
            a = self._acc.setdefault(phase, [0, 0.0, 0.0])
            a[0] += 1
            a[1] += ms
            a[2] = max(a[2], ms)

    def stats(self) -> dict:
        with self._lock:
            return {p: {"count": a[0],
                        "time_in_millis": round(a[1], 3),
                        "max_millis": round(a[2], 3)}
                    for p, a in self._acc.items() if a[0]}


def _threshold_ms(settings, level: str,
                  kind: str = "search.slowlog.threshold.query") -> float | None:
    """index.<kind>.<level> -> ms (live: read per request, so a settings
    update applies immediately)."""
    for key in (f"index.{kind}.{level}", f"{kind}.{level}"):
        v = settings.get(key)
        if v is not None:
            from ..mapping.mapper import parse_ttl_ms
            try:
                return float(parse_ttl_ms(v))
            except Exception:  # noqa: BLE001
                return None
    return None


class SlowLog:
    """Query slowlog: threshold-gated log lines + a bounded in-memory tail
    (the reference writes log files; the tail makes it assertable and
    REST-visible). Subclasses set KIND (the settings-key prefix) and
    PAYLOAD_FIELD (what the log line carries)."""

    KIND = "search.slowlog.threshold.query"
    PAYLOAD_FIELD = "source"
    LOGGER_NAME = "elasticsearch_tpu.index.search.slowlog.query"

    def __init__(self, maxlen: int = 128):
        self.logger = logging.getLogger(self.LOGGER_NAME)
        self.tail: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def snapshot(self) -> list:
        """Race-free copy for REST rendering (the HTTP server is threaded
        and searches append concurrently)."""
        with self._lock:
            return list(self.tail)

    def maybe_log(self, settings, index: str, took_ms: float,
                  body) -> str | None:
        """Returns the level logged at, or None."""
        for level, log_fn in (("warn", self.logger.warning),
                              ("info", self.logger.info),
                              ("debug", self.logger.debug),
                              ("trace", self.logger.debug)):
            thr = _threshold_ms(settings, level, kind=self.KIND)
            if thr is not None and took_ms >= thr:
                import json
                payload = json.dumps(body)[:512] \
                    if isinstance(body, (dict, list)) else str(body)[:128]
                entry = {"level": level, "index": index,
                         "took_millis": round(took_ms, 2),
                         self.PAYLOAD_FIELD: payload}
                with self._lock:
                    self.tail.append(entry)
                log_fn("[%s] took[%sms], %s[%s]", index,
                       entry["took_millis"], self.PAYLOAD_FIELD, payload)
                return level
        return None


class IndexingSlowLog(SlowLog):
    """Indexing slowlog (ref index/indexing/slowlog/
    ShardSlowLogIndexingService.java — index.indexing.slowlog.threshold.
    index.<level> thresholds applied per write)."""

    KIND = "indexing.slowlog.threshold.index"
    PAYLOAD_FIELD = "id"
    LOGGER_NAME = "elasticsearch_tpu.index.indexing.slowlog.index"
