"""Per-phase search timers, histogram metrics, request profiling + slowlog.

The observability floor (SURVEY §5.1/§5.5; VERDICT r4 #10):
  * PhaseTimers — parse / device(query) / fetch / render wall-time
    accumulators, surfaced through `_nodes/stats` and `_stats`. This is
    the TPU analog of the reference's per-phase stats (SearchStats
    queryTime/fetchTime) — here the interesting split is host parse vs
    device program vs response render, because host overhead is where
    TPU serving loses its speedup.
  * MetricsRegistry — histogram-capable named timers (count/sum/min/max/
    p50/p99 from a bounded reservoir), the `profiling` section of
    `_nodes/stats`.
  * RequestProfiler — the per-request timing tree behind `"profile": true`
    on `_search` (ref search/profile/ Profilers + InternalProfiler in
    later reference versions). The TPU twist the reference never had: jit
    retraces and host↔device transfers silently dominate tail latency, so
    the profiler also diffs process-wide compile events (jax.monitoring)
    and counts bytes crossing the device boundary per request.
  * SlowLog — per-index query slowlog with live-updatable thresholds
    (ref index/search/slowlog/ShardSlowLogSearchService.java: warn/info/
    debug/trace thresholds from index settings, applied per request),
    stamped with the request's trace/opaque ids so one id correlates the
    slowlog, the task listing and the profile output.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import threading
import time
import uuid
from collections import deque


class PhaseTimers:
    """Lock-cheap accumulators: {phase: (count, total_ms, max_ms)}."""

    PHASES = ("parse", "device", "fetch", "render", "total")

    def __init__(self):
        self._lock = threading.Lock()
        self._acc: dict[str, list] = {p: [0, 0.0, 0.0] for p in self.PHASES}

    def record(self, phase: str, ms: float) -> None:
        with self._lock:
            a = self._acc.setdefault(phase, [0, 0.0, 0.0])
            a[0] += 1
            a[1] += ms
            a[2] = max(a[2], ms)

    def stats(self) -> dict:
        with self._lock:
            return {p: {"count": a[0],
                        "time_in_millis": round(a[1], 3),
                        "max_millis": round(a[2], 3)}
                    for p, a in self._acc.items() if a[0]}


class MetricsRegistry:
    """Named wall-time histograms: count/sum/min/max plus p50/p99 computed
    from a bounded sample reservoir (the reference keeps count+sum only;
    tail percentiles are what a latency SLO actually needs)."""

    def __init__(self, reservoir: int = 512):
        self._lock = threading.Lock()
        self._reservoir = reservoir
        self._timers: dict[str, dict] = {}

    def record(self, name: str, ms: float) -> None:
        with self._lock:
            t = self._timers.get(name)
            if t is None:
                t = self._timers[name] = {
                    "count": 0, "sum": 0.0,
                    "min": float("inf"), "max": 0.0,
                    "samples": deque(maxlen=self._reservoir)}
            t["count"] += 1
            t["sum"] += ms
            t["min"] = min(t["min"], ms)
            t["max"] = max(t["max"], ms)
            t["samples"].append(ms)

    @contextlib.contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, (time.perf_counter() - t0) * 1000)

    def stats(self) -> dict:
        with self._lock:
            snap = {n: (t["count"], t["sum"], t["min"], t["max"],
                        sorted(t["samples"]))
                    for n, t in self._timers.items()}
        out = {}
        for name, (count, total, mn, mx, samples) in snap.items():
            entry = {"count": count,
                     "time_in_millis": round(total, 3),
                     "min_millis": round(mn, 3),
                     "max_millis": round(mx, 3)}
            if samples:
                entry["p50_millis"] = round(
                    samples[len(samples) // 2], 3)
                entry["p99_millis"] = round(
                    samples[min(len(samples) - 1,
                                int(len(samples) * 0.99))], 3)
            out[name] = entry
        return out


# ---------------------------------------------------------------------------
# Device-level counters: jit compiles (retraces) via jax.monitoring, bytes
# crossing the host↔device boundary via the device_fetch/note_h2d seams.
# Process-wide accumulators; RequestProfiler diffs them around a request.
# ---------------------------------------------------------------------------

_DEVICE_EVENTS = {"compiles": 0, "compile_ms": 0.0}
_DEVICE_LOCK = threading.Lock()
_LISTENER_INSTALLED = False


def _install_compile_listener() -> None:
    """Register a jax.monitoring duration listener (idempotent). Compile
    events fire only on an actual retrace+compile, never on a cache-hit
    dispatch — exactly the signal the no-retrace tripwire needs. Degrades
    to zeros on jax builds without the monitoring API."""
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return
    _LISTENER_INSTALLED = True
    try:
        import jax

        def _on_duration(name, secs, **kw):  # noqa: ANN001 — jax callback
            if "/jax/core/compile/" not in name:
                return
            with _DEVICE_LOCK:
                if name.endswith("backend_compile_duration"):
                    _DEVICE_EVENTS["compiles"] += 1
                _DEVICE_EVENTS["compile_ms"] += secs * 1000.0

        jax.monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:  # noqa: BLE001 — observability must never break serving
        pass


def device_events_snapshot() -> tuple[int, float]:
    with _DEVICE_LOCK:
        return _DEVICE_EVENTS["compiles"], _DEVICE_EVENTS["compile_ms"]


def _nbytes(x) -> int:
    if isinstance(x, dict):
        return sum(_nbytes(v) for v in x.values())
    if isinstance(x, (list, tuple)):
        return sum(_nbytes(v) for v in x)
    return int(getattr(x, "nbytes", 0))


def device_fetch(x):
    """jax.device_get with per-request accounting: when a profiler is
    active, the fetch counts as one device round-trip and its payload as
    device→host bytes. The hot paths call this INSTEAD of jax.device_get,
    so `"profile": true` sees every transfer without touching the kernels."""
    import jax
    out = jax.device_get(x)
    prof = _PROFILER.get()
    if prof is not None:
        prof.note_dispatch()
        prof.note_d2h(_nbytes(out))
    return out


_PROFILER: contextvars.ContextVar["RequestProfiler | None"] = \
    contextvars.ContextVar("es_request_profiler", default=None)


def current_profiler() -> "RequestProfiler | None":
    return _PROFILER.get()


@contextlib.contextmanager
def use_profiler(prof: "RequestProfiler"):
    tok = _PROFILER.set(prof)
    try:
        yield prof
    finally:
        _PROFILER.reset(tok)


class RequestProfiler:
    """Per-request timing tree: coordinator phases, per-shard query
    execution with per-DSL-node score/match wall time (non-jit-visible
    timers around the jitted calls — query_dsl.Node instruments itself
    against the active profiler), plus the device section (jit cache
    hit/miss, compile time when a retrace fired, host↔device bytes)."""

    def __init__(self, trace_id: str | None = None):
        _install_compile_listener()
        self.trace_id = trace_id or uuid.uuid4().hex[:16]
        self.phases: dict[str, float] = {}
        self.shards: list[dict] = []
        self._shard_stack: list[dict] = []
        self._lock = threading.Lock()
        self.dispatches = 0
        self.d2h_bytes = 0
        self.h2d_bytes = 0
        self._jit0 = device_events_snapshot()

    # -- coordinator phases ------------------------------------------------

    def record_phase(self, name: str, ms: float) -> None:
        with self._lock:
            self.phases[name] = self.phases.get(name, 0.0) + ms

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record_phase(name, (time.perf_counter() - t0) * 1000)

    # -- per-shard tree ----------------------------------------------------

    @contextlib.contextmanager
    def shard(self, index: str, shard_id: int):
        entry = {"index": index, "shard_id": shard_id,
                 "time_in_millis": 0.0, "query": {}}
        with self._lock:
            self.shards.append(entry)
            self._shard_stack.append(entry)
        t0 = time.perf_counter()
        try:
            yield entry
        finally:
            entry["time_in_millis"] = round(
                (time.perf_counter() - t0) * 1000, 3)
            with self._lock:
                self._shard_stack.pop()

    def record_node(self, node_type: str, op: str, ms: float) -> None:
        """One DSL-node execution (op: score|match) — aggregated per node
        type inside the current shard, or under a synthetic 'coordinator'
        shard when node execution happens outside a shard scope."""
        with self._lock:
            if self._shard_stack:
                tree = self._shard_stack[-1]["query"]
            else:
                if not self.shards or self.shards[-1].get("index") != "_coordinator":
                    self.shards.append({"index": "_coordinator",
                                        "shard_id": -1,
                                        "time_in_millis": 0.0, "query": {}})
                tree = self.shards[-1]["query"]
            b = tree.setdefault(node_type, {
                "score_count": 0, "score_time_in_millis": 0.0,
                "match_count": 0, "match_time_in_millis": 0.0})
            b[f"{op}_count"] += 1
            b[f"{op}_time_in_millis"] = round(
                b[f"{op}_time_in_millis"] + ms, 3)

    # -- device counters ---------------------------------------------------

    def note_dispatch(self, n: int = 1) -> None:
        with self._lock:
            self.dispatches += n

    def note_d2h(self, nbytes: int) -> None:
        with self._lock:
            self.d2h_bytes += int(nbytes)

    def note_h2d(self, nbytes: int) -> None:
        with self._lock:
            self.h2d_bytes += int(nbytes)

    def device_section(self) -> dict:
        compiles, compile_ms = device_events_snapshot()
        misses = compiles - self._jit0[0]
        return {"jit_cache_misses": misses,
                "jit_cache_hits": max(self.dispatches - misses, 0),
                "compile_time_in_millis": round(
                    compile_ms - self._jit0[1], 3),
                "bytes_device_to_host": self.d2h_bytes,
                "bytes_host_to_device": self.h2d_bytes}

    def render(self, opaque_id: str | None = None) -> dict:
        out = {"trace_id": self.trace_id,
               "phases": {k: round(v, 3) for k, v in self.phases.items()},
               "shards": [{"id": f"[{s['index']}][{s['shard_id']}]", **s}
                          for s in self.shards],
               "device": self.device_section()}
        if opaque_id is not None:
            out["x_opaque_id"] = opaque_id
        return out


def _threshold_ms(settings, level: str,
                  kind: str = "search.slowlog.threshold.query") -> float | None:
    """index.<kind>.<level> -> ms (live: read per request, so a settings
    update applies immediately)."""
    for key in (f"index.{kind}.{level}", f"{kind}.{level}"):
        v = settings.get(key)
        if v is not None:
            from ..mapping.mapper import parse_ttl_ms
            try:
                return float(parse_ttl_ms(v))
            except Exception:  # noqa: BLE001
                return None
    return None


class SlowLog:
    """Query slowlog: threshold-gated log lines + a bounded in-memory tail
    (the reference writes log files; the tail makes it assertable and
    REST-visible). Subclasses set KIND (the settings-key prefix) and
    PAYLOAD_FIELD (what the log line carries)."""

    KIND = "search.slowlog.threshold.query"
    PAYLOAD_FIELD = "source"
    LOGGER_NAME = "elasticsearch_tpu.index.search.slowlog.query"

    def __init__(self, maxlen: int = 128):
        self.logger = logging.getLogger(self.LOGGER_NAME)
        self.tail: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def snapshot(self) -> list:
        """Race-free copy for REST rendering (the HTTP server is threaded
        and searches append concurrently)."""
        with self._lock:
            return list(self.tail)

    def maybe_log(self, settings, index: str, took_ms: float,
                  body, trace_id: str | None = None,
                  opaque_id: str | None = None) -> str | None:
        """Returns the level logged at, or None. trace_id/opaque_id stamp
        the tail entry so a slow request correlates with its task listing
        and profile output (the X-Opaque-Id contract)."""
        for level, log_fn in (("warn", self.logger.warning),
                              ("info", self.logger.info),
                              ("debug", self.logger.debug),
                              ("trace", self.logger.debug)):
            thr = _threshold_ms(settings, level, kind=self.KIND)
            if thr is not None and took_ms >= thr:
                import json
                payload = json.dumps(body)[:512] \
                    if isinstance(body, (dict, list)) else str(body)[:128]
                entry = {"level": level, "index": index,
                         "took_millis": round(took_ms, 2),
                         self.PAYLOAD_FIELD: payload}
                if trace_id is not None:
                    entry["trace_id"] = trace_id
                if opaque_id is not None:
                    entry["x_opaque_id"] = opaque_id
                with self._lock:
                    self.tail.append(entry)
                log_fn("[%s] took[%sms], %s[%s]", index,
                       entry["took_millis"], self.PAYLOAD_FIELD, payload)
                return level
        return None


class IndexingSlowLog(SlowLog):
    """Indexing slowlog (ref index/indexing/slowlog/
    ShardSlowLogIndexingService.java — index.indexing.slowlog.threshold.
    index.<level> thresholds applied per write)."""

    KIND = "indexing.slowlog.threshold.index"
    PAYLOAD_FIELD = "id"
    LOGGER_NAME = "elasticsearch_tpu.index.indexing.slowlog.index"
