"""Distributed task management: every coordinator and shard-level action
registers here with an id, parent task id, action name, start time and
description.

Analog of the reference's TaskManager + ListTasksAction surface
(tasks/TaskManager, rest/action/admin/cluster/node/tasks — `GET /_tasks`,
`GET /_tasks/{id}`, `GET /_cat/tasks` in later reference versions). Parent
linkage crosses the cluster transport as a `_task` header on the shard
messages (cluster/node.py), so a shard task on a remote copy-holder shows
its coordinator as parent — the reference's TaskId(nodeId, id) wire header.

Trace propagation rides the same context: each task carries the request's
generated trace id plus the caller-supplied `X-Opaque-Id`, and child scopes
inherit both. One id then correlates the task listing, the slowlog tail and
the profile output.
"""

from __future__ import annotations

import contextlib
import contextvars
import re
import threading
import time
import uuid
from collections import deque

_CURRENT: contextvars.ContextVar["Task | None"] = \
    contextvars.ContextVar("es_current_task", default=None)


def current_task() -> "Task | None":
    return _CURRENT.get()


class Task:
    __slots__ = ("id", "node", "seq", "action", "description",
                 "parent_task_id", "start_time_ms", "_start_mono",
                 "opaque_id", "trace_id")

    def __init__(self, node: str, seq: int, action: str, description: str,
                 parent_task_id: str | None, opaque_id: str | None,
                 trace_id: str):
        self.node = node
        self.seq = seq
        self.id = f"{node}:{seq}"
        self.action = action
        self.description = description
        self.parent_task_id = parent_task_id
        self.start_time_ms = int(time.time() * 1000)
        self._start_mono = time.monotonic()
        self.opaque_id = opaque_id
        self.trace_id = trace_id

    def running_time_ns(self) -> int:
        return int((time.monotonic() - self._start_mono) * 1e9)

    def info(self, detailed: bool = False) -> dict:
        out = {"node": self.node, "id": self.seq, "type": "transport",
               "action": self.action,
               "start_time_in_millis": self.start_time_ms,
               "running_time_in_nanos": self.running_time_ns(),
               "cancellable": False,
               "headers": {}}
        if self.parent_task_id is not None:
            out["parent_task_id"] = self.parent_task_id
        if self.opaque_id is not None:
            out["headers"]["X-Opaque-Id"] = self.opaque_id
        out["headers"]["trace_id"] = self.trace_id
        if detailed:
            out["description"] = self.description
        return out


class TaskManager:
    """Node-level registry of in-flight actions. Registration is a dict
    insert under a lock — cheap enough to wrap every request AND every
    per-shard phase. A bounded ring of recently-completed task infos keeps
    short-lived tasks assertable (the reference's tasks are observable via
    the list API only while running; the ring is this repo's test seam,
    exposed under `GET /_tasks?recent=true`)."""

    def __init__(self, node_id: str, recent: int = 128):
        self.node_id = node_id
        self._lock = threading.Lock()
        self._seq = 0
        self._tasks: dict[str, Task] = {}
        self.total_started = 0
        self._recent: deque = deque(maxlen=recent)

    def register(self, action: str, description: str = "",
                 parent_task_id: str | None = None,
                 opaque_id: str | None = None,
                 trace_id: str | None = None) -> Task:
        with self._lock:
            self._seq += 1
            self.total_started += 1
            task = Task(self.node_id, self._seq, action, description,
                        parent_task_id, opaque_id,
                        trace_id or uuid.uuid4().hex[:16])
            self._tasks[task.id] = task
            return task

    def unregister(self, task: Task) -> None:
        with self._lock:
            self._tasks.pop(task.id, None)
            self._recent.append(task.info(detailed=True))

    @contextlib.contextmanager
    def scope(self, action: str, description: str = "",
              parent_task_id: str | None = None,
              opaque_id: str | None = None,
              trace_id: str | None = None):
        """Register a task for the duration of the block and make it the
        current task — children opened inside inherit parent/trace/opaque
        automatically (coordinator → shard linkage without plumbing)."""
        cur = _CURRENT.get()
        if cur is not None:
            if parent_task_id is None:
                parent_task_id = cur.id
            if opaque_id is None:
                opaque_id = cur.opaque_id
            if trace_id is None:
                trace_id = cur.trace_id
        task = self.register(action, description, parent_task_id,
                             opaque_id, trace_id)
        tok = _CURRENT.set(task)
        try:
            yield task
        finally:
            _CURRENT.reset(tok)
            self.unregister(task)

    # -- listing (the GET /_tasks wire shape) ------------------------------

    @staticmethod
    def _action_match(action: str, patterns: list[str] | None) -> bool:
        """ES simple-match: ONLY `*` is a wildcard — action names contain
        `[`/`]` (phase suffixes), which fnmatch would read as char classes."""
        if not patterns:
            return True
        return any(
            re.fullmatch(".*".join(re.escape(part)
                                   for part in p.split("*")), action)
            for p in patterns)

    def task_infos(self, actions: str | None = None,
                   detailed: bool = False) -> dict[str, dict]:
        patterns = [p for p in str(actions).split(",") if p] \
            if actions else None
        with self._lock:
            tasks = list(self._tasks.values())
        return {t.id: t.info(detailed)
                for t in tasks if self._action_match(t.action, patterns)}

    def list_tasks(self, actions: str | None = None,
                   detailed: bool = False) -> dict:
        return {"nodes": {self.node_id: {
            "name": self.node_id,
            "transport_address": "local[1]",
            "tasks": self.task_infos(actions, detailed)}}}

    def get(self, task_id: str) -> Task | None:
        with self._lock:
            return self._tasks.get(task_id)

    def recent_infos(self, actions: str | None = None) -> list[dict]:
        patterns = [p for p in str(actions).split(",") if p] \
            if actions else None
        with self._lock:
            recent = list(self._recent)
        return [i for i in recent
                if self._action_match(i["action"], patterns)]

    def stats(self) -> dict:
        with self._lock:
            return {"running": len(self._tasks),
                    "total_started": self.total_started}
