"""Immutable layered settings.

TPU-native analog of the reference settings system
(/root/reference/src/main/java/org/elasticsearch/common/settings/ImmutableSettings.java,
node/internal/InternalSettingsPreparer.java): flat dot-path keys over nested
dicts, typed getters with units (bytes, time), env/sysprop-style overlays, and
a builder for merging layers (file < env < API), per SURVEY.md §5.6.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Iterator, Mapping

_TIME_UNITS = {
    "nanos": 1e-9, "micros": 1e-6, "ms": 1e-3, "s": 1.0,
    "m": 60.0, "h": 3600.0, "d": 86400.0, "w": 604800.0,
}
_BYTE_UNITS = {
    "b": 1, "kb": 1 << 10, "k": 1 << 10, "mb": 1 << 20, "m": 1 << 20,
    "gb": 1 << 30, "g": 1 << 30, "tb": 1 << 40, "t": 1 << 40,
    "pb": 1 << 50, "p": 1 << 50,
}
_UNIT_RE = re.compile(r"^\s*(-?\d+(?:\.\d+)?)\s*([a-zA-Z%]*)\s*$")


def _flatten(prefix: str, obj: Any, out: dict[str, Any]) -> None:
    if isinstance(obj, Mapping):
        for k, v in obj.items():
            key = f"{prefix}{k}"
            if isinstance(v, Mapping):
                _flatten(key + ".", v, out)
            else:
                out[key] = v
    else:
        out[prefix.rstrip(".")] = obj


class Settings(Mapping[str, Any]):
    """Immutable flat-key settings map with typed accessors."""

    def __init__(self, data: Mapping[str, Any] | None = None):
        flat: dict[str, Any] = {}
        if data:
            _flatten("", dict(data), flat)
        self._map: dict[str, Any] = flat

    # -- Mapping protocol -------------------------------------------------
    def __getitem__(self, key: str) -> Any:
        return self._map[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._map)

    def __len__(self) -> int:
        return len(self._map)

    def __repr__(self) -> str:
        return f"Settings({self._map!r})"

    # -- typed getters ----------------------------------------------------
    def get(self, key: str, default: Any = None) -> Any:
        return self._map.get(key, default)

    def get_str(self, key: str, default: str | None = None) -> str | None:
        v = self._map.get(key)
        return default if v is None else str(v)

    def get_int(self, key: str, default: int | None = None) -> int | None:
        v = self._map.get(key)
        return default if v is None else int(v)

    def get_float(self, key: str, default: float | None = None) -> float | None:
        v = self._map.get(key)
        return default if v is None else float(v)

    def get_bool(self, key: str, default: bool | None = None) -> bool | None:
        v = self._map.get(key)
        if v is None:
            return default
        if isinstance(v, bool):
            return v
        return str(v).lower() in ("true", "1", "on", "yes")

    def get_time(self, key: str, default: float | None = None) -> float | None:
        """Parse a time value ('30s', '5m', '100ms') into seconds."""
        v = self._map.get(key)
        if v is None:
            return default
        if isinstance(v, (int, float)):
            return float(v) / 1000.0  # bare numbers are millis, like the reference
        m = _UNIT_RE.match(str(v))
        if not m:
            raise ValueError(f"cannot parse time value [{v}] for [{key}]")
        num, unit = float(m.group(1)), m.group(2).lower()
        if unit not in _TIME_UNITS:
            raise ValueError(f"unknown time unit [{unit}] for [{key}]")
        return num * _TIME_UNITS[unit]

    def get_bytes(self, key: str, default: int | None = None) -> int | None:
        """Parse a byte-size value ('512mb', '10%s of nothing' not supported)."""
        v = self._map.get(key)
        if v is None:
            return default
        if isinstance(v, (int, float)):
            return int(v)
        m = _UNIT_RE.match(str(v))
        if not m:
            raise ValueError(f"cannot parse byte size [{v}] for [{key}]")
        num, unit = float(m.group(1)), m.group(2).lower()
        if unit == "":
            return int(num)
        if unit not in _BYTE_UNITS:
            raise ValueError(f"unknown byte unit [{unit}] for [{key}]")
        return int(num * _BYTE_UNITS[unit])

    def get_list(self, key: str, default: list | None = None) -> list | None:
        v = self._map.get(key)
        if v is None:
            # array-style flat keys: key.0, key.1, ...
            idx = []
            for k, val in self._map.items():
                m = re.match(re.escape(key) + r"\.(\d+)$", k)
                if m:
                    idx.append((int(m.group(1)), val))
            if idx:
                return [val for _, val in sorted(idx)]
            return default
        if isinstance(v, (list, tuple)):
            return list(v)
        return [s.strip() for s in str(v).split(",") if s.strip()]

    def by_prefix(self, prefix: str) -> "Settings":
        """Sub-settings with `prefix` stripped (reference getByPrefix)."""
        s = Settings()
        s._map = {k[len(prefix):]: v for k, v in self._map.items() if k.startswith(prefix)}
        return s

    def as_dict(self) -> dict[str, Any]:
        return dict(self._map)

    def as_nested(self) -> dict[str, Any]:
        """Re-nest flat keys into a tree (for JSON rendering)."""
        root: dict[str, Any] = {}
        for k, v in sorted(self._map.items()):
            parts = k.split(".")
            node = root
            ok = True
            for p in parts[:-1]:
                nxt = node.setdefault(p, {})
                if not isinstance(nxt, dict):
                    ok = False
                    break
                node = nxt
            if ok:
                node[parts[-1]] = v
            else:
                root[k] = v
        return root

    # -- builder ----------------------------------------------------------
    def merged(self, *overlays: "Settings | Mapping[str, Any] | None") -> "Settings":
        out = dict(self._map)
        for o in overlays:
            if o is None:
                continue
            o = o if isinstance(o, Settings) else Settings(o)
            out.update(o._map)
        s = Settings()
        s._map = out
        return s

    @staticmethod
    def from_env(env: Mapping[str, str] | None = None, prefix: str = "ES_TPU_") -> "Settings":
        """Overlay from environment variables: ES_TPU_FOO_BAR -> foo.bar
        (analog of the reference's -Des.* sysprop merge)."""
        env = os.environ if env is None else env
        out = {}
        for k, v in env.items():
            if k.startswith(prefix):
                out[k[len(prefix):].lower().replace("__", "-").replace("_", ".")] = v
        s = Settings()
        s._map = out
        return s

    @staticmethod
    def from_json(text: str) -> "Settings":
        return Settings(json.loads(text))


EMPTY = Settings()
