"""ResourceWatcherService: mtime-based file/dir change notification.

The analog of /root/reference/src/main/java/org/elasticsearch/watcher/
(ResourceWatcherService.java — registered watchers checked on an interval;
FileWatcher + FileChangesListener onFileCreated/Changed/Deleted). The
reference drives file-script hot reload with this; here NodeService points
it at a `scripts/` dir for the same effect.
"""

from __future__ import annotations

import os
import threading


class FileWatcher:
    """Watches one directory (non-recursive): detects created / changed /
    deleted files between check() calls."""

    def __init__(self, path: str, listener):
        self.path = path
        self.listener = listener       # on_file_created/changed/deleted(p)
        self._seen: dict[str, float] = {}
        self._init_done = False

    def check(self) -> None:
        try:
            entries = {os.path.join(self.path, f): os.path.getmtime(
                os.path.join(self.path, f))
                for f in os.listdir(self.path)
                if os.path.isfile(os.path.join(self.path, f))}
        except OSError:
            entries = {}
        if not self._init_done:
            # first pass primes state AND reports existing files as created
            for p in sorted(entries):
                self.listener.on_file_created(p)
            self._seen = entries
            self._init_done = True
            return
        for p in sorted(entries):
            if p not in self._seen:
                self.listener.on_file_created(p)
            elif entries[p] != self._seen[p]:
                self.listener.on_file_changed(p)
        for p in sorted(set(self._seen) - set(entries)):
            self.listener.on_file_deleted(p)
        self._seen = entries


class ResourceWatcherService:
    """Registry + optional interval thread (ref ResourceWatcherService
    HIGH/MEDIUM/LOW frequencies; one cadence suffices here)."""

    def __init__(self, interval_s: float = 5.0):
        self.interval_s = interval_s
        self._watchers: list[FileWatcher] = []
        self._lock = threading.Lock()
        self._stop: threading.Event | None = None
        self._thread: threading.Thread | None = None

    def add(self, watcher: FileWatcher) -> FileWatcher:
        with self._lock:
            self._watchers.append(watcher)
        watcher.check()                 # prime immediately, like the ref
        return watcher

    def check_now(self) -> None:
        with self._lock:
            watchers = list(self._watchers)
        for w in watchers:
            w.check()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop = threading.Event()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.check_now()
                except Exception:  # noqa: BLE001 — keep watching
                    pass
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="es[resource_watcher]")
        self._thread.start()

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        self._thread = None
