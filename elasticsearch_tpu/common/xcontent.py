"""XContent: pluggable request/response body formats.

The analog of /root/reference/src/main/java/org/elasticsearch/common/
xcontent/ (XContentType.java — JSON, SMILE, YAML, CBOR with auto-detection
from bytes/Content-Type; every REST body decodes through one seam).

JSON is native. YAML rides PyYAML (safe_load). CBOR is a self-contained
RFC 7049 codec below (major types 0-7, the subset JSON-shaped documents
need). SMILE is not implemented — callers get a clear 406 instead of a
guess (the reference's SMILE is a Jackson binary format with no Python
stdlib analog; CBOR covers the binary-body use case).
"""

from __future__ import annotations

import json
import struct
from typing import Any


def detect(content_type: str | None, body: bytes) -> str:
    """-> "json" | "yaml" | "cbor" (XContentType.fromMediaTypeOrFormat +
    the magic-byte sniff in XContentFactory.xContentType)."""
    ct = (content_type or "").lower()
    if "yaml" in ct:
        return "yaml"
    if "cbor" in ct:
        return "cbor"
    if "smile" in ct:
        raise ValueError("SMILE bodies are not supported; send JSON, "
                         "YAML, or CBOR")
    if "json" in ct:
        return "json"
    # sniff: CBOR maps start 0xA0-0xBF / 0xD9 tag; YAML docs "---"
    if body[:1] and 0xA0 <= body[0] <= 0xBF or body[:1] == b"\xd9":
        return "cbor"
    if body[:3] == b"---":
        return "yaml"
    return "json"


def decode(body: bytes, content_type: str | None = None) -> Any:
    fmt = detect(content_type, body)
    if fmt == "json":
        return json.loads(body)
    if fmt == "yaml":
        import yaml
        return yaml.safe_load(body)
    return cbor_loads(body)


def encode(obj: Any, fmt: str = "json") -> tuple[bytes, str]:
    """-> (payload bytes, content type)."""
    if fmt == "yaml":
        import yaml
        return (yaml.safe_dump(obj, default_flow_style=False,
                               sort_keys=False).encode("utf-8"),
                "application/yaml")
    if fmt == "cbor":
        return cbor_dumps(obj), "application/cbor"
    return (json.dumps(obj).encode("utf-8"),
            "application/json; charset=UTF-8")


# ---------------------------------------------------------------------------
# Minimal CBOR (RFC 7049): the JSON-shaped subset — ints, floats, strings,
# bytes, bools, null, arrays, maps
# ---------------------------------------------------------------------------

def _head(major: int, arg: int) -> bytes:
    if arg < 24:
        return bytes([(major << 5) | arg])
    if arg < 0x100:
        return bytes([(major << 5) | 24, arg])
    if arg < 0x10000:
        return bytes([(major << 5) | 25]) + struct.pack(">H", arg)
    if arg < 0x100000000:
        return bytes([(major << 5) | 26]) + struct.pack(">I", arg)
    return bytes([(major << 5) | 27]) + struct.pack(">Q", arg)


def cbor_dumps(obj: Any) -> bytes:
    out = bytearray()
    _enc(obj, out)
    return bytes(out)


def _enc(obj: Any, out: bytearray) -> None:
    if obj is None:
        out.append(0xF6)
    elif obj is True:
        out.append(0xF5)
    elif obj is False:
        out.append(0xF4)
    elif isinstance(obj, int):
        if obj >= 0:
            out += _head(0, obj)
        else:
            out += _head(1, -1 - obj)
    elif isinstance(obj, float):
        out.append(0xFB)
        out += struct.pack(">d", obj)
    elif isinstance(obj, bytes):
        out += _head(2, len(obj))
        out += obj
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        out += _head(3, len(b))
        out += b
    elif isinstance(obj, (list, tuple)):
        out += _head(4, len(obj))
        for v in obj:
            _enc(v, out)
    elif isinstance(obj, dict):
        out += _head(5, len(obj))
        for k, v in obj.items():
            _enc(str(k), out)
            _enc(v, out)
    else:
        raise TypeError(f"cannot CBOR-encode {type(obj).__name__}")


def cbor_loads(data: bytes) -> Any:
    obj, pos = _dec(data, 0)
    return obj


def _dec(data: bytes, pos: int) -> tuple[Any, int]:
    ib = data[pos]
    pos += 1
    major, info = ib >> 5, ib & 0x1F
    if major == 7:
        if info == 20:
            return False, pos
        if info == 21:
            return True, pos
        if info == 22 or info == 23:
            return None, pos
        if info == 26:
            return struct.unpack(">f", data[pos:pos + 4])[0], pos + 4
        if info == 27:
            return struct.unpack(">d", data[pos:pos + 8])[0], pos + 8
        raise ValueError(f"unsupported CBOR simple value {info}")
    if info < 24:
        arg = info
    elif info == 24:
        arg = data[pos]
        pos += 1
    elif info == 25:
        arg = struct.unpack(">H", data[pos:pos + 2])[0]
        pos += 2
    elif info == 26:
        arg = struct.unpack(">I", data[pos:pos + 4])[0]
        pos += 4
    elif info == 27:
        arg = struct.unpack(">Q", data[pos:pos + 8])[0]
        pos += 8
    else:
        raise ValueError(f"unsupported CBOR length encoding {info}")
    if major == 0:
        return arg, pos
    if major == 1:
        return -1 - arg, pos
    if major == 2:
        return data[pos:pos + arg], pos + arg
    if major == 3:
        return data[pos:pos + arg].decode("utf-8"), pos + arg
    if major == 4:
        out = []
        for _ in range(arg):
            v, pos = _dec(data, pos)
            out.append(v)
        return out, pos
    if major == 5:
        m = {}
        for _ in range(arg):
            k, pos = _dec(data, pos)
            v, pos = _dec(data, pos)
            m[k] = v
        return m, pos
    if major == 6:                       # tag: skip, decode the content
        return _dec(data, pos)
    raise ValueError(f"unsupported CBOR major type {major}")
