"""Generic byte-accounted LRU cache — the one cache core every cache uses.

Analog of the reference's common/cache/Cache (guava-style builder in
org.elasticsearch.common.cache: weigher, maximumWeight, expireAfter,
RemovalListener) — the shared substrate under IndicesRequestCache,
Lucene's LRUQueryCache and the fielddata cache. Here every node-level
cache (request responses, parsed query plans, fielddata columns, packed
serving views, geo-distance mirrors) is an instance of this class, so
eviction policy, byte accounting and hit/miss/eviction stats are uniform
and a new cache joins the `_nodes/stats` + `/_metrics` surfaces for free.

Design points:
  * thread-safe LRU over an OrderedDict (get promotes, evict pops oldest);
  * pluggable `weigher(value) -> bytes` + max-bytes / max-entries budgets;
  * optional TTL with an injectable clock (the Meter/StatsSampler pattern:
    tests drive exact expiry sequences with no sleeping);
  * removal listeners fire on every exit path (replace/evict/expire/
    invalidate/clear) with the reason — breaker releases hang off these;
  * optional circuit breaker: entries charge it on insert and release on
    removal; when a charge trips, the cache evicts its own LRU tail to
    make room and, if the budget still doesn't fit, REFUSES the insert
    (counted as an overflow) instead of raising — a full cache degrades
    to uncached serving, never to a 5xx.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable

from .breaker import CircuitBreakingException


class RemovalReason:
    """Why an entry left the cache (removal-listener argument)."""

    REPLACED = "replaced"
    EVICTED = "evicted"        # LRU/byte-budget/breaker-pressure eviction
    EXPIRED = "expired"        # TTL
    INVALIDATED = "invalidated"
    CLEARED = "cleared"


class _Entry:
    __slots__ = ("value", "weight", "expiry")

    def __init__(self, value, weight: int, expiry: float | None):
        self.value = value
        self.weight = weight
        self.expiry = expiry


class Cache:
    """Thread-safe LRU with byte accounting. See module docstring."""

    def __init__(self, name: str = "cache", *,
                 max_bytes: int = 0, max_entries: int = 0,
                 ttl_s: float | None = None,
                 weigher: Callable[[Any], int] | None = None,
                 clock: Callable[[], float] | None = None,
                 removal_listener=None, breaker=None):
        self.name = name
        self.max_bytes = int(max_bytes)
        self.max_entries = int(max_entries)
        self.ttl_s = ttl_s
        self._weigher = weigher
        self._clock = clock or time.monotonic
        self._listeners = list(removal_listener) \
            if isinstance(removal_listener, (list, tuple)) \
            else ([removal_listener] if removal_listener else [])
        self.breaker = breaker
        self._lock = threading.RLock()
        self._entries: "OrderedDict[Any, _Entry]" = OrderedDict()
        self._bytes = 0
        # monotone counters (leaf names follow the OpenMetrics conventions
        # the /_metrics walk expects: *_total = counter, rest = gauge)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.expirations = 0
        self.overflows = 0          # inserts refused (breaker/budget)
        self.puts = 0

    # -- internals (caller holds the lock) ---------------------------------

    def _weight(self, value) -> int:
        if self._weigher is None:
            return 0
        try:
            return max(int(self._weigher(value)), 0)
        except Exception:  # noqa: BLE001 — a broken weigher must not 500
            return 0

    def _remove_locked(self, key, reason: str) -> _Entry | None:
        ent = self._entries.pop(key, None)
        if ent is None:
            return None
        self._bytes -= ent.weight
        if self.breaker is not None and ent.weight:
            self.breaker.release(ent.weight)
        for fn in self._listeners:
            try:
                fn(key, ent.value, reason)
            except Exception:  # noqa: BLE001 — listeners must not break us
                pass
        return ent

    def _evict_one_locked(self) -> bool:
        try:
            key = next(iter(self._entries))
        except StopIteration:
            return False
        self._remove_locked(key, RemovalReason.EVICTED)
        self.evictions += 1
        return True

    def _expired_locked(self, ent: _Entry) -> bool:
        return ent.expiry is not None and self._clock() >= ent.expiry

    # -- public API --------------------------------------------------------

    def get(self, key, default=None):
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                return default
            if self._expired_locked(ent):
                self._remove_locked(key, RemovalReason.EXPIRED)
                self.expirations += 1
                self.misses += 1
                return default
            self._entries.move_to_end(key)
            self.hits += 1
            return ent.value

    def peek(self, key, default=None):
        """get() without stats or LRU promotion — for introspection walks
        that must not skew hit ratios or recency."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None or self._expired_locked(ent):
                return default
            return ent.value

    def make_room(self, breaker, n_bytes: int) -> None:
        """Charge `n_bytes` against `breaker`, evicting this cache's LRU
        tail under pressure until the charge fits. Raises
        CircuitBreakingException only once the cache has nothing left to
        evict — the admission-control seam fielddata builds go through
        BEFORE doing the expensive work."""
        with self._lock:
            while True:
                try:
                    breaker.add_estimate(int(n_bytes))
                    return
                except CircuitBreakingException:
                    if not self._evict_one_locked():
                        raise

    def put(self, key, value, weight: int | None = None) -> bool:
        """Insert (LRU-newest). `weight` overrides the weigher when the
        caller already knows the entry's bytes. Returns False when the
        entry was refused — single entry over the byte budget, or the
        breaker still trips after evicting everything else — so callers
        degrade to uncached."""
        weight = self._weight(value) if weight is None else max(int(weight), 0)
        with self._lock:
            if self.max_bytes > 0 and weight > self.max_bytes:
                self.overflows += 1
                return False
            self._remove_locked(key, RemovalReason.REPLACED)
            if self.breaker is not None and weight:
                try:
                    self.make_room(self.breaker, weight)
                except CircuitBreakingException:
                    self.overflows += 1
                    return False
            expiry = self._clock() + self.ttl_s \
                if self.ttl_s is not None else None
            self._entries[key] = _Entry(value, weight, expiry)
            self._bytes += weight
            self.puts += 1
            while (self.max_entries > 0
                   and len(self._entries) > self.max_entries) \
                    or (self.max_bytes > 0 and self._bytes > self.max_bytes):
                if not self._evict_one_locked():
                    break
            return key in self._entries

    def get_or_compute(self, key, fn):
        """get() or compute-and-put. The compute runs OUTSIDE the lock
        (it may be expensive); two racers may both compute, last insert
        wins — the reference's loading-cache accepts the same race."""
        hit = self.get(key, default=_MISSING)
        if hit is not _MISSING:
            return hit
        value = fn()
        self.put(key, value)
        return value

    def invalidate(self, key) -> bool:
        with self._lock:
            return self._remove_locked(
                key, RemovalReason.INVALIDATED) is not None

    def invalidate_where(self, pred) -> int:
        """Remove every entry where pred(key, value) — `_cache/clear`
        index filtering."""
        with self._lock:
            doomed = [k for k, e in self._entries.items()
                      if pred(k, e.value)]
            for k in doomed:
                self._remove_locked(k, RemovalReason.INVALIDATED)
            return len(doomed)

    def clear(self) -> int:
        with self._lock:
            n = len(self._entries)
            for k in list(self._entries):
                self._remove_locked(k, RemovalReason.CLEARED)
            return n

    def prune_expired(self) -> int:
        with self._lock:
            doomed = [k for k, e in self._entries.items()
                      if self._expired_locked(e)]
            for k in doomed:
                self._remove_locked(k, RemovalReason.EXPIRED)
                self.expirations += 1
            return len(doomed)

    # -- introspection -----------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key) -> bool:
        with self._lock:
            ent = self._entries.get(key)
            return ent is not None and not self._expired_locked(ent)

    @property
    def memory_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def entries_snapshot(self) -> list[tuple[Any, Any, int]]:
        """[(key, value, weight)] — race-free copy for stats walks."""
        with self._lock:
            return [(k, e.value, e.weight)
                    for k, e in self._entries.items()]

    def stats(self) -> dict:
        with self._lock:
            return {
                "memory_size_in_bytes": self._bytes,
                "entries": len(self._entries),
                "max_size_in_bytes": self.max_bytes,
                "hits_total": self.hits,
                "misses_total": self.misses,
                "evictions_total": self.evictions,
                "expirations_total": self.expirations,
                "overflows_total": self.overflows,
            }


_MISSING = object()


def parse_size(raw, total: int, default: int = 0) -> int:
    """'10%' (of `total`), '64mb', plain ints -> bytes. The reference's
    ByteSizeValue-or-percentage settings parser (e.g.
    `indices.requests.cache.size: 1%`)."""
    if raw is None:
        return default
    s = str(raw).strip().lower()
    try:
        if s.endswith("%"):
            return int(total * float(s[:-1]) / 100.0)
        for suffix, mult in (("pb", 1 << 50), ("tb", 1 << 40),
                             ("gb", 1 << 30), ("mb", 1 << 20),
                             ("kb", 1 << 10), ("b", 1)):
            if s.endswith(suffix):
                return int(float(s[: -len(suffix)]) * mult)
        return int(float(s))
    except (TypeError, ValueError):
        return default
