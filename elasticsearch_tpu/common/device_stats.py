"""Device telemetry: per-program XLA accounting, HBM high-water tracking,
and the lane-decision flight recorder (ISSUE 16).

Three concerns the serving stack had no eyes on:

**Program registry** — every compiled program dispatched from host code
(the plan-signature caches in search/blockwise, parallel/mesh_exec and
parallel/distributed_search, plus the module-level jitted kernels in
ops/) records invocation count, cumulative dispatch wall time and
compile-event attribution. Cost analysis (flops / bytes accessed) is
computed LAZILY at scrape time by re-lowering against the captured
argument avals — `Lowered.cost_analysis()` runs no backend compile and
fires no jax.monitoring compile events (verified: the no-retrace
tripwires stay exact across scrapes) — and is None-safe on backends
that report nothing. The hot path pays two `perf_counter` reads and a
couple of dict updates per dispatch: no host syncs, no retraces
(tests/test_no_retrace.py pins this).

**HBM accounting** — `device.memory_stats()` polled into the stats
sampler ring with a process-lifetime high-water mark per device. CPU
backends return None; the gauges degrade to zero rather than erroring,
so the same scrape works on every platform (ROADMAP item 2c's budget
math reads the TPU numbers).

**Lane-decision flight recorder** — a contextvar-carried per-request
record of every execution-ladder decision: which lane each component
chose and every (lane, reason) decline on the way down. The same note
feeds three surfaces at once: the per-request recorder (profile output),
a zero-duration span event on the active trace, and the global
`es_search_lane_decisions_total{lane=,reason=}` counter family that
subsumes the scattered ad-hoc fallback counters (old names stay exposed
as aliases).
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time

_LOCK = threading.Lock()

# registry bound: programs enter via bounded plan caches, so this cap is a
# backstop against key churn, not a working-set limit
_MAX_PROGRAMS = 512


class ProgramRecord:
    """One compiled program's lifetime accounting. `device_ms` is wall
    time around dispatch with `block_until_ready` on the program's OWN
    outputs — so on async backends a call is charged for its own device
    work, not for whatever an unrelated concurrent program (another
    node's pool, since ISSUE 19) left in the queue. Program cache keys
    carry the owning node's device set (`_mesh_devkey`), so records from
    different pools never alias."""

    __slots__ = ("name", "key", "invocations", "device_ms", "compile_ms",
                 "compiles", "last_invoked", "_fn", "_avals", "_cost",
                 "_cost_done")

    def __init__(self, name: str, key: str, fn):
        self.name = name
        self.key = key
        self.invocations = 0
        self.device_ms = 0.0
        self.compile_ms = 0.0
        self.compiles = 0
        self.last_invoked = 0.0
        self._fn = fn
        self._avals = None          # (args, kwargs) as ShapeDtypeStructs
        self._cost = None
        self._cost_done = False

    def cost(self) -> dict | None:
        """flops / bytes-accessed via a scrape-time re-lower against the
        captured avals. Computed once, cached; None when the backend
        reports nothing or the program can't re-lower (None-safe)."""
        with _LOCK:
            if self._cost_done:
                return self._cost
            avals = self._avals
        cost = None
        if avals is not None:
            try:
                args, kwargs = avals
                ca = self._fn.lower(*args, **kwargs).cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if ca else None
                if isinstance(ca, dict):
                    fl = ca.get("flops")
                    by = ca.get("bytes accessed")
                    cost = {
                        "flops": float(fl) if fl is not None else None,
                        "bytes_accessed": float(by)
                        if by is not None else None}
            except Exception:  # noqa: BLE001 — cost is best-effort telemetry
                cost = None
        with _LOCK:
            self._cost = cost
            self._cost_done = True
        return cost

    def as_dict(self, with_cost: bool = True) -> dict:
        out = {"name": self.name, "key": self.key,
               "invocations": self.invocations,
               "device_time_in_millis": round(self.device_ms, 3),
               "compile_time_in_millis": round(self.compile_ms, 3),
               "compiles": self.compiles}
        if with_cost:
            c = self.cost()
            out["flops"] = c["flops"] if c else None
            out["bytes_accessed"] = c["bytes_accessed"] if c else None
        return out


_REGISTRY: dict[tuple[str, str], ProgramRecord] = {}


def _aval_of(x):
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        import jax
        return jax.ShapeDtypeStruct(x.shape, x.dtype)
    return x


class InstrumentedProgram:
    """Transparent wrapper around a jitted callable: per-call wall-ms +
    invocation counting, first-call aval capture, compile attribution by
    diffing the process-wide compile-event counters around the dispatch.
    Calls made INSIDE an active trace (jit-of-jit) pass straight through
    unaccounted — they are not device dispatches."""

    __slots__ = ("jit", "record")

    def __init__(self, name: str, fn, key=""):
        self.jit = fn
        k = (name, str(key))
        with _LOCK:
            rec = _REGISTRY.get(k)
            if rec is None:
                if len(_REGISTRY) >= _MAX_PROGRAMS:
                    # evict the least-recently-invoked record (backstop)
                    oldest = min(_REGISTRY,
                                 key=lambda kk: _REGISTRY[kk].last_invoked)
                    del _REGISTRY[oldest]
                rec = _REGISTRY[k] = ProgramRecord(name, str(key), fn)
        self.record = rec

    def __call__(self, *args, **kwargs):
        import jax.core as _core
        if not _core.trace_state_clean():
            return self.jit(*args, **kwargs)
        from .metrics import current_profiler, device_events_snapshot
        c0, cms0 = device_events_snapshot()
        t0 = time.perf_counter()
        out = self.jit(*args, **kwargs)
        try:
            # charge THIS program for its own device work: without the
            # barrier an async backend bills the next caller's wall
            # clock for whatever this dispatch left enqueued
            import jax
            jax.block_until_ready(out)
        except Exception:  # noqa: BLE001 — non-array outputs stay timed
            pass
        dt = (time.perf_counter() - t0) * 1000.0
        c1, cms1 = device_events_snapshot()
        rec = self.record
        with _LOCK:
            rec.invocations += 1
            rec.device_ms += dt
            rec.last_invoked = t0
            if c1 > c0:
                rec.compiles += c1 - c0
                rec.compile_ms += cms1 - cms0
            if rec._avals is None:
                try:
                    import jax
                    rec._avals = jax.tree_util.tree_map(
                        _aval_of, (args, kwargs))
                except Exception:  # noqa: BLE001 — cost stays None-safe
                    rec._avals = None
        prof = current_profiler()
        if prof is not None:
            prof.note_program(rec.name, dt)
        return out


def instrument(name: str, fn, key="") -> InstrumentedProgram:
    """Wrap a jitted callable so its dispatches enter the registry.
    Idempotent on already-wrapped callables."""
    if isinstance(fn, InstrumentedProgram):
        return fn
    from .metrics import _install_compile_listener
    _install_compile_listener()
    return InstrumentedProgram(name, fn, key=key)


def registry_snapshot(top_n: int = 50, with_cost: bool = True) -> dict:
    """The `GET /_nodes/device_stats` payload: top-N programs by
    cumulative dispatch time + whole-registry rollups. `with_cost` forces
    the lazy cost analysis (scrape-time work, never dispatch-time)."""
    with _LOCK:
        recs = list(_REGISTRY.values())
    recs.sort(key=lambda r: r.device_ms, reverse=True)
    return {
        "program_count": len(recs),
        "invocations_total": sum(r.invocations for r in recs),
        "device_time_in_millis": round(
            sum(r.device_ms for r in recs), 3),
        "compile_time_in_millis": round(
            sum(r.compile_ms for r in recs), 3),
        "compiles_total": sum(r.compiles for r in recs),
        "programs": [r.as_dict(with_cost=with_cost)
                     for r in recs[:top_n]]}


def program_metrics() -> dict[str, dict]:
    """Per-program-site rollup for the `es_xla_program_*` metric family:
    records aggregate by site name (low-cardinality labels; the full
    per-plan-key detail lives on the device_stats endpoint). Costs are
    reported only when already computed — a /_metrics scrape must never
    trigger re-lowering work."""
    with _LOCK:
        recs = list(_REGISTRY.values())
    out: dict[str, dict] = {}
    for r in recs:
        b = out.setdefault(r.name, {
            "invocations_total": 0, "device_time_in_millis": 0.0,
            "compile_time_in_millis": 0.0, "compiles": 0, "programs": 0})
        b["invocations_total"] += r.invocations
        b["device_time_in_millis"] = round(
            b["device_time_in_millis"] + r.device_ms, 3)
        b["compile_time_in_millis"] = round(
            b["compile_time_in_millis"] + r.compile_ms, 3)
        b["compiles"] += r.compiles
        b["programs"] += 1
    return out


def compile_ms_total() -> float:
    with _LOCK:
        return sum(r.compile_ms for r in _REGISTRY.values())


def reset_registry() -> None:
    """Test seam only."""
    with _LOCK:
        _REGISTRY.clear()


def reset_lane_decisions() -> None:
    """Test seam only."""
    with _LOCK:
        _LANE_DECISIONS.clear()


# ---------------------------------------------------------------------------
# HBM accounting
# ---------------------------------------------------------------------------

_HBM_HIGH_WATER: dict[str, int] = {}


def hbm_poll() -> dict[str, dict]:
    """Per-device memory stats keyed `platform:id`. Backends without
    memory_stats (CPU) report zeros with supported=False instead of
    erroring — the sampler ring and gauges stay shape-stable across
    platforms. Updates the process-lifetime high-water mark."""
    try:
        import jax
        devs = jax.devices()
    except Exception:  # noqa: BLE001 — no backend at all
        return {}
    out: dict[str, dict] = {}
    for d in devs:
        ident = f"{getattr(d, 'platform', 'dev')}:{getattr(d, 'id', 0)}"
        try:
            ms = d.memory_stats()
        except Exception:  # noqa: BLE001 — backend refuses: degrade
            ms = None
        if not ms:
            out[ident] = {"bytes_in_use": 0, "peak_bytes": 0,
                          "high_water_bytes":
                              _HBM_HIGH_WATER.get(ident, 0),
                          "limit_bytes": 0, "supported": False}
            continue
        in_use = int(ms.get("bytes_in_use", 0))
        peak = int(ms.get("peak_bytes_in_use", in_use))
        with _LOCK:
            hw = max(_HBM_HIGH_WATER.get(ident, 0), peak, in_use)
            _HBM_HIGH_WATER[ident] = hw
        out[ident] = {"bytes_in_use": in_use, "peak_bytes": peak,
                      "high_water_bytes": hw,
                      "limit_bytes": int(ms.get("bytes_limit", 0)),
                      "supported": True}
    return out


def hbm_peak_bytes() -> int:
    """Max high-water across devices (the bench headline gauge)."""
    polled = hbm_poll()
    return max((v["high_water_bytes"] for v in polled.values()), default=0)


# ---------------------------------------------------------------------------
# Lane-decision flight recorder
# ---------------------------------------------------------------------------

# (lane, reason) -> count; reason "chosen" marks the lane that served.
# This single labeled family subsumes the ad-hoc *_fallbacks_total
# counters (which stay exposed under their old names as aliases).
_LANE_DECISIONS: dict[tuple[str, str], int] = {}


class LaneRecorder:
    """Per-request ordered record of ladder decisions. Shared by
    reference across the `_ShardJob` context copies (contextvars.copy
    keeps the same object), so concurrent shard jobs of ONE request
    append to one record while a different request's recorder — a
    different contextvar value — stays untouched."""

    __slots__ = ("entries",)

    def __init__(self):
        self.entries: list[dict] = []

    def note(self, component: str, lane: str, reason: str) -> None:
        # list.append is atomic under the GIL; entries may interleave
        # across shard threads but never cross requests
        self.entries.append(
            {"component": component, "lane": lane, "reason": reason})

    def explain(self) -> list[dict]:
        """Group the flat decision stream per component: the lane chosen
        (if any) plus every decline that preceded it."""
        by_comp: dict[str, dict] = {}
        order: list[str] = []
        for e in self.entries:
            c = e["component"]
            if c not in by_comp:
                by_comp[c] = {"component": c, "lane": None, "declines": []}
                order.append(c)
            if e["reason"] == "chosen":
                by_comp[c]["lane"] = e["lane"]
            else:
                by_comp[c]["declines"].append(
                    {"lane": e["lane"], "reason": e["reason"]})
        return [by_comp[c] for c in order]

    def chose(self, lane: str) -> bool:
        return any(e["lane"] == lane and e["reason"] == "chosen"
                   for e in self.entries)


_LANE_RECORDER: contextvars.ContextVar["LaneRecorder | None"] = \
    contextvars.ContextVar("es_lane_recorder", default=None)


def current_lanes() -> LaneRecorder | None:
    return _LANE_RECORDER.get()


@contextlib.contextmanager
def record_lanes(rec: LaneRecorder | None = None):
    rec = rec if rec is not None else LaneRecorder()
    tok = _LANE_RECORDER.set(rec)
    try:
        yield rec
    finally:
        _LANE_RECORDER.reset(tok)


def _note(component: str, lane: str, reason: str) -> None:
    with _LOCK:
        k = (lane, reason)
        _LANE_DECISIONS[k] = _LANE_DECISIONS.get(k, 0) + 1
    rec = _LANE_RECORDER.get()
    if rec is not None:
        rec.note(component, lane, reason)
    # zero-duration marker on the active trace span (no-op untraced):
    # forced-retained traces carry the full ladder walk
    from .tracing import add_event
    add_event("lane", component=component, lane=lane, reason=reason)


def lane_chosen(component: str, lane: str) -> None:
    """The ladder settled: `component` is served by `lane`."""
    _note(component, lane, "chosen")


def lane_decline(component: str, lane: str, reason: str) -> None:
    """`lane` refused this request at `component` for `reason`; the
    ladder continues downward."""
    _note(component, lane, reason)


def lane_decisions_snapshot() -> dict[str, int]:
    """Flat `lane:reason -> count` view (bench headline / tests)."""
    with _LOCK:
        return {f"{lane}:{reason}": n
                for (lane, reason), n in sorted(_LANE_DECISIONS.items())}


def lane_decision_metrics() -> dict[tuple[str, str], dict]:
    """The `es_search_lane_decisions_total{lane=,reason=}` payload:
    tuple-keyed registry for the multi-label OpenMetrics walk."""
    with _LOCK:
        return {k: {"decisions_total": n}
                for k, n in _LANE_DECISIONS.items()}
