"""Device-memory circuit breakers — HBM accounting with clean rejection.

Analog of the reference's hierarchical breaker service
(/root/reference/src/main/java/org/elasticsearch/indices/breaker/
HierarchyCircuitBreakerService.java:43,51-61 and
common/breaker/ChildMemoryCircuitBreaker.java): child breakers account
bytes against their own limit AND a shared parent limit; a breach raises
CircuitBreakingException (HTTP 429) instead of letting the device OOM.

TPU mapping: the dominant device residents are segment postings/columns
("fielddata" breaker) and the packed serving view's duplicate postings
("request" breaker, evictable — a breach there degrades to the per-segment
lane instead of raising).
"""

from __future__ import annotations

import threading


class CircuitBreakingException(Exception):
    """Would-exceed-budget rejection (maps to HTTP 429)."""

    def __init__(self, breaker: str, wanted: int, limit: int, used: int):
        super().__init__(
            f"[{breaker}] data for device memory would be [{used + wanted}] "
            f"bytes, which is larger than the limit of [{limit}] bytes")
        self.breaker = breaker
        self.wanted = wanted
        self.limit = limit
        self.used = used


class CircuitBreaker:
    """One child breaker: used-bytes counter with a limit share."""

    def __init__(self, name: str, limit: int, parent: "CircuitBreakerService"):
        self.name = name
        self.limit = limit
        self.parent = parent
        self.used = 0
        self.max_used = 0      # high-water mark: device-memory headroom is
        self.tripped = 0       # judged against the PEAK, not the instant

    def add_estimate(self, n_bytes: int, check: bool = True) -> None:
        """Account n_bytes; raise (charging nothing) when over this child's
        limit or the parent total. check=False force-charges (recovery/boot
        paths must load regardless, like the reference's unbreakable adds)."""
        with self.parent._lock:
            if check and self.limit > 0 and self.used + n_bytes > self.limit:
                self.tripped += 1
                raise CircuitBreakingException(
                    self.name, n_bytes, self.limit, self.used)
            if check:
                self.parent._check_parent(self, n_bytes)
            self.used += n_bytes
            self.max_used = max(self.max_used, self.used)

    def release(self, n_bytes: int) -> None:
        with self.parent._lock:
            self.used = max(0, self.used - n_bytes)

    def stats(self) -> dict:
        return {"limit_size_in_bytes": self.limit,
                "estimated_size_in_bytes": self.used,
                "max_estimated_size_in_bytes": self.max_used,
                "tripped": self.tripped}


class CircuitBreakerService:
    """Parent limit + named children (fielddata = resident segments,
    request = evictable serving views)."""

    def __init__(self, settings=None):
        get = settings.get_bytes if settings is not None else lambda k, d: d
        total = get("indices.breaker.total.limit", 6 << 30)
        self._lock = threading.RLock()
        self.total_limit = int(total)
        self.breakers: dict[str, CircuitBreaker] = {}
        self.breaker("fielddata",
                     int(get("indices.breaker.fielddata.limit",
                             int(self.total_limit * 0.8))))
        self.breaker("request",
                     int(get("indices.breaker.request.limit",
                             int(self.total_limit * 0.6))))

    def breaker(self, name: str, limit: int | None = None) -> CircuitBreaker:
        b = self.breakers.get(name)
        if b is None:
            b = CircuitBreaker(name, limit if limit is not None
                               else self.total_limit, self)
            self.breakers[name] = b
        return b

    def _check_parent(self, child: CircuitBreaker, wanted: int) -> None:
        # caller holds the lock
        total_used = sum(b.used for b in self.breakers.values())
        if self.total_limit > 0 and total_used + wanted > self.total_limit:
            child.tripped += 1
            raise CircuitBreakingException(
                "parent", wanted, self.total_limit, total_used)

    def stats(self) -> dict:
        with self._lock:
            out = {n: b.stats() for n, b in self.breakers.items()}
            out["parent"] = {
                "limit_size_in_bytes": self.total_limit,
                "estimated_size_in_bytes": sum(
                    b.used for b in self.breakers.values())}
            return out


# a process-wide no-limit service for embedded/test use without accounting
NOOP = CircuitBreakerService()
NOOP.total_limit = 0
for _b in NOOP.breakers.values():
    _b.limit = 0
