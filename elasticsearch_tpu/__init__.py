"""elasticsearch_tpu — a TPU-native distributed search & analytics engine.

A ground-up rebuild of the capabilities of Elasticsearch 2.0 (reference:
/root/reference, surveyed in SURVEY.md) designed for TPUs: per-shard inverted
indexes and columnar fielddata live as dense device tensors, BM25 scoring and
aggregations are batched XLA/Pallas programs, and cross-shard reduces are mesh
collectives (jax.lax.top_k / psum) instead of coordinator-side merge loops.

Layer map (mirrors SURVEY.md §1):
  common/    — settings, circuit breakers, wire/json helpers       (ref L0)
  analysis/  — tokenizers, token filters, analyzers                (ref index/analysis)
  mapping/   — schema: field types, dynamic mapping                (ref index/mapper)
  index/     — tensor segments, engine, translog, shards           (ref index/engine, translog, shard)
  ops/       — device kernels: BM25 scoring, top-k, segment ops    (replaces Lucene's hot loops)
  search/    — query DSL compilation, query/fetch phases, aggs     (ref index/query, search/)
  parallel/  — mesh, doc routing, cross-shard collective reduce    (ref cluster/routing, SearchPhaseController)
  cluster/   — cluster state, routing table, allocation, service   (ref cluster/)
  rest/      — HTTP REST API surface                               (ref rest/, http/)
"""

# Exact integer semantics for longs/dates (epoch millis) require 64-bit device
# types; we enable x64 globally and pass explicit dtypes everywhere hot
# (scores are always float32/bfloat16, ids int32).
import os

import jax

jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: serving shapes are pow2-bucketed
# (serving/packed_view.py), so the compile set is small and stable — caching
# it on disk makes cold-start p99 a one-time cost per machine instead of a
# per-process multi-second stall (ref: the reference warms searchers via
# indices/warmer/; here the "warm" artifact is the compiled executable).
_cache_dir = os.environ.get(
    "ELASTICSEARCH_TPU_XLA_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "elasticsearch_tpu",
                 "xla"))
if _cache_dir and _cache_dir != "0":
    try:
        os.makedirs(_cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
    except Exception:  # noqa: BLE001 — cache is an optimization only
        pass

__version__ = "0.1.0"
