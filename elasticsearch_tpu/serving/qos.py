"""Serving-QoS: admission control + load shedding in front of the device.

The reference earns its tail latency from machinery this reproduction
lacked: a search pool that REJECTS under saturation instead of queueing
unboundedly (EsRejectedExecutionException -> 429), load-balanced reads
across replica copies (OperationRouting.java:144-154) and five typed
connection classes per node pair so bulk/recovery traffic can never
starve query and cluster-state traffic (NettyTransport.java:180-184).
On a TPU the same goals map onto inference-serving staples:

  * `QosController` — per-traffic-class admission in front of the search
    pool. It tracks queue depth, breaker pressure and an EWMA of device
    latency; excess load sheds as HTTP 429 + `Retry-After` (never a 5xx,
    never an unbounded queue), and BEFORE shedding it degrades
    gracefully: the dynamic batcher shrinks its coalescing window and
    the plan cache is preferred over fresh parses.
  * `Ewma` — latency EWMA + mean absolute deviation; `deadline_ms()` is
    the adaptive p99-of-EWMA the hedged-read coordinator arms its backup
    timer with (cluster/node.py `_query_with_hedge`).
  * module-level hedge counters — the cluster coordinator records
    fired/win/cancel outcomes here so the single exposition
    (`es_search_hedged_total{outcome=}`), the sampler ring and bench.py
    all read one source.

Traffic classes mirror the reference's five connection types
(recovery/bulk/reg/state/ping); the REST edge maps request classes onto
them and the transport layer gives each class its own connection budget
(cluster/transport.py)."""

from __future__ import annotations

import threading
import time

# the five reference connection classes (NettyTransport.java:180-184);
# REST admission uses search/bulk; recovery/state/ping exist for the
# transport's per-class budgets and the shed-accounting labels
TRAFFIC_CLASSES = ("search", "bulk", "recovery", "state", "ping")

# fraction of `node.search.qos.max_inflight` each class may hold; state
# and ping are control-plane traffic and are never shed (a cluster that
# sheds its own heartbeats under load partitions itself)
DEFAULT_SHARES = {"search": 0.6, "bulk": 0.3, "recovery": 0.1,
                  "state": 1.0, "ping": 1.0}

_NEVER_SHED = frozenset({"state", "ping"})


class QosShedException(Exception):
    """Admission refused: maps to HTTP 429 + Retry-After at the REST
    boundary (the EsRejectedExecutionException contract, upgraded with a
    client backoff hint)."""

    def __init__(self, tclass: str, reason: str, retry_after_s: float):
        super().__init__(
            f"qos shed [{tclass}]: {reason} (retry in {retry_after_s:.0f}s)")
        self.tclass = tclass
        self.reason = reason
        self.retry_after_s = retry_after_s


class Ewma:
    """Latency EWMA + mean-absolute-deviation (the TCP RTO estimator
    shape): `deadline_ms()` = ewma + k*dev is the adaptive percentile
    deadline hedged reads arm their backup timer with. Unlocked — every
    field write is a single atomic store and readers tolerate a torn
    pair (both fields move smoothly)."""

    __slots__ = ("alpha", "value", "dev", "n")

    def __init__(self, alpha: float = 0.2):
        self.alpha = alpha
        self.value = 0.0
        self.dev = 0.0
        self.n = 0

    def observe(self, ms: float) -> None:
        if self.n == 0:
            self.value = ms
            self.dev = ms / 2.0
        else:
            err = ms - self.value
            self.value += self.alpha * err
            self.dev += self.alpha * (abs(err) - self.dev)
        self.n += 1

    def deadline_ms(self, k: float = 3.0) -> float:
        """Adaptive p99-of-EWMA: mean + k deviations (k=3 ~ p99 for the
        latency shapes a serving tier sees)."""
        return self.value + k * self.dev


def _as_bool(v, default: bool) -> bool:
    if v is None:
        return default
    if isinstance(v, str):
        return v.strip().lower() not in ("false", "0", "no", "off")
    return bool(v)


class QosController:
    """Per-node admission control. All thresholds are live-read from
    settings so `_settings`-style overlays and tests apply without a
    restart; the clock is injectable so EWMA tests never sleep.

    Settings:
      node.search.qos.enable             default true
      node.search.qos.max_inflight       default 256 admission slots
      node.search.qos.<class>.share      per-class slot fraction
                                         (DEFAULT_SHARES)
      node.search.qos.degrade_threshold  default 0.7 — above: shrink the
                                         batch window, prefer cached plans
      node.search.qos.shed_threshold     default 0.9 — above: shed
                                         sheddable classes with 429
      node.search.qos.shed_latency_ms    default 5000 — the EWMA-p99
                                         device latency that counts as
                                         pressure 1.0
    """

    def __init__(self, settings=None, thread_pool=None, breakers=None,
                 clock=None):
        self._settings = settings
        self._thread_pool = thread_pool
        self._breakers = breakers
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self.latency = Ewma()
        self._latency_at = None        # clock time of the last sample
        self._inflight = {c: 0 for c in TRAFFIC_CLASSES}
        self.admitted = {c: 0 for c in TRAFFIC_CLASSES}
        self.shed = {c: 0 for c in TRAFFIC_CLASSES}
        self.degraded_total = 0
        self._degraded = False
        from ..common.metrics import Meter
        self.shed_meter = Meter(clock=clock)

    # -- live settings -----------------------------------------------------

    def _get(self, key, default):
        if self._settings is None:
            return default
        return self._settings.get(key, default)

    def enabled(self) -> bool:
        return _as_bool(self._get("node.search.qos.enable", True), True)

    def _max_inflight(self) -> int:
        try:
            return max(1, int(self._get("node.search.qos.max_inflight",
                                        256)))
        except (TypeError, ValueError):
            return 256

    def _slots(self, tclass: str) -> int:
        share = self._get(f"node.search.qos.{tclass}.share",
                          DEFAULT_SHARES.get(tclass, 0.5))
        try:
            share = float(share)
        except (TypeError, ValueError):
            share = DEFAULT_SHARES.get(tclass, 0.5)
        return max(0, int(self._max_inflight() * share))

    def _threshold(self, key: str, default: float) -> float:
        try:
            return float(self._get(f"node.search.qos.{key}", default))
        except (TypeError, ValueError):
            return default

    # -- pressure signals --------------------------------------------------

    def record_latency(self, ms: float) -> None:
        """Feed the device-latency EWMA (the coordinator calls this with
        every search's device-phase wall time)."""
        with self._lock:
            self.latency.observe(ms)
            self._latency_at = self._clock()

    def queue_frac(self) -> float:
        """Search-pool queue occupancy in [0, 1]."""
        if self._thread_pool is None:
            return 0.0
        pool = self._thread_pool.pools.get("search")
        if pool is None or not pool.queue_size:
            return 0.0
        return min(1.0, pool._q.qsize() / pool.queue_size)

    def breaker_frac(self) -> float:
        """Parent-breaker occupancy in [0, 1]."""
        if self._breakers is None:
            return 0.0
        limit = getattr(self._breakers, "total_limit", 0)
        if not limit:
            return 0.0
        with self._breakers._lock:
            used = sum(b.used for b in self._breakers.breakers.values())
        return min(1.0, max(0.0, used / limit))

    def latency_frac(self) -> float:
        """EWMA-p99 device latency relative to the shed ceiling, decayed
        with idle time. The decay breaks a shed livelock (ISSUE 12
        satellite, found driving the quantized tier's first query): one
        compile-heavy request can spike the EWMA past the ceiling, and
        because SHED requests never execute, no new sample could ever
        bring it back down — the node 429'd forever. A stale estimate is
        a weak estimate: with no fresh device latency for a while the
        signal halves per `node.search.qos.latency_halflife_s` (default
        30 s, ≤0 restores the undecayed signal), so probe traffic gets
        admitted to re-measure reality."""
        ceiling = self._threshold("shed_latency_ms", 5000.0)
        if ceiling <= 0:
            return 0.0
        frac = min(1.0, self.latency.deadline_ms() / ceiling)
        half_life = self._threshold("latency_halflife_s", 30.0)
        if half_life > 0 and self._latency_at is not None:
            idle = max(0.0, self._clock() - self._latency_at)
            frac *= 0.5 ** (idle / half_life)
        return frac

    def pressure(self) -> float:
        """The overload score in [0, 1]: the WORST of queue depth,
        breaker occupancy and EWMA device latency — any one of them
        saturating means new work will only queue, burn memory, or miss
        its deadline."""
        return max(self.queue_frac(), self.breaker_frac(),
                   self.latency_frac())

    @property
    def degraded(self) -> bool:
        """True while pressure sits in the degrade band: the batcher
        shrinks its window, plan caches are preferred. Recomputed by the
        admission path; reads are cheap."""
        return self._degraded

    # -- admission ---------------------------------------------------------

    def retry_after_s(self) -> float:
        """Client backoff hint: roughly the time for the current queue to
        drain at the EWMA latency, floored at 1s, capped at 30s."""
        if self._thread_pool is not None:
            pool = self._thread_pool.pools.get("search")
            depth = pool._q.qsize() if pool is not None else 0
        else:
            depth = 0
        est = (depth + 1) * max(self.latency.value, 1.0) / 1000.0
        return min(30.0, max(1.0, est))

    def admit(self, tclass: str) -> "_Admission":
        """Admission check for one request of `tclass`. Returns a context
        manager holding the in-flight slot; raises QosShedException when
        the request must shed. Control-plane classes (state/ping) are
        never shed."""
        if not self.enabled():
            return _Admission(self, None)
        if tclass not in self._inflight:
            tclass = "search"
        p = self.pressure()
        degrade = self._threshold("degrade_threshold", 0.7)
        shed_at = self._threshold("shed_threshold", 0.9)
        with self._lock:
            was_degraded = self._degraded
            self._degraded = p >= degrade
            if self._degraded and not was_degraded:
                self.degraded_total += 1
            if tclass not in _NEVER_SHED:
                slots = self._slots(tclass)
                if self._inflight[tclass] >= slots:
                    self.shed[tclass] += 1
                    self.shed_meter.mark()
                    raise QosShedException(
                        tclass, f"class budget exhausted "
                        f"({self._inflight[tclass]}/{slots} in flight)",
                        self.retry_after_s())
                if p >= shed_at:
                    self.shed[tclass] += 1
                    self.shed_meter.mark()
                    raise QosShedException(
                        tclass, f"node overloaded (pressure {p:.2f})",
                        self.retry_after_s())
            self._inflight[tclass] += 1
            self.admitted[tclass] += 1
        return _Admission(self, tclass)

    def _release(self, tclass: str) -> None:
        with self._lock:
            self._inflight[tclass] = max(0, self._inflight[tclass] - 1)

    # -- degrade hooks (the batcher reads these) ---------------------------

    def batch_window(self, base: int) -> int:
        """Coalescing window for the dynamic batcher: full when healthy,
        quartered under degrade pressure so per-batch latency shrinks
        before any request sheds."""
        if self._degraded:
            return max(4, base // 4)
        return base

    def follower_wait_s(self) -> float:
        """Deadline-aware max-wait for batcher followers: generous
        relative to the EWMA device latency (leader + one full batch),
        bounded so a wedged leader can never hold a follower the silent
        30 s the old hard-coded timeout did."""
        est = self.latency.deadline_ms() / 1000.0
        return min(30.0, max(1.0, 4.0 * est + 1.0))

    # -- stats -------------------------------------------------------------

    def class_stats(self) -> dict:
        """{class: leaves} for the labeled `qos` metric section
        (es_qos_shed_total{class=} et al.)."""
        with self._lock:
            return {c: {"shed_total": self.shed[c],
                        "admitted_total": self.admitted[c],
                        "inflight": self._inflight[c],
                        "slots": self._slots(c)}
                    for c in TRAFFIC_CLASSES}

    def control_plane_shed(self) -> int:
        """Sheds charged against never-shed classes — must stay 0 by
        construction; the chaos invariant checker asserts it after every
        disruption round so a regression in the admission gate is caught
        with a reproducing seed attached."""
        with self._lock:
            return sum(self.shed[c] for c in _NEVER_SHED)

    def stats(self) -> dict:
        return {"pressure": round(self.pressure(), 4),
                "queue_frac": round(self.queue_frac(), 4),
                "breaker_frac": round(self.breaker_frac(), 4),
                "latency_frac": round(self.latency_frac(), 4),
                "ewma_latency_ms": round(self.latency.value, 3),
                "ewma_deadline_ms": round(self.latency.deadline_ms(), 3),
                "degraded": 1 if self._degraded else 0,
                "degraded_total": self.degraded_total,
                "shed_rate_1m": round(self.shed_meter.rate(60), 4),
                "by_class": self.class_stats()}


class _Admission:
    """The held admission slot; releases on exit. `tclass is None` means
    QoS was disabled at admit time — nothing to release."""

    __slots__ = ("_qos", "_tclass")

    def __init__(self, qos: QosController, tclass: str | None):
        self._qos = qos
        self._tclass = tclass

    def __enter__(self) -> "_Admission":
        return self

    def __exit__(self, *exc) -> bool:
        if self._tclass is not None:
            self._qos._release(self._tclass)
        return False


# ---------------------------------------------------------------------------
# hedged-read accounting: the cluster coordinator records outcomes here so
# /_metrics, the sampler ring and bench.py read one process-wide source.
# ---------------------------------------------------------------------------

HEDGE_OUTCOMES = ("fired", "win_primary", "win_backup", "canceled",
                  "failed", "moving")
# "moving": the hedge armed/fired because the chosen copy is part of an
# in-flight relocation (ISSUE 15's rebalance-under-traffic cover) — its
# node is also streaming recovery chunks, so the deadline tightens by
# cluster.search.hedge.moving_factor and fires even on a cold EWMA.

_hedge_lock = threading.Lock()
_hedge_counts = {o: 0 for o in HEDGE_OUTCOMES}
_hedge_meter = None


def record_hedge(outcome: str) -> None:
    global _hedge_meter
    with _hedge_lock:
        if _hedge_meter is None:
            from ..common.metrics import Meter
            _hedge_meter = Meter()
        _hedge_counts[outcome] = _hedge_counts.get(outcome, 0) + 1
        if outcome == "fired":
            _hedge_meter.mark()


def hedge_snapshot() -> dict:
    with _hedge_lock:
        return dict(_hedge_counts)


def hedge_rate(window: int = 60) -> float:
    with _hedge_lock:
        return _hedge_meter.rate(window) if _hedge_meter is not None else 0.0


# ---------------------------------------------------------------------------
# Per-transport-class latency EWMAs (ISSUE 19 pod tier)
# ---------------------------------------------------------------------------
# Cross-host pre-reduced merges ride the "dcn" transport class; their
# latencies observe HERE, never into the per-node `_node_lat` EWMAs that
# arm the hedge deadline — a slow DCN link must not inflate the ICI
# deadline for co-hosted copies (and vice versa). One Ewma per class,
# same alpha/deviations math as the hedge tier, surfaced by
# transport_latency_snapshot() for the metrics scrape and the bench.

_transport_lat_lock = threading.Lock()
_transport_lat: dict[str, Ewma] = {}


def observe_transport_latency(tclass: str, ms: float) -> None:
    with _transport_lat_lock:
        lat = _transport_lat.get(tclass)
        if lat is None:
            lat = _transport_lat[tclass] = Ewma()
        lat.observe(ms)


def transport_latency_snapshot() -> dict:
    """{class: {"ewma_ms", "deadline_ms", "n"}} for every observed
    transport class."""
    with _transport_lat_lock:
        return {c: {"ewma_ms": lat.value, "deadline_ms": lat.deadline_ms(),
                    "n": lat.n}
                for c, lat in _transport_lat.items()}


def reset_transport_latency() -> None:
    with _transport_lat_lock:
        _transport_lat.clear()
