"""Dynamic request batcher: concurrent solo `_search` requests coalesce
into ONE packed device program.

The reference gets its QPS from thread-pool concurrency (one Lucene search
per thread, search/SearchService + the SEARCH thread pool); a TPU gets it
from BATCHING — the packed kernel's cost is nearly flat in Q, so serving
32 queued requests in one program costs barely more than serving one.

Design: continuous batching with ZERO added latency when idle. The first
request for a compatibility group becomes the LEADER and executes
immediately with whatever is queued at that moment (itself). Requests
arriving while the device is busy queue up; when the leader finishes it
takes the whole accumulated queue as the next batch. Under load, batch
size self-tunes to (arrival rate x device latency) — exactly the dynamic
batching window, without a sleep on the idle path.

ref: the role of org.elasticsearch.threadpool.ThreadPool's SEARCH pool —
but the unit of concurrency is a device batch, not a thread.
"""

from __future__ import annotations

import threading
import time


class _Entry:
    __slots__ = ("body", "spec", "event", "out", "err", "t_submit")

    def __init__(self, body, spec):
        self.body = body
        self.spec = spec
        self.event = threading.Event()
        self.out = None          # response dict, or None -> general path
        self.err = None
        self.t_submit = time.perf_counter()


class SearchBatcher:
    """Per-node coalescer for packed-eligible solo searches."""

    MAX_BATCH = 32               # one device batch == one warm Q bucket

    def __init__(self, node):
        self.node = node
        self._lock = threading.Lock()
        self._queues: dict[tuple, list[_Entry]] = {}
        self._busy: set[tuple] = set()
        self.batches = 0         # observability: device batches executed
        self.batched_requests = 0
        # batch-occupancy histogram {batch size: batches}: how full the
        # coalescing window actually runs — THE serving-efficiency gauge
        # (occupancy 1 = no coalescing happened; near MAX_BATCH = the
        # arrival rate saturates the device latency window)
        self.occupancy: dict[int, int] = {}

    def submit(self, key: tuple, name: str, body: dict, spec,
               size: int, from_: int, t0: float):
        """Execute (or join) a packed batch for this request. Returns the
        response dict, or None when the request must take the general path
        (unservable batch / view refusal)."""
        e = _Entry(body, spec)
        with self._lock:
            self._queues.setdefault(key, []).append(e)
            leader = key not in self._busy
            if leader:
                self._busy.add(key)
        if not leader:
            e.event.wait(timeout=30.0)
            if e.err is not None:
                raise e.err
            return e.out

        try:
            while True:
                with self._lock:
                    batch = self._queues.pop(key, [])
                    if not batch:
                        break
                    if len(batch) > self.MAX_BATCH:
                        self._queues[key] = batch[self.MAX_BATCH:]
                        batch = batch[:self.MAX_BATCH]
                self._run(key, name, batch, size, from_, t0)
        finally:
            with self._lock:
                self._busy.discard(key)
                leftover = self._queues.pop(key, [])
            for x in leftover:   # no leader left: don't strand them
                x.out = None
                x.event.set()
        if e.err is not None:
            raise e.err
        return e.out

    def _run(self, key, name, batch, size, from_, t0):
        # queue-wait timer: time each entry spent waiting for the device
        # (leader ≈ 0; followers accrue while the previous batch runs) —
        # the admission-latency half of batcher cost, invisible to the
        # device timers because it happens entirely on the host
        now = time.perf_counter()
        metrics = getattr(self.node, "metrics", None)
        if metrics is not None:
            for x in batch:
                metrics.record("batcher.queue_wait",
                               (now - x.t_submit) * 1000)
        try:
            outs = self.node._packed_search(
                name, [x.body for x in batch], size=size, from_=from_,
                t0=t0, specs=[x.spec for x in batch])
        except Exception as ex:  # noqa: BLE001 — degrade each to general
            self.node._packed_error()
            for x in batch:
                x.out = None
                x.event.set()
            return
        with self._lock:
            self.batches += 1
            self.batched_requests += len(batch)
            self.occupancy[len(batch)] = \
                self.occupancy.get(len(batch), 0) + 1
        for i, x in enumerate(batch):
            x.out = None if outs is None else outs[i]
            x.event.set()

    def stats(self) -> dict:
        with self._lock:
            return {"batches": self.batches,
                    "batched_requests": self.batched_requests,
                    "occupancy": dict(sorted(self.occupancy.items()))}
