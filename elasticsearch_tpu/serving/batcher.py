"""Dynamic request batcher: concurrent solo `_search` requests coalesce
into ONE packed device program.

The reference gets its QPS from thread-pool concurrency (one Lucene search
per thread, search/SearchService + the SEARCH thread pool); a TPU gets it
from BATCHING — the packed kernel's cost is nearly flat in Q, so serving
32 queued requests in one program costs barely more than serving one.

Design: continuous batching with ZERO added latency when idle. The first
request for a compatibility group becomes the LEADER and executes
immediately with whatever is queued at that moment (itself). Requests
arriving while the device is busy queue up; when the leader finishes it
takes the whole accumulated queue as the next batch. Under load, batch
size self-tunes to (arrival rate x device latency) — exactly the dynamic
batching window, without a sleep on the idle path.

Two lanes share the leader/follower core (ISSUE 9):

  * the PACKED lane (`submit`) — packed-spec-eligible bodies ride the
    packed view kernel as before;
  * the COALESCED GENERAL lane (`join_batched`/`drain_batched`) — bodies
    the packed kernel can't serve but `_search_batched` can (plan-shaped
    queries, aggs, knn, rescore) coalesce onto the stacked/blockwise/mesh
    Q>1 replica axis. The first request LEADS by running the ordinary
    solo path (idle-path latency stays zero and solo responses are
    byte-identical to the pre-QoS engine); requests arriving while it
    runs queue as followers, and the leader drains them as Q>1
    `_search_batched` batches — results bitwise-identical to solo
    execution (tests/test_qos.py parity matrix).

Followers wait under a DEADLINE-AWARE timeout (QosController.
follower_wait_s — a multiple of the EWMA device latency, never the old
silent hard-coded 30 s); timeouts and leader-exit strandings are counted
and surfaced on `/_metrics` (`es_search_batcher_wait_timeouts_total`,
`es_search_batcher_stranded_total`), and batch-execution errors are
recorded (`run_errors_total` + `last_error`), not discarded.

ref: the role of org.elasticsearch.threadpool.ThreadPool's SEARCH pool —
but the unit of concurrency is a device batch, not a thread.
"""

from __future__ import annotations

import logging
import threading
import time

logger = logging.getLogger("elasticsearch_tpu.serving.batcher")

#: sentinel returned by `join_batched` when the caller holds leadership —
#: it must run the solo path itself, then call `drain_batched`.
LEAD = object()


class _Entry:
    __slots__ = ("body", "spec", "event", "out", "err", "t_submit",
                 "abandoned")

    def __init__(self, body, spec):
        self.body = body
        self.spec = spec
        self.event = threading.Event()
        self.out = None          # response dict, or None -> general path
        self.err = None
        self.t_submit = time.perf_counter()
        self.abandoned = False   # follower timed out; don't spend a row


class SearchBatcher:
    """Per-node coalescer for packed-eligible solo searches."""

    MAX_BATCH = 32               # one device batch == one warm Q bucket

    _log_budget = 10             # rate-limited anomaly logging (per class)

    def __init__(self, node):
        self.node = node
        self._lock = threading.Lock()
        self._queues: dict[tuple, list[_Entry]] = {}
        self._busy: set[tuple] = set()
        self.batches = 0         # observability: device batches executed
        self.batched_requests = 0
        # batch-occupancy histogram {batch size: batches}: how full the
        # coalescing window actually runs — THE serving-efficiency gauge
        # (occupancy 1 = no coalescing happened; near MAX_BATCH = the
        # arrival rate saturates the device latency window)
        self.occupancy: dict[int, int] = {}
        # ISSUE 9 satellite: the silent failure paths are now counted —
        # stranded followers (leader exited with entries still queued),
        # follower wait timeouts (the old hard 30 s fell through with no
        # signal), and batch-execution errors (the swallowed `ex`)
        self.stranded = 0
        self.wait_timeouts = 0
        self.run_errors = 0
        self.last_error: str | None = None

    # -- shared plumbing ---------------------------------------------------

    def _window(self) -> int:
        """Coalescing window: MAX_BATCH when healthy; the QoS controller
        shrinks it under degrade pressure (smaller batches = lower
        per-batch latency) before any request sheds."""
        qos = getattr(self.node, "qos", None)
        if qos is not None:
            return qos.batch_window(self.MAX_BATCH)
        return self.MAX_BATCH

    def _wait_timeout(self) -> float:
        qos = getattr(self.node, "qos", None)
        if qos is not None:
            return qos.follower_wait_s()
        return 30.0

    @classmethod
    def _log_anomaly(cls, msg: str, *args, exc_info: bool = False) -> None:
        if cls._log_budget > 0:
            cls._log_budget -= 1
            logger.warning(msg, *args, exc_info=exc_info)

    def _wait(self, e: _Entry):
        """Follower wait with the deadline-aware timeout; a timeout falls
        to the general path, counted and logged instead of silent."""
        if not e.event.wait(timeout=self._wait_timeout()):
            e.abandoned = True
            with self._lock:
                self.wait_timeouts += 1
            self._log_anomaly(
                "batcher follower timed out after %.1fs waiting for its "
                "leader; serving via the general path",
                self._wait_timeout())
            return None
        if e.err is not None:
            raise e.err
        return e.out

    def _release(self, key: tuple) -> None:
        """Leader exit: release leadership and unblock any leftover
        followers (they serve themselves on the general path) — counted,
        because a nonzero rate means the leader loop exited abnormally."""
        with self._lock:
            self._busy.discard(key)
            leftover = self._queues.pop(key, [])
            self.stranded += len(leftover)
        for x in leftover:   # no leader left: don't strand them silently
            x.out = None
            x.event.set()
        if leftover:
            self._log_anomaly(
                "batcher leader exited with %d followers still queued; "
                "they fall to the general path", len(leftover))

    # -- the packed lane ---------------------------------------------------

    def submit(self, key: tuple, name: str, body: dict, spec,
               size: int, from_: int, t0: float):
        """Execute (or join) a packed batch for this request. Returns the
        response dict, or None when the request must take the general path
        (unservable batch / view refusal)."""
        key = ("packed", *key)
        e = _Entry(body, spec)
        with self._lock:
            self._queues.setdefault(key, []).append(e)
            leader = key not in self._busy
            if leader:
                self._busy.add(key)
        if not leader:
            return self._wait(e)

        try:
            while True:
                with self._lock:
                    batch = self._queues.pop(key, [])
                    batch = [x for x in batch if not x.abandoned]
                    if not batch:
                        break
                    window = self._window()
                    if len(batch) > window:
                        self._queues[key] = batch[window:]
                        batch = batch[:window]
                self._run(key, name, batch, size, from_, t0)
        finally:
            self._release(key)
        if e.err is not None:
            raise e.err
        return e.out

    def _run(self, key, name, batch, size, from_, t0):
        # queue-wait timer: time each entry spent waiting for the device
        # (leader ≈ 0; followers accrue while the previous batch runs) —
        # the admission-latency half of batcher cost, invisible to the
        # device timers because it happens entirely on the host
        now = time.perf_counter()
        metrics = getattr(self.node, "metrics", None)
        if metrics is not None:
            for x in batch:
                metrics.record("batcher.queue_wait",
                               (now - x.t_submit) * 1000)
        try:
            outs = self.node._packed_search(
                name, [x.body for x in batch], size=size, from_=from_,
                t0=t0, specs=[x.spec for x in batch])
        except Exception as ex:  # noqa: BLE001 — degrade each to general
            self._record_error(ex)
            self.node._packed_error()
            for x in batch:
                x.out = None
                x.event.set()
            return
        self._book(batch)
        for i, x in enumerate(batch):
            x.out = None if outs is None else outs[i]
            x.event.set()

    # -- the coalesced general lane (ISSUE 9) ------------------------------

    def join_batched(self, key: tuple, body: dict):
        """The coalesced general lane's entry point. Returns the LEAD
        sentinel when the caller acquired leadership — it must execute
        the ordinary solo path for itself and call `drain_batched(key,
        index)` when done (a try/finally at the call site). Otherwise the
        caller is a follower: blocks until the leader serves it and
        returns the response dict, or None when it must fall to the
        general path (timeout / strand / unservable batch)."""
        key = ("gen", *key)
        with self._lock:
            if key not in self._busy:
                self._busy.add(key)
                return LEAD
            e = _Entry(body, None)
            self._queues.setdefault(key, []).append(e)
        return self._wait(e)

    def drain_batched(self, key: tuple, index: str) -> None:
        """Leader epilogue: serve every follower that queued behind this
        leader's solo execution as Q>1 `_search_batched` batches, then
        release leadership. Never raises — a failing batch degrades its
        members to the general path."""
        key = ("gen", *key)
        try:
            while True:
                with self._lock:
                    batch = self._queues.pop(key, [])
                    batch = [x for x in batch if not x.abandoned]
                    if not batch:
                        break
                    window = self._window()
                    if len(batch) > window:
                        self._queues[key] = batch[window:]
                        batch = batch[:window]
                self._run_batched(index, batch)
        finally:
            self._release(key)

    def _run_batched(self, index: str, batch: list[_Entry]) -> None:
        now = time.perf_counter()
        metrics = getattr(self.node, "metrics", None)
        if metrics is not None:
            for x in batch:
                metrics.record("batcher.queue_wait",
                               (now - x.t_submit) * 1000)
        try:
            outs = self.node._search_batched(
                [(index, x.body) for x in batch])
        except Exception as ex:  # noqa: BLE001 — degrade each to general
            self._record_error(ex)
            self._log_anomaly(
                "coalesced batch failed; members fall to the general "
                "path", exc_info=True)
            for x in batch:
                x.out = None
                x.event.set()
            return
        self._book(batch)
        for x, out in zip(batch, outs):
            x.out = out
            x.event.set()

    # -- accounting --------------------------------------------------------

    def _book(self, batch: list[_Entry]) -> None:
        with self._lock:
            self.batches += 1
            self.batched_requests += len(batch)
            self.occupancy[len(batch)] = \
                self.occupancy.get(len(batch), 0) + 1

    def _record_error(self, ex: BaseException) -> None:
        with self._lock:
            self.run_errors += 1
            self.last_error = f"{type(ex).__name__}: {ex}"

    def stats(self) -> dict:
        with self._lock:
            return {"batches": self.batches,
                    "batched_requests": self.batched_requests,
                    "stranded_total": self.stranded,
                    "wait_timeouts_total": self.wait_timeouts,
                    "run_errors_total": self.run_errors,
                    "last_error": self.last_error,
                    "occupancy": dict(sorted(self.occupancy.items()))}
