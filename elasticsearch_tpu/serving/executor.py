"""Packed-path request planning + response building.

Bridges NodeService and PackedIndexView: decides which request bodies are
servable by the one-program packed kernel, extracts per-query knobs from the
parsed query tree, and assembles responses — either as dicts (API parity with
the general path) or as raw JSON text (the fast lane for `_source: false`
top-k responses, where building 256k hit dicts per msearch would cost more
host time than the device program itself).

ref: the reference's QueryPhase + SearchPhaseController split; here the
"controller reduce" already happened on device (global top-k over the packed
doc space), so response building is the only host work left.
"""

from __future__ import annotations

import numpy as np

from .packed_view import (F_RANGE, F_TERM, F_TERM_VALS, PackedIndexView,
                          PackedQuery)

# body keys the packed path understands; anything else (sort, aggs, rescore,
# knn, search_after, highlight, ...) falls back to the general path
PACKED_BODY_KEYS = {"query", "size", "from", "_source"}


def _packable_filters(plan):
    """mask/neg nodes -> (negated?, node) pairs the packed kernel's filter
    slots can evaluate (term + range over columnar fields, within the
    static slot budget), or None if any node needs the general path."""
    from ..search.query_dsl import MatchAllNode, RangeNode, TermFilterNode

    out = []
    nr = nt = 0
    for neg, nodes in ((False, plan.mask_nodes), (True, plan.neg_nodes)):
        for n in nodes:
            if isinstance(n, MatchAllNode):
                if neg:
                    return None     # must_not match_all: matches nothing
                continue
            if isinstance(n, RangeNode):
                if not n.bounds_per_query:
                    return None
                lo, hi = n.bounds_per_query[0][0], n.bounds_per_query[0][1]
                if not all(isinstance(x, (int, float, type(None)))
                           and not isinstance(x, bool) for x in (lo, hi)):
                    # keyword (string) bounds are fine; mixed junk is not
                    if not all(isinstance(x, (str, type(None)))
                               for x in (lo, hi)):
                        return None
                nr += 1
                out.append((neg, n))
            elif isinstance(n, TermFilterNode):
                vals = n.values_per_query[0] if n.values_per_query else []
                if len(vals) > F_TERM_VALS:
                    return None
                nt += 1
                out.append((neg, n))
            else:
                return None
    if nr > F_RANGE or nt > F_TERM:
        return None
    return out


def packed_spec_of(parser, body: dict):
    """-> (PackedQuery, field, k1, b) if the body is packed-servable,
    else None. Mirrors sparse_exec.extract_sparse_plan eligibility;
    filter/must_not contexts ride the kernel's columnar filter slots
    (BASELINE config #2's bool{match + filter} shape)."""
    from ..search.sparse_exec import extract_sparse_plan

    if any(k not in PACKED_BODY_KEYS for k in body):
        return None
    try:
        node = parser.parse(body.get("query") or {"match_all": {}})
    except Exception:          # noqa: BLE001 — let the general path raise
        return None
    plan = extract_sparse_plan(node)
    if plan is None:
        return None
    filters = _packable_filters(plan)
    if filters is None:
        return None
    if filters and not plan.terms_per_query[0]:
        # pure-filter queries have no scored postings to draw candidates
        # from; the general path serves them
        return None
    return (PackedQuery(terms=plan.terms_per_query[0],
                        boost=plan.match_boost * plan.scale,
                        operator=plan.operator, msm=plan.msm,
                        const=plan.const_boost * plan.scale,
                        filters=tuple(filters)),
            plan.field, plan.k1, plan.b)


def response_dict(view: PackedIndexView, index_name: str, srow: np.ndarray,
                  drow: np.ndarray, total: int, *, n_shards: int, took: int,
                  from_: int, size: int, src_spec, src_filter_fn) -> dict:
    """Assemble one search response (general dict form)."""
    sl = srow[from_:from_ + size]
    dl = drow[from_:from_ + size]
    n = int((sl > -np.inf).sum())
    hits = []
    for i in range(n):
        src, tname, doc_id = view.source_of(int(dl[i]))
        if src_spec is False:
            src = None
        elif src_filter_fn is not None:
            src = src_filter_fn(src)
        hit = {"_index": index_name, "_type": tname, "_id": doc_id,
               "_score": float(sl[i])}
        if src is not None:      # `_source: false` omits the key
            hit["_source"] = src
        hits.append(hit)
    mx = float(srow[0]) if srow.size and srow[0] > -np.inf else None
    return {
        "took": took, "timed_out": False,
        "_shards": {"total": n_shards, "successful": n_shards, "failed": 0},
        "hits": {"total": int(total), "max_score": mx, "hits": hits},
    }


def response_raw(view: PackedIndexView, index_name: str, srow: np.ndarray,
                 drow: np.ndarray, total: int, *, n_shards: int, took: int,
                 from_: int, size: int) -> str:
    """Assemble one `_source: false` response as raw JSON text with
    vectorized numpy string ops — no per-hit Python objects."""
    sl = srow[from_:from_ + size]
    dl = drow[from_:from_ + size]
    n = int((sl > -np.inf).sum())
    if n:
        # %.9g survives a float32 round-trip, so raw and dict lanes
        # serialize identical score values (advisor r3)
        ids = view.ids_packed[dl[:n]]
        ss = np.char.mod("%.9g", sl[:n].astype(np.float64))
        prefix = ('{"_index":"' + index_name + '","_type":"'
                  + (view.single_type or "_doc") + '","_id":"')
        parts = np.char.add(np.char.add(np.char.add(prefix, ids),
                                        '","_score":'), ss)
        hits_str = "},".join(parts.tolist()) + "}"
    else:
        hits_str = ""
    mx = "%.9g" % float(srow[0]) \
        if srow.size and srow[0] > -np.inf else "null"
    return ('{"took":%d,"timed_out":false,"_shards":{"total":%d,'
            '"successful":%d,"failed":0},"hits":{"total":%d,"max_score":%s,'
            '"hits":[%s]}}' % (took, n_shards, n_shards, int(total), mx,
                               hits_str))
