"""Serving layer: device-resident packed index views + batched execution.

The round-3 answer to "the product is slower than its own CPU proxy": serve
every eligible request through ONE device program over ALL shards/segments
(serving/packed_view.py), with one packed upload and one packed download,
instead of a per-segment kernel with multiple host round-trips.
"""

from .packed_view import PackedIndexView, PackedQuery

__all__ = ["PackedIndexView", "PackedQuery"]
