"""PackedIndexView: all shards' segments of one index fused into a single
device-resident postings structure, served by ops/bm25_sparse.bm25_serve_packed
in ONE device program per request batch.

Why (measured, see BASELINE.md): this TPU sits behind a tunnel with
~20-115 ms round-trip latency per host<->device interaction. The round-2
serving path ran one kernel per segment and fetched three result arrays per
kernel — ~6+ round trips per request, so the product was slower than its own
XLA-CPU proxy despite a 94x kernel. This view makes the whole request cost:

    1 H2D (packed i32 slot table) + 1 program + 1 D2H (packed i32 results)

It is the TPU analog of the reference's per-shard search fan-out collapsing
into a single batched program: the scatter-gather of
search/action/SearchServiceTransportAction.java becomes tensor concatenation,
and SearchPhaseController.sortDocs's cross-shard merge becomes the kernel's
global top-k (the doc space is packed across shards, so the top-k IS the
reduce). Term statistics are naturally index-global — equivalent to running
the DFS phase (search/dfs/DfsPhase.java:57-81) on every request, which is
*better* scoring parity than per-shard IDF.

The view is immutable w.r.t. the segment set; deletes only refresh the packed
liveness row (Segment.live_gen tracks that). IndexService caches the view
keyed by segment set and rebuilds liveness on tombstone changes.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp

from ..index.segment import Segment, next_pow2
from ..ops.bm25_sparse import bm25_serve_packed, bm25_serve_packed_filtered

# Fixed postings chunk: compile-cache keys depend on (Q, S) pow2 buckets only,
# never on the corpus' df distribution.
CHUNK = 512

# static filter-slot budget per query (compile-cache keys); queries needing
# more fall back to the general path (serving/executor.py enforces)
F_RANGE = 2      # AND-ed range slots
F_TERM = 2       # AND-ed term slots
F_TERM_VALS = 4  # OR-ed values per term slot

_JSON_UNSAFE = re.compile(r'["\\\x00-\x1f]')


class FilterColumnRefused(Exception):
    """The request breaker refused a filter column — serve via the
    per-segment lane instead (not an error)."""


@dataclass
class PackedQuery:
    """One query row of a packed batch (per-query knobs the kernel supports
    without recompiling: term set, boost, operator/minimum_should_match, an
    additive constant applied host-side, and columnar filters evaluated on
    device — (negated?, TermFilterNode|RangeNode) pairs)."""
    terms: list[str]
    boost: float = 1.0
    operator: str = "or"
    msm: int = 1
    const: float = 0.0
    filters: tuple = ()


@dataclass
class PackedFilterColumn:
    """One field's filter column over the global packed doc space, f64-
    encoded for the kernel: numeric values (NaN = missing) or keyword
    ordinals in the union vocabulary (-1 = missing)."""
    kind: str                      # "numeric" | "keyword"
    vals: jax.Array                # f64[n_pad_total]
    vocab: list[str] | None = None


class PackedField:
    """One text field's postings packed across every segment of the index."""

    def __init__(self, doc_ids: jax.Array, tf: jax.Array, dl: jax.Array,
                 terms: np.ndarray, starts: np.ndarray, lens: np.ndarray,
                 sum_dl: float, total_p: int = 0):
        self.doc_ids = doc_ids          # i32[P_pad] device, PAD-padded
        self.tf = tf                    # f32[P_pad]
        self.dl = dl                    # f32[P_pad]
        self.terms = terms              # U[V] sorted unique terms (host)
        self.starts = starts            # i32[V, NSEG] packed-space starts
        self.lens = lens                # i32[V, NSEG] per-segment df
        self.df = lens.sum(axis=1)      # i64[V] global df
        self.sum_dl = sum_dl
        self.total_p = total_p          # real postings (un-padded)

    def term_ids(self, terms: list[str]) -> np.ndarray:
        """Vectorized term lookup; -1 for absent terms."""
        if not len(self.terms):
            return np.full(len(terms), -1, np.int64)
        q = np.asarray(terms)    # own U width — casting to the index's dtype
                                 # would truncate long query terms into
                                 # false matches
        idx = np.searchsorted(self.terms, q)
        idx_c = np.minimum(idx, len(self.terms) - 1)
        found = self.terms[idx_c] == q
        return np.where(found, idx_c, -1)


class PackedIndexView:
    """The fused serving structure for one index (all shards, all segments)."""

    def __init__(self, segments: list[tuple[int, Segment]], breaker=None,
                 base: "PackedIndexView | None" = None):
        """segments: (shard_idx, segment) in stable insertion order.
        breaker: optional "request" CircuitBreaker — each lazily-packed
        field charges its device bytes; a breach makes that field
        unservable by this view (field() returns None) instead of raising.
        base: a previous view whose entries are an IDENTITY PREFIX of
        `segments` — its built fields/filter columns are EXTENDED with the
        appended segments' postings instead of repacked from scratch, so an
        NRT refresh costs O(new postings), not O(index) (advisor r3)."""
        self.entries = segments
        self.breaker = breaker
        sizes = np.array([s.n_pad for _, s in segments], np.int64)
        self.bases = np.zeros(len(segments) + 1, np.int64)
        np.cumsum(sizes, out=self.bases[1:])
        self.n_total = int(self.bases[-1])
        self.n_pad_total = next_pow2(self.n_total + 1, floor=8)
        self.doc_count = sum(s.n_docs for _, s in segments)
        self.pad_doc = self.n_total      # global PAD sentinel (never live)

        # host columns for vectorized fetch: _id / _type per global doc id
        max_id = max((max((len(i) for i in s.ids), default=1)
                      for _, s in segments), default=1)
        self.ids_packed = np.full(self.n_pad_total, "", dtype=f"U{max_id}")
        types: set[str] = set()
        for ei, (_, seg) in enumerate(segments):
            if seg.n_docs:
                self.ids_packed[self.bases[ei]:self.bases[ei] + seg.n_docs] = \
                    seg.ids
                types.update(seg.types)
        self.single_type = types.pop() if len(types) == 1 else None
        # raw-JSON hits need no escaping only if every id/type is clean;
        # the "," separator is itself JSON-safe, so an id containing any
        # unsafe char (incl. newline) is always caught. Mixed-type indexes
        # use the dict lane (per-doc _type).
        joined = ",".join(",".join(s.ids) for _, s in segments)
        self.ids_json_safe = (self.single_type is not None
                              and _JSON_UNSAFE.search(joined) is None
                              and _JSON_UNSAFE.search(self.single_type)
                              is None)

        self._fields: dict[str, PackedField | None] = {}
        self._refused: set[str] = set()   # breaker-refused (≠ absent) fields
        self._filter_cols: dict[str, PackedFilterColumn | None] = {}
        self._filter_stacks: dict[tuple, jax.Array] = {}
        self._live_key: tuple | None = None
        self._live_dev: jax.Array | None = None
        self.device_calls = 0           # serving counters (observability)
        self.memory_bytes = 0
        self.extended_from_base = False
        if base is not None:
            self._seed_from(base)

    def _seed_from(self, base: "PackedIndexView") -> None:
        """Extend the base view's built structures with the appended
        segments (entries[len(base.entries):])."""
        assert len(base.entries) <= len(self.entries) and all(
            b[1] is s[1] for b, s in zip(base.entries, self.entries)), \
            "base must be an identity prefix"
        from ..common.breaker import CircuitBreakingException
        for fname, pf in base._fields.items():
            if pf is None:
                continue
            try:
                self._fields[fname] = self._extend_field(fname, base, pf)
            except CircuitBreakingException:
                self._refused.add(fname)
                self._fields[fname] = None
        for fname, col in base._filter_cols.items():
            if col is None:
                continue
            try:
                self._filter_cols[fname] = self._extend_filter_col(
                    fname, base, col)
            except CircuitBreakingException:
                pass    # rebuilt lazily (and re-gated) on next use
        self.extended_from_base = True

    def _extend_field(self, name: str, base: "PackedIndexView",
                      pf: PackedField) -> PackedField:
        """Append the new segments' postings BLOCKS to an existing packed
        field: device-side concat of the old buffers (no host repack of old
        data), plus a vectorized remap of the [V, NSEG] slice table into the
        union term dictionary. Host work is O(new postings + vocab)."""
        new = [(len(base.entries) + i, seg)
               for i, (_, seg) in enumerate(self.entries[len(base.entries):])]
        per_seg = []
        for ei, seg in new:
            fx = seg.text.get(name)
            if fx is None or seg.n_docs == 0:
                continue
            host_ids = fx.doc_ids_host if fx.doc_ids_host is not None \
                else np.asarray(fx.doc_ids)[:fx.n_postings]
            per_seg.append((ei, fx, host_ids[:fx.n_postings]))
        if not per_seg:
            # stale PAD sentinels inside the old buffer are masked by the
            # kernel's per-slot valid lanes, so the arrays are reusable —
            # but the old view's charge was released by IndexService, so the
            # still-resident buffers must be re-charged into THIS view
            # (check=False: memory already exists) or repeated NRT refreshes
            # progressively undercount the request breaker (advisor r4).
            reused = int(pf.doc_ids.size) * 12   # doc_ids+tf+dl at p_pad
            if self.breaker is not None and reused:
                self.breaker.add_estimate(reused, check=False)
            self.memory_bytes += reused
            return pf

        base_p = pf.total_p
        total_new = sum(len(h) for _, _, h in per_seg)
        p_pad = next_pow2(base_p + total_new + CHUNK, floor=CHUNK * 2)
        if self.breaker is not None:
            self.breaker.add_estimate(p_pad * 12)
        tail_docs = np.full(p_pad - base_p, self.pad_doc, np.int32)
        tail_tf = np.zeros(p_pad - base_p, np.float32)
        tail_dl = np.ones(p_pad - base_p, np.float32)

        seg_term_arrays = [np.asarray(list(fx.terms), dtype="U")
                           for _, fx, _ in per_seg]
        all_terms = np.unique(np.concatenate([pf.terms] + seg_term_arrays)) \
            if len(pf.terms) else np.unique(np.concatenate(seg_term_arrays))
        V = len(all_terms)
        nseg_old = pf.starts.shape[1]
        starts = np.zeros((V, nseg_old + len(per_seg)), np.int32)
        lens = np.zeros((V, nseg_old + len(per_seg)), np.int64)
        if len(pf.terms):
            pos_old = np.searchsorted(all_terms, pf.terms)
            starts[pos_old, :nseg_old] = pf.starts
            lens[pos_old, :nseg_old] = pf.lens

        off = base_p
        sum_dl = pf.sum_dl
        for si, (ei, fx, host_ids) in enumerate(per_seg):
            P = len(host_ids)
            lo = off - base_p
            tail_docs[lo:lo + P] = host_ids + int(self.bases[ei])
            tail_tf[lo:lo + P] = np.asarray(fx.tf[:P])
            tail_dl[lo:lo + P] = np.asarray(fx.dl[:P])
            st = seg_term_arrays[si]
            pos = np.searchsorted(all_terms, st)
            starts[pos, nseg_old + si] = fx.term_starts[: len(st)] + off
            lens[pos, nseg_old + si] = fx.term_lens[: len(st)]
            sum_dl += fx.sum_dl
            off += P

        doc_ids = jnp.concatenate([pf.doc_ids[:base_p],
                                   jnp.asarray(tail_docs)])
        tf = jnp.concatenate([pf.tf[:base_p], jnp.asarray(tail_tf)])
        dl = jnp.concatenate([pf.dl[:base_p], jnp.asarray(tail_dl)])
        self.memory_bytes += p_pad * 12
        return PackedField(doc_ids=doc_ids, tf=tf, dl=dl, terms=all_terms,
                           starts=starts, lens=lens, sum_dl=sum_dl,
                           total_p=base_p + total_new)

    def _extend_filter_col(self, name: str, base: "PackedIndexView",
                           col: PackedFilterColumn) -> PackedFilterColumn:
        """Extend a filter column over the appended doc space. Keyword
        columns may need an ordinal REMAP when new segments introduce new
        vocabulary — numeric ones are a pure concat."""
        if self.breaker is not None:
            self.breaker.add_estimate(self.n_pad_total * 8)
        new_entries = list(enumerate(self.entries))[len(base.entries):]
        if col.kind == "numeric":
            tail = np.full(self.n_pad_total - base.n_total, np.nan)
            for ei, (_, seg) in new_entries:
                nc = seg.numerics.get(name)
                if nc is None or seg.n_docs == 0:
                    continue
                lo = int(self.bases[ei]) - base.n_total
                v = np.asarray(nc.vals).astype(np.float64)
                miss = np.asarray(nc.missing)
                n = min(seg.n_pad, len(v))
                tail[lo:lo + n] = np.where(miss[:n], np.nan, v[:n])
            vals = jnp.concatenate([col.vals[: base.n_total],
                                    jnp.asarray(tail)])
            self.memory_bytes += self.n_pad_total * 8
            return PackedFilterColumn("numeric", vals)
        # keyword: union vocab; remap old ordinals only if vocab grew
        new_vocabs = [seg.keywords[name].values
                      for _, (_, seg) in new_entries
                      if name in seg.keywords]
        vocab = sorted(set(col.vocab).union(*new_vocabs)) if new_vocabs \
            else col.vocab
        union_of = {v: i for i, v in enumerate(vocab)}
        if vocab != col.vocab:
            lut = np.array([union_of[v] for v in col.vocab] + [-1.0])
            old = np.asarray(col.vals[: base.n_total]).astype(np.int64)
            head = jnp.asarray(lut[old])
        else:
            head = col.vals[: base.n_total]
        tail = np.full(self.n_pad_total - base.n_total, -1.0)
        for ei, (_, seg) in new_entries:
            kc = seg.keywords.get(name)
            if kc is None or seg.n_docs == 0:
                continue
            lo = int(self.bases[ei]) - base.n_total
            lut = np.array([union_of[v] for v in kc.values] + [-1.0])
            ords = np.asarray(kc.ords)
            n = min(seg.n_pad, len(ords))
            tail[lo:lo + n] = lut[ords[:n]]
        vals = jnp.concatenate([head, jnp.asarray(tail)])
        self.memory_bytes += self.n_pad_total * 8
        return PackedFilterColumn("keyword", vals, vocab=vocab)

    # -- liveness (rebuilt on tombstone changes only) ----------------------

    def _live_gen_key(self) -> tuple:
        return tuple(s.live_gen for _, s in self.entries)

    @property
    def live_dev(self) -> jax.Array:
        key = self._live_gen_key()
        if self._live_dev is None or key != self._live_key:
            live = np.zeros(self.n_pad_total, bool)
            for ei, (_, seg) in enumerate(self.entries):
                live[self.bases[ei]:self.bases[ei] + seg.n_pad] = \
                    seg.root_live_host   # nested rows never serve as hits
            live[self.n_total:] = False
            self._live_dev = jnp.asarray(live)
            self._live_key = key
        return self._live_dev

    # -- field packing (lazy, cached) --------------------------------------

    def field(self, name: str) -> PackedField | None:
        if name not in self._fields:
            self._fields[name] = self._pack_field(name)
            if self._fields[name] is not None:
                # precompile the solo-latency shapes for this field's
                # postings buckets so cold p99 is one compile, not many
                # (persistent XLA cache makes this ~free after first run)
                self.warmup(field=name)
        return self._fields[name]

    def servable(self, name: str) -> bool:
        """False when the request breaker refused this field's packed
        postings — the caller must fall back to the per-segment lane."""
        self.field(name)
        return name not in self._refused

    def _pack_field(self, name: str) -> PackedField | None:
        per_seg = []                    # (entry_idx, fx, host doc_ids)
        for ei, (_, seg) in enumerate(self.entries):
            fx = seg.text.get(name)
            if fx is None or seg.n_docs == 0:
                continue
            host_ids = fx.doc_ids_host
            if host_ids is None:        # segment loaded without host mirror
                host_ids = np.asarray(fx.doc_ids[:fx.n_postings])
            per_seg.append((ei, fx, host_ids[:fx.n_postings]))
        if not per_seg:
            return None

        total_p = sum(len(h) for _, _, h in per_seg)
        p_pad = next_pow2(total_p + CHUNK, floor=CHUNK * 2)
        doc_ids = np.full(p_pad, self.pad_doc, np.int32)
        tf = np.zeros(p_pad, np.float32)
        dl = np.ones(p_pad, np.float32)

        # merged sorted term dict via per-segment searchsorted alignment
        seg_term_arrays = [np.asarray(list(fx.terms), dtype="U")
                           for _, fx, _ in per_seg]
        all_terms = (np.unique(np.concatenate(seg_term_arrays))
                     if seg_term_arrays else np.array([], "U1"))
        V = len(all_terms)
        nseg = len(per_seg)
        starts = np.zeros((V, nseg), np.int32)
        lens = np.zeros((V, nseg), np.int64)

        off = 0
        sum_dl = 0.0
        for si, (ei, fx, host_ids) in enumerate(per_seg):
            P = len(host_ids)
            doc_ids[off:off + P] = host_ids + int(self.bases[ei])
            tf[off:off + P] = np.asarray(fx.tf[:P])
            dl[off:off + P] = np.asarray(fx.dl[:P])
            st = seg_term_arrays[si]
            pos = np.searchsorted(all_terms, st)
            starts[pos, si] = fx.term_starts[:len(st)] + off
            lens[pos, si] = fx.term_lens[:len(st)]
            sum_dl += fx.sum_dl
            off += P

        if self.breaker is not None:
            from ..common.breaker import CircuitBreakingException
            try:
                self.breaker.add_estimate(p_pad * 12)
            except CircuitBreakingException:
                # NOT the same as an absent field (which legitimately serves
                # empty results): refusal must push the query to the
                # per-segment lane, so callers check servable()
                self._refused.add(name)
                return None
        self.memory_bytes += p_pad * 12
        return PackedField(
            doc_ids=jnp.asarray(doc_ids), tf=jnp.asarray(tf),
            dl=jnp.asarray(dl), terms=all_terms, starts=starts,
            lens=lens.astype(np.int64), sum_dl=sum_dl, total_p=total_p)

    # -- stats (parity with query_dsl.CollectionStats) ---------------------

    def avgdl(self, field: str) -> float:
        pf = self.field(field)
        sum_dl = pf.sum_dl if pf is not None else 0.0
        return max(sum_dl, 1.0) / max(self.doc_count, 1)

    # -- batch execution ---------------------------------------------------

    def search(self, field: str, queries: list[PackedQuery], *, k: int,
               k1: float = 1.2, b: float = 0.75):
        """Run the whole batch in one device program.

        Returns (scores f32[Q,k] (-inf = empty), docs i64[Q,k] global packed
        doc ids, hits i64[Q]). Q is the REAL query count (pad rows stripped).
        """
        Q = len(queries)
        pf = self.field(field)
        if pf is None or self.n_total == 0:
            return (np.full((Q, k), -np.inf, np.float32),
                    np.full((Q, k), -1, np.int64), np.zeros(Q, np.int64))

        packed_q, S, R = self._build_slots(pf, queries, field, k1, b)
        k_pad = next_pow2(k, floor=8)
        Q_pad = packed_q.shape[0]
        if any(q.filters for q in queries):
            (fields, fr_col, fr_lo, fr_hi, fr_neg,
             ft_col, ft_targets, ft_neg) = \
                self._filter_descriptors(queries, Q_pad)
            out = bm25_serve_packed_filtered(
                packed_q, pf.doc_ids, pf.tf, pf.dl, self.live_dev,
                jnp.int32(self.pad_doc), jnp.float32(k1), jnp.float32(b),
                jnp.float32(self.avgdl(field)), jnp.float32(0.0),
                self._filter_stack(fields),
                jnp.asarray(fr_col), jnp.asarray(fr_lo),
                jnp.asarray(fr_hi), jnp.asarray(fr_neg),
                jnp.asarray(ft_col), jnp.asarray(ft_targets),
                jnp.asarray(ft_neg),
                S=S, CHUNK=CHUNK, R=R, k=k_pad,
                FR=F_RANGE, FT=F_TERM, TV=F_TERM_VALS)
        else:
            out = bm25_serve_packed(
                packed_q, pf.doc_ids, pf.tf, pf.dl, self.live_dev,
                jnp.int32(self.pad_doc), jnp.float32(k1), jnp.float32(b),
                jnp.float32(self.avgdl(field)), jnp.float32(0.0),
                S=S, CHUNK=CHUNK, R=R, k=k_pad)
        self.device_calls += 1
        arr = np.asarray(out)            # the ONE D2H transfer
        arr = arr[:Q]
        scores = np.ascontiguousarray(arr[:, :k_pad]).view(np.float32)[:, :k]
        docs = arr[:, k_pad:2 * k_pad][:, :k].astype(np.int64)
        hits = arr[:, 2 * k_pad].astype(np.int64)
        consts = np.array([q.const for q in queries], np.float32)
        if consts.any():
            scores = np.where(scores > -np.inf,
                              scores + consts[:, None], scores)
        docs = np.where(scores > -np.inf, docs, -1)
        return scores, docs, hits

    def _build_slots(self, pf: PackedField, queries: list[PackedQuery],
                     field: str, k1: float, b: float):
        """Vectorized slot-table construction: terms -> fixed-CHUNK postings
        slots scattered into the packed i32[Q_pad, 3S+1] upload."""
        Q = len(queries)
        # Q buckets are {1, 32, 64, 128, ...}: the dynamic batcher produces
        # arbitrary batch sizes, and a compile per pow2 bucket would stall
        # serving for seconds each — two warm shapes cover all solo +
        # batched traffic instead (warmup() compiles exactly these)
        Q_pad = 1 if Q == 1 else max(32, next_pow2(Q))
        nseg = pf.starts.shape[1]

        qi_l: list[int] = []
        tid_l: list[int] = []
        w_l: list[float] = []
        min_match = np.ones(Q_pad, np.int32)
        max_terms = 1
        N = max(self.doc_count, 1)
        for qi, q in enumerate(queries):
            tids = pf.term_ids(q.terms) if q.terms else np.empty(0, np.int64)
            n_terms = len(q.terms)
            max_terms = max(max_terms, n_terms)
            if q.operator == "and":
                min_match[qi] = max(n_terms, 1)
            else:
                min_match[qi] = max(q.msm, 1)
            for t, tid in zip(q.terms, tids):
                if tid < 0:
                    continue
                df = int(pf.df[tid])
                idf = math.log(1 + (N - df + 0.5) / (df + 0.5))
                qi_l.append(qi)
                tid_l.append(int(tid))
                w_l.append(idf * (k1 + 1) * q.boost)

        # R floor matches warmup()'s shapes: two extra rolls cost ~nothing,
        # one avoided compile shape saves seconds of cold p99
        R = next_pow2(max_terms, floor=4)
        if not qi_l:
            S = 4
            packed = np.zeros((Q_pad, 3 * S + 1), np.int32)
            packed[:, 3 * S] = min_match
            return jnp.asarray(packed), S, R

        qi_a = np.asarray(qi_l, np.int64)
        tid_a = np.asarray(tid_l, np.int64)
        w_a = np.asarray(w_l, np.float32)

        # expand (query, term) -> (query, term, segment), drop empty slices
        lens_e = pf.lens[tid_a]                       # [E, NSEG]
        starts_e = pf.starts[tid_a]                   # [E, NSEG]
        qf = np.repeat(qi_a, nseg)
        lf = lens_e.reshape(-1)
        sf = starts_e.reshape(-1)
        wf = np.repeat(w_a, nseg)
        nz = lf > 0
        qf, lf, sf, wf = qf[nz], lf[nz], sf[nz], wf[nz]

        # expand each slice into ceil(len/CHUNK) fixed-size chunks
        nch = -(-lf // CHUNK)
        row = np.repeat(np.arange(len(lf)), nch)
        within = np.arange(len(row)) - np.repeat(
            np.concatenate([[0], np.cumsum(nch)[:-1]]), nch)
        slot_q = qf[row]
        slot_start = (sf[row] + within * CHUNK).astype(np.int32)
        slot_len = np.minimum(CHUNK, lf[row] - within * CHUNK).astype(np.int32)
        slot_w = wf[row]

        # per-query slot positions (row-major scatter); input is built in
        # ascending qi order, so a stable cumcount is just arange - group start
        counts = np.bincount(slot_q, minlength=Q_pad)
        group_start = np.concatenate([[0], np.cumsum(counts)[:-1]])
        pos = np.arange(len(slot_q)) - group_start[slot_q]

        # small (latency-bound) batches get a high S floor so nearly every
        # solo query lands on ONE warm compile shape; large (throughput-
        # bound) batches size S tightly — their shape amortizes over the
        # batch and the first msearch warms it
        S = next_pow2(int(counts.max()), floor=32 if Q_pad <= 32 else 4)
        packed = np.zeros((Q_pad, 3 * S + 1), np.int32)
        packed[slot_q, pos] = slot_start
        packed[slot_q, S + pos] = slot_len
        packed[slot_q, 2 * S + pos] = slot_w.view(np.int32)
        packed[:, 3 * S] = min_match
        return jnp.asarray(packed), S, R

    # -- filter columns (lazy, cached) -------------------------------------

    def filter_column(self, name: str) -> PackedFilterColumn | None:
        """The f64 filter column for one field over the global doc space.
        None = no segment has the field (a filter on it matches nothing).
        Raises FilterColumnRefused when the request breaker refuses the
        device bytes — the caller serves via the per-segment lane."""
        if name in self._filter_cols:
            return self._filter_cols[name]
        has_kw = any(name in seg.keywords for _, seg in self.entries)
        has_num = any(name in seg.numerics for _, seg in self.entries)
        if not has_kw and not has_num:
            self._filter_cols[name] = None
            return None
        if self.breaker is not None:
            from ..common.breaker import CircuitBreakingException
            try:
                self.breaker.add_estimate(self.n_pad_total * 8)
            except CircuitBreakingException as e:
                raise FilterColumnRefused(name) from e
        if has_num:
            vals = np.full(self.n_pad_total, np.nan)
            for ei, (_, seg) in enumerate(self.entries):
                nc = seg.numerics.get(name)
                if nc is None or seg.n_docs == 0:
                    continue
                base = int(self.bases[ei])
                v = np.asarray(nc.vals).astype(np.float64)
                miss = np.asarray(nc.missing)
                n = min(seg.n_pad, len(v))
                vals[base:base + n] = np.where(miss[:n], np.nan, v[:n])
            col = PackedFilterColumn("numeric", jnp.asarray(vals))
        else:
            vocab = sorted(set().union(*(
                seg.keywords[name].values for _, seg in self.entries
                if name in seg.keywords)))
            union_of = {v: i for i, v in enumerate(vocab)}
            vals = np.full(self.n_pad_total, -1.0)
            for ei, (_, seg) in enumerate(self.entries):
                kc = seg.keywords.get(name)
                if kc is None or seg.n_docs == 0:
                    continue
                base = int(self.bases[ei])
                lut = np.array([union_of[v] for v in kc.values] + [-1.0])
                ords = np.asarray(kc.ords)
                n = min(seg.n_pad, len(ords))
                vals[base:base + n] = lut[ords[:n]]
            col = PackedFilterColumn("keyword", jnp.asarray(vals),
                                     vocab=vocab)
        self.memory_bytes += self.n_pad_total * 8
        self._filter_cols[name] = col
        return col

    def _filter_stack(self, fields: tuple) -> jax.Array:
        st = self._filter_stacks.get(fields)
        if st is None:
            if fields:
                st = jnp.stack([self._filter_cols[f].vals for f in fields])
            else:
                st = jnp.zeros((1, self.n_pad_total), jnp.float64)
            self._filter_stacks[fields] = st
        return st

    def _filter_descriptors(self, queries: list[PackedQuery], Q_pad: int):
        """-> (fields tuple, fr_col, fr_lo, fr_hi, fr_neg, ft_col,
        ft_targets, ft_neg) numpy descriptor arrays for the kernel.
        Raises FilterColumnRefused if a needed column was breaker-refused."""
        from ..search.query_dsl import RangeNode, TermFilterNode

        fields: list[str] = []

        def col_idx(name):
            col = self.filter_column(name)
            if col is None:
                return -2, None     # active slot, absent field
            if name not in fields:
                fields.append(name)
            return fields.index(name), col

        fr_col = np.full((Q_pad, F_RANGE), -1, np.int32)
        fr_lo = np.zeros((Q_pad, F_RANGE))
        fr_hi = np.zeros((Q_pad, F_RANGE))
        fr_neg = np.zeros((Q_pad, F_RANGE), np.int32)
        ft_col = np.full((Q_pad, F_TERM), -1, np.int32)
        ft_targets = np.full((Q_pad, F_TERM, F_TERM_VALS), np.nan)
        ft_neg = np.zeros((Q_pad, F_TERM), np.int32)

        for qi, q in enumerate(queries):
            ri = ti = 0
            for neg, node in q.filters:
                if isinstance(node, RangeNode):
                    ci, col = col_idx(node.field_name)
                    lo, hi, inc_lo, inc_hi = node.bounds_per_query[0]
                    if col is not None and col.kind == "keyword":
                        # lexicographic bounds -> inclusive ordinal bounds
                        # over the union vocab (mirrors RangeNode's kc path)
                        import bisect as _b
                        l = 0
                        if lo is not None:
                            l = _b.bisect_left(col.vocab, str(lo))
                            if not inc_lo and l < len(col.vocab) \
                                    and col.vocab[l] == str(lo):
                                l += 1
                        h = len(col.vocab) - 1
                        if hi is not None:
                            h = _b.bisect_right(col.vocab, str(hi)) - 1
                            if not inc_hi and h >= 0 \
                                    and col.vocab[h] == str(hi):
                                h -= 1
                        flo, fhi = float(l), float(h)
                    else:
                        flo = -np.inf if lo is None else float(lo)
                        fhi = np.inf if hi is None else float(hi)
                        if lo is not None and not inc_lo:
                            flo = np.nextafter(flo, np.inf)
                        if hi is not None and not inc_hi:
                            fhi = np.nextafter(fhi, -np.inf)
                    fr_col[qi, ri] = ci
                    fr_lo[qi, ri] = flo
                    fr_hi[qi, ri] = fhi
                    fr_neg[qi, ri] = int(neg)
                    ri += 1
                elif isinstance(node, TermFilterNode):
                    ci, col = col_idx(node.field_name)
                    vals = node.values_per_query[0] \
                        if node.values_per_query else []
                    for vi, v in enumerate(vals[:F_TERM_VALS]):
                        if col is None:
                            break
                        if col.kind == "keyword":
                            import bisect as _b
                            p = _b.bisect_left(col.vocab, str(v))
                            ft_targets[qi, ti, vi] = float(p) \
                                if p < len(col.vocab) \
                                and col.vocab[p] == str(v) else np.nan
                        else:
                            try:
                                ft_targets[qi, ti, vi] = float(v)
                            except (TypeError, ValueError):
                                ft_targets[qi, ti, vi] = np.nan
                    ft_col[qi, ti] = ci
                    ft_neg[qi, ti] = int(neg)
                    ti += 1
        return (tuple(fields), fr_col, fr_lo, fr_hi, fr_neg,
                ft_col, ft_targets, ft_neg)

    # -- host-side doc resolution ------------------------------------------

    def resolve(self, docs: np.ndarray):
        """global doc ids -> (entry_idx, local) via the base table."""
        ei = np.searchsorted(self.bases, docs, side="right") - 1
        ei = np.clip(ei, 0, len(self.entries) - 1)
        local = docs - self.bases[ei]
        return ei, local

    def source_of(self, doc: int):
        ei = int(np.searchsorted(self.bases, doc, side="right") - 1)
        seg = self.entries[ei][1]
        local = int(doc - self.bases[ei])
        return seg.stored[local], seg.types[local], seg.ids[local]

    def warmup(self, field: str,
               shapes=((1, 32, 16), (32, 32, 16), (1, 64, 16)),
               filtered_shapes=((1, 32, 16), (32, 32, 16))) -> None:
        """Precompile the solo + batcher shapes so first queries don't eat a
        multi-second XLA compile (p99 guard): Q in {1, 32} covers every solo
        and dynamically-batched request (the Q/S buckets in _build_slots
        steer traffic onto exactly these), for both the plain and the
        filtered kernel. The persistent compile cache makes this a one-time
        cost per machine."""
        pf = self._fields.get(field)
        if pf is None:
            return
        common = (pf.doc_ids, pf.tf, pf.dl, self.live_dev,
                  jnp.int32(self.pad_doc), jnp.float32(1.2),
                  jnp.float32(0.75), jnp.float32(1.0), jnp.float32(0.0))
        for (q, s, k) in shapes:
            packed = np.zeros((q, 3 * s + 1), np.int32)
            packed[:, 3 * s] = 1
            bm25_serve_packed(jnp.asarray(packed), *common,
                              S=s, CHUNK=CHUNK, R=4, k=k)
        for (q, s, k) in filtered_shapes:
            packed = np.zeros((q, 3 * s + 1), np.int32)
            packed[:, 3 * s] = 1
            bm25_serve_packed_filtered(
                jnp.asarray(packed), *common,
                jnp.zeros((1, self.n_pad_total), jnp.float64),
                jnp.full((q, F_RANGE), -1, jnp.int32),
                jnp.zeros((q, F_RANGE)), jnp.zeros((q, F_RANGE)),
                jnp.zeros((q, F_RANGE), jnp.int32),
                jnp.full((q, F_TERM), -1, jnp.int32),
                jnp.full((q, F_TERM, F_TERM_VALS), jnp.nan),
                jnp.zeros((q, F_TERM), jnp.int32),
                S=s, CHUNK=CHUNK, R=4, k=k,
                FR=F_RANGE, FT=F_TERM, TV=F_TERM_VALS)
