"""NodeService: the single-process coordinator over local indices.

Plays the role of the reference's Node + action layer for the local case
(/root/reference/src/main/java/org/elasticsearch/node/Node.java + action/ —
SURVEY.md §2.7): create/delete index (master ops), document CRUD + bulk
(replicated-write template collapses to the local primary), and the search
scatter-gather driver (TransportSearchTypeAction QUERY_THEN_FETCH:
§3.2 call stack — query phase on all shards, controller reduce, fetch from
winners only, aggregation tree reduce).
"""

from __future__ import annotations

import fnmatch
import functools
import logging
import os
import re
import threading
import time
from typing import Any

from .common import tracing
from .common.settings import Settings
from .index.engine import (DocumentMissingException, EngineResult,
                           VersionConflictException)
from .index.index_service import IndexService
from .search import controller
from .search.aggs import parse_aggs, merge_shard_partials, render as render_aggs
from .search.query_dsl import QueryParsingException
from .search.shard_searcher import ShardSearcher
from .serving.executor import PACKED_BODY_KEYS


class IndexMissingException(Exception):
    def __init__(self, index: str):
        # the reference's message format: "[name] missing"
        # (ref IndexMissingException.java)
        super().__init__(f"[{index}] missing")
        self.index = index


class IndexAlreadyExistsException(Exception):
    def __init__(self, index: str):
        super().__init__(f"index [{index}] already exists")
        self.index = index


class InvalidIndexNameException(Exception):
    pass


class IndexClosedException(Exception):
    """Operations on a closed index are blocked (ref ClusterBlockException
    for INDEX_CLOSED_BLOCK; HTTP 403)."""

    def __init__(self, index: str):
        super().__init__(f"blocked by: [FORBIDDEN/4/index closed] [{index}]")
        self.index = index


# invalid characters, not an allowlist: unicode index names are legal
# (ref MetaDataCreateIndexService.validateIndexName)
_INDEX_BAD_CHARS = set(' "*\\<>|,/?#')


class _ValidIndex:
    @staticmethod
    def match(name: str):
        if not name or name != name.lower():
            return None
        if name.startswith(("_", "-", "+")):
            return None
        if any(c in _INDEX_BAD_CHARS for c in name):
            return None
        if name in (".", ".."):
            return None
        return True


_VALID_INDEX = _ValidIndex()

logger = logging.getLogger("elasticsearch_tpu.node")


def alias_dict(x) -> dict:
    """Normalize persisted alias forms (legacy name lists or prop dicts)
    into {name: props}; a bare "routing" fans out to both routings
    (ref AliasAction/AliasMetaData semantics)."""
    if isinstance(x, dict):
        out = {k: dict(v or {}) for k, v in x.items()}
    else:
        out = {a: {} for a in (x or [])}
    for props in out.values():
        if "routing" in props:
            props.setdefault("index_routing", props["routing"])
            props.setdefault("search_routing", props["routing"])
        for k in ("routing", "index_routing", "search_routing"):
            if k in props:
                props[k] = str(props[k])   # routing values are strings
    return out


class NodeService:
    """One node holding every shard locally (multi-node arrives with the
    cluster layer; the API surface is already the distributed one)."""

    def __init__(self, data_path: str, settings: Settings | None = None,
                 cluster_name: str = "elasticsearch-tpu"):
        self.data_path = data_path
        self.settings = settings or Settings()
        self.cluster_name = cluster_name
        # CPython GC tuning — the JVM-flags analog (the reference ships
        # curated GC defaults in bin/elasticsearch.in.sh). A node keeps
        # millions of long-lived container objects alive (segment postings,
        # caches, buffered docs); CPython's default (700, 10, 10) gc
        # thresholds re-walk all of them every few bulk requests — measured
        # ~40% of a 100k-doc ingest spent in gen2 sweeps. Raising the
        # thresholds keeps cycle collection alive but amortized.
        # node.gc.threshold0 <= 0 opts out entirely.
        import gc
        _gt0 = int(self.settings.get("node.gc.threshold0", 50_000))
        if _gt0 > 0:
            gc.set_threshold(
                _gt0, int(self.settings.get("node.gc.threshold1", 25)),
                int(self.settings.get("node.gc.threshold2", 25)))
        from .common.breaker import CircuitBreakerService
        self.breakers = CircuitBreakerService(self.settings)
        # device ownership (ISSUE 19): `node.devices` carves this node's
        # disjoint device subset out of jax.devices() (DevicePool with a
        # private dispatch lock → EXEC_LOCK off the per-node hot path);
        # `cluster.mesh.coordinator` arms jax.distributed multi-host
        # init. Both default off → the legacy shared pool.
        from .parallel.mesh import maybe_init_distributed, resolve_device_pool
        maybe_init_distributed(self.settings)
        self.device_pool = resolve_device_pool(self.settings)
        # node-level cache subsystem (indices/cache_service.py): request
        # responses, parsed query plans, fielddata columns — byte-accounted
        # LRU tiers behind one core (ref IndicesRequestCache +
        # LRUQueryCache + IndicesFieldDataCache)
        from .indices import IndicesCacheService
        self.caches = IndicesCacheService(self.settings, self.breakers)
        self.indices: dict[str, IndexService] = {}
        self.closed: dict[str, dict] = {}     # closed index -> metadata
        self.templates: dict[str, dict] = {}
        # scroll contexts: id -> (index expr, body, cursor, expiry)
        # (ref SearchService keep-alive reaper, SearchService.java:132,166);
        # locked: the REST server is threaded
        import threading
        self._scrolls: dict[str, dict] = {}
        self._scroll_seq = 0
        self._scroll_lock = threading.Lock()
        os.makedirs(data_path, exist_ok=True)
        from .snapshots import SnapshotsService
        self.snapshots = SnapshotsService(self)
        from .common.metrics import (IndexingSlowLog, Meter, MetricsRegistry,
                                     PhaseTimers, SlowLog)
        self.phase_timers = PhaseTimers()
        self.metrics = MetricsRegistry()
        self.slowlog = SlowLog()
        self.indexing_slowlog = IndexingSlowLog()
        # node-wide windowed op rates (1m/5m/15m EWMA) — `_nodes/stats`
        # `rates` section + the /_metrics scrape; per-index meters live on
        # each IndexService
        self.meters: dict[str, Meter] = {"search": Meter(),
                                         "indexing": Meter(),
                                         "get": Meter()}
        # task registry: every coordinator + shard-level action in flight
        # (ref tasks/TaskManager; GET /_tasks)
        from .common.tasks import TaskManager
        self.tasks = TaskManager("tpu-node-0")
        # span tracer (common/tracing.py): per-request span trees rooted
        # at the task trace id, retained in a bounded ring under
        # node.tracing.* settings — GET /_traces
        from .common.tracing import Tracer
        self.tracer = Tracer(self.settings)
        # named bounded executors (ref ThreadPool.java:116); the HTTP layer
        # routes each request class through its pool, overflow -> 429
        from .common.threadpool import ThreadPool
        self.thread_pool = ThreadPool(self.settings)
        # serving-QoS admission control (serving/qos.py, ISSUE 9): per-
        # traffic-class load shedding in front of the pools, driven by
        # queue depth + breaker pressure + an EWMA of request latency —
        # the same signal the batcher's deadline-aware window and the
        # hedged-read coordinator key off
        from .serving.qos import QosController
        self.qos = QosController(self.settings,
                                 thread_pool=self.thread_pool,
                                 breakers=self.breakers)
        # NodeEnvironment dir lock (ref env/NodeEnvironment.java:118 —
        # an flock on the node dir so two nodes can't share data paths)
        self._node_lock = open(os.path.join(data_path, "node.lock"), "w")
        try:
            import fcntl
            fcntl.flock(self._node_lock, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            self._node_lock.close()
            raise RuntimeError(
                f"failed to obtain node lock on [{data_path}]: is another "
                f"node using the same data path?") from None
        # lifecycle state machine (ref common/component/Lifecycle.java)
        from .common.lifecycle import Lifecycle
        self.lifecycle = Lifecycle()
        # plugins (ref plugins/PluginsService.java:91)
        from .common.plugins import PluginsService
        self.plugins = PluginsService(os.path.join(data_path, "plugins"))
        # file-script hot reload via the resource watcher (ref watcher/
        # ResourceWatcherService + config/scripts file scripts); the
        # scripts-dir watcher attaches after search_templates exists below
        from .common.watcher import ResourceWatcherService
        self.watcher = ResourceWatcherService()
        from .serving.batcher import SearchBatcher
        self._batcher = SearchBatcher(self)
        tpl_path = os.path.join(data_path, "_templates.json")
        if os.path.exists(tpl_path):
            import json
            with open(tpl_path) as f:
                self.templates.update(json.load(f))
        # stored SEARCH templates (mustache-lite bodies, search/templates.py)
        self.search_templates: dict[str, Any] = {}
        st_path = os.path.join(data_path, "_search_templates.json")
        if os.path.exists(st_path):
            import json
            with open(st_path) as f:
                self.search_templates.update(json.load(f))
        self._recover_indices()
        for svc in self.indices.values():
            svc.mappers.search_templates = self.search_templates
        from .common.watcher import FileWatcher
        scripts_dir = os.path.join(data_path, "scripts")
        os.makedirs(scripts_dir, exist_ok=True)
        self.watcher.add(FileWatcher(scripts_dir, _ScriptDirListener(self)))
        self.watcher.start()     # interval thread: hot reload after boot
        self.plugins.on_node_start(self)
        import threading as _th
        self._maint_stop = _th.Event()
        _th.Thread(target=self._maintenance_loop, daemon=True,
                   name="es[index_maintenance]").start()
        # stats-history sampler (common/monitor.StatsSampler): a bounded
        # ring of node-gauge snapshots on a cadence (ref monitor/ services;
        # `node.sampler.interval` seconds, <=0 disables the thread — tests
        # drive sample() manually either way)
        from .common.monitor import StatsSampler
        try:
            interval = float(self.settings.get("node.sampler.interval", 10))
        except (TypeError, ValueError):
            interval = 10.0
        self.sampler = StatsSampler(self._sampler_snapshot,
                                    interval_s=interval)
        self.sampler.start()
        # self-monitoring collector (ISSUE 17 tentpole (c)): opt-in
        # sampler->`.monitoring-es-*` pipeline through the bulk lane,
        # served back by GET /_monitoring/overview via the sorted +
        # sub-agg device lanes (common/monitoring.py)
        from .common.monitoring import MonitoringCollector
        self.monitoring = MonitoringCollector.from_settings(self)
        if self.monitoring is not None:
            self.monitoring.start()
        # watcher alerting tier (ISSUE 20): registry recovered from the
        # `.watches` index; document watches ride the monitoring
        # collector's percolate batch, aggregation watches the scheduler
        # (watcher/service.py). `self.watcher` is the file-resource
        # watcher above — hence `watcher_service`.
        from .watcher.service import WatcherService
        self.watcher_service = WatcherService.from_settings(self)
        self.lifecycle.move_to_started()

    # -- index management (master ops, ref MetaDataCreateIndexService) ----

    def _recover_indices(self) -> None:
        """Reopen on-disk indices (gateway recovery, SURVEY.md §5.4(b));
        closed indices register metadata-only (no engines)."""
        import json
        for name in sorted(os.listdir(self.data_path)):
            meta_path = os.path.join(self.data_path, name, "_meta.json")
            if not os.path.exists(meta_path):
                continue
            with open(meta_path) as f:
                meta = json.load(f)
            if meta.get("state") == "close":
                self.closed[name] = meta
                continue
            self.indices[name] = IndexService(
                name, os.path.join(self.data_path, name),
                Settings(meta.get("settings", {})), meta.get("mappings", {}),
                breakers=self.breakers, caches=self.caches)
            self.indices[name].aliases = alias_dict(meta.get("aliases", []))

    def _persist_index_meta(self, svc: IndexService) -> None:
        import json
        meta = {"settings": dict(svc.settings),
                "mappings": svc.mappings_dict(),
                "aliases": dict(sorted(svc.aliases.items()))}
        path = os.path.join(svc.path, "_meta.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, path)

    def create_index(self, name: str, settings: dict | None = None,
                     mappings: dict | None = None,
                     aliases: dict | None = None) -> IndexService:
        if name in self.indices or name in self.closed:
            raise IndexAlreadyExistsException(name)
        if not _VALID_INDEX.match(name) or name != name.lower():
            raise InvalidIndexNameException(f"invalid index name [{name}]")
        merged_settings = dict(settings or {})
        merged_mappings = dict(mappings or {})
        merged_aliases = alias_dict(aliases or {})
        # index templates (ref MetaDataIndexTemplateService): apply by pattern
        for tname, tpl in sorted(self.templates.items(),
                                 key=lambda kv: kv[1].get("order", 0)):
            if fnmatch.fnmatch(name, tpl.get("template", "*")):
                for k, v in (tpl.get("settings") or {}).items():
                    merged_settings.setdefault(k, v)
                for t, m in (tpl.get("mappings") or {}).items():
                    merged_mappings.setdefault(t, m)
                for a, props in alias_dict(tpl.get("aliases")
                                           or {}).items():
                    merged_aliases.setdefault(a, props)
        svc = IndexService(name, os.path.join(self.data_path, name),
                           Settings(merged_settings), merged_mappings,
                           breakers=self.breakers, caches=self.caches)
        errs = getattr(svc.mappers.analysis, "build_errors", None)
        if errs:
            # strict at CREATE time (the user can fix the request); node
            # RECOVERY of existing indices stays lenient (code review r5)
            svc.close()
            import shutil
            shutil.rmtree(svc.path, ignore_errors=True)
            raise ValueError("analysis configuration: " + "; ".join(errs))
        svc.aliases = merged_aliases
        svc.mappers.search_templates = self.search_templates
        self.indices[name] = svc
        self._persist_index_meta(svc)
        return svc

    def delete_index(self, name: str) -> None:
        import shutil
        deleted_closed = False
        for n in list(self.closed):
            if n == name or fnmatch.fnmatch(n, name) \
                    or name in ("_all", "*", ""):
                self.closed.pop(n)
                shutil.rmtree(os.path.join(self.data_path, n),
                              ignore_errors=True)
                deleted_closed = True
        if deleted_closed and name not in self.indices \
                and "*" not in name and name not in ("_all", ""):
            return     # the exact name was a closed index: done
        for n in self._resolve(name):
            svc = self.indices.pop(n)
            svc.close()
            svc.delete_files()

    def close_index(self, expr: str) -> list[str]:
        """Close indices: engines shut down, device memory released, data
        retained; reads/writes are blocked until reopened
        (ref MetaDataIndexStateService.closeIndex)."""
        names = self._resolve(expr)
        for n in names:
            svc = self.indices.pop(n)
            meta = {"settings": dict(svc.settings),
                    "mappings": svc.mappings_dict(),
                    "aliases": dict(sorted(svc.aliases.items())),
                    "state": "close"}
            svc.flush()
            svc.close()
            self.closed[n] = meta
            self._persist_meta_dict(n, meta)
        return names

    def open_index(self, expr: str) -> list[str]:
        """Reopen closed indices (ref MetaDataIndexStateService.openIndex)."""
        names = [n for n in self.closed
                 if n == expr or fnmatch.fnmatch(n, expr)
                 or expr in ("_all", "*", "")]
        if not names and "*" not in expr and expr not in self.indices:
            raise IndexMissingException(expr)
        for n in names:
            meta = self.closed.pop(n)
            meta = {**meta, "state": "open"}
            svc = IndexService(n, os.path.join(self.data_path, n),
                               Settings(meta.get("settings", {})),
                               meta.get("mappings", {}),
                               breakers=self.breakers, caches=self.caches)
            svc.aliases = alias_dict(meta.get("aliases", []))
            svc.mappers.search_templates = self.search_templates
            self.indices[n] = svc
            self._persist_meta_dict(n, meta)
        return names

    def _persist_meta_dict(self, name: str, meta: dict) -> None:
        import json
        path = os.path.join(self.data_path, name, "_meta.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, path)

    def _resolve(self, expr: str) -> list[str]:
        """Index expression: name, alias, comma list, wildcards, _all.
        Wildcards expand to OPEN indices only (expand_wildcards=open, the
        reference default); naming a closed index directly is a 403."""
        if expr in ("_all", "*", ""):
            return list(self.indices)
        out: list[str] = []
        for part in expr.split(","):
            if part in self.indices:
                out.append(part)
                continue
            if part in self.closed:
                raise IndexClosedException(part)
            matched = [n for n, svc in self.indices.items()
                       if part in svc.aliases or fnmatch.fnmatch(n, part)]
            if not matched and "*" not in part:
                raise IndexMissingException(part)
            out.extend(m for m in matched if m not in out)
        return out

    def index_service(self, name: str) -> IndexService:
        svcs = self._resolve(name)
        if not svcs:
            raise IndexMissingException(name)
        return self.indices[svcs[0]]

    # -- document ops ------------------------------------------------------

    def index_doc(self, index: str, doc_id: str | None, source: dict,
                  type_name: str = "_doc", auto_create: bool = True,
                  **kw) -> tuple[str, EngineResult]:
        """ref TransportIndexAction.java:63 — auto-creates the index like
        the reference's create-index-on-first-doc behavior."""
        if index not in self.indices:
            if index in self.closed:
                raise IndexClosedException(index)
            if not auto_create:
                raise IndexMissingException(index)
            if not _VALID_INDEX.match(index):
                raise InvalidIndexNameException(index)
            self.create_index(index)
        if doc_id is None:
            import uuid
            doc_id = uuid.uuid4().hex[:20]
        svc = self.indices[index]
        t0 = time.perf_counter()
        res = svc.index_doc(doc_id, source, type_name=type_name, **kw)
        self.meters["indexing"].mark()
        self.indexing_slowlog.maybe_log(
            svc.settings, index, (time.perf_counter() - t0) * 1000, doc_id)
        return index, res

    def get_doc(self, index: str, doc_id: str, **kw):
        self.meters["get"].mark()
        return self.index_service(index).get_doc(doc_id, **kw)

    def delete_doc(self, index: str, doc_id: str, **kw):
        self.meters["indexing"].mark()
        return self.index_service(index).delete_doc(doc_id, **kw)

    def update_doc(self, index: str, doc_id: str, body: dict,
                   type_name: str = "_doc",
                   version: int | None = None,
                   routing: str | None = None,
                   parent: str | None = None,
                   timestamp=None, ttl=None,
                   sync: bool | None = None) -> tuple[EngineResult, bool]:
        """Scripted/partial update: get -> transform -> reindex
        (ref action/update/UpdateHelper.java:61). Returns (result, noop).
        Auto-creates the index like the reference's update-with-upsert.
        routing/parent route the get AND carry into the re-index so child
        documents keep their _parent (code review r5)."""
        if index not in self.indices:
            if not _VALID_INDEX.match(index):
                raise InvalidIndexNameException(index)
            self.create_index(index)
        svc = self.index_service(index)
        if routing is None and parent is None \
                and svc.mappers.parent_type_of(type_name):
            from .mapping.mapper import RoutingMissingException
            raise RoutingMissingException(
                f"routing is required for [{index}]/[{type_name}]/"
                f"[{doc_id}]")
        cur = svc.get_doc(doc_id, routing=routing, parent=parent)
        if not cur.found:
            if version is not None:
                # update-with-version on a missing doc is a CONFLICT
                # (ref UpdateRequest validation / VersionConflictEngine-
                # Exception on upsert-with-version)
                raise VersionConflictException(doc_id, -1, version)
            if "upsert" in body:
                upsert = dict(body["upsert"])
                # inline metadata in the upsert doc (legacy ES form the
                # YAML suites use: {"foo": "bar", "_parent": 5})
                meta_parent = upsert.pop("_parent", None)
                meta_routing = upsert.pop("_routing", None)
                res = svc.index_doc(
                    doc_id, upsert, type_name=type_name,
                    routing=routing if routing is not None
                    else (str(meta_routing)
                          if meta_routing is not None else None),
                    parent=parent if parent is not None
                    else (str(meta_parent)
                          if meta_parent is not None else None),
                    timestamp=timestamp, ttl=ttl, sync=sync)
                return res, False
            if body.get("doc_as_upsert") and "doc" in body:
                res = svc.index_doc(doc_id, body["doc"], type_name=type_name,
                                    routing=routing, parent=parent,
                                    timestamp=timestamp, ttl=ttl, sync=sync)
                return res, False
            raise DocumentMissingException(f"[{type_name}][{doc_id}]: document missing")
        if version is not None and cur.version != version:
            raise VersionConflictException(doc_id, cur.version, version)
        src = dict(cur.source)
        if "script" in body:
            from .script.engine import run_update_script
            src, op = run_update_script(body["script"], src,
                                        params=body.get("params")
                                        or (body["script"].get("params")
                                            if isinstance(body["script"], dict)
                                            else None))
            # honor ctx.op like the reference's UpdateHelper: delete deletes,
            # anything other than index (none/create) is a noop
            # (ref UpdateHelper.java:246-249 else-branch -> Operation.NONE)
            if op == "delete":
                res = svc.delete_doc(doc_id, sync=sync)
                return res, False
            if op != "index":
                return EngineResult(doc_id=doc_id, version=cur.version,
                                    created=False), True
        elif "doc" in body:
            merged = _deep_merge(src, body["doc"])
            # metadata-only updates (new ttl/timestamp) are NOT noops
            if body.get("detect_noop", True) and merged == src \
                    and ttl is None and timestamp is None:
                return EngineResult(doc_id=doc_id, version=cur.version,
                                    created=False), True
            src = merged
        if parent is None and svc.mappers.parent_type_of(cur.type_name):
            # child docs route by parent id, so the stored routing IS the
            # parent (ref UpdateHelper preserves _parent across the reindex)
            parent = routing if routing is not None else cur.routing
        res = svc.index_doc(doc_id, src, type_name=cur.type_name,
                            version=cur.version,
                            routing=routing if routing is not None
                            else cur.routing,
                            parent=parent,
                            timestamp=timestamp, ttl=ttl, sync=sync)
        return res, False

    def bulk(self, operations: list[tuple[str, dict, dict | None]]) -> list[dict]:
        """ops: (action, meta, source). ref TransportBulkAction splits by
        shard; TransportShardBulkAction applies a shard's slice as ONE pass.

        Contiguous runs of index/create/delete ops ride the VECTORIZED
        batch lane (index/bulk_ingest.py): per index, one
        IndexService.bulk_ingest call — batched analysis, columnar segment
        append, group-commit translog. Updates, unknown actions, disabled
        indices (`index.bulk.vectorized.enable: false`) and any setup
        failure fall back to the per-doc path with identical per-item
        semantics. ALL actions (updates included) share the deferred-sync
        contract: translog fsyncs collapse to ONE sync per touched index
        at the end — the reference's per-request durability."""
        from .common.breaker import CircuitBreakingException
        from .common.metrics import record_bulk_ingest
        from .index.bulk_ingest import BulkOp
        from .index.engine import EngineResult

        items: list = [None] * len(operations)
        touched: set[str] = set()
        fallback_ops = 0

        def error_item(pos, action, index, doc_id, e) -> None:
            if isinstance(e, VersionConflictException):
                st = 409
            elif isinstance(e, CircuitBreakingException):
                st = 429
            else:
                st = 400
            items[pos] = {action: {"_index": index, "_id": doc_id,
                                   "status": st, "error": str(e)}}

        def per_op(pos, action, meta, source) -> None:
            nonlocal fallback_ops
            fallback_ops += 1
            index = meta.get("_index")
            type_name = meta.get("_type", "_doc")
            doc_id = meta.get("_id")
            try:
                if action in ("index", "create"):
                    _, res = self.index_doc(
                        index, doc_id, source, type_name=type_name,
                        op_type="create" if action == "create" else "index",
                        routing=meta.get("_routing") or meta.get("routing"),
                        parent=meta.get("_parent") or meta.get("parent"),
                        sync=False)
                    touched.add(index)
                    items[pos] = {action: {
                        "_index": index, "_type": type_name, "_id": res.doc_id,
                        "_version": res.version,
                        "status": 201 if res.created else 200}}
                elif action == "delete":
                    res = self.delete_doc(index, doc_id, sync=False)
                    touched.add(index)
                    items[pos] = {"delete": {
                        "_index": index, "_type": type_name, "_id": doc_id,
                        "_version": res.version, "found": res.found,
                        "status": 200 if res.found else 404}}
                elif action == "update":
                    # updates join the deferred-sync + group-commit
                    # contract like index/delete (they used to fsync per
                    # op AND miss the end-of-request sync entirely)
                    res, noop = self.update_doc(index, doc_id, source,
                                                type_name=type_name,
                                                sync=False)
                    touched.add(index)
                    items[pos] = {"update": {
                        "_index": index, "_type": type_name, "_id": doc_id,
                        "_version": res.version, "status": 200}}
                else:
                    items[pos] = {action: {
                        "status": 400,
                        "error": f"unknown action [{action}]"}}
            except Exception as e:  # noqa: BLE001 — per-item error contract
                error_item(pos, action, index, doc_id, e)

        run: list[tuple[int, str, dict, dict | None, int]] = []

        def flush_run() -> None:
            nonlocal fallback_ops
            if not run:
                return
            groups: dict = {}
            for entry in run:
                groups.setdefault(entry[2].get("_index"), []).append(entry)
            for index, entries in groups.items():
                svc = None
                try:
                    if index not in self.indices:
                        if index in self.closed:
                            raise IndexClosedException(index)
                        if not _VALID_INDEX.match(index):
                            raise InvalidIndexNameException(index)
                        self.create_index(index)
                    svc = self.indices[index]
                except Exception:  # noqa: BLE001 — per-op path reports it
                    svc = None
                if svc is None or not svc._bulk_vectorized:
                    for pos, action, meta, source, _rl in entries:
                        per_op(pos, action, meta, source)
                    continue
                batch = []
                batch_append = batch.append
                for pos, action, meta, source, raw_len in entries:
                    m_get = meta.get
                    doc_id = m_get("_id")
                    if doc_id is None:
                        if action == "delete":   # delete without id: let
                            per_op(pos, action, meta, source)  # it 400
                            continue
                        import uuid
                        doc_id = uuid.uuid4().hex[:20]
                    elif doc_id.__class__ is not str:
                        doc_id = str(doc_id)
                    routing = m_get("_routing")
                    if routing is None:
                        routing = m_get("routing")
                    parent = m_get("_parent")
                    if parent is None:
                        parent = m_get("parent")
                    # positional BulkOp: kwarg binding costs real time at
                    # 100k ops/request
                    batch_append((pos, action, meta, BulkOp(
                        action, doc_id, source,
                        m_get("_type") or "_doc",
                        routing, parent, raw_len=raw_len)))
                if not batch:
                    continue
                ops = [b[3] for b in batch]
                try:
                    results = svc.bulk_ingest(ops)
                except Exception as e:  # noqa: BLE001 — must not 500 the
                    # request: unapplied ops report the failure per item
                    results = [e] * len(ops)
                touched.add(index)
                self.meters["indexing"].mark(len(ops))
                for (pos, action, meta, op), res in zip(batch, results):
                    if not isinstance(res, EngineResult):
                        error_item(pos, action, index, op.doc_id, res)
                    elif action == "delete":
                        items[pos] = {"delete": {
                            "_index": index, "_type": op.type_name,
                            "_id": meta.get("_id"), "_version": res.version,
                            "found": res.found,
                            "status": 200 if res.found else 404}}
                    else:
                        items[pos] = {action: {
                            "_index": index, "_type": op.type_name,
                            "_id": res.doc_id, "_version": res.version,
                            "status": 201 if res.created else 200}}
            run.clear()

        for pos, op_t in enumerate(operations):
            # ops are (action, meta, source) or (action, meta, source,
            # raw_len) — _parse_bulk adds the raw source line's byte
            # length so the engine's buffer estimate skips a dict walk
            action = op_t[0]
            if action in ("index", "create", "delete"):
                run.append((pos, action, op_t[1], op_t[2],
                            op_t[3] if len(op_t) > 3 else 0))
            else:
                flush_run()          # order matters: an update may read a
                per_op(pos, action, op_t[1], op_t[2])  # doc this bulk indexed
        flush_run()
        for name in touched:
            svc = self.indices.get(name)
            if svc is not None:
                svc.sync_translogs()
        # shared indexing-buffer budget across shards (the reference's
        # IndexingMemoryController runs on a schedule; per-bulk keeps the
        # invariant without a thread)
        self.check_indexing_memory()
        if operations:
            record_bulk_ingest(len(operations),
                               vectorized=fallback_ops == 0)
        return items

    # -- search (the QUERY_THEN_FETCH driver, SURVEY §3.2) -----------------

    def _trace_ids(self) -> tuple[str | None, str | None]:
        """(trace_id, opaque_id) of the current request, from the active
        task (REST path) or profiler (direct calls) — stamps slowlog
        entries so one id correlates slowlog + tasks + profile."""
        from .common.metrics import current_profiler
        from .common.tasks import current_task
        t = current_task()
        if t is not None:
            return t.trace_id, t.opaque_id
        p = current_profiler()
        if p is not None:
            return p.trace_id, None
        return None, None

    def _record_phase(self, phase: str, ms: float) -> None:
        self.phase_timers.record(phase, ms)
        self.metrics.record(f"search.{phase}", ms)
        if phase == "total":
            # feed the QoS latency EWMA: every served search, every lane
            self.qos.record_latency(ms)

    def _parse_cached(self, name: str, query):
        """Parse a query through the node-level query-plan cache
        (indices/cache_service): repeated query templates skip host-side
        re-parse, and a stable tree keeps the jit compile-cache keys
        stable too. Parsed trees are execution-stateless (every
        per-segment computation flows through SegmentContext), so sharing
        one tree across requests is safe; bodies the cache refuses (date
        math, templates, ...) parse fresh."""
        svc = self.indices[name]
        from .search.query_parser import QueryParser
        key = self.caches.plan_key(name, svc._incarnation,
                                   svc.mappers.mapping_version(), query)
        node = self.caches.get_plan(key)
        if node is None:
            node = QueryParser(svc.mappers).parse(query)
            self.caches.put_plan(key, node)
        return node

    def search(self, index: str, body: dict | None = None,
               size: int | None = None, from_: int | None = None,
               scroll: str | None = None, scan: bool = False,
               request_cache: bool | None = None) -> dict:
        """Entry point: installs a RequestProfiler when the body carries
        `"profile": true` (ref search/profile — the per-request timing
        tree), then runs the QUERY_THEN_FETCH driver."""
        body = body or {}
        if not body.get("profile") or scroll is not None:
            return self._search_exec(index, body, size=size, from_=from_,
                                     scroll=scroll, scan=scan,
                                     request_cache=request_cache)
        from .common.metrics import (RequestProfiler, current_profiler,
                                     use_profiler)
        from .common.tasks import current_task
        task = current_task()
        if current_profiler() is not None:   # nested (warmer/percolate)
            return self._search_exec(index, body, size=size, from_=from_,
                                     request_cache=request_cache)
        prof = RequestProfiler(
            trace_id=task.trace_id if task is not None else None)
        from .common.device_stats import record_lanes
        with use_profiler(prof), record_lanes() as lanes:
            resp = self._search_exec(index, body, size=size, from_=from_,
                                     request_cache=False)
        resp["profile"] = prof.render(
            opaque_id=task.opaque_id if task is not None else None)
        # the lane-decision flight record: which execution lane served each
        # component and every (lane, reason) decline on the ladder walk
        resp["profile"]["lanes"] = lanes.explain()
        return resp

    def _search_exec(self, index: str, body: dict | None = None,
                     size: int | None = None, from_: int | None = None,
                     scroll: str | None = None, scan: bool = False,
                     request_cache: bool | None = None) -> dict:
        t0 = time.perf_counter()
        tns0 = tracing.now_ns()
        body = body or {}
        if "template" in body and "query" not in body:
            # body-level search template (ref RestSearchTemplateAction when
            # the template arrives inside a plain _search body)
            from .search.templates import render_template
            rendered = render_template(body["template"],
                                       self.search_templates)
            if isinstance(rendered, str):
                import json as _json
                rendered = _json.loads(rendered)
            body = {**{k: v for k, v in body.items() if k != "template"},
                    **rendered}
        size = int(body.get("size", 10) if size is None else size)
        from_ = int(body.get("from", 0) if from_ is None else from_)
        if scroll is not None:
            return self._scroll_start(index, body, size, scroll, scan=scan)
        names = self._resolve(index)
        if not names:
            raise IndexMissingException(index)
        for n in names:   # stats-group tallies (body "stats": [tags])
            for tag in body.get("stats") or []:
                svc = self.indices[n]
                svc.search_groups[tag] = svc.search_groups.get(tag, 0) + 1

        # shard request cache (indices/cache_service.IndicesRequestCache):
        # size-0 bodies are cacheable by default, keyed on body + reader
        # generation; any refresh/delete/merge rotates the generation =
        # auto-invalidation. `index.requests.cache.enable: false` opts an
        # index out; an explicit `?request_cache=true` overrides it per
        # request (the reference's per-request override contract).
        cacheable = (request_cache is not False and size == 0
                     and from_ == 0
                     and (request_cache or "script_fields" not in body))
        if cacheable and request_cache is None:
            cacheable = all(_req_cache_enabled(self.indices[n].settings)
                            for n in names)
        cache_key = None
        if cacheable:
            import json as _json
            try:
                body_json = _json.dumps(body, sort_keys=True, default=str)
                # wall-clock-relative date math must never cache (the
                # reference refuses now-based requests the same way)
                if "now" in body_json:
                    cache_key = None
                else:
                    gens = tuple(
                        (n, self.indices[n]._incarnation,
                         self.indices[n].reader_generation())
                        for n in names)
                    # the raw index EXPRESSION is part of the key: a
                    # filtered alias and its index must not share entries
                    cache_key = (str(index), body_json, gens)
            except TypeError:
                cache_key = None
            if cache_key is not None:
                hit = self.caches.request_cache.get(cache_key)
                if hit is not None:
                    for n in names:
                        self.indices[n].request_cache_hits += 1
                    return hit
                for n in names:
                    self.indices[n].request_cache_misses += 1

        alias_flt = self._alias_filters_by_index(index, names)
        if len(names) == 1 and alias_flt:
            # single index: wrapping the body keeps the packed lane eligible
            body = {**body, "query": self._wrap_alias_query(
                body.get("query"), alias_flt[names[0]])}
            alias_flt = {}
        from .search.sort import parse_sort
        sort = parse_sort(body.get("sort"),
                          [self.indices[n].mappers for n in names])

        # the packed fast path: one device program over every shard/segment
        # of the index (serving/packed_view) — the production serving lane.
        # Concurrent solo requests COALESCE through the batcher: under load
        # the device serves whole queues of independent requests as one
        # program (serving/batcher.py), which is where TPU QPS comes from.
        from .common.device_stats import lane_chosen, lane_decline
        if len(names) == 1:
            try:
                from .search.query_parser import QueryParser
                from .serving.executor import packed_spec_of
                spec = packed_spec_of(
                    QueryParser(self.indices[names[0]].mappers), body)
                if spec is None:
                    lane_decline("serve", "packed", "plan_shape")
                else:
                    key = (names[0], size, from_, spec[1], spec[2], spec[3])
                    with tracing.span("packed_batch", index=names[0]):
                        # queue wait + the shared device program of the
                        # coalesced batch (serving/batcher.py): the span
                        # covers this request's whole stay in the lane
                        out = self._batcher.submit(key, names[0], body,
                                                   spec, size, from_, t0)
                    if out is None:
                        lane_decline("serve", "packed", "batcher_declined")
                    else:
                        lane_chosen("serve", "packed")
                        # batcher lane: only TOTAL is honest here — the
                        # request's wall time includes queue wait and
                        # shared-batch work, not this request's device time
                        took = (time.perf_counter() - t0) * 1000
                        self._record_phase("total", took)
                        tid, oid = self._trace_ids()
                        if self.slowlog.maybe_log(
                                self.indices[names[0]].settings, names[0],
                                took, body, trace_id=tid,
                                opaque_id=oid) is not None:
                            tracing.mark_slowlog()
                        return out
            except Exception:  # noqa: BLE001 — degrade to the general path
                lane_decline("serve", "packed", "error")
                self._packed_error()

        # coalesced general lane (serving/batcher.py, ISSUE 9): bodies the
        # packed kernel can't serve but the batched executor can (plan-
        # shaped queries, aggs, knn, rescore) coalesce behind a leader.
        # The LEADER runs the ordinary solo path below — idle-path latency
        # and solo responses are exactly the pre-QoS engine's — while
        # requests arriving during its run queue as followers and are
        # served as ONE Q>1 batched program riding the stacked/blockwise/
        # mesh replica axis, bitwise-identical to solo execution
        # (tests/test_qos.py parity matrix). Cacheable bodies skip the
        # lane so the request cache keeps filling.
        if (len(names) == 1 and cache_key is None
                and not body.get("profile") and self.qos.enabled()):
            from .common.metrics import current_profiler as _cur_prof
            bkey = self._msearch_batch_key(names[0], body) \
                if _cur_prof() is None else None
            if bkey is not None:
                from .serving.batcher import LEAD
                got = self._batcher.join_batched(bkey, body)
                if got is LEAD:
                    try:
                        return self._search_general(
                            index, names, body, size, from_, sort,
                            alias_flt, cache_key, t0, tns0)
                    finally:
                        self._batcher.drain_batched(bkey, names[0])
                if got is not None:
                    # follower served from the shared batch: only TOTAL is
                    # honest (wall time includes queue wait + shared work)
                    lane_chosen("serve", "batched")
                    took = (time.perf_counter() - t0) * 1000
                    self._record_phase("total", took)
                    tid, oid = self._trace_ids()
                    if self.slowlog.maybe_log(
                            self.indices[names[0]].settings, names[0],
                            took, body, trace_id=tid,
                            opaque_id=oid) is not None:
                        tracing.mark_slowlog()
                    return got
                # timeout/strand/unservable batch: serve solo below
        return self._search_general(index, names, body, size, from_, sort,
                                    alias_flt, cache_key, t0, tns0)

    def _search_general(self, index, names, body, size, from_, sort,
                        alias_flt, cache_key, t0, tns0):
        """The general QUERY_THEN_FETCH driver (mesh -> concurrent fan-out
        -> per-segment ladder) — everything below the fast serving lanes.
        Split from _search_exec so a coalescing LEADER can execute it for
        itself and drain its followers in a finally."""
        # SearchStats query_total for the general path (the packed/batcher
        # lanes and _search_batched count their own serves)
        self.meters["search"].mark()
        for n in names:
            self.indices[n].query_total += 1
            self.indices[n].meters["search"].mark()

        searchers: list[ShardSearcher] = []
        index_of: list[str] = []
        for n in names:
            for s in self.indices[n].searchers():
                searchers.append(s)
                index_of.append(n)

        agg_specs = parse_aggs(body.get("aggs") or body.get("aggregations"))
        query = body.get("query", {"match_all": {}})
        if _contains_mlt(query):
            query = self._expand_mlt(query, names)
        knn = body.get("knn")
        from .search.query_parser import parse_rank
        rank_spec = parse_rank(body.get("rank"))
        rescore_spec = body.get("rescore")
        if isinstance(rescore_spec, list):
            rescore_spec = rescore_spec[0] if rescore_spec else None
        # rescore window must be collected in the query phase
        window = int(rescore_spec.get("window_size", size)) \
            if rescore_spec else 0

        search_after = body.get("search_after")
        if isinstance(search_after, list) and not search_after:
            search_after = None
        if search_after is not None and sort is None:
            raise QueryParsingException("search_after requires a sort")
        if rescore_spec is not None and sort is not None:
            # the reference's RescorePhase rejects rescore+sort outright
            raise QueryParsingException("rescore cannot be used with a sort")
        if rank_spec is not None:
            # hybrid fusion (ISSUE 10): both retrievers must exist, and the
            # fused list has no sort/rescore interpretation
            if knn is None:
                raise QueryParsingException(
                    "rank requires a knn section to fuse with the query")
            if sort is not None:
                raise QueryParsingException("rank cannot be used with a sort")
            if rescore_spec is not None:
                raise QueryParsingException(
                    "rank cannot be combined with rescore")
        rank_window = 0
        knn_nprobe = None
        knn_exact = False
        knn_quant = None
        if knn is not None:
            if agg_specs:
                # the knn phase computes no agg partials; silently returning
                # empty aggregations would be a lie (advisor r1 finding)
                raise QueryParsingException(
                    "aggregations are not supported with knn search")
            raw_np = knn.get("nprobe")
            knn_nprobe = int(raw_np) if raw_np is not None else None
            knn_exact = bool(knn.get("exact", False))
            # per-request quantization override (ISSUE 12): pin the int8/
            # pq scan or force the f32 IVF lane regardless of the index
            # default — the bench measures all three on ONE index this way
            knn_quant = knn.get("quantization")
            if knn_quant is not None and str(knn_quant).strip().lower() \
                    not in ("none", "int8", "pq"):
                raise QueryParsingException(
                    f"knn quantization must be one of [none, int8, pq], "
                    f"got [{knn_quant}]")
            qv_single = knn.get("query_vector")
            if qv_single is None:
                qvs = knn.get("query_vectors")
                if not qvs:
                    raise QueryParsingException(
                        "knn requires query_vector (or query_vectors with "
                        "exactly one entry)")
                if len(qvs) != 1:
                    raise QueryParsingException(
                        "knn search takes one query_vector per request; use "
                        "ShardSearcher.execute_knn for batched vectors")
                qv_single = qvs[0]
            if "field" not in knn:
                raise QueryParsingException("knn requires a field")
            if rank_spec is not None:
                # fusion ranks over a per-retriever window, then returns
                # the caller's size — knn.k defaults to the window
                rank_window = rank_spec.window_size or max(size + from_, 10)
                knn_k = int(knn.get("k", rank_window))
            else:
                # k is the user's neighbor count contract: the response
                # carries at most min(k, size) hits (never silently raised
                # — the reduce below shrinks size instead; k defaults to
                # covering pagination)
                knn_k = int(knn.get("k", size + from_))
                size = min(size, max(knn_k - from_, 0))

        # index-global term statistics, shared by every shard: BOTH serving
        # lanes score with the same IDF, so packed vs fallback answers are
        # identical (VERDICT r3 weak #4; ref search/dfs/DfsPhase semantics,
        # here the default because stats are one host reduce away)
        global_stats = None
        nodes_by_index: dict[str, Any] = {}
        if knn is None or rank_spec is not None:
            from .search.query_dsl import CollectionStats
            terms_by_field: dict[str, set] = {}
            for n in names:
                from .search.query_parser import merge_query_batch
                q_n = self._wrap_alias_query(query, alias_flt[n]) \
                    if n in alias_flt else query
                parsed = self._parse_cached(n, q_n)
                parsed.collect_terms(terms_by_field)
                nodes_by_index[n] = merge_query_batch([parsed])
            all_segs = [seg for s in searchers for seg in s.segments]
            global_stats = CollectionStats.from_segments(
                all_segs, terms_by_field)

        t_parse_done = time.perf_counter()
        self._record_phase("parse", (t_parse_done - t0) * 1000)
        tracing.add_span("parse", tns0, tracing.now_ns())
        from .common.metrics import current_profiler
        prof = current_profiler()
        if prof is not None:
            prof.record_phase("parse", (t_parse_done - t0) * 1000)
        def _run_shard(i: int, s: ShardSearcher,
                       submit_ns: int | None = None):
            # shard-level action registered under the coordinator task
            # (ref TransportSearchTypeAction per-shard phase actions).
            # The trace's shard span covers submit→done; queue_wait
            # (submit→start) and run (start→done) split it so a saturated
            # search pool is visibly queue time, not shard work.
            start_ns = tracing.now_ns()
            with self.tasks.scope(
                    "indices:data/read/search[phase/query]",
                    description=f"shard [{index_of[i]}][{s.shard_id}]"), \
                 _maybe_shard_profile(prof, index_of[i], s.shard_id), \
                 tracing.span("shard",
                              start_ns=submit_ns if submit_ns is not None
                              else start_ns,
                              index=index_of[i], shard=s.shard_id):
                if submit_ns is not None:
                    tracing.add_span("queue_wait", submit_ns, start_ns)
                with tracing.span("run", start_ns=start_ns):
                    if knn is not None:
                        fnode = s.parse([knn["filter"]]) \
                            if knn.get("filter") else None
                        r = s.execute_knn(knn["field"], [qv_single],
                                          k=knn_k,
                                          metric=knn.get("metric",
                                                         "cosine"),
                                          filter_node=fnode,
                                          nprobe=knn_nprobe,
                                          exact=knn_exact,
                                          quantization=knn_quant)
                        if rank_spec is not None:
                            # hybrid fusion: the text retriever runs in
                            # the SAME shard pass; fuse_hybrid merges the
                            # two global lists after the fan-out
                            r_text = s.execute_query_phase(
                                nodes_by_index[index_of[i]],
                                size=rank_window, from_=0,
                                global_stats=global_stats,
                                track_scores=True)
                            r = (r_text, r)
                    else:
                        r = s.execute_query_phase(
                            nodes_by_index[index_of[i]],
                            size=max(size, window),
                            from_=from_, sort=sort,
                            global_stats=global_stats,
                            aggs=agg_specs if agg_specs else None,
                            search_after=search_after,
                            track_scores=bool(body.get("track_scores",
                                                       False))
                            if sort is not None else True)
                    if rescore_spec is not None:
                        r = s.rescore(r, rescore_spec)
            return r

        shard_failures = 0
        shard_failure_details: list[dict] = []
        mesh_reduced = None
        mesh_aggs_merged = None
        with tracing.span("query"):
            # mesh-sharded query lane (parallel/mesh_exec): when this node
            # owns every shard and the device mesh can seat them, the
            # whole multi-shard query phase — per-shard stacked execution,
            # agg partial collect AND the cross-shard merge — runs as ONE
            # shard_map program with ONE device fetch and zero host-side
            # per-shard merges. kNN bodies ride their own mesh program
            # (parallel/mesh_knn: exact matmul or IVF under the sharded
            # axis). Sorted bodies ride the encoded-key sorted program
            # (ISSUE 17, mesh_exec.execute_sorted) — ineligible encodings
            # decline with a stable reason. Rescore/rank bodies,
            # cross-host shards and unsupported plan/agg shapes fall
            # through to the fan-out.
            if (len(names) == 1 and len(searchers) > 1 and knn is None
                    and rescore_spec is None):
                mesh_out = self._try_mesh(
                    names[0], searchers, nodes_by_index[names[0]],
                    global_stats, size=size, from_=from_,
                    agg_specs=agg_specs or None, sort=sort,
                    search_after=search_after,
                    track_scores=bool(body.get("track_scores", False))
                    if sort is not None else True)
                if mesh_out is not None:
                    mesh_rows, mesh_aggs_merged = mesh_out
                    mesh_reduced = mesh_rows[0] if mesh_rows else None
            elif (len(names) == 1 and len(searchers) > 1
                  and knn is not None and rank_spec is None
                  and rescore_spec is None):
                mesh_reduced = self._try_mesh_knn(
                    names[0], searchers, knn, k=knn_k, qv=[qv_single],
                    nprobe=knn_nprobe, exact=knn_exact,
                    quantization=knn_quant, size=size, from_=from_)
            if mesh_reduced is not None:
                results = []
            elif len(searchers) == 1:
                # sequential fast path: no job/context machinery, errors
                # raise straight through exactly as before
                results = [_run_shard(0, searchers[0])]
            else:
                # concurrent fan-out onto the bounded `search` pool. Each
                # job runs in a COPY of the coordinator's context so
                # tasks.scope parenting, the active profiler AND the active
                # trace span propagate; claim-once semantics let the
                # coordinator steal any job the pool hasn't started
                # (deadlock-free even when coordinators themselves occupy
                # the search pool), and pool-queue overflow simply leaves
                # the remainder to run inline.
                import contextvars
                from .common.threadpool import EsRejectedExecutionException
                jobs = []
                for i, s in enumerate(searchers):
                    ctx = contextvars.copy_context()
                    jobs.append(_ShardJob(
                        functools.partial(ctx.run, _run_shard, i, s,
                                          tracing.now_ns())))
                try:
                    for job in jobs[1:]:
                        self.thread_pool.execute("search", job.run)
                except EsRejectedExecutionException:
                    pass
                jobs[0].run()
                results = []
                first_error = None
                for i, job in enumerate(jobs):
                    job.join()
                    if job.error is not None:
                        # shard-failure accounting (ref per-shard onFailure
                        # in TransportSearchTypeAction): the response
                        # carries the failure; only an all-shards failure
                        # raises
                        shard_failures += 1
                        first_error = first_error or job.error
                        shard_failure_details.append({
                            "index": index_of[i],
                            "shard": searchers[i].shard_id,
                            "reason": f"{type(job.error).__name__}: "
                                      f"{job.error}"})
                        er = _empty_shard_result(
                            searchers[i].shard_id, sort=sort)
                        results.append((er, er) if rank_spec is not None
                                       else er)
                    else:
                        results.append(job.result)
                if shard_failures == len(searchers) \
                        and first_error is not None:
                    raise first_error

        t_device_done = time.perf_counter()
        tns_fetch0 = tracing.now_ns()
        self._record_phase("device",
                           (t_device_done - t_parse_done) * 1000)
        if prof is not None:
            prof.record_phase("query", (t_device_done - t_parse_done) * 1000)
        # the mesh lane already reduced ON DEVICE — sort_docs (the host
        # cross-shard merge) runs only for the fan-out path; rank bodies
        # fuse the two retrievers' GLOBAL lists on device instead
        if mesh_reduced is not None:
            reduced = mesh_reduced
        elif rank_spec is not None:
            reduced = controller.fuse_hybrid(
                [t for t, _ in results], [v for _, v in results],
                rank_spec, from_=from_, size=size)
        else:
            reduced = controller.sort_docs(results, from_=from_, size=size,
                                           sort=sort)
        src_filter = body.get("_source")
        fields_spec = body.get("fields")
        if isinstance(fields_spec, str):
            fields_spec = [fields_spec]
        hits = controller.fetch_and_merge(
            reduced, searchers,
            source_filter=(lambda s: _source_filter(s, src_filter))
            if src_filter is not None else None,
            fields_spec=fields_spec)
        for slot, h in enumerate(hits):
            h["_index"] = index_of[reduced.shard_order[slot]]

        hl_spec = None
        if body.get("highlight") and knn is None:
            from .search.highlight import highlight_hit, parse_highlight
            hl_spec = parse_highlight(body["highlight"])
        t_hl0 = time.perf_counter()
        if hl_spec is not None:
            from .search.shard_searcher import LOCAL_MASK, SEG_SHIFT
            for slot, h in enumerate(hits):
                si = reduced.shard_order[slot]
                key = reduced.doc_keys[slot]
                seg = searchers[si].segments[key >> SEG_SHIFT]
                raw_src = seg.stored[key & LOCAL_MASK]
                mappers = self.indices[index_of[si]].mappers

                def an_for(fname, _m=mappers):
                    for dm in _m._mappers.values():
                        if fname in dm.fields:
                            return dm.search_analyzer_for(fname)
                    return _m.analysis.analyzer("standard")

                hl = highlight_hit(hl_spec, raw_src, terms_by_field, an_for)
                if hl:
                    h["highlight"] = hl
        if hl_spec is not None and prof is not None:
            prof.record_phase("highlight",
                              (time.perf_counter() - t_hl0) * 1000)

        if body.get("script_fields"):
            # per-hit computed fields (ref search/fetch/script/
            # ScriptFieldsFetchSubPhase + lang-expression doc[...] access)
            from .script.engine import run_search_script
            from .search.shard_searcher import LOCAL_MASK, SEG_SHIFT
            for slot, h in enumerate(hits):
                si = reduced.shard_order[slot]
                key = reduced.doc_keys[slot]
                seg = searchers[si].segments[key >> SEG_SHIFT]
                raw_src = seg.stored[key & LOCAL_MASK]
                flds = h.setdefault("fields", {})
                for fname, fspec in body["script_fields"].items():
                    val = run_search_script(
                        fspec, raw_src, params=(fspec or {}).get("params")
                        if isinstance(fspec, dict) else None)
                    flds[fname] = [val]

        shards_section: dict[str, Any] = {
            "total": len(searchers),
            "successful": len(searchers) - shard_failures,
            "failed": shard_failures}
        if shard_failure_details:
            shards_section["failures"] = shard_failure_details
        resp: dict[str, Any] = {
            "took": int((time.perf_counter() - t0) * 1000),
            "timed_out": False,
            "_shards": shards_section,
            "hits": {"total": reduced.total_hits,
                     "max_score": None if reduced.max_score != reduced.max_score
                     else reduced.max_score,
                     "hits": hits},
        }
        if agg_specs:
            t_agg0 = time.perf_counter()
            with tracing.span("aggregations"):
                if mesh_aggs_merged is not None:
                    # the mesh program already collected + merged the
                    # partials on device (parallel/mesh_aggs.py)
                    merged = mesh_aggs_merged
                else:
                    merged = merge_shard_partials(
                        agg_specs, [r.aggs for r in results if r.aggs])
                resp["aggregations"] = render_aggs(agg_specs, merged)
            if prof is not None:
                prof.record_phase("aggregations",
                                  (time.perf_counter() - t_agg0) * 1000)
        if body.get("suggest"):
            resp["suggest"] = self.suggest(index, body["suggest"])
        now = time.perf_counter()
        tracing.add_span("fetch", tns_fetch0, tracing.now_ns())
        self._record_phase("fetch", (now - t_device_done) * 1000)
        self._record_phase("total", (now - t0) * 1000)
        if prof is not None:
            # response-assembly remainder: everything after the device
            # phase that isn't already booked (reduce/fetch/highlight/aggs)
            post = sum(v for k, v in prof.phases.items()
                       if k not in ("parse", "query"))
            prof.record_phase("serialize", max(
                (now - t_device_done) * 1000 - post, 0.0))
        resp["took"] = int((now - t0) * 1000)
        tid, oid = self._trace_ids()
        slow = None
        for n in names:     # every searched index's thresholds apply
            slow = self.slowlog.maybe_log(self.indices[n].settings, n,
                                          (now - t0) * 1000, body,
                                          trace_id=tid, opaque_id=oid) \
                or slow
        if slow is not None:
            # a slowlogged request always keeps its trace — the slowlog
            # entry's trace_id must resolve in GET /_traces
            tracing.mark_slowlog()
        if cache_key is not None:
            # byte-accounted LRU insert charging the `request` breaker; a
            # refused insert (budget/breaker pressure) just means this
            # response goes out uncached — never a 5xx
            self.caches.request_cache.put(cache_key, names, resp)
        return resp

    def _alias_filters_by_index(self, expr: str,
                                names: list[str]) -> dict[str, list]:
        """Per-index alias filters: each index searched THROUGH a filtered
        alias gets that alias's filter applied to ITS shards only; multiple
        filtered aliases targeting one index OR together (ref
        cluster/metadata/AliasMetaData + filtering-alias resolution in
        TransportSearchTypeAction — filters are per-index, should-combined)."""
        by_index: dict[str, list] = {}
        unfiltered: set[str] = set()   # reached concretely or via a
        for part in str(expr).split(","):   # filter-less alias → no filter
            for n in names:
                if part == n or ("*" in part and fnmatch.fnmatch(n, part)):
                    unfiltered.add(n)
                    continue
                props = self.indices[n].aliases.get(part)
                if props is None:
                    continue
                if props.get("filter"):
                    by_index.setdefault(n, []).append(props["filter"])
                else:
                    unfiltered.add(n)
        for n in unfiltered:
            by_index.pop(n, None)
        return by_index

    @staticmethod
    def _wrap_alias_query(query, filters: list):
        flt = filters[0] if len(filters) == 1 \
            else {"bool": {"should": filters}}
        return {"bool": {"must": [query or {"match_all": {}}],
                         "filter": [flt]}}

    def _expand_mlt(self, q, names: list[str]):
        """Rewrite more_like_this specs into term-disjunction queries
        (ref index/query/MoreLikeThisQueryParser + common/lucene/search/
        MoreLikeThisQuery: select the like-text's top tf*idf terms, query
        them as a should-of-terms). Runs BEFORE parsing because term
        selection needs corpus statistics the parser doesn't hold."""
        if isinstance(q, list):
            return [self._expand_mlt(x, names) for x in q]
        if not isinstance(q, dict):
            return q
        if not any(_is_mlt_entry(k, v) for k, v in q.items()):
            return {k: self._expand_mlt(v, names) for k, v in q.items()}

        spec = q.get("more_like_this")
        if spec is None:
            spec = q.get("mlt")
        fields = spec.get("fields") or ["_all"]
        min_tf = int(spec.get("min_term_freq", 2))
        min_df = int(spec.get("min_doc_freq", 5))
        max_terms = int(spec.get("max_query_terms", 25))
        texts: list[str] = []
        if spec.get("like_text"):
            texts.append(str(spec["like_text"]))
        likes = spec.get("like", [])
        likes = likes if isinstance(likes, list) else [likes]
        doc_refs = [d for d in likes if isinstance(d, dict)] \
            + list(spec.get("docs") or []) \
            + [{"_id": i} for i in (spec.get("ids") or [])]
        texts += [t for t in likes if isinstance(t, str)]
        exclude_ids: list[str] = []

        def _texts_from(source: dict):
            for f in fields:
                v = source.get(f) if f != "_all" else None
                if isinstance(v, str):
                    texts.append(v)
                elif f == "_all":
                    texts.extend(x for x in source.values()
                                 if isinstance(x, str))

        for ref in doc_refs:
            if "doc" in ref and isinstance(ref["doc"], dict):
                _texts_from(ref["doc"])      # artificial document form
                continue
            if "_id" not in ref:
                continue
            try:
                got = self.get_doc(ref.get("_index", names[0]),
                                   str(ref["_id"]))
            except IndexMissingException:
                continue
            if got.found and got.source:
                exclude_ids.append(str(ref["_id"]))
                _texts_from(got.source)

        # ignore_like: terms appearing in these docs are STRUCK from the
        # selected term set (ref MoreLikeThisQueryParser "ignore_like" /
        # unlike handling)
        ignore_texts: list[str] = []
        ignores = spec.get("ignore_like") or spec.get("unlike") or []
        ignores = ignores if isinstance(ignores, list) else [ignores]
        for ref in ignores:
            if isinstance(ref, str):
                ignore_texts.append(ref)
                continue
            if isinstance(ref, dict) and "doc" in ref:
                ignore_texts.extend(x for x in ref["doc"].values()
                                    if isinstance(x, str))
                continue
            if not isinstance(ref, dict) or "_id" not in ref:
                continue
            try:
                got = self.get_doc(ref.get("_index", names[0]),
                                   str(ref["_id"]))
            except IndexMissingException:
                continue
            if got.found and got.source:
                ignore_texts.extend(x for x in got.source.values()
                                    if isinstance(x, str))

        segments = [seg for n in names
                    for e in self.indices[n].shards for seg in e.segments]
        all_fields = {f for seg in segments for f in seg.text} \
            if fields == ["_all"] else set(fields)
        should = []
        from .search.query_dsl import MatchNoneNode  # noqa: F401 (shape doc)
        for field in sorted(all_fields):
            mappers = self.indices[names[0]].mappers
            an = None
            for dm in mappers._mappers.values():
                if field in dm.fields:
                    an = dm.search_analyzer_for(field)
                    break
            if an is None:
                an = mappers.analysis.analyzer("standard")
            tf: dict[str, int] = {}
            for t in texts:
                for tok in an(t):
                    tf[tok] = tf.get(tok, 0) + 1
            for t in ignore_texts:
                for tok in an(t):
                    tf.pop(tok, None)
            import math as _m
            n_docs = max(sum(s.n_docs for s in segments), 1)
            scored = []
            for term, f in tf.items():
                if f < min_tf:
                    continue
                df = sum(s.doc_freq(field, term) for s in segments)
                if df < min_df:
                    continue
                scored.append((f * _m.log(1 + n_docs / (df + 1)), term))
            scored.sort(reverse=True)
            terms = [t for _, t in scored[:max_terms]]
            if terms:
                # the reference's default minimum_should_match for MLT
                # is 30% of the selected terms
                msm = max(1, round(0.3 * len(terms)))
                should.append({"match": {field: {
                    "query": " ".join(terms),
                    "minimum_should_match": msm}}})
        if not should:
            return {"match_none": {}}
        out: dict = {"bool": {"should": should, "minimum_should_match": 1}}
        if exclude_ids and not spec.get("include", False):
            # the reference excludes the input docs themselves
            # (ref MoreLikeThisQueryParser include=false default)
            out["bool"]["must_not"] = [{"ids": {"values": exclude_ids}}]
        return out

    def _percolate_filter(self, name: str, flt, out: dict) -> dict:
        """Body filter/query restricts WHICH registered .percolator docs
        participate, evaluated against their own indexed fields
        (ref PercolatorService percolate-with-filter)."""
        if flt is None or not out["matches"]:
            return out
        res = self.search(name, {
            "query": {"bool": {"filter": [flt]}},
            "size": 10_000, "_source": False})
        allowed = {h["_id"] for h in res["hits"]["hits"]}
        out["matches"] = [m for m in out["matches"] if m["_id"] in allowed]
        out["total"] = len(out["matches"])
        return out

    def percolate(self, index: str, body: dict,
                  type_name: str = "_doc",
                  doc_id: str | None = None) -> dict:
        """Match a doc against the index's registered queries
        (ref percolator/PercolatorService.java:108-132) — through the
        dense doc×query matrix executor (search/percolate_exec.py),
        which itself ladders down to the per-doc loop."""
        from .search.percolate_exec import percolate_batch
        names = self._resolve(index)
        if not names:
            raise IndexMissingException(index)
        doc = (body or {}).get("doc")
        if doc is None and doc_id is not None:
            got = self.get_doc(names[0], doc_id)
            if not got.found:
                raise DocumentMissingException(
                    f"[{type_name}][{doc_id}]: document missing")
            doc = got.source
        if doc is None:
            raise QueryParsingException("percolate requires a doc")
        flt = (body or {}).get("filter") or (body or {}).get("query")
        total = 0
        matches: list = []
        from .common.device_stats import current_lanes, record_lanes
        # reuse an active recorder (chaos parity sweeps wrap their own)
        with record_lanes(current_lanes()) as lanes:
            for n in names:
                out = percolate_batch(self.indices[n], n,
                                      [(doc, type_name)],
                                      caches=self.caches,
                                      devices=self.device_pool.devices
                                      if self.device_pool else None)[0]
                out = self._percolate_filter(n, flt, out)
                total += out["total"]
                matches.extend(out["matches"])
        resp = {"took": 0, "_shards": {"total": len(names),
                                       "successful": len(names),
                                       "failed": 0},
                "total": total, "matches": matches}
        if (body or {}).get("profile"):
            # the percolate ladder's explain surface: which rung carried
            # the request (mesh / dense / loop) and why others declined
            resp["profile"] = {"lanes": lanes.explain()}
        return resp

    def mpercolate(self, index: str, bodies: list[dict],
                   type_name: str = "_doc") -> dict:
        """Batched percolation: every doc becomes one row of the SAME
        dense doc×query matrix program — the whole batch costs one device
        dispatch per index, not one per doc (ISSUE 18 `_mpercolate`)."""
        from .search.percolate_exec import percolate_batch
        names = self._resolve(index)
        if not names:
            raise IndexMissingException(index)
        docs: list[tuple[dict, str]] = []
        for b in bodies:
            doc = (b or {}).get("doc")
            if doc is None:
                raise QueryParsingException("percolate requires a doc")
            docs.append((doc, (b or {}).get("type", type_name)))
        shards = {"total": len(names), "successful": len(names),
                  "failed": 0}
        merged = [{"took": 0, "_shards": dict(shards),
                   "total": 0, "matches": []} for _ in docs]
        for n in names:
            outs = percolate_batch(self.indices[n], n, docs,
                                   caches=self.caches,
                                   devices=self.device_pool.devices
                                   if self.device_pool else None)
            for i, out in enumerate(outs):
                flt = (bodies[i] or {}).get("filter") \
                    or (bodies[i] or {}).get("query")
                out = self._percolate_filter(n, flt, out)
                merged[i]["total"] += out["total"]
                merged[i]["matches"].extend(out["matches"])
        return {"responses": merged}

    def refresh_doc_shard(self, index: str, doc_id: str,
                          routing: str | None = None) -> None:
        """Per-op ?refresh=true refreshes only the WRITTEN shard (ref
        TransportShardReplicationOperationAction per-shard refresh) — other
        shards' pending deletes stay invisible until their own refresh."""
        for name in self._resolve(index):   # aliases resolve like writes do
            svc = self.indices.get(name)
            if svc is not None:
                svc.shard_for(doc_id, routing).refresh()

    def termvectors(self, index: str, doc_id: str, type_name: str = "_doc",
                    fields: list[str] | None = None, realtime: bool = True,
                    term_statistics: bool = False,
                    field_statistics: bool = True,
                    positions: bool = True, offsets: bool = True,
                    routing: str | None = None,
                    parent: str | None = None) -> dict:
        """Per-document term vectors (ref action/termvectors/
        TransportTermVectorsAction + TermVectorsResponse): term/position/
        offset lists re-derived from the stored source through the SAME
        analysis chain that indexed it (tensor segments don't keep per-doc
        postings slices addressable by doc, so re-analysis — which is
        exact, same analyzer, same source — replaces Lucene's stored term
        vectors)."""
        import time as _time
        t0 = _time.perf_counter()
        names = self._resolve(index)
        if not names:
            raise IndexMissingException(index)
        name = names[0]
        svc = self.indices[name]
        res = svc.get_doc(doc_id, routing=routing, parent=parent,
                          realtime=realtime)
        out = {"_index": name, "_type": res.type_name if res.found
               else type_name, "_id": doc_id, "found": res.found,
               "took": 0}
        if not res.found:
            return out
        out["_version"] = res.version
        mapper = svc.mappers.document_mapper(res.type_name, create=False) \
            or svc.mappers.document_mapper(type_name)
        segments = [seg for e in svc.shards for seg in e.segments]

        def flat(prefix, obj, into):
            for k, v in obj.items():
                path = f"{prefix}{k}"
                if isinstance(v, dict):
                    flat(path + ".", v, into)
                else:
                    into[path] = v

        flat_src: dict[str, Any] = {}
        flat("", res.source or {}, flat_src)
        tv: dict[str, dict] = {}
        for field, value in flat_src.items():
            ft = mapper.fields.get(field)
            if ft is None or ft.type != "text":
                continue
            if fields is not None and field not in fields:
                continue
            analyzer = mapper._analyzer_for(ft)
            texts = value if isinstance(value, list) else [value]
            terms: dict[str, dict] = {}
            pos = 0
            for text in texts:
                for m in re.finditer(r"\w+(?:[.']\w+)*", str(text)):
                    toks = analyzer(m.group(0))
                    if not toks:
                        continue     # filtered out (stopword etc.)
                    t = toks[0]
                    entry = terms.setdefault(t, {"term_freq": 0,
                                                 "tokens": []})
                    entry["term_freq"] += 1
                    tok: dict = {}
                    if positions:
                        tok["position"] = pos
                    if offsets:
                        tok["start_offset"] = m.start()
                        tok["end_offset"] = m.end()
                    if tok:
                        entry["tokens"].append(tok)
                    pos += 1
            if not terms:
                continue
            if term_statistics:
                for t, entry in terms.items():
                    df = ttf = 0
                    for seg in segments:
                        fx = seg.text.get(field)
                        if fx is None:
                            continue
                        s, ln, tid = fx.lookup(t)
                        if tid >= 0:
                            df += ln
                            import numpy as _np
                            ttf += int(_np.asarray(fx.tf)[s:s + ln].sum())
                    entry["doc_freq"] = df
                    entry["ttf"] = ttf
            fstat = None
            if field_statistics:
                import numpy as _np
                sum_df = doc_count = 0
                sum_ttf = 0.0
                for seg in segments:
                    fx = seg.text.get(field)
                    if fx is None:
                        continue
                    sum_df += int(fx.term_lens.sum())
                    sum_ttf += fx.sum_dl        # Σ tokens == Σ tf
                    if fx.doc_ids_host is not None:
                        # docs CONTAINING the field (ref FieldStats
                        # docCount), not all docs in the segment
                        uniq = _np.unique(fx.doc_ids_host)
                        doc_count += int(
                            seg.live_host[uniq].sum())
                    else:
                        doc_count += seg.root_live_count
                fstat = {"sum_doc_freq": sum_df,
                         "doc_count": doc_count,
                         "sum_ttf": int(sum_ttf)}
            entry_out: dict = {}
            if fstat is not None:
                entry_out["field_statistics"] = fstat
            entry_out["terms"] = terms
            tv[field] = entry_out
        out["term_vectors"] = tv
        out["took"] = int((_time.perf_counter() - t0) * 1000)
        return out

    def suggest(self, index: str, body: dict) -> dict:
        """Run suggesters over the index's term dictionaries
        (ref search/suggest/SuggestPhase.java:43)."""
        from .search.suggest import run_suggest
        names = self._resolve(index)
        if not names:
            raise IndexMissingException(index)
        segments = [seg for n in names
                    for e in self.indices[n].shards for seg in e.segments]
        return run_suggest(body, segments,
                           mappers=self.indices[names[0]].mappers)

    def _packed_search(self, name: str, bodies: list[dict], *, size: int,
                       from_: int, t0: float, raw: bool = False,
                       specs: list | None = None) -> list | None:
        """Serve a batch of same-shaped requests through the packed view:
        ONE device program across all shards/segments, one upload, one
        download (serving/). Returns per-body responses (dicts, or raw JSON
        strings when `raw` and `_source: false`), or None to fall back."""
        from .serving.executor import (packed_spec_of, response_dict,
                                       response_raw)
        svc = self.indices[name]
        view = svc.packed_view()
        if view is None:
            return None
        if specs is None:
            from .search.query_parser import QueryParser
            parser = QueryParser(svc.mappers)
            specs = [packed_spec_of(parser, body) for body in bodies]
        if any(s is None for s in specs):
            return None
        field, k1, b = specs[0][1], specs[0][2], specs[0][3]
        if any(s[1] != field or s[2] != k1 or s[3] != b for s in specs[1:]):
            return None
        if not view.servable(field):
            return None     # request breaker refused the packed postings
        queries = [s[0] for s in specs]
        k = max(size + from_, 1)
        from .serving.packed_view import FilterColumnRefused
        try:
            scores, docs, hits = view.search(field, queries, k=k, k1=k1, b=b)
        except FilterColumnRefused:
            return None    # breaker refused a filter column: general path
        took = int((time.perf_counter() - t0) * 1000)
        out = []
        for qi, body in enumerate(bodies):
            src_spec = body.get("_source", True)
            if raw and src_spec is False and view.ids_json_safe:
                out.append(response_raw(
                    view, name, scores[qi], docs[qi], hits[qi],
                    n_shards=svc.n_shards, took=took,
                    from_=from_, size=size))
            else:
                fn = (lambda s: _source_filter(s, src_spec)) \
                    if src_spec not in (True, False) else None
                out.append(response_dict(
                    view, name, scores[qi], docs[qi], hits[qi],
                    n_shards=svc.n_shards, took=took, from_=from_,
                    size=size, src_spec=src_spec, src_filter_fn=fn))
        # count AFTER successful response assembly — a failure above falls
        # back to the general path and must not be booked as a packed serve
        svc.search_stats["packed"] = \
            svc.search_stats.get("packed", 0) + len(bodies)
        svc.query_total += len(bodies)
        svc.meters["search"].mark(len(bodies))
        self.meters["search"].mark(len(bodies))
        return out

    # -- mesh-sharded query lane (parallel/mesh_exec, ISSUE 6) -------------

    def _try_mesh(self, name: str, searchers, node_tree, global_stats, *,
                  size: int, from_: int, n_queries: int = 1,
                  agg_specs=None, sort=None, search_after=None,
                  track_scores: bool = True):
        """One mesh-lane attempt for a multi-shard query batch: returns
        (per-row ReducedDocs list, merged agg partial | None) from the
        on-device collective reduce (single searches take row 0), or
        None to fall back to the PR-4 concurrent fan-out (opt-out
        settings, joins, unsupported plan/agg shapes, too few devices,
        breaker-declined/oversized mesh stacks, or any execution error).

        With `agg_specs`, the agg tree rides the SAME program
        (parallel/mesh_aggs.py) — the merged partial equals the fan-out's
        per-shard collect + host merge bit-for-bit. With `sort`, the
        encoded-key sorted program (ISSUE 17) replaces the host merge;
        winners' user-facing sort values materialize host-side per hit."""
        from .common.device_stats import lane_chosen, lane_decline
        svc = self.indices[name]
        if not svc._mesh_enabled \
                or not _mesh_enabled_setting(self.settings):
            lane_decline("query", "mesh", "opt_out")
            return None
        from .search.query_dsl import contains_joins
        if contains_joins(node_tree):
            lane_decline("query", "mesh", "joins")
            return None
        from .parallel import mesh_exec
        if not mesh_exec.plan_types_supported(node_tree):
            lane_decline("query", "mesh", "plan_unsupported")
            return None
        if mesh_exec.mesh_for(len(searchers),
                              pool=self.device_pool) is None:
            # cross-host topology / fewer devices than shards
            lane_decline("query", "mesh", "no_mesh")
            return None
        k = max(size + from_, 1)
        try:
            stack = self.caches.mesh_stacks.get_or_build(
                name, svc._incarnation,
                [list(s.segments) for s in searchers],
                breaker=self.breakers.breaker("fielddata"),
                pool=self.device_pool)
            if stack is None:
                lane_decline("query", "mesh", "stack_declined")
                return None
            with tracing.span("mesh_reduce", index=name,
                              shards=len(searchers), k=k):
                if sort is not None:
                    out = mesh_exec.execute_sorted(
                        stack, node_tree, global_stats, sort,
                        search_after, k=k, Q=n_queries,
                        agg_specs=agg_specs)
                else:
                    out = mesh_exec.execute(
                        stack, node_tree, global_stats, k=k, Q=n_queries,
                        block_docs=svc._block_docs
                        if svc._blockwise_enabled else None,
                        agg_specs=agg_specs)
            if out is None:
                # plan/agg shape has no collective form (field shapes),
                # or the sort encoding declined (reason already recorded)
                lane_decline("query", "mesh",
                             "agg_shape" if agg_specs else "plan_shape")
                if agg_specs:
                    svc.search_stats["mesh_agg_fallbacks"] = \
                        svc.search_stats.get("mesh_agg_fallbacks", 0) + 1
                return None
        except Exception:  # noqa: BLE001 — the fan-out is always correct
            lane_decline("query", "mesh", "error")
            self._mesh_error(svc)
            return None
        keys, shard_of, scores, totals, mxs, agg_per_shard = out
        lane_chosen("query", "mesh")
        svc.search_stats["mesh"] = svc.search_stats.get("mesh", 0) + 1
        svc.search_stats["mesh_dispatches"] = \
            svc.search_stats.get("mesh_dispatches", 0) + 1
        if sort is not None:
            svc.search_stats["mesh_sorted_dispatches"] = \
                svc.search_stats.get("mesh_sorted_dispatches", 0) + 1
        if agg_specs:
            svc.search_stats["mesh_agg_dispatches"] = \
                svc.search_stats.get("mesh_agg_dispatches", 0) + 1
        if mesh_exec.last_block_mode == "blockwise":
            svc.search_stats["blockwise_dispatches"] = \
                svc.search_stats.get("blockwise_dispatches", 0) + 1
        from .common.metrics import current_profiler, record_shard_fetches
        record_shard_fetches(1)     # ONE fetch served every shard
        prof = current_profiler()
        if prof is not None:
            prof.note_path("mesh")
        if sort is not None:
            rows = _mesh_rows_sorted(
                keys, shard_of, scores, totals, mxs, searchers,
                n_queries=n_queries, size=size, from_=from_, sort=sort,
                track_scores=track_scores)
        else:
            rows = _mesh_rows(keys, shard_of, scores, totals, mxs,
                              n_queries=n_queries, size=size, from_=from_)
        agg_merged = None
        if agg_per_shard is not None:
            from .search.aggs.aggregators import merge_shard_partials
            agg_merged = merge_shard_partials(agg_specs, agg_per_shard)
        return rows, agg_merged

    # -- mesh kNN lane (parallel/mesh_knn, ISSUE 11) -----------------------

    def _try_mesh_knn(self, name: str, searchers, knn: dict, *, k: int,
                      qv, nprobe, exact: bool, size: int, from_: int,
                      quantization: str | None = None):
        """One mesh attempt for a multi-shard kNN body: all co-hosted
        shards' vector columns execute as ONE shard_map program — exact
        matmul or the IVF centroid-route + cluster scan under the sharded
        axis — with the cross-shard top-k reduce on device. Returns
        ReducedDocs or None to fall back to the per-shard fan-out (mixed
        IVF/exact segment lanes, non-uniform nlist, filter plans without a
        mesh form, opt-outs, any error)."""
        from .common.device_stats import lane_chosen, lane_decline
        svc = self.indices[name]
        if not svc._mesh_enabled \
                or not _mesh_enabled_setting(self.settings):
            lane_decline("knn", "mesh_knn", "opt_out")
            return None
        from .parallel import mesh_exec, mesh_knn
        if mesh_exec.mesh_for(len(searchers),
                              pool=self.device_pool) is None:
            lane_decline("knn", "mesh_knn", "no_mesh")
            return None
        try:
            vstack = self.caches.mesh_vector_stacks.get_or_build(
                name, svc._incarnation, knn["field"],
                [list(s.segments) for s in searchers],
                breaker=self.breakers.breaker("fielddata"),
                pool=self.device_pool)
            if vstack is None:
                lane_decline("knn", "mesh_knn", "vstack_declined")
                return None
            fnode = None
            if knn.get("filter"):
                fnode = searchers[0].parse([knn["filter"]])
            stack = None
            if fnode is not None:
                stack = self.caches.mesh_stacks.get_or_build(
                    name, svc._incarnation,
                    [list(s.segments) for s in searchers],
                    breaker=self.breakers.breaker("fielddata"),
                    pool=self.device_pool)
                if stack is None:
                    lane_decline("knn", "mesh_knn", "stack_declined")
                    return None
            with tracing.span("mesh_reduce", index=name,
                              shards=len(searchers), k=k, knn=True):
                out = mesh_knn.execute(
                    vstack, qv, k=k,
                    metric=knn.get("metric", "cosine"),
                    knn_opts=searchers[0].knn_opts,
                    nprobe=nprobe, exact=exact,
                    quantization=quantization,
                    acquire_ivf=lambda si, seg, vc:
                        searchers[si]._acquire_ivf(
                            seg, vc, knn["field"], nprobe, exact),
                    acquire_quant=lambda si, seg, vc, ivf, mode:
                        searchers[si]._acquire_quant(
                            seg, vc, knn["field"], ivf, mode),
                    filter_node=fnode, filter_stack=stack)
            if out is None:
                # mesh_knn.execute noted the specific (lane, reason) itself
                svc.search_stats["mesh_ann_fallbacks"] = \
                    svc.search_stats.get("mesh_ann_fallbacks", 0) + 1
                return None
        except Exception:  # noqa: BLE001 — the fan-out is always correct
            lane_decline("knn", "mesh_knn", "error")
            self._mesh_error(svc)
            return None
        keys, shard_of, scores, totals, mxs, used_ivf, used_quant = out
        lane_chosen("knn", "mesh_knn")
        svc.search_stats["mesh"] = svc.search_stats.get("mesh", 0) + 1
        svc.search_stats["mesh_dispatches"] = \
            svc.search_stats.get("mesh_dispatches", 0) + 1
        svc.search_stats["mesh_ann_dispatches"] = \
            svc.search_stats.get("mesh_ann_dispatches", 0) + 1
        if used_ivf:
            svc.search_stats["ann_dispatches"] = \
                svc.search_stats.get("ann_dispatches", 0) + 1
        if used_quant:
            svc.search_stats["ann_quantized_dispatches"] = \
                svc.search_stats.get("ann_quantized_dispatches", 0) + 1
            svc.search_stats[f"ann_quantized_{used_quant}"] = \
                svc.search_stats.get(f"ann_quantized_{used_quant}", 0) + 1
        from .common.metrics import current_profiler, record_shard_fetches
        record_shard_fetches(1)
        prof = current_profiler()
        if prof is not None:
            prof.note_path("mesh")
        return _mesh_rows(keys, shard_of, scores, totals, mxs,
                          n_queries=1, size=size, from_=from_)[0]

    _mesh_error_logged = 0

    def _mesh_error(self, svc=None) -> None:
        """The mesh lane degrades to the fan-out on any exception — but a
        silently-swallowed bug in it would read as a perf regression, so
        count and (rate-limited) log."""
        if svc is not None:
            svc.search_stats["mesh_errors"] = \
                svc.search_stats.get("mesh_errors", 0) + 1
        if NodeService._mesh_error_logged < 10:
            NodeService._mesh_error_logged += 1
            logger.warning("mesh query lane failed; served via the "
                           "concurrent fan-out instead", exc_info=True)

    _packed_error_logged = 0

    def _packed_error(self) -> None:
        """The packed lane degrades to the general path on any exception —
        but silently-swallowed bugs in the fast lane would read as a perf
        regression, so count and (rate-limited) log them."""
        self.search_stats_errors = getattr(self, "search_stats_errors", 0) + 1
        if NodeService._packed_error_logged < 10:
            NodeService._packed_error_logged += 1
            logger.warning("packed serving lane failed; served via the "
                           "general path instead", exc_info=True)

    def count(self, index: str, body: dict | None = None) -> dict:
        out = self.search(index, {**(body or {}), "size": 0})
        return {"count": out["hits"]["total"], "_shards": out["_shards"]}

    # -- msearch: batched multi-search (ref action/search/MultiSearchRequest;
    # rest/action/search/RestMultiSearchAction). The TPU twist: requests
    # whose query trees share a plan shape merge into ONE batched device
    # program (merge_query_batch) — the batching that the ≥10x QPS target
    # comes from (SURVEY.md §7: the unit of device work is a batch of
    # queries, not one query at a time). ----------------------------------

    # single source of truth for which body keys the fast lanes understand
    # (serving/executor.PACKED_BODY_KEYS) — the plan-shape batched lane and
    # the packed lane must never diverge in eligibility
    _BATCHABLE_KEYS = PACKED_BODY_KEYS

    def msearch(self, requests: list[tuple[dict, dict]],
                raw: bool = False) -> dict | bytes:
        """Batched multi-search. With `raw=True` returns the response body
        as pre-serialized bytes when possible (the packed path builds hit
        JSON vectorized — see serving/executor.py)."""
        import json
        from .serving.executor import packed_spec_of
        t0 = time.perf_counter()
        responses: list = [None] * len(requests)
        metas: list[tuple[str, dict]] = []
        packed_groups: dict[Any, list[int]] = {}
        packed_specs: dict[int, Any] = {}
        parsers: dict[str, Any] = {}
        leftovers: list[int] = []
        for i, (header, body) in enumerate(requests):
            index = (header or {}).get("index") or "_all"
            body = body or {}
            metas.append((index, body))
            key = None
            try:
                names = self._resolve(index)
                if len(names) == 1:
                    name = names[0]
                    if name not in parsers:
                        from .search.query_parser import QueryParser
                        parsers[name] = QueryParser(
                            self.indices[name].mappers)
                    spec = packed_spec_of(parsers[name], body)
                    if spec is not None:
                        packed_specs[i] = spec
                        key = (name, int(body.get("size", 10)),
                               int(body.get("from", 0)),
                               repr(body.get("_source", True)))
            except Exception:  # noqa: BLE001 — solo path reports the error
                key = None
            if key is not None:
                packed_groups.setdefault(key, []).append(i)
            else:
                leftovers.append(i)

        for key, idxs in packed_groups.items():
            name, size, from_, _src = key
            try:
                outs = self._packed_search(
                    name, [metas[i][1] for i in idxs], size=size,
                    from_=from_, t0=t0, raw=raw,
                    specs=[packed_specs[i] for i in idxs])
            except Exception:  # noqa: BLE001 — per-item error contract:
                self._packed_error()
                outs = None    # a failing group degrades to the solo path
            if outs is None:
                leftovers.extend(idxs)
            else:
                for i, out in zip(idxs, outs):
                    responses[i] = out

        # general path for whatever the packed lane couldn't serve:
        # plan-shape device batching, then solo
        groups: dict[Any, list[int]] = {}
        for i in leftovers:
            key = self._msearch_batch_key(*metas[i])
            groups.setdefault(key if key is not None else ("solo", i),
                              []).append(i)
        for key, idxs in groups.items():
            if (isinstance(key, tuple) and key and key[0] == "solo") \
                    or len(idxs) == 1:
                for i in idxs:
                    responses[i] = self._msearch_one(*metas[i])
                continue
            try:
                outs = self._search_batched([metas[i] for i in idxs])
            except Exception:  # noqa: BLE001 — batch miss, serve solo
                outs = [self._msearch_one(*metas[i]) for i in idxs]
            for i, out in zip(idxs, outs):
                responses[i] = out

        if raw:
            payload = '{"responses":[' + ",".join(
                r if isinstance(r, str) else json.dumps(r)
                for r in responses) + ']}'
            return payload.encode()
        return {"responses": responses}

    def _msearch_one(self, index: str, body: dict) -> dict:
        try:
            return self.search(index, body)
        except Exception as e:  # noqa: BLE001 — per-item error contract
            from .rest.http_server import _status_of
            # the reference's Name[detail] error rendering
            return {"error": f"{type(e).__name__}[{e}]",
                    "status": _status_of(e)}

    def _msearch_batch_key(self, index: str, body: dict):
        """Group key for device batching, or None if the request needs the
        general path (sort/knn/... or an unparseable query). Requests with
        IDENTICAL agg trees batch together: the query phase runs once with
        Q rows and agg collect runs per row against device masks — the
        analytics-workload analog of the packed lane (BASELINE config #3)."""
        aggs = body.get("aggs") or body.get("aggregations")
        if any(k not in self._BATCHABLE_KEYS
               and k not in ("aggs", "aggregations", "knn", "rescore")
               for k in body):
            return None
        try:
            import json as _json
            knn = body.get("knn")
            if knn is not None:
                # batched exact kNN: one MXU matmul per shard serves the
                # whole group (per-query vectors vary; shape must not)
                if aggs is not None or body.get("rescore") is not None \
                        or knn.get("filter") is not None:
                    return None
                qv = knn.get("query_vector")
                if qv is None:
                    return None
                raw_np = knn.get("nprobe")
                return (index, int(body.get("size", 10)),
                        int(body.get("from", 0)), "knn", knn.get("field"),
                        int(knn.get("k", 10)),
                        knn.get("metric", "cosine"), len(qv),
                        int(raw_np) if raw_np is not None else None,
                        bool(knn.get("exact", False)),
                        str(knn.get("quantization") or ""))
            agg_key = None
            if aggs is not None:
                from .search.aggs.aggregators import has_top_hits, parse_aggs
                if has_top_hits(parse_aggs(aggs)):
                    return None     # top_hits needs per-row scores
                agg_key = _json.dumps(aggs, sort_keys=True)
            names = self._resolve(index)
            if not names:
                return None
            node = self._parse_cached(
                names[0], body.get("query") or {"match_all": {}})
            rescore_key = None
            rescore = body.get("rescore")
            if rescore is not None:
                # batched hybrid rescore: same plan + knobs, per-row vectors
                if isinstance(rescore, list):
                    if len(rescore) != 1:
                        return None
                    rescore = rescore[0]
                rs = rescore.get("query", rescore)
                rq = rs.get("rescore_query")
                if rq is None or body.get("sort") is not None:
                    return None
                rescore_key = (self._parse_cached(names[0], rq).plan_key(),
                               int(rescore.get("window_size", 0)),
                               rs.get("score_mode", "total"),
                               float(rs.get("query_weight", 1.0)),
                               float(rs.get("rescore_query_weight", 1.0)))
            return (index, int(body.get("size", 10)),
                    int(body.get("from", 0)), node.plan_key(), agg_key,
                    rescore_key)
        except Exception:  # noqa: BLE001
            return None

    def _search_batched(self, metas: list[tuple[str, dict]]) -> list[dict]:
        """Execute same-shaped requests as one batched query phase per shard;
        per-row reduce + fetch mirrors the single-search flow."""
        t0 = time.perf_counter()
        index, first_body = metas[0]
        size = int(first_body.get("size", 10))
        from_ = int(first_body.get("from", 0))
        names = self._resolve(index)
        searchers: list[ShardSearcher] = []
        index_of: list[str] = []
        for n in names:
            for s in self.indices[n].searchers():
                searchers.append(s)
                index_of.append(n)
        knn = first_body.get("knn")
        if knn is not None:
            # batched exact kNN: one matmul per shard for the whole group
            qvs = [b["knn"]["query_vector"] for _, b in metas]
            knn_k = int(knn.get("k", 10))
            raw_np = knn.get("nprobe")
            results = [
                s.execute_knn(knn["field"], qvs, k=max(knn_k, size + from_),
                              metric=knn.get("metric", "cosine"),
                              nprobe=int(raw_np) if raw_np is not None
                              else None,
                              exact=bool(knn.get("exact", False)),
                              quantization=knn.get("quantization"))
                for s in searchers]
            size = min(size, max(knn_k - from_, 0))
            return self._batched_reduce(metas, searchers, index_of, results,
                                        size, from_, None, t0)

        queries = [b.get("query") or {"match_all": {}} for _, b in metas]
        rescore_spec0 = first_body.get("rescore")
        if isinstance(rescore_spec0, list):
            rescore_spec0 = rescore_spec0[0] if rescore_spec0 else None
        window = int(rescore_spec0.get("window_size", size)) \
            if rescore_spec0 else 0
        # parse once per index (shards share a MapperService), not per shard;
        # index-global stats keep this lane score-consistent with the packed
        # lane (same IDF everywhere)
        from .search.query_dsl import CollectionStats
        nodes_by_index = {}
        terms_by_field: dict[str, set] = {}
        for n in names:
            from .search.query_parser import merge_query_batch
            nodes_by_index[n] = merge_query_batch(
                [self._parse_cached(n, q) for q in queries])
            nodes_by_index[n].collect_terms(terms_by_field)
        global_stats = CollectionStats.from_segments(
            [seg for s in searchers for seg in s.segments], terms_by_field)

        # mesh-batched lane (ISSUE 8 satellite, ROADMAP item 1 follow-up):
        # a Q>1 plan-shaped batch over a single multi-shard index rides the
        # mesh's "replica" axis — the whole batch's query phase AND the
        # cross-shard merge run as ONE collective program with ONE device
        # fetch. Aggs/knn/rescore/count-only groups keep the fan-out below
        # (same ladder as the single-search coordinator).
        if (len(names) == 1 and len(searchers) > 1
                and rescore_spec0 is None and size + from_ > 0
                and not (first_body.get("aggs")
                         or first_body.get("aggregations"))):
            mesh_out = self._try_mesh(
                names[0], searchers, nodes_by_index[names[0]],
                global_stats, size=size, from_=from_,
                n_queries=len(queries))
            mesh_rows = mesh_out[0] if mesh_out is not None else None
            if mesh_rows is not None:
                outs = self._batched_reduce(metas, searchers, index_of,
                                            None, size, from_, None, t0,
                                            reduced_rows=mesh_rows)
                self.meters["search"].mark(len(metas))
                for n in names:
                    svc = self.indices[n]
                    svc.query_total += len(metas)
                    svc.search_stats["batched"] = \
                        svc.search_stats.get("batched", 0) + len(metas)
                    svc.meters["search"].mark(len(metas))
                return outs

        aggs_body = first_body.get("aggs") or first_body.get("aggregations")
        count_only = size + from_ == 0 and rescore_spec0 is None
        seg_masks: list | None = None
        if count_only or aggs_body is not None:
            # ONE match-mask program per segment serves totals (count-only
            # fast path) AND agg collect — never computed twice
            from .search.query_dsl import SegmentContext
            Q = len(queries)
            seg_masks = []
            for i, s in enumerate(searchers):
                for seg in s.segments:
                    if seg.n_docs == 0:
                        continue
                    ctx = SegmentContext(seg, Q, global_stats)
                    m = nodes_by_index[index_of[i]].match_mask(ctx) \
                        & seg.live[None, :]
                    seg_masks.append((i, seg, m))
        total_devs: list = []
        if count_only:
            # agg/count-only batch: SKIP scoring entirely. The dense [Q, N]
            # scoring pass cost the r5 agg bench ~99% of its time at 1M
            # docs. The per-segment totals stay ON DEVICE here and ride the
            # agg collect's single device_get below (one tunnel round-trip
            # for the whole batch).
            total_devs = [(i, m.sum(axis=1)) for i, _seg, m in seg_masks]
            results = None
        else:
            results = [
                s.execute_query_phase(nodes_by_index[index_of[i]],
                                      size=max(size, window),
                                      from_=from_, n_queries=len(queries),
                                      global_stats=global_stats)
                for i, s in enumerate(searchers)]
        if rescore_spec0 is not None:
            specs = []
            for _, b in metas:
                rs = b.get("rescore")
                specs.append(rs[0] if isinstance(rs, list) else rs)
            results = [s.rescore_batch(r, specs)
                       for s, r in zip(searchers, results)]

        # identical agg trees across the batch (guaranteed by the group
        # key): the shared match-mask programs above gate per-row device
        # collect — the config #3 analytics fast lane
        agg_rendered: list[dict] | None = None
        totals_host: list = []
        if aggs_body is not None:
            from .search.aggs.aggregators import (collect_shard,
                                                  collect_shards_batched,
                                                  merge_shard_partials,
                                                  parse_aggs)
            from .search.aggs.aggregators import render as render_aggs
            agg_specs = parse_aggs(aggs_body)
            Q = len(queries)
            by_shard: dict[int, tuple[list, list]] = {}
            for i, seg, m in seg_masks:
                segs, ms = by_shard.setdefault(i, ([], []))
                segs.append(seg)
                ms.append(m)
            # leaf agg trees: ONE device program per (agg, segment) covers
            # every row, ONE device_get covers the whole batch (+ count-only
            # totals riding along)
            rows_by_shard, totals_host = collect_shards_batched(
                agg_specs, by_shard,
                extra_devs=[d for _, d in total_devs])
            agg_rendered = []
            if rows_by_shard is not None:
                for qi in range(Q):
                    partials = [rows[qi]
                                for rows in rows_by_shard.values()]
                    agg_rendered.append(render_aggs(
                        agg_specs,
                        merge_shard_partials(agg_specs, partials)))
            else:
                # general per-row path (sub-aggs, non-columnar fields, ...)
                for qi in range(Q):
                    partials = [collect_shard(
                        agg_specs, segs, [m[qi] for m in ms],
                        query_parser=searchers[i].parser)
                        for i, (segs, ms) in by_shard.items()]
                    agg_rendered.append(render_aggs(
                        agg_specs, merge_shard_partials(agg_specs,
                                                        partials)))
        elif total_devs:
            import jax
            totals_host = jax.device_get([d for _, d in total_devs])

        if results is None:
            # materialize the count-only QuerySearchResults from the fused
            # fetch's totals
            from .search.shard_searcher import QuerySearchResult
            import numpy as _np
            Q = len(queries)
            totals = {i: _np.zeros((Q,), _np.int64)
                      for i in range(len(searchers))}
            for (i, _d), hv in zip(total_devs, totals_host):
                totals[i] += _np.asarray(hv)
            results = [QuerySearchResult(
                shard_id=s.shard_id,
                doc_keys=_np.full((Q, 0), -1, _np.int64),
                scores=_np.full((Q, 0), _np.nan, _np.float32),
                sort_values=None, total_hits=totals[i],
                max_score=_np.full((Q,), _np.nan, _np.float32))
                for i, s in enumerate(searchers)]

        outs = self._batched_reduce(metas, searchers, index_of, results,
                                    size, from_, agg_rendered, t0)
        # count AFTER successful assembly — a raise above degrades the
        # batch to the solo path, which books its own query_total (the
        # packed lane documents the same convention)
        self.meters["search"].mark(len(metas))
        for n in names:
            svc = self.indices[n]
            svc.query_total += len(metas)
            svc.search_stats["batched"] = \
                svc.search_stats.get("batched", 0) + len(metas)
            svc.meters["search"].mark(len(metas))
        return outs

    def _batched_reduce(self, metas, searchers, index_of, results,
                        size, from_, agg_rendered, t0,
                        reduced_rows=None) -> list[dict]:
        took = int((time.perf_counter() - t0) * 1000)
        outs = []
        for qi, (_, body) in enumerate(metas):
            # the mesh-batched lane hands per-row ReducedDocs straight from
            # the device reduce — sort_docs (the host merge) is skipped
            reduced = reduced_rows[qi] if reduced_rows is not None \
                else controller.sort_docs(results, from_=from_, size=size,
                                          query_row=qi)
            src_filter = body.get("_source")
            fields_spec = body.get("fields")
            if isinstance(fields_spec, str):
                fields_spec = [fields_spec]
            hits = controller.fetch_and_merge(
                reduced, searchers,
                source_filter=(lambda s: _source_filter(s, src_filter))
                if src_filter is not None else None,
                fields_spec=fields_spec)
            for slot, h in enumerate(hits):
                h["_index"] = index_of[reduced.shard_order[slot]]
            out = {
                "took": took,
                "timed_out": False,
                "_shards": {"total": len(searchers),
                            "successful": len(searchers), "failed": 0},
                "hits": {"total": reduced.total_hits,
                         "max_score": None
                         if reduced.max_score != reduced.max_score
                         else reduced.max_score,
                         "hits": hits},
            }
            if agg_rendered is not None:
                out["aggregations"] = agg_rendered[qi]
            outs.append(out)
        return outs

    # -- scroll (cursored reads, ref §3.5 scroll/scan call stack) ----------

    def _scroll_start(self, index: str, body: dict, size: int,
                      keep_alive: str, scan: bool = False) -> dict:
        """Open a scroll context: PIN a point-in-time snapshot of every
        shard's segment set (frozen liveness), then advance with
        search_after cursors over the pinned searchers — O(depth) total,
        and concurrent writes/deletes/merges never change what the scroll
        sees (ref search/scan/ScanContext.java:55 pinning the reader,
        SearchService.java:316-330 context keep-alive)."""
        import threading

        names = self._resolve(index)
        if not names:
            raise IndexMissingException(index)
        alias_flt = self._alias_filters_by_index(index, names)
        if any(k in body for k in ("knn", "rescore", "search_after",
                                   "rank")):
            raise QueryParsingException(
                "scroll does not support knn/rescore/search_after/rank")
        from .search.sort import DOC, SCORE, SortSpec, parse_sort
        user_sort = parse_sort(body.get("sort"),
                               [self.indices[n].mappers for n in names])
        implicit = user_sort is None
        if scan:
            # scan: doc order, no scoring (ref search_type=scan +
            # search/scan/ScanContext) — first response carries only total
            user_sort = None
            implicit = True
            specs = [SortSpec(field=DOC, order="asc")]
        else:
            specs = list(user_sort) if user_sort else \
                [SortSpec(field=SCORE, order="desc")]
        if not any(sp.field == DOC for sp in specs):
            # _doc tiebreak makes the cursor a total order: batches never
            # repeat or skip docs with equal primary keys
            specs = specs + [SortSpec(field=DOC, order="asc")]

        # pin: share device arrays, freeze the liveness bitmap
        import dataclasses as _dc
        searchers: list[ShardSearcher] = []
        index_of: list[str] = []
        for n in names:
            svc = self.indices[n]
            for e in svc.shards:
                segs = [_dc.replace(seg, live_host=seg.live_host.copy(),
                                    live_count=seg.live_count)
                        for seg in e.segments]
                # shard ids unique ACROSS indices: the _doc cursor key
                # embeds them, and a collision would skip docs mid-scroll
                searchers.append(ShardSearcher(len(searchers), segs,
                                               svc.mappers))
                index_of.append(n)

        query = body.get("query", {"match_all": {}})
        from .search.query_dsl import CollectionStats
        from .search.query_parser import QueryParser, merge_query_batch
        nodes_by_index: dict[str, Any] = {}
        terms_by_field: dict[str, set] = {}
        for n in names:
            q_n = self._wrap_alias_query(query, alias_flt[n]) \
                if n in alias_flt else query
            parsed = QueryParser(self.indices[n].mappers).parse(q_n)
            parsed.collect_terms(terms_by_field)
            nodes_by_index[n] = merge_query_batch([parsed])
        stats = CollectionStats.from_segments(
            [seg for s in searchers for seg in s.segments], terms_by_field)

        with self._scroll_lock:
            self._reap_scrolls()
            self._scroll_seq += 1
            sid = f"scroll-{self._scroll_seq}"
            ctx = {"searchers": searchers, "index_of": index_of,
                   "nodes": nodes_by_index, "specs": specs, "stats": stats,
                   "cursor": None, "implicit_sort": implicit,
                   "source": body.get("_source"),
                   "fields": body.get("fields"),
                   "aggs": body.get("aggs") or body.get("aggregations"),
                   "expiry": time.monotonic() + _duration_secs(keep_alive),
                   "keep_alive": keep_alive, "lock": threading.Lock()}
            self._scrolls[sid] = ctx
        if scan:
            # the scan contract: the initial response has totals only;
            # docs start flowing on the first scroll call
            ctx["size"] = size
            out = self._scroll_batch(ctx, 0)
            ctx["size"] = size
        else:
            out = self._scroll_batch(ctx, size)
        out["_scroll_id"] = sid
        return out

    def scroll(self, scroll_id: str, keep_alive: str | None = None) -> dict:
        with self._scroll_lock:
            self._reap_scrolls()
            ctx = self._scrolls.get(scroll_id)
            if ctx is None:
                raise IndexMissingException(
                    f"scroll [{scroll_id}] expired or unknown")
            if keep_alive:
                ctx["keep_alive"] = keep_alive
            ctx["expiry"] = time.monotonic() \
                + _duration_secs(ctx["keep_alive"])
        out = self._scroll_batch(ctx, ctx.get("size", 10))
        out["_scroll_id"] = scroll_id
        return out

    def _scroll_batch(self, ctx: dict, size: int | None = None) -> dict:
        t0 = time.perf_counter()
        # per-context lock: two concurrent scrolls on the same id must not
        # read the same cursor and return duplicate batches
        with ctx["lock"]:
            if size is None:
                size = ctx.get("size", 10)
            ctx["size"] = size
            searchers = ctx["searchers"]
            agg_specs = None
            if ctx["cursor"] is None and ctx["aggs"]:
                agg_specs = parse_aggs(ctx["aggs"])
            results = [
                s.execute_query_phase(
                    ctx["nodes"][ctx["index_of"][i]], size=size,
                    sort=ctx["specs"], search_after=ctx["cursor"],
                    global_stats=ctx["stats"],
                    track_scores=False,   # the _score spec re-enables it
                    aggs=agg_specs)
                for i, s in enumerate(searchers)]
            reduced = controller.sort_docs(results, from_=0, size=size,
                                           sort=ctx["specs"])
            src_filter = ctx["source"]
            fields_spec = ctx.get("fields")
            if isinstance(fields_spec, str):
                fields_spec = [fields_spec]
            hits = controller.fetch_and_merge(
                reduced, searchers,
                source_filter=(lambda s: _source_filter(s, src_filter))
                if src_filter is not None else None,
                fields_spec=fields_spec)
            for slot, h in enumerate(hits):
                h["_index"] = ctx["index_of"][reduced.shard_order[slot]]
            if hits:
                ctx["cursor"] = hits[-1]["sort"]
            if ctx["implicit_sort"]:
                # default scroll is score-ordered; the synthetic sort keys
                # are cursor plumbing, not part of the user's response shape
                for h in hits:
                    h.pop("sort", None)
            resp: dict[str, Any] = {
                "took": int((time.perf_counter() - t0) * 1000),
                "timed_out": False,
                "_shards": {"total": len(searchers),
                            "successful": len(searchers), "failed": 0},
                "hits": {"total": reduced.total_hits,
                         "max_score": None
                         if reduced.max_score != reduced.max_score
                         else reduced.max_score,
                         "hits": hits},
            }
            if agg_specs:
                merged = merge_shard_partials(
                    agg_specs, [r.aggs for r in results if r.aggs])
                resp["aggregations"] = render_aggs(agg_specs, merged)
            return resp

    def clear_scroll(self, scroll_ids: list[str]) -> int:
        with self._scroll_lock:
            return sum(1 for sid in scroll_ids
                       if self._scrolls.pop(sid, None) is not None)

    def _reap_scrolls(self) -> None:
        # caller holds _scroll_lock
        now = time.monotonic()
        for sid in [s for s, c in self._scrolls.items() if c["expiry"] < now]:
            del self._scrolls[sid]

    # -- admin -------------------------------------------------------------

    def refresh(self, index: str = "_all") -> None:
        for n in self._resolve(index):
            self.indices[n].refresh()
            self._run_warmers(n)

    def _run_warmers(self, name: str) -> None:
        """Execute registered warmer searches against the FRESH searcher
        (ref indices/warmer/IndicesWarmer + IndexWarmersMetaData: warmers
        run on every new reader so caches/packed views are hot before the
        first real query). Best-effort: a broken warmer logs, never fails
        the refresh."""
        svc = self.indices.get(name)
        warmers = getattr(svc, "warmers", None)
        if not warmers:
            return
        for wname, spec in list(warmers.items()):
            body = dict(spec.get("source") or {})
            body.setdefault("size", 0)
            try:
                self.search(name, body, request_cache=False)
                svc.warmer_runs = getattr(svc, "warmer_runs", 0) + 1
            except Exception as e:  # noqa: BLE001
                logger.warning("warmer [%s] on [%s] failed: %s",
                               wname, name, e)

    def flush(self, index: str = "_all") -> None:
        for n in self._resolve(index):
            self.indices[n].flush()
            self._persist_index_meta(self.indices[n])

    def force_merge(self, index: str = "_all",
                    max_num_segments: int = 1) -> None:
        """ref the _optimize API (action/admin/indices/optimize)."""
        for n in self._resolve(index):
            self.indices[n].force_merge(max_num_segments)

    def put_mapping(self, index: str, type_name: str, mapping: dict) -> None:
        for n in self._resolve(index):
            self.indices[n].mappers.merge(type_name, mapping)
            self._persist_index_meta(self.indices[n])

    def put_template(self, name: str, body: dict) -> None:
        self.templates[name] = body
        self._persist_templates()

    def _persist_templates(self) -> None:
        import json
        path = os.path.join(self.data_path, "_templates.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.templates, f)
        os.replace(tmp, path)

    def _persist_search_templates(self) -> None:
        import json
        path = os.path.join(self.data_path, "_search_templates.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.search_templates, f)
        os.replace(tmp, path)

    def delete_by_query(self, index: str, body: dict) -> int:
        """Delete every doc matching the query (ref the 1.x _query API,
        action/deletebyquery/) — scroll the match set, bulk-delete by id."""
        query_body = {"query": body.get("query", body or {"match_all": {}}),
                      "size": 1000, "_source": False}
        out = self.search(index, query_body, scroll="1m")
        sid = out.get("_scroll_id")
        deleted = 0
        try:
            while True:
                hits = out["hits"]["hits"]
                if not hits:
                    break
                for h in hits:
                    try:
                        self.delete_doc(h["_index"], h["_id"], sync=False)
                        deleted += 1
                    except Exception:  # noqa: BLE001 — already gone
                        pass
                out = self.scroll(sid)
        finally:
            if sid:
                self.clear_scroll([sid])
        for n in self._resolve(index):
            self.indices[n].sync_translogs()
        return deleted

    # -- index maintenance scheduler: LIVE dynamic settings ----------------

    def run_index_maintenance(self) -> dict:
        """One pass of the per-index schedulers that the reference runs as
        background services, each reading its threshold from LIVE settings
        so `_settings` updates apply to a running index immediately:
          * index.refresh_interval  — periodic NRT refresh
            (ref index/shard/IndexShard refresh scheduler; default here is
            manual-refresh to keep NRT tests deterministic)
          * index.translog.flush_threshold_ops — flush when the translog
            accumulates that many ops (ref index/translog/
            TranslogService.java:105-115)
        Returns {"refreshed": n, "flushed": n}."""
        now = time.monotonic()
        refreshed = flushed = 0
        for name, svc in list(self.indices.items()):
            s = svc.settings
            ri = s.get("index.refresh_interval", s.get("refresh_interval"))
            if ri not in (None, "", "-1", -1):
                from .mapping.mapper import parse_ttl_ms
                try:
                    interval = parse_ttl_ms(ri) / 1000.0
                except Exception:  # noqa: BLE001
                    interval = None
                last = getattr(svc, "_last_sched_refresh", 0.0)
                if interval is not None and now - last >= interval:
                    svc._last_sched_refresh = now
                    try:
                        svc.refresh()
                        self._run_warmers(name)
                        refreshed += 1
                    except Exception:  # noqa: BLE001 — keep the scheduler
                        pass
            fto = s.get("index.translog.flush_threshold_ops",
                        s.get("translog.flush_threshold_ops"))
            if fto not in (None, ""):
                try:
                    fto = int(fto)
                except ValueError:
                    continue
                for e in svc.shards:
                    if e.translog.ops_since_commit >= fto > 0:
                        try:
                            e.flush()
                            flushed += 1
                        except Exception:  # noqa: BLE001
                            pass
        return {"refreshed": refreshed, "flushed": flushed}

    def _maintenance_loop(self) -> None:
        while not self._maint_stop.wait(0.25):
            try:
                self.run_index_maintenance()
            except Exception:  # noqa: BLE001 — scheduler must survive
                pass

    # -- TTL purger (ref indices/ttl/IndicesTTLService.java:66) -----------

    def purge_expired_docs(self, now_ms: int | None = None) -> int:
        """Sweep every shard for docs whose _ttl expiry lies in the past
        and delete them (the reference's 60s PurgerThread does exactly
        this with a bulk request)."""
        import numpy as _np
        now = int(time.time() * 1000) if now_ms is None else int(now_ms)
        deleted = 0
        for name, svc in list(self.indices.items()):
            expired: list[tuple[str, Any]] = []
            for e in svc.shards:
                with e._lock:
                    segments = list(e.segments)
                for seg in segments:
                    nc = seg.numerics.get("_ttl_expiry")
                    if nc is None:
                        continue
                    vals = _np.asarray(nc.vals)
                    miss = _np.asarray(nc.missing)
                    hits = _np.flatnonzero(~miss[:seg.n_docs]
                                           & (vals[:seg.n_docs] < now))
                    for local in hits:
                        local = int(local)
                        if not seg.live_host[local] \
                                or seg.types[local].startswith("__"):
                            continue
                        expired.append((seg.ids[local],
                                        seg.routings[local]))
            for doc_id, routing in expired:
                try:
                    svc.delete_doc(doc_id, routing=routing)
                    deleted += 1
                except Exception:  # noqa: BLE001 — already re-deleted/raced
                    pass
            if expired:
                svc.refresh()
        return deleted

    def start_ttl_purger(self, interval_s: float = 60.0) -> None:
        """Background purger thread (off by default; tests drive
        purge_expired_docs directly)."""
        import threading as _th
        if getattr(self, "_ttl_thread", None) is not None:
            return
        self._ttl_stop = _th.Event()

        def loop():
            while not self._ttl_stop.wait(interval_s):
                try:
                    self.purge_expired_docs()
                except Exception:  # noqa: BLE001 — keep the purger alive
                    pass
        self._ttl_thread = _th.Thread(target=loop, daemon=True,
                                      name="es[ttl_purger]")
        self._ttl_thread.start()

    # -- IndexingMemoryController (ref indices/memory/
    #    IndexingMemoryController.java:60) ---------------------------------

    def check_indexing_memory(self) -> int:
        """One shared indexing-buffer byte budget across ALL shards
        (`indices.memory.index_buffer_size`); over budget, the largest
        buffers refresh until back under. Returns refreshes triggered."""
        raw = self.settings.get("indices.memory.index_buffer_size",
                                "128mb")
        try:
            budget = _parse_bytes(str(raw))
        except ValueError:
            budget = 128 << 20
        engines = [e for svc in self.indices.values() for e in svc.shards]
        total = sum(e._buffer_bytes for e in engines)
        refreshed = 0
        while total > budget:
            biggest = max(engines, key=lambda e: e._buffer_bytes)
            if biggest._buffer_bytes <= 0:
                break
            total -= biggest._buffer_bytes
            biggest.refresh()
            refreshed += 1
        return refreshed

    def cluster_health(self, level: str = "cluster") -> dict:
        shards = sum(s.n_shards for s in self.indices.values())
        unassigned = sum(s.n_shards * s.n_replicas
                         for s in self.indices.values())
        per_index = {}
        if level in ("indices", "shards"):
            for n, s in self.indices.items():
                ih = {"status": "yellow" if s.n_replicas else "green",
                      "number_of_shards": s.n_shards,
                      "number_of_replicas": s.n_replicas,
                      "active_primary_shards": s.n_shards,
                      "active_shards": s.n_shards,
                      "relocating_shards": 0, "initializing_shards": 0,
                      "unassigned_shards": s.n_shards * s.n_replicas}
                if level == "shards":
                    ih["shards"] = {
                        str(i): {"status": ih["status"],
                                 "primary_active": True,
                                 "active_shards": 1,
                                 "relocating_shards": 0,
                                 "initializing_shards": 0,
                                 "unassigned_shards": s.n_replicas}
                        for i in range(s.n_shards)}
                per_index[n] = ih
        return {** ({"indices": per_index}
                    if level in ("indices", "shards") else {}),
            "cluster_name": self.cluster_name,
            "status": "yellow" if unassigned else "green",
            "timed_out": False,
            "number_of_nodes": 1,
            "number_of_data_nodes": 1,
            "active_primary_shards": shards,
            "active_shards": shards,
            "relocating_shards": 0,
            "initializing_shards": 0,
            "unassigned_shards": sum(
                s.n_shards * s.n_replicas for s in self.indices.values()),
            "delayed_unassigned_shards": 0,
            "number_of_pending_tasks": 0,
            "number_of_in_flight_fetch": 0,
            "task_max_waiting_in_queue_millis": 0,
            "active_shards_percent_as_number": 100.0,
        }

    def stats(self) -> dict:
        return {"indices": {n: s.stats() for n, s in self.indices.items()},
                "breakers": self.breakers.stats(),
                "caches": self.caches.stats(),
                "search_batcher": self._batcher.stats()}

    # -- telemetry (the /_metrics exposition + stats-history sampler) ------

    def device_stats_payload(self, top_n: int = 50) -> dict:
        """`GET /_nodes/device_stats` (ISSUE 16): the per-program XLA
        registry (compile ms, invocations, cumulative dispatch time, lazy
        flops/bytes-accessed cost — None-safe on CPU), per-device HBM
        stats with the process high-water mark, and the global
        lane-decision counters. Cost analysis is forced HERE (a scrape-
        time re-lower), never on the dispatch path."""
        from .common import device_stats
        return {
            "programs": device_stats.registry_snapshot(
                top_n=top_n, with_cost=True),
            "hbm": device_stats.hbm_poll(),
            "lane_decisions": device_stats.lane_decisions_snapshot(),
        }

    def metric_sections(self) -> dict:
        """Every stats registry of this node as OpenMetrics walk input:
        {section: (label_name | None, payload)}. A NEW stats source joins
        the `/_metrics` scrape (and the strict-parser tripwire test) by
        adding one entry here — labeled registries (pools, breakers,
        timers, indices) pick up new entries automatically."""
        from .common import device_stats, monitor
        from .common.metrics import device_events_snapshot, transfer_snapshot
        batcher = self._batcher.stats()
        occupancy = batcher.pop("occupancy", {})
        per_index = {}
        for n, svc in self.indices.items():
            seg = [e.segment_stats() for e in svc.shards]
            rc = self.caches.request_cache.index_stats(n)
            per_index[n] = {
                "docs": svc.doc_count(),
                "store_size_in_bytes": sum(s["memory_in_bytes"]
                                           for s in seg),
                "segments": sum(s["count"] for s in seg),
                "search_total": svc.query_total,
                "indexing_total": svc.indexing_stats["index_total"],
                "delete_total": svc.indexing_stats["delete_total"],
                "request_cache_hits_total": svc.request_cache_hits,
                "request_cache_misses_total": svc.request_cache_misses,
                "request_cache_memory_in_bytes": rc["bytes"],
                "request_cache_evictions_total": rc["evictions"],
                "search_rate_1m": svc.meters["search"].rate(60),
                "indexing_rate_1m": svc.meters["indexing"].rate(60),
            }
        compiles, compile_ms = device_events_snapshot()
        os_st = monitor.os_stats()
        proc = monitor.process_stats()
        load = os_st.get("load_average") or [0.0]
        # device execution-path counters summed across indices: how many
        # per-segment programs ran vs how many segment-stacked ones (the
        # stacked dense lane replaces G dispatches + G fetches with 1 + 1)
        from .common.metrics import shard_fetch_histogram
        path_totals: dict[str, int] = {}
        for svc in self.indices.values():
            for pk, pv in svc.search_stats.items():
                path_totals[pk] = path_totals.get(pk, 0) + pv
        from .common.metrics import (bulk_docs_histogram,
                                     bulk_ingest_snapshot, host_merge_count,
                                     peak_score_matrix_bytes)
        from .script.jax_compile import script_compiles_snapshot
        from .search.percolate_exec import percolate_stats_snapshot
        from .serving.qos import hedge_snapshot
        _perc_raw = percolate_stats_snapshot()
        _perc_stats = {
            "dispatches": {ln: _perc_raw[ln]
                           for ln in ("dense", "loop", "mesh")},
            "docs": _perc_raw["docs"],
            "matrix_cells": _perc_raw["matrix_cells"],
            "residual_queries": _perc_raw["residual_queries"],
        }
        qos_stats = self.qos.stats()
        qos_by_class = qos_stats.pop("by_class")
        search_exec = {
            "segment_dispatches_total":
                path_totals.get("segment_dispatches", 0),
            "stacked_dispatches_total":
                path_totals.get("stacked_dispatches", 0),
            "stacked_queries_total": path_totals.get("stacked", 0),
            "stacked_errors_total": path_totals.get("stacked_errors", 0),
            # streaming blockwise dense lane (ISSUE 8): executions that ran
            # the tree per doc block under a running on-device top-k, plus
            # the process-peak score-matrix residency a dense query phase
            # materialized (O(Q×block) blockwise vs O(Q×n_pad) full)
            "blockwise_dispatches_total":
                path_totals.get("blockwise_dispatches", 0),
            "peak_score_matrix_bytes": peak_score_matrix_bytes(),
            # mesh-sharded lane (ISSUE 6): whole-index collective programs
            # vs per-shard stacked/segment dispatches, plus how many
            # host-side cross-shard merges still ran (fan-out path)
            "mesh_dispatches_total": path_totals.get("mesh_dispatches", 0),
            "mesh_queries_total": path_totals.get("mesh", 0),
            "mesh_errors_total": path_totals.get("mesh_errors", 0),
            # aggs + IVF kNN through the mesh program (ISSUE 11): how
            # much of each workload rides the collective lane vs falls
            # down the ladder to the fan-out
            "mesh_agg_dispatches_total":
                path_totals.get("mesh_agg_dispatches", 0),
            "mesh_agg_fallbacks_total":
                path_totals.get("mesh_agg_fallbacks", 0),
            # sorted queries through the dense lanes (ISSUE 17): encoded
            # sort keys ranked on device by the per-shard stacked program
            # vs the whole-index mesh collective — bodies that decline
            # the encoding still ride the loop and show up in the lane
            # decision family below, not here
            "stacked_sorted_queries_total":
                path_totals.get("stacked_sorted", 0),
            "mesh_sorted_dispatches_total":
                path_totals.get("mesh_sorted_dispatches", 0),
            "mesh_ann_dispatches_total":
                path_totals.get("mesh_ann_dispatches", 0),
            "mesh_ann_fallbacks_total":
                path_totals.get("mesh_ann_fallbacks", 0),
            "host_merges_total": host_merge_count(),
            # IVF-clustered ANN lane (ISSUE 10): segment executions that
            # routed through the centroid->cluster-scan kernel vs declined
            # builds that fell back to the exact matmul
            "ann_dispatches_total": path_totals.get("ann_dispatches", 0),
            "ann_fallbacks_total": path_totals.get("ann_fallbacks", 0),
            # quantized ANN tier (ISSUE 12): scans served on int8/PQ codes
            # (the per-mode split rides the labeled search_ann_quantized
            # section below) vs declines back to the f32 IVF scan
            "ann_quantized_fallbacks_total":
                path_totals.get("ann_quantized_fallbacks", 0),
            "sparse_queries_total": path_totals.get("sparse", 0),
            "dense_queries_total": path_totals.get("dense", 0),
            "packed_queries_total": path_totals.get("packed", 0),
        }
        return {
            "threadpool": ("pool", self.thread_pool.stats()),
            "breaker": ("breaker", self.breakers.stats()),
            "search_phase": ("phase", self.phase_timers.stats()),
            "timer": ("timer", self.metrics.stats()),
            "search_batcher": (None, batcher),
            "batch_occupancy": ("size",
                                {str(k): {"count": v}
                                 for k, v in occupancy.items()}),
            "index": ("index", per_index),
            # the cache subsystem: one sample set per tier (request /
            # query_plan / fielddata / registered extras), uniform leaves
            "cache": ("cache", self.caches.stats()),
            # stacked-vs-segment dispatch counters (ISSUE 4) plus a
            # fetches-per-shard-query histogram: bucket n = a shard query
            # phase that needed n device round-trips (stacked lane: 1)
            "search": (None, search_exec),
            # quantized-scan adoption split by mode (ISSUE 12):
            # es_search_ann_quantized_dispatches_total{mode="int8"|"pq"}
            "search_ann_quantized": ("mode", {
                "int8": {"dispatches_total":
                         path_totals.get("ann_quantized_int8", 0)},
                "pq": {"dispatches_total":
                       path_totals.get("ann_quantized_pq", 0)}}),
            "search_fetches": ("fetches_per_query",
                               {str(n): {"count": c}
                                for n, c in sorted(
                                    shard_fetch_histogram().items())}),
            # reverse-search lane adoption (ISSUE 18):
            # es_search_percolate_dispatches_total{lane=} — how many
            # percolate dispatches the dense doc×query matrix carried vs
            # the per-doc loop vs the mesh rung
            "search_percolate": ("lane", {
                lane: {"dispatches_total": n}
                for lane, n in _perc_stats["dispatches"].items()}),
            "percolate": (None, {
                "docs_total": _perc_stats["docs"],
                "matrix_cells_total": _perc_stats["matrix_cells"],
                "residual_queries_total": _perc_stats["residual_queries"]}),
            # expression->XLA script compiler (ISSUE 18):
            # es_script_compiles_total{target=} counts TRUE builds only —
            # cached template re-use with different params must not bump it
            "script": ("target", {
                t: {"compiles_total": n}
                for t, n in script_compiles_snapshot().items()} or {
                "function_score": {"compiles_total": 0}}),
            # bulk-ingest lane (ISSUE 7): vectorized vs per-doc-fallback
            # request/doc counters + ingest docs/s, and a docs-per-bulk
            # pow2 histogram (how much batching clients actually send)
            "indexing": (None, {**bulk_ingest_snapshot(),
                                "ingest_docs_per_sec":
                                    self.meters["indexing"].rate(60)}),
            "bulk_docs": ("docs_per_bulk",
                          {str(n): {"count": c}
                           for n, c in sorted(
                               bulk_docs_histogram().items())}),
            # serving-QoS (ISSUE 9): per-class admission/shed counters +
            # the pressure/EWMA gauges, and hedged-read outcomes
            # (es_qos_shed_total{class=}, es_search_hedged_total{outcome=})
            "qos": ("class", qos_by_class),
            "qos_node": (None, qos_stats),
            "search_hedged": ("outcome",
                              {o: {"total": c}
                               for o, c in hedge_snapshot().items()}),
            # watcher alerting tier (ISSUE 20): evaluation/fire/throttle
            # counters + per-watch last-fire gauges
            # (es_watcher_watch_*{watch=}); zeros when watcher.enable is
            # false so the scrape shape stays stable
            "watcher": (None, self.watcher_service.metric_totals()
                        if getattr(self, "watcher_service", None) else
                        {"evaluations_total": 0, "fires_total": 0,
                         "throttled_total": 0, "errors_total": 0,
                         "percolate_rides_total": 0,
                         "alerts_indexed_total": 0,
                         "retention_deletes_total": 0, "watches": 0}),
            "watcher_watch": ("watch",
                              self.watcher_service.metric_per_watch()
                              if getattr(self, "watcher_service", None)
                              else {}),
            "jit": (None, {"compiles": compiles,
                           "compile_time_in_millis": round(compile_ms, 3)}),
            # per-program-site XLA accounting (ISSUE 16): invocations,
            # cumulative dispatch time, attributed compiles per site —
            # es_xla_program_*{program=}; full per-plan-key detail + cost
            # analysis live on GET /_nodes/device_stats
            "xla_program": ("program", device_stats.program_metrics()),
            # per-device HBM gauges (zeros + supported=False on CPU) —
            # the high-water mark is the 100M-vectors budget number
            "device_hbm": ("device", {
                ident: {k2: v2 for k2, v2 in st.items()
                        if k2 != "supported"}
                for ident, st in device_stats.hbm_poll().items()}),
            # the lane-decision counter family (ISSUE 16):
            # es_search_lane_decisions_total{lane=,reason=} — one label
            # pair per ladder decision; the old *_fallbacks/_errors
            # counters above stay as aliases
            "search_lane": (("lane", "reason"),
                            device_stats.lane_decision_metrics()),
            "transfer": (None, transfer_snapshot()),
            "tasks": (None, self.tasks.stats()),
            # span tracer: started/retained/sampled-out trace counters,
            # ring-eviction + span-cap drop counters, live gauges
            "tracing": (None, self.tracer.stats()),
            "rate": ("op", {n: m.stats() for n, m in self.meters.items()}),
            "process": (None, {
                "resident_bytes": proc.get("mem", {})
                .get("resident_in_bytes", 0),
                "threads": proc.get("threads", 0),
                "open_file_descriptors":
                    proc.get("open_file_descriptors", 0)}),
            "os": (None, {"load_1m": load[0],
                          "cpu_percent": os_st["cpu"]["percent"],
                          "mem_used_bytes": os_st.get("mem", {})
                          .get("used_in_bytes", 0)}),
        }

    def _sampler_snapshot(self) -> dict:
        """Flat gauge snapshot for the stats-history ring: the signals an
        incident inspection reaches for first (queue pressure, rejection,
        device-memory headroom, rates, batch coalescing, host health)."""
        from .common import device_stats, monitor
        from .common.metrics import bulk_ingest_snapshot, device_events_snapshot
        _bulk_snap = bulk_ingest_snapshot()
        _hbm = device_stats.hbm_poll()
        _hbm_in_use = sum(v["bytes_in_use"] for v in _hbm.values())
        _hbm_peak = max((v["high_water_bytes"] for v in _hbm.values()),
                        default=0)
        pool = self.thread_pool.stats().get("search", {})
        br = self.breakers.stats()
        batcher = self._batcher.stats()
        os_st = monitor.os_stats()
        load = os_st.get("load_average") or [0.0]
        out = {
            "heap_used_bytes": monitor._rss(),
            "threads": monitor.process_stats().get("threads", 0),
            "load_1m": load[0],
            "cpu_percent": os_st["cpu"]["percent"],
            "search_rate_1m": self.meters["search"].rate(60),
            "indexing_rate_1m": self.meters["indexing"].rate(60),
            "get_rate_1m": self.meters["get"].rate(60),
            # ingest docs/s + batch-lane adoption (vectorized vs fallback
            # docs) ride the 1-hour history ring: an ingest-rate incident
            # inspection sees both the rate and WHICH lane carried it
            "ingest_docs_per_sec": self.meters["indexing"].rate(60),
            "bulk_vectorized_docs_total":
                _bulk_snap["vectorized_docs_total"],
            "bulk_fallback_docs_total": _bulk_snap["fallback_docs_total"],
            "pool_search_queue": pool.get("queue", 0),
            "pool_search_active": pool.get("active", 0),
            "pool_search_rejected_total": pool.get("rejected", 0),
            "batcher_batches_total": batcher["batches"],
            "batcher_batched_requests_total": batcher["batched_requests"],
            "docs": sum(s.doc_count() for s in self.indices.values()),
            "tasks_running": self.tasks.stats()["running"],
            "jit_compiles_total": device_events_snapshot()[0],
            # per-device HBM residency (ISSUE 16): bytes_in_use tracks the
            # live working set, hbm_peak the process high-water — the
            # ring answers "what did device memory look like at 14:05"
            "hbm_bytes_in_use": _hbm_in_use,
            "hbm_peak_bytes": _hbm_peak,
            "request_cache_memory_bytes":
                self.caches.request_cache.cache.memory_bytes,
            "request_cache_hits_total": self.caches.request_cache.cache.hits,
            "fielddata_cache_memory_bytes":
                self.caches.fielddata.cache.memory_bytes,
            "segment_stack_cache_memory_bytes":
                self.caches.segment_stacks.cache.memory_bytes,
            "mesh_stack_cache_memory_bytes":
                self.caches.mesh_stacks.cache.memory_bytes,
            # mesh vector stacks (ISSUE 11) + mesh agg/ANN lane adoption:
            # an incident inspection sees whether agg/kNN traffic rides
            # the collective lane or fell down the ladder
            "mesh_vector_stack_cache_memory_bytes":
                self.caches.mesh_vector_stacks.cache.memory_bytes,
            # vector-serving memory + lane adoption (ISSUE 10): IVF
            # centroid/CSR residency and how much kNN traffic the ANN
            # lane carried
            "ann_index_cache_memory_bytes":
                self.caches.ann_indexes.cache.memory_bytes,
            # quantized tier residency split (ISSUE 12): codes at their
            # true 1/4-1/32 bytes, codebooks separately — the incident
            # view of what the quantized stack actually costs
            "ann_quant_cache_memory_bytes":
                self.caches.ann_indexes.quant.memory_bytes,
            "ann_quant_code_bytes":
                max(self.caches.ann_indexes.quant_code_bytes, 0),
            "ann_quant_codebook_bytes":
                max(self.caches.ann_indexes.quant_book_bytes, 0),
            # registered-query corpus residency (ISSUE 18): what the
            # reverse-search registry costs in host bytes right now
            "percolator_registry_cache_memory_bytes":
                self.caches.percolator_registry.cache.memory_bytes,
        }
        mesh_totals = {"mesh_agg_dispatches": 0, "mesh_ann_dispatches": 0}
        for svc in self.indices.values():
            for mk in mesh_totals:
                mesh_totals[mk] += svc.search_stats.get(mk, 0)
        out["mesh_agg_dispatches_total"] = mesh_totals["mesh_agg_dispatches"]
        out["mesh_ann_dispatches_total"] = mesh_totals["mesh_ann_dispatches"]
        from .common.metrics import peak_score_matrix_bytes
        out["peak_score_matrix_bytes"] = peak_score_matrix_bytes()
        # serving-QoS gauges (ISSUE 9): queue depth, shed/hedge rates —
        # the signals a tail-latency incident inspection reaches for
        from .serving.qos import hedge_rate, hedge_snapshot
        qos = self.qos.stats()
        out["qos_pressure"] = qos["pressure"]
        out["qos_queue_depth"] = pool.get("queue", 0)
        out["qos_shed_rate_1m"] = qos["shed_rate_1m"]
        out["qos_shed_total"] = sum(c["shed_total"]
                                    for c in qos["by_class"].values())
        out["qos_degraded"] = qos["degraded"]
        out["hedge_rate_1m"] = hedge_rate(60)
        out["hedged_fired_total"] = hedge_snapshot()["fired"]
        # peer-recovery stream counters (ISSUE 15): bytes moved and
        # throttle back-pressure ride the history ring so a rebalance
        # wave's cost is visible next to the latency gauges it protects
        from .cluster.recovery import snapshot as recovery_snapshot
        rec = recovery_snapshot()
        out["recovery_bytes_total"] = rec["bytes_total"]
        out["recovery_throttle_waits_total"] = rec["throttle_waits_total"]
        bst = batcher
        out["batcher_stranded_total"] = bst["stranded_total"]
        out["batcher_wait_timeouts_total"] = bst["wait_timeouts_total"]
        # pod-plane health (ISSUE 20 satellite of ISSUE 19): exec-lock
        # contention, per-class transport latency EWMAs (dcn always
        # present — a pod watch must see 0.0, not a missing field) and
        # the process-wide pod reduce dispatch totals join the ring so
        # watches over `.monitoring-es-*` can alert on pod health
        from .parallel.mesh_exec import exec_lock_stats
        els = exec_lock_stats()
        out["exec_lock_waits"] = (els.get("shared_waits", 0)
                                  + els.get("pool_waits", 0))
        out["exec_lock_shared_waits"] = els.get("shared_waits", 0)
        out["exec_lock_pool_waits"] = els.get("pool_waits", 0)
        from .serving.qos import transport_latency_snapshot
        tlat = transport_latency_snapshot()
        for cls in sorted(set(tlat) | {"dcn"}):
            out[f"transport_latency_ewma_ms_{cls}"] = \
                tlat.get(cls, {}).get("ewma_ms", 0.0)
        from .cluster.host_reduce import pod_reduce_snapshot
        out.update(pod_reduce_snapshot())
        tr = self.tracer.stats()
        out["tracing_active_traces"] = tr["active_traces"]
        out["tracing_dropped_total"] = tr["dropped_traces_total"]
        for name, b in br.items():
            out[f"breaker_{name}_used_bytes"] = b["estimated_size_in_bytes"]
        return out

    def close(self) -> None:
        if not self.lifecycle.move_to_closed():
            return                      # idempotent double-close
        self.watcher.stop()
        if getattr(self, "watcher_service", None) is not None:
            self.watcher_service.close()  # joins the scheduler thread
        if getattr(self, "monitoring", None) is not None:
            self.monitoring.close()     # joins the collector thread
        if getattr(self, "sampler", None) is not None:
            self.sampler.stop()
        if getattr(self, "_maint_stop", None) is not None:
            self._maint_stop.set()
        if getattr(self, "_ttl_stop", None) is not None:
            self._ttl_stop.set()
        for svc in self.indices.values():
            svc.close()
        self.caches.close()     # releases request-breaker charges
        self.thread_pool.shutdown()
        try:
            import fcntl
            fcntl.flock(self._node_lock, fcntl.LOCK_UN)
            self._node_lock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------

class _ShardJob:
    """Claim-once shard execution for the concurrent query fan-out: a
    search-pool worker runs the job if it picks it up first, otherwise the
    coordinator steals it and runs it inline (join() claims before
    waiting). Because the coordinator can always execute every job itself,
    the fan-out stays deadlock-free even when the coordinators themselves
    occupy the same bounded pool."""

    __slots__ = ("fn", "done", "result", "error", "_claim")

    def __init__(self, fn):
        self.fn = fn
        self.done = threading.Event()
        self.result = None
        self.error = None
        self._claim = threading.Lock()

    def run(self) -> None:
        if not self._claim.acquire(blocking=False):
            return                          # someone else owns it
        try:
            self.result = self.fn()
        except BaseException as e:  # noqa: BLE001 — surfaced via .error
            self.error = e
        finally:
            self.done.set()

    def join(self) -> None:
        self.run()                          # steal if still queued
        self.done.wait()


def _empty_shard_result(shard_id: int, sort=None):
    """Placeholder result for a failed shard: keeps the reduce's
    result-per-searcher alignment while contributing zero hits."""
    import numpy as np

    from .search.shard_searcher import QuerySearchResult
    sv = None
    if sort is not None:
        sv = np.empty((1, 1), dtype=object)
        sv[0, 0] = None
    return QuerySearchResult(
        shard_id=shard_id,
        doc_keys=np.full((1, 1), -1, np.int64),
        scores=np.full((1, 1), np.nan, np.float32),
        sort_values=sv,
        total_hits=np.zeros((1,), np.int64),
        max_score=np.full((1,), np.nan, np.float32))


def _maybe_shard_profile(prof, index: str, shard_id: int):
    """prof.shard(...) when profiling, else a no-op context."""
    import contextlib
    if prof is None:
        return contextlib.nullcontext()
    return prof.shard(index, shard_id)


def _is_mlt_entry(k, v) -> bool:
    """True only for MLT QUERY nodes — a field literally named 'mlt' in a
    match/term leaf must not be hijacked (code review r4)."""
    return k in ("more_like_this", "mlt") and isinstance(v, dict) \
        and ({"like_text", "like", "docs", "ids", "fields"} & v.keys())


def _contains_mlt(q) -> bool:
    if isinstance(q, dict):
        return any(_is_mlt_entry(k, v) or _contains_mlt(v)
                   for k, v in q.items())
    if isinstance(q, list):
        return any(_contains_mlt(x) for x in q)
    return False


def _mesh_rows(keys, shard_of, scores, totals, mxs, *, n_queries: int,
               size: int, from_: int):
    """Per-row ReducedDocs from a mesh program's fetched outputs. Totals/
    max arrive PER SHARD ([S, Q]): int totals sum exactly, max over finite
    per-shard row-maxes equals the fan-out's global max bit-for-bit."""
    import math as _math

    import numpy as np

    from .search.controller import ReducedDocs
    window = slice(from_, from_ + size)
    rows = []
    for qi in range(n_queries):
        row_k, row_sh, row_s = keys[qi], shard_of[qi], scores[qi]
        valid = row_k >= 0
        vk, vsh, vs = row_k[valid], row_sh[valid], row_s[valid]
        mx_col = mxs[:, qi]
        mx_fin = mx_col[np.isfinite(mx_col)]
        mxv = float(mx_fin.max()) if mx_fin.size else float("nan")
        rows.append(ReducedDocs(
            shard_order=[int(x) for x in vsh[window]],
            doc_keys=[int(x) for x in vk[window]],
            scores=[float(x) for x in vs[window]],
            sort_values=None,
            total_hits=int(totals[:, qi].sum()),
            max_score=mxv if _math.isfinite(mxv) else float("nan")))
    return rows


def _mesh_rows_sorted(keys, shard_of, scores, totals, mxs, searchers, *,
                      n_queries: int, size: int, from_: int, sort,
                      track_scores: bool):
    """Per-row ReducedDocs for a SORTED mesh program (ISSUE 17): hit
    order arrived in encoded-key order from the device; only the winners'
    user-facing sort values materialize here — k real values per query,
    never a device round-trip. Scores follow the sorted-loop contract
    (NaN unless track_scores)."""
    import math as _math

    import numpy as np

    from .search import sort as sort_mod
    from .search.controller import ReducedDocs
    from .search.shard_searcher import LOCAL_MASK, SEG_SHIFT
    window = slice(from_, from_ + size)
    rows = []
    for qi in range(n_queries):
        valid = keys[qi] >= 0
        vk = keys[qi][valid][window]
        vsh = shard_of[qi][valid][window]
        vs = scores[qi][valid][window]
        svs, out_scores = [], []
        for dk, sh, sc in zip(vk, vsh, vs):
            seg = searchers[int(sh)].segments[int(dk) >> SEG_SHIFT]
            sc = float(sc) if track_scores else float("nan")
            out_scores.append(sc)
            svs.append(sort_mod.materialize(
                seg, sort, int(dk) & LOCAL_MASK, sc, int(dk), int(sh)))
        mx_col = mxs[:, qi]
        mx_fin = mx_col[np.isfinite(mx_col)]
        mxv = float(mx_fin.max()) if mx_fin.size and track_scores \
            else float("nan")
        rows.append(ReducedDocs(
            shard_order=[int(x) for x in vsh],
            doc_keys=[int(x) for x in vk],
            scores=out_scores,
            sort_values=svs,
            total_hits=int(totals[:, qi].sum()),
            max_score=mxv if _math.isfinite(mxv) else float("nan")))
    return rows


def _mesh_enabled_setting(settings) -> bool:
    """`node.search.mesh.enable` (default true) — the node-level opt-out
    of the mesh-sharded query lane (read live, so tests and `_settings`
    overlays apply without a restart)."""
    v = settings.get("node.search.mesh.enable", True)
    if isinstance(v, str):
        return v.strip().lower() not in ("false", "0", "no", "off")
    return bool(v)


def _req_cache_enabled(settings) -> bool:
    """`index.requests.cache.enable` (default true) — the per-index
    request-cache opt-out (ref IndicesRequestCache INDEX_CACHE_REQUEST_
    ENABLED setting)."""
    v = settings.get("index.requests.cache.enable",
                     settings.get("requests.cache.enable", True))
    if isinstance(v, str):
        return v.strip().lower() not in ("false", "0", "no", "off")
    return bool(v)


def _duration_secs(s: str) -> float:
    m = re.match(r"^(\d+(?:\.\d+)?)(ms|s|m|h|d)?$", str(s).strip())
    if not m:
        return 60.0
    n = float(m.group(1))
    return n * {"ms": 0.001, "s": 1, "m": 60, "h": 3600,
                "d": 86400, None: 1}[m.group(2)]


def _deep_merge(base: dict, patch: dict) -> dict:
    out = dict(base)
    for k, v in patch.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


class _ScriptDirListener:
    """FileWatcher listener: *.mustache / *.json files in <data>/scripts
    become stored search templates named by file stem (the reference's
    config/scripts file scripts, hot-reloaded by the resource watcher)."""

    def __init__(self, node: "NodeService"):
        self.node = node

    def _load(self, path: str) -> None:
        stem, ext = os.path.splitext(os.path.basename(path))
        if ext not in (".mustache", ".json"):
            return
        try:
            with open(path) as f:
                content = f.read()
        except OSError:
            return
        self.node.search_templates[stem] = content
        for svc in self.node.indices.values():
            svc.mappers.search_templates = self.node.search_templates

    def on_file_created(self, path: str) -> None:
        self._load(path)

    def on_file_changed(self, path: str) -> None:
        self._load(path)

    def on_file_deleted(self, path: str) -> None:
        stem, ext = os.path.splitext(os.path.basename(path))
        if ext in (".mustache", ".json"):
            self.node.search_templates.pop(stem, None)


def _parse_bytes(v: str) -> int:
    """"128mb" / "1gb" / "512kb" / plain ints -> bytes (ByteSizeValue)."""
    s = str(v).strip().lower()
    for suffix, mult in (("pb", 1 << 50), ("tb", 1 << 40), ("gb", 1 << 30),
                         ("mb", 1 << 20), ("kb", 1 << 10), ("b", 1)):
        if s.endswith(suffix):
            return int(float(s[: -len(suffix)]) * mult)
    return int(float(s))


def _source_filter(src: dict, spec) -> dict | None:
    """None = omit the _source key entirely (the `_source: false` contract —
    the reference drops the field, it does not send an empty object)."""
    if spec is False:
        return None
    if spec is True or spec is None:
        return src
    # path-aware include/exclude over FLATTENED source paths, so
    # "include.field1" style dotted patterns reach nested objects
    # (ref search/fetch/source/FetchSourceSubPhase)
    from .search.shard_searcher import _filter_source
    return _filter_source(src, spec)
