"""Multi-device percolation: the doc×query matrix sharded on the QUERY
axis (ISSUE 18 mesh rung).

The dense program scans pow2 blocks of the query axis, so the natural
mesh decomposition is block-parallel: each device runs the SAME compiled
program over a contiguous slice of the query-block xs (the doc batch is
small and replicates), and the per-device stripes concatenate back into
the full matrix. One device fetch per device — on a single-device host
the ladder declines this rung with the stable reason "single-device"
before dispatch (percolate_exec.percolate_batch)."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


def mesh_matrix(prog, operands, xs: dict, nb: int, devices) -> np.ndarray:
    """Run `prog` (the percolate scan, xs leading axis = nb query blocks)
    with the block axis split across `devices`. Slices stay pow2-aligned
    (every device gets ceil-pow2-balanced runs of whole blocks) so the
    per-device program shares ONE compile with the single-device lane
    when the slice sizes match a cached signature."""
    nd = min(len(devices), nb)
    bounds = [round(i * nb / nd) for i in range(nd + 1)]
    futures = []
    for di in range(nd):
        lo, hi = bounds[di], bounds[di + 1]
        if lo == hi:
            continue
        dev = devices[di]
        ops_d = [jax.device_put(jnp.asarray(a), dev) for a in operands]
        xs_d = {k: jax.device_put(jnp.asarray(v[lo:hi]), dev)
                for k, v in xs.items()}
        futures.append(prog(*ops_d, xs_d))      # async dispatch per device
    from ..common.metrics import device_fetch
    stripes = [np.asarray(device_fetch(f)) for f in futures]
    return np.concatenate(stripes, axis=1)
