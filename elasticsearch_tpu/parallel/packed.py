"""Packed multi-shard index: every shard's hot tensors stacked on a leading
shard axis so the whole index is ONE pytree shardable over a device mesh.

This is the TPU-native replacement for the reference's "N independent shard
JVMs" layout (SURVEY.md §2.10.1): shard i of the reference becomes slice i of
each stacked array, `jax.sharding` places slices on devices, and the query
fan-out (ref action/search/type/TransportSearchTypeAction.java:124 per-shard
network sends) becomes a single SPMD program over the mesh — no per-shard RPC
on the data plane at all.

Uniform shapes across shards (padding to the max, pow2-bucketed) are the price
of SPMD; BASELINE's hash routing keeps shard sizes balanced so the padding
waste is bounded.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp

from ..index.segment import Segment, next_pow2, pad_to
from ..ops.bm25_sparse import required_padding, slot_budget as _slot_budget


@dataclass
class PackedTextField:
    """One text field across S shards (device arrays lead with shard axis).
    No per-doc doc_len column here: the sparse kernel reads the denormalized
    per-posting `dl` instead, so a [S, N_pad] doc_len would be dead HBM."""
    field: str
    doc_ids: jax.Array        # i32[S, P_pad]
    tf: jax.Array             # f32[S, P_pad]
    dl: jax.Array             # f32[S, P_pad] per-posting doc length
    sum_dl: jax.Array         # f32[S]
    max_df: int               # largest postings list across shards
    # host-side per-shard term dicts for query preparation
    terms: list[dict[str, int]]
    term_starts: list[np.ndarray]
    term_lens: list[np.ndarray]


@dataclass
class PackedVectorField:
    """Dense vectors across S shards: [S, N_pad, D] for mesh kNN."""
    field: str
    vecs: jax.Array
    dims: int


@dataclass
class PackedIndex:
    """S shards of one index, packed for SPMD execution."""
    n_shards: int
    n_pad: int                # uniform padded doc capacity per shard
    live: jax.Array           # bool[S, N_pad]
    doc_counts: jax.Array     # i32[S] live doc count per shard
    text: dict[str, PackedTextField]
    # fetch-phase host state: per-shard stored sources + ids
    ids: list[list[str]]
    stored: list[list[dict]]
    vectors: dict[str, "PackedVectorField"] = None  # set in from_segments

    @staticmethod
    def from_segments(shard_segments: list[Segment]) -> "PackedIndex":
        """Pack one merged segment per shard. (Engines force_merge to 1
        segment before packing — the merged-tensor analog of an fsynced
        Lucene commit.)"""
        S = len(shard_segments)
        for seg in shard_segments:
            if seg.live_count < seg.n_docs:
                raise ValueError(
                    f"segment {seg.seg_id} has tombstones; force_merge the "
                    "shard before packing (the sparse scoring kernel assumes "
                    "all packed docs are live)")
        n_pad = max(next_pow2(s.n_docs) for s in shard_segments)

        live = np.zeros((S, n_pad), bool)
        counts = np.zeros((S,), np.int32)
        fields: set[str] = set()
        for si, seg in enumerate(shard_segments):
            # nested block rows never serve as top-level hits (root live)
            live[si, :seg.n_docs] = seg.root_live_host[:seg.n_docs]
            counts[si] = seg.root_live_count
            fields.update(seg.text.keys())

        text: dict[str, PackedTextField] = {}
        for f in sorted(fields):
            max_df = max((seg.text[f].max_df for seg in shard_segments
                          if f in seg.text), default=0)
            # shared sparse-kernel invariant (ops/bm25_sparse.required_padding)
            p_pad = max(required_padding(seg.text[f].n_postings, max_df)
                        if f in seg.text else 8 for seg in shard_segments)
            doc_ids = np.full((S, p_pad), n_pad, np.int32)  # PAD sentinel
            tf = np.zeros((S, p_pad), np.float32)
            dl = np.ones((S, p_pad), np.float32)
            sum_dl = np.zeros((S,), np.float32)
            terms, t_starts, t_lens = [], [], []
            for si, seg in enumerate(shard_segments):
                fx = seg.text.get(f)
                if fx is None:
                    terms.append({})
                    t_starts.append(np.zeros(0, np.int32))
                    t_lens.append(np.zeros(0, np.int32))
                    continue
                np_doc_ids = np.asarray(fx.doc_ids)[:fx.n_postings]
                doc_ids[si, :fx.n_postings] = np_doc_ids
                tf[si, :fx.n_postings] = np.asarray(fx.tf)[:fx.n_postings]
                dl[si, :fx.n_postings] = np.asarray(fx.dl)[:fx.n_postings]
                sum_dl[si] = fx.sum_dl
                terms.append(fx.terms)
                t_starts.append(fx.term_starts)
                t_lens.append(fx.term_lens)
            text[f] = PackedTextField(
                field=f, doc_ids=jnp.asarray(doc_ids), tf=jnp.asarray(tf),
                dl=jnp.asarray(dl), sum_dl=jnp.asarray(sum_dl), max_df=max_df,
                terms=terms, term_starts=t_starts, term_lens=t_lens)

        vec_fields: set[str] = set()
        for seg in shard_segments:
            vec_fields.update(seg.vectors.keys())
        vectors: dict[str, PackedVectorField] = {}
        for f in sorted(vec_fields):
            dims = next(seg.vectors[f].dims for seg in shard_segments
                        if f in seg.vectors)
            mat = np.zeros((S, n_pad, dims), np.float32)
            for si, seg in enumerate(shard_segments):
                vc = seg.vectors.get(f)
                if vc is not None:
                    v = np.asarray(vc.vecs)
                    mat[si, :v.shape[0]] = v
            vectors[f] = PackedVectorField(field=f, vecs=jnp.asarray(mat),
                                           dims=dims)

        ids = [list(seg.ids) for seg in shard_segments]
        stored = [list(seg.stored) for seg in shard_segments]
        return PackedIndex(n_shards=S, n_pad=n_pad, live=jnp.asarray(live),
                           doc_counts=jnp.asarray(counts), text=text,
                           ids=ids, stored=stored, vectors=vectors)

    def prepare_term_queries(self, field: str, queries: list[list[str]],
                             t_pad: int | None = None):
        """Host-side query prep: per-shard CSR starts/lens for each query's
        terms -> (term_starts i32[S,Q,T], term_lens i32[S,Q,T]).

        Per-shard lookups differ because each shard has its own term dict
        (exactly like per-shard Lucene term dictionaries); the device program
        psums df across shards for global IDF (the DFS phase, SURVEY §2.10.4).
        """
        S, Q = self.n_shards, len(queries)
        T = t_pad or max(1, max(len(q) for q in queries))
        fx = self.text[field]
        starts = np.zeros((S, Q, T), np.int32)
        lens = np.zeros((S, Q, T), np.int32)
        for si in range(S):
            tdict = fx.terms[si]
            ts, tl = fx.term_starts[si], fx.term_lens[si]
            for qi, q in enumerate(queries):
                for ti, term in enumerate(q[:T]):
                    tid = tdict.get(term, -1)
                    if tid >= 0:
                        starts[si, qi, ti] = ts[tid]
                        lens[si, qi, ti] = tl[tid]
        return jnp.asarray(starts), jnp.asarray(lens)

    def slot_budget(self, term_lens) -> int:
        """Static per-term slot budget Wt (shared rule: ops/bm25_sparse)."""
        return _slot_budget(term_lens)

    def fetch(self, global_key: int) -> tuple[str, dict]:
        """Resolve (shard << 32 | local) to (doc_id, source)."""
        shard = global_key >> 32
        local = global_key & 0xFFFFFFFF
        return self.ids[shard][local], self.stored[shard][local]
