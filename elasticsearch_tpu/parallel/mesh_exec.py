"""Mesh-sharded serving data plane: the whole multi-shard query phase as
ONE shard_map program over the ("replica", "shard") mesh.

PR 4 collapsed a shard's per-segment round-trips into one stacked program
and one fetch, but the coordinator still merged per-shard results in host
Python over the thread-pool fan-out — S device fetches and a host-side
sort per multi-shard query. This module packs the shards' segment stacks
one level up onto a `[S_pad, G_pad, N_pad, ...]` mesh stack sharded over
the `"shard"` axis (parallel/mesh.index_sharding), generalizes the
shard_map query step of parallel/distributed_search.py from BM25-only to
the stacked DSL executor of search/stacked.py, and fuses the cross-shard
reduce on device:

    per-shard stacked execution  (exactly search/stacked.py's math, per
                                  device block — bitwise-equal scores)
    per-shard stacked_reduce     (liveness gate, totals, row-max,
                                  per-segment top-k + in-shard merge)
    cross-shard reduce           (all_gather of shard-encoded candidates
                                  + one lax.top_k; psum totals; pmax max)

so a multi-shard unsorted query pays ZERO host-side per-shard merges and
ONE device fetch total. The `"replica"` axis carries query-batch
parallelism (queries shard over it; the index replicates over it — the
reference's R-copies-serve-reads model as a mesh axis). On hardware the
reduce rides ICI collectives; across pods XLA lowers to DCN (SURVEY §5.8:
collectives inside the host, RPC only between hosts).

Candidate order inside the merge is shard order, then in-shard merge
order — exactly the (primary, shard_idx, pos) tie order the host-side
controller.sort_docs produces, and `lax.top_k` keeps the earlier
candidate on equal scores, so results are bitwise-identical to the PR-4
concurrent fan-out.

Coverage: the typed stacked handlers (match/term/terms/range/exists/ids/
bool/constant_score/dis_max/boosting). Node types that would need the
per-segment generic fallback cannot run inside a collective program —
the plan declines and the coordinator falls back to the fan-out
(fallback ladder: mesh -> fan-out -> per-segment loop). Compiled
programs memoize on the plan signature (node structure + static scalars
+ pow2 work windows), so refresh->query cycles inside a bucket compile
nothing (tests/test_no_retrace.py).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass, field as dc_field

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..common.cache import Cache
from ..index.segment import Segment, next_pow2
from ..ops import bm25
from ..search.query_dsl import (
    BoolNode, BoostingNode, ConstantScoreNode, DisMaxNode, ExistsNode,
    IdsNode, MatchAllNode, MatchNoneNode, MatchNode, Node, RangeNode,
    SegmentContext, TermFilterNode, _bisect, _coerce_to_column, _next_down,
    _next_up, _pow2_window,
)
from .distributed_search import _shard_map
from .mesh import (REPLICA_AXIS, SHARD_AXIS, SHARED_EXEC_LOCK, index_sharding,
                   make_mesh)

SEG_SHIFT = 32

# operand placement kinds: leading-axis sharding of host-prepared arrays
_OP_S = "s"        # [S_pad, ...]            -> P("shard")
_OP_SQ = "sq"      # [S_pad, G, Q, ...]      -> P("shard", None, "replica")
_OP_Q = "q"        # [Q, ...]                -> P("replica")
_OP_R = "r"        # scalar                  -> P() (replicated)

_MESH_LOCK = threading.Lock()
_MESH_MEMO: dict[tuple[int, int], jax.sharding.Mesh] = {}

# ONE collective program in flight per device POOL: two concurrent
# shard_map executions on the SAME devices can interleave their
# collective rendezvous across devices and deadlock (observed with two
# cluster nodes' host reduces overlapping in one test process). Nodes
# that OWN a disjoint device subset (parallel/mesh.DevicePool, ISSUE 19)
# dispatch under their pool's private lock and run concurrently;
# EXEC_LOCK is the legacy lock of the SHARED pool (all of jax.devices())
# — the fallback when no ownership is configured. All dispatch sites go
# through exec_guard() below, which also counts acquisitions/waits per
# path (the bench's exec_lock_waits + the no-retrace tripwire).
EXEC_LOCK = SHARED_EXEC_LOCK

_EXEC_STATS_LOCK = threading.Lock()
_EXEC_STATS = {"shared_acquisitions": 0, "shared_waits": 0,
               "pool_acquisitions": 0, "pool_waits": 0}


@contextmanager
def exec_guard(pool=None):
    """Serialize device dispatch per pool. pool=None (or the shared
    pool) -> the legacy EXEC_LOCK; an owned DevicePool -> its private
    lock, uncontended across nodes by construction. A "wait" is counted
    only when the lock was not immediately available."""
    lock = EXEC_LOCK if pool is None else pool.lock
    shared = lock is EXEC_LOCK
    if not lock.acquire(blocking=False):
        with _EXEC_STATS_LOCK:
            _EXEC_STATS["shared_waits" if shared else "pool_waits"] += 1
        lock.acquire()
    with _EXEC_STATS_LOCK:
        _EXEC_STATS["shared_acquisitions" if shared
                    else "pool_acquisitions"] += 1
    try:
        yield
    finally:
        lock.release()


def exec_lock_stats() -> dict:
    with _EXEC_STATS_LOCK:
        return dict(_EXEC_STATS)


def reset_exec_lock_stats() -> None:
    with _EXEC_STATS_LOCK:
        for k in _EXEC_STATS:
            _EXEC_STATS[k] = 0


def _mesh_devkey(mesh) -> tuple:
    """Device-identity component of compiled-program cache keys: two
    nodes with different device subsets must never share a program."""
    return tuple(int(d.id) for d in mesh.devices.flat)

# compiled shard_map programs keyed by plan signature — the jit analog of
# DistributedSearcher's step memo, bounded on the common Cache core
_PROGRAMS = Cache("mesh_programs", max_entries=256)

# score-materialization mode of the LAST mesh execution: "blockwise"
# (search/blockwise.py scan inside the shard_map body — peak score memory
# O(Q × block) per device) | "materialized" (full [G, Q, N] tensors).
# Coordinator counters and tests read it after execute().
last_block_mode: str | None = None


def mesh_for(n_shards: int, pool=None):
    """(mesh, s_pad, n_replicas) for an S-shard index, or None when this
    pool lacks the devices (fewer than S_pad): the caller falls back to
    the thread-pool fan-out — the cross-host/undersized topology path.
    pool=None means the legacy shared pool over all of jax.devices();
    an owned DevicePool restricts the mesh to that node's device subset."""
    if n_shards < 1:
        return None
    s_pad = next_pow2(n_shards, floor=1)
    devs = pool.devices if pool is not None else jax.devices()
    n_dev = len(devs)
    if n_dev < s_pad:
        return None
    r = max(n_dev // s_pad, 1)
    if pool is not None:
        got = pool.mesh_for(s_pad, n_replicas=r)
        if got is None:
            return None
        return got
    with _MESH_LOCK:
        mesh = _MESH_MEMO.get((r, s_pad))
        if mesh is None:
            mesh = make_mesh(n_shards=s_pad, n_replicas=r)
            _MESH_MEMO[(r, s_pad)] = mesh
    return mesh, s_pad, r


# ---------------------------------------------------------------------------
# The mesh stack: S shards' live segments as [S_pad, G_pad, ...] tensors
# ---------------------------------------------------------------------------

@dataclass
class MeshTextField:
    doc_ids: jax.Array               # i32[S_pad, G_pad, P_pad]
    tf: jax.Array                    # f32[S_pad, G_pad, P_pad]
    doc_len: jax.Array               # f32[S_pad, G_pad, N_pad]
    max_postings: int = 0


@dataclass
class MeshKeywordField:
    ords: jax.Array                  # i32[S_pad, G_pad, N_pad]


@dataclass
class MeshNumericField:
    vals: jax.Array                  # [S_pad, G_pad, N_pad] i64 | f64
    missing: jax.Array               # bool[S_pad, G_pad, N_pad]
    dtype: str


@dataclass
class MeshStack:
    """Immutable packed view of an index's shards on the device mesh.

    `shard_rows[s]` lists (original segment index, Segment) per stack row
    of shard s — the reduce encodes THAT index into doc keys, so the
    coordinator's fetch phase resolves keys against the shard's full
    segment list unchanged. Liveness is re-assembled (not rebuilt) when
    any segment's tombstone generation moves, exactly like SegmentStack."""
    shard_rows: tuple                # per shard: tuple[(orig_idx, Segment)]
    s_count: int
    s_pad: int
    g_pad: int
    n_pad: int
    mesh: jax.sharding.Mesh = None
    n_replicas: int = 1
    text: dict = dc_field(default_factory=dict)
    keywords: dict = dc_field(default_factory=dict)
    numerics: dict = dc_field(default_factory=dict)
    mixed: frozenset = frozenset()
    nbytes: int = 0
    seg_ids_dev: jax.Array | None = None     # i64[S_pad, G_pad]
    pool: object = None                      # owning DevicePool (None=shared)

    def __post_init__(self):
        self._live_key = None
        self._live_dev = None

    def live_stack(self) -> jax.Array:
        """bool[S_pad, G_pad, N_pad] root-doc liveness, padding all-False;
        cached on every segment's tombstone generation."""
        key = tuple(seg.live_gen for rows in self.shard_rows
                    for _i, seg in rows)
        if self._live_key != key or self._live_dev is None:
            arr = np.zeros((self.s_pad, self.g_pad, self.n_pad), bool)
            for si, rows in enumerate(self.shard_rows):
                for gi, (_i, seg) in enumerate(rows):
                    arr[si, gi, : seg.n_pad] = np.asarray(seg.root_live_host)
            self._live_dev = jax.device_put(arr, index_sharding(self.mesh))
            self._live_key = key
        return self._live_dev


def _mesh_field_kinds(segments):
    text, kw, num = set(), set(), set()
    for seg in segments:
        text.update(seg.text)
        kw.update(seg.keywords)
        num.update(seg.numerics)
    mixed = (text & kw) | (text & num) | (kw & num)
    return text, kw, num, mixed


def estimate_mesh_stack_bytes(per_shard_segments) -> int:
    """Device bytes a mesh stack over these shards will occupy — the
    pre-build fielddata-breaker charge. Mirrors build_mesh_stack()'s
    allocation arithmetic exactly (the SegmentStack convention)."""
    live_rows = [[s for s in segs if s.n_docs > 0]
                 for segs in per_shard_segments]
    all_live = [s for rows in live_rows for s in rows]
    if not all_live:
        return 0
    s_pad = next_pow2(len(per_shard_segments), floor=1)
    g_pad = next_pow2(max(len(r) for r in live_rows), floor=1)
    n_pad = max(s.n_pad for s in all_live)
    text, kw, num, _ = _mesh_field_kinds(all_live)
    total = s_pad * g_pad * n_pad + s_pad * g_pad * 8  # live mask + seg ids
    for f in text:
        p_pad = next_pow2(max((s.text[f].n_postings for s in all_live
                               if f in s.text), default=1), floor=8)
        total += s_pad * g_pad * (p_pad * 8 + n_pad * 4)
    total += len(kw) * s_pad * g_pad * n_pad * 4
    total += len(num) * s_pad * g_pad * n_pad * 9
    return total


def build_mesh_stack(per_shard_segments, mesh, s_pad: int,
                     n_replicas: int, pool=None) -> MeshStack | None:
    """Pack every shard's live segments into mesh-sharded tensors. The
    per-shard slice mirrors search/stacked.build_stack — same fills, same
    sentinels — so per-shard scores computed over a mesh block are
    bitwise-equal to the shard's own SegmentStack execution."""
    from ..common import tracing
    with tracing.span("mesh_stack_build",
                      shards=len(per_shard_segments)) as sp:
        out = _build_mesh_stack(per_shard_segments, mesh, s_pad, n_replicas)
        if sp is not None and out is not None:
            sp.attrs["bytes"] = out.nbytes
    if out is not None:
        out.pool = pool
    return out


def _build_mesh_stack(per_shard_segments, mesh, s_pad, n_replicas):
    shard_rows = tuple(
        tuple((i, s) for i, s in enumerate(segs) if s.n_docs > 0)
        for segs in per_shard_segments)
    all_live = [seg for rows in shard_rows for _i, seg in rows]
    if not all_live:
        return None
    g_pad = next_pow2(max(len(r) for r in shard_rows), floor=1)
    n_pad = max(s.n_pad for s in all_live)
    text_f, kw_f, num_f, mixed = _mesh_field_kinds(all_live)
    sharding = index_sharding(mesh)
    nbytes = s_pad * g_pad * n_pad + s_pad * g_pad * 8

    text: dict[str, MeshTextField] = {}
    for f in sorted(text_f):
        p_max = max((s.text[f].n_postings for s in all_live if f in s.text),
                    default=1)
        p_pad = next_pow2(p_max, floor=8)
        doc_ids = np.full((s_pad, g_pad, p_pad), n_pad, np.int32)
        tf = np.zeros((s_pad, g_pad, p_pad), np.float32)
        doc_len = np.ones((s_pad, g_pad, n_pad), np.float32)
        for si, rows in enumerate(shard_rows):
            for gi, (_i, seg) in enumerate(rows):
                fx = seg.text.get(f)
                if fx is None:
                    continue
                Pn = fx.n_postings
                if Pn:
                    src = fx.doc_ids_host if fx.doc_ids_host is not None \
                        else np.asarray(fx.doc_ids)[:Pn]
                    doc_ids[si, gi, :Pn] = src[:Pn]
                    tf[si, gi, :Pn] = np.asarray(fx.tf)[:Pn]
                doc_len[si, gi, : fx.doc_len.shape[0]] = \
                    np.asarray(fx.doc_len)
        text[f] = MeshTextField(
            doc_ids=jax.device_put(doc_ids, sharding),
            tf=jax.device_put(tf, sharding),
            doc_len=jax.device_put(doc_len, sharding),
            max_postings=p_max)
        nbytes += s_pad * g_pad * (p_pad * 8 + n_pad * 4)

    keywords: dict[str, MeshKeywordField] = {}
    for f in sorted(kw_f):
        ords = np.full((s_pad, g_pad, n_pad), -1, np.int32)
        for si, rows in enumerate(shard_rows):
            for gi, (_i, seg) in enumerate(rows):
                kc = seg.keywords.get(f)
                if kc is not None:
                    o = np.asarray(kc.ords)
                    ords[si, gi, : o.shape[0]] = o
        keywords[f] = MeshKeywordField(ords=jax.device_put(ords, sharding))
        nbytes += s_pad * g_pad * n_pad * 4

    numerics: dict[str, MeshNumericField] = {}
    for f in sorted(num_f):
        dtypes = {s.numerics[f].dtype for s in all_live if f in s.numerics}
        if len(dtypes) > 1:
            mixed = mixed | {f}          # cross-shard dtype conflict
            nbytes += s_pad * g_pad * n_pad * 9
            continue
        dt = dtypes.pop()
        vals = np.zeros((s_pad, g_pad, n_pad),
                        np.int64 if dt == "i64" else np.float64)
        missing = np.ones((s_pad, g_pad, n_pad), bool)
        for si, rows in enumerate(shard_rows):
            for gi, (_i, seg) in enumerate(rows):
                nc = seg.numerics.get(f)
                if nc is not None:
                    v = np.asarray(nc.vals)
                    vals[si, gi, : v.shape[0]] = v
                    missing[si, gi, : v.shape[0]] = np.asarray(nc.missing)
        numerics[f] = MeshNumericField(
            vals=jax.device_put(vals, sharding),
            missing=jax.device_put(missing, sharding), dtype=dt)
        nbytes += s_pad * g_pad * n_pad * 9

    seg_ids = np.zeros((s_pad, g_pad), np.int64)
    for si, rows in enumerate(shard_rows):
        for gi, (orig, _seg) in enumerate(rows):
            seg_ids[si, gi] = orig
    return MeshStack(
        shard_rows=shard_rows, s_count=len(per_shard_segments),
        s_pad=s_pad, g_pad=g_pad, n_pad=n_pad, mesh=mesh,
        n_replicas=n_replicas, text=text, keywords=keywords,
        numerics=numerics, mixed=frozenset(mixed), nbytes=nbytes,
        seg_ids_dev=jax.device_put(seg_ids, index_sharding(mesh)))


# ---------------------------------------------------------------------------
# Plan: host prep emits sharded operands; device closures mirror
# search/stacked.py's handlers over one shard's block
# ---------------------------------------------------------------------------

class _Unsupported(Exception):
    """Node/field shape the collective program cannot serve — the caller
    falls back to the concurrent fan-out (which can)."""


class _PlanCtx:
    def __init__(self, stack: MeshStack, n_queries: int, stats):
        self.stack = stack
        self.Q = n_queries
        self.stats = stats
        self.ops: list[tuple[np.ndarray, str]] = []
        self.fields: dict[str, str] = {}     # field -> kind, first-use order

    def emit(self, arr, kind: str) -> None:
        self.ops.append((np.asarray(arr), kind))

    def use_field(self, name: str, kind: str) -> None:
        self.fields.setdefault(name, kind)


class _DevCtx:
    """Per-device view inside the shard_map: one shard's blocks."""

    def __init__(self, fields: dict, ops: list, g_pad: int, n_pad: int,
                 n_queries: int):
        self.fields = fields
        self._ops = iter(ops)
        self.g_pad = g_pad
        self.n_pad = n_pad
        self.Q = n_queries

    def pop(self):
        return next(self._ops)

    def zeros(self):
        return jnp.zeros((self.g_pad, self.Q, self.n_pad), jnp.float32)

    def false(self):
        return jnp.zeros((self.g_pad, self.Q, self.n_pad), bool)

    def true(self):
        return jnp.ones((self.g_pad, self.Q, self.n_pad), bool)


def _match_host(node: MatchNode, pctx: _PlanCtx):
    """[S,G,Q,T] CSR pointers per (shard, segment) + the shared
    (stats-derived, segment-independent) idf weights — the mesh analog of
    stacked._match_host."""
    stack, Q = pctx.stack, pctx.Q
    T = max((len(t) for t in node.terms_per_query), default=1) or 1
    starts = np.zeros((stack.s_pad, stack.g_pad, Q, T), np.int32)
    lens = np.zeros((stack.s_pad, stack.g_pad, Q, T), np.int32)
    weights = np.zeros((Q, T), np.float32)
    n_terms = np.zeros((Q,), np.int32)
    for si, rows in enumerate(stack.shard_rows):
        for gi, (_i, seg) in enumerate(rows):
            s_, l_, w_, n_ = node._host_arrays(
                SegmentContext(seg, Q, pctx.stats))
            starts[si, gi], lens[si, gi] = s_, l_
            weights, n_terms = w_, n_
    return starts, lens, weights, n_terms


def _p_match(node: MatchNode, pctx: _PlanCtx):
    f = node.field_name
    if node.sim in ("lm_dirichlet", "lm_jm"):
        # LM similarities fall down the ladder to the fan-out/loop lanes
        raise _Unsupported(f"lm similarity [{node.sim}]")
    if f not in pctx.stack.text:
        return (("match_absent",), lambda d: (d.zeros(), d.false()))
    pctx.use_field(f, "text")
    starts, lens, weights, n_terms = _match_host(node, pctx)
    W = _pow2_window(lens)
    pctx.emit(starts, _OP_SQ)
    pctx.emit(lens, _OP_SQ)
    pctx.emit(weights, _OP_Q)
    sim, k1, b = node.sim, float(node.k1), float(node.b)
    msm_mode = node.operator == "and" or node.minimum_should_match > 1
    if msm_mode:
        need = n_terms if node.operator == "and" else np.broadcast_to(
            np.float32(max(node.minimum_should_match, 1)), (pctx.Q,))
        pctx.emit(np.asarray(need, np.float32), _OP_Q)
    if sim != "classic":
        pctx.emit(np.float32(pctx.stats.avgdl(f)), _OP_R)
    sig = ("match", f, sim, msm_mode, k1, b, W)

    def dev(d: _DevCtx):
        sf = d.fields[f]
        st, ln, w = d.pop(), d.pop(), d.pop()
        need_b = d.pop() if msm_mode else None
        if sim == "classic":
            def one(di, tfv, dl, st_, ln_):
                return bm25.classic_score_batch(
                    di, tfv, dl, st_, ln_, w, W=W, n_pad=d.n_pad)
            scores = jax.vmap(one)(sf.doc_ids, sf.tf, sf.doc_len, st, ln)
        else:
            avgdl = d.pop()
            def one(di, tfv, dl, st_, ln_):
                return bm25.bm25_score_batch(
                    di, tfv, dl, st_, ln_, w, jnp.float32(k1),
                    jnp.float32(b), avgdl.astype(jnp.float32),
                    W=W, n_pad=d.n_pad)
            scores = jax.vmap(one)(sf.doc_ids, sf.tf, sf.doc_len, st, ln)
        if msm_mode:
            ones_w = jnp.ones_like(w)
            def cnt(di, tfv, dl, st_, ln_):
                return bm25.bm25_score_batch(
                    di, jnp.ones_like(tfv), jnp.full_like(dl, 1.0),
                    st_, ln_, ones_w, jnp.float32(0.0), jnp.float32(0.0),
                    jnp.float32(1.0), W=W, n_pad=d.n_pad)
            counts = jax.vmap(cnt)(sf.doc_ids, sf.tf, sf.doc_len, st, ln)
            match = counts >= jnp.maximum(need_b.astype(jnp.float32),
                                          1.0)[None, :, None]
        else:
            match = scores > 0
        return jnp.where(match, scores, 0.0), match

    return sig, dev


def _pm_match(node: MatchNode, pctx: _PlanCtx):
    """Presence-only filter mask (the term_match_mask fast path)."""
    if node.operator == "and" or node.minimum_should_match > 1:
        sig, dev = _p_match(node, pctx)
        return (("m", sig)), (lambda d: dev(d)[1])
    f = node.field_name
    if f not in pctx.stack.text:
        return (("m_match_absent",), lambda d: d.false())
    pctx.use_field(f, "text")
    starts, lens, _, _ = _match_host(node, pctx)
    W = _pow2_window(lens)
    pctx.emit(starts, _OP_SQ)
    pctx.emit(lens, _OP_SQ)
    sig = ("m_match", f, W)

    def dev(d: _DevCtx):
        sf = d.fields[f]
        st, ln = d.pop(), d.pop()
        def one(di, st_, ln_):
            return bm25.term_match_mask(di, st_, ln_, W=W, n_pad=d.n_pad)
        return jax.vmap(one)(sf.doc_ids, st, ln)

    return sig, dev


def _p_term(node: TermFilterNode, pctx: _PlanCtx):
    stack, Q = pctx.stack, pctx.Q
    f = node.field_name
    if f in stack.mixed:
        raise _Unsupported(f"mixed field [{f}]")
    boost = float(node.boost)
    V = max((len(v) for v in node.values_per_query), default=1) or 1
    if f in stack.keywords:
        pctx.use_field(f, "keyword")
        targets = np.full((stack.s_pad, stack.g_pad, Q, V), -2, np.int64)
        for si, rows in enumerate(stack.shard_rows):
            for gi, (_i, seg) in enumerate(rows):
                kc = seg.keywords.get(f)
                if kc is None:
                    continue
                for qi, vals in enumerate(node.values_per_query):
                    for vi, v in enumerate(vals):
                        o = kc.ord_of(str(v))
                        if o >= 0:
                            targets[si, gi, qi, vi] = o
        pctx.emit(targets, _OP_SQ)

        def dev(d: _DevCtx):
            col = d.fields[f].ords.astype(jnp.int64)
            tg = d.pop()
            match = (col[:, None, :, None]
                     == tg[:, :, None, :]).any(axis=3)
            return jnp.where(match, jnp.float32(boost), 0.0), match
        return ("term_kw", f, boost), dev

    if f in stack.numerics:
        nf = stack.numerics[f]
        pctx.use_field(f, "numeric")
        if nf.dtype == "f64":
            tf64 = np.full((Q, V), np.nan)
            for qi, vals in enumerate(node.values_per_query):
                for vi, v in enumerate(vals):
                    tf64[qi, vi] = float(v)
            pctx.emit(tf64, _OP_Q)

            def dev(d: _DevCtx):
                num = d.fields[f]
                tq = d.pop()
                match = (num.vals[:, None, :, None]
                         == tq[None, :, None, :]).any(axis=3)
                match = match & ~num.missing[:, None, :]
                return jnp.where(match, boost, 0.0), match
            return ("term_f64", f, boost), dev
        targets = np.full((Q, V), np.iinfo(np.int64).min, np.int64)
        for qi, vals in enumerate(node.values_per_query):
            for vi, v in enumerate(vals):
                targets[qi, vi] = _coerce_to_column(v, nf)
        pctx.emit(targets, _OP_Q)

        def dev(d: _DevCtx):
            num = d.fields[f]
            tq = d.pop()
            match = (num.vals[:, None, :, None]
                     == tq[None, :, None, :]).any(axis=3)
            match = match & ~num.missing[:, None, :]
            return jnp.where(match, jnp.float32(boost), 0.0), match
        return ("term_i64", f, boost), dev

    if f in stack.text:
        sub = MatchNode(boost=node.boost, field_name=f,
                        terms_per_query=[[str(v) for v in vals]
                                         for vals in node.values_per_query])
        sig, dev = _p_match(sub, pctx)
        return ("term_text", sig), dev
    return (("term_absent",), lambda d: (d.zeros(), d.false()))


def _p_range(node: RangeNode, pctx: _PlanCtx):
    stack, Q = pctx.stack, pctx.Q
    f = node.field_name
    if f in stack.mixed:
        raise _Unsupported(f"mixed field [{f}]")
    boost = float(node.boost)
    if f in stack.numerics:
        nf = stack.numerics[f]
        pctx.use_field(f, "numeric")
        if nf.dtype == "i64":
            lo_fill, hi_fill = np.iinfo(np.int64).min, np.iinfo(np.int64).max
            dt = np.int64
        else:
            lo_fill, hi_fill = -np.inf, np.inf
            dt = np.float64
        los = np.full(Q, lo_fill, dt)
        his = np.full(Q, hi_fill, dt)
        for qi, (lo, hi, inc_lo, inc_hi) in enumerate(node.bounds_per_query):
            if lo is not None:
                los[qi] = lo if inc_lo else _next_up(lo, dt)
            if hi is not None:
                his[qi] = hi if inc_hi else _next_down(hi, dt)
        pctx.emit(los, _OP_Q)
        pctx.emit(his, _OP_Q)

        def dev(d: _DevCtx):
            num = d.fields[f]
            lo_b, hi_b = d.pop(), d.pop()
            match = (num.vals[:, None, :] >= lo_b[None, :, None]) \
                & (num.vals[:, None, :] <= hi_b[None, :, None]) \
                & ~num.missing[:, None, :]
            return jnp.where(match, jnp.float32(boost), 0.0), match
        return ("range_num", f, nf.dtype, boost), dev

    if f in stack.keywords:
        pctx.use_field(f, "keyword")
        los = np.zeros((stack.s_pad, stack.g_pad, Q), np.int32)
        his = np.full((stack.s_pad, stack.g_pad, Q), -1, np.int32)
        for si, rows in enumerate(stack.shard_rows):
            for gi, (_i, seg) in enumerate(rows):
                kc = seg.keywords.get(f)
                if kc is None:
                    continue
                his[si, gi, :] = len(kc.values) - 1
                for qi, (lo, hi, inc_lo, inc_hi) \
                        in enumerate(node.bounds_per_query):
                    if lo is not None:
                        i = _bisect(kc.values, str(lo), left=True)
                        if not inc_lo and i < len(kc.values) \
                                and kc.values[i] == str(lo):
                            i += 1
                        los[si, gi, qi] = i
                    if hi is not None:
                        i = _bisect(kc.values, str(hi), left=False) - 1
                        if not inc_hi and i >= 0 and kc.values[i] == str(hi):
                            i -= 1
                        his[si, gi, qi] = i
        pctx.emit(los, _OP_SQ)
        pctx.emit(his, _OP_SQ)

        def dev(d: _DevCtx):
            ords = d.fields[f].ords
            lo_b, hi_b = d.pop(), d.pop()
            match = (ords[:, None, :] >= lo_b[:, :, None]) \
                & (ords[:, None, :] <= hi_b[:, :, None]) \
                & (ords[:, None, :] >= 0)
            return jnp.where(match, jnp.float32(boost), 0.0), match
        return ("range_kw", f, boost), dev
    return (("range_absent",), lambda d: (d.zeros(), d.false()))


def _p_exists(node: ExistsNode, pctx: _PlanCtx):
    stack = pctx.stack
    f = node.field_name
    if f in stack.mixed:
        raise _Unsupported(f"mixed field [{f}]")
    boost = float(node.boost)
    if f in stack.numerics:
        pctx.use_field(f, "numeric")

        def dev(d: _DevCtx):
            num = d.fields[f]
            match = jnp.broadcast_to(~num.missing[:, None, :],
                                     (d.g_pad, d.Q, d.n_pad))
            return jnp.where(match, jnp.float32(boost), 0.0), match
        return ("exists_num", f, boost), dev
    if f in stack.keywords:
        pctx.use_field(f, "keyword")

        def dev(d: _DevCtx):
            kw = d.fields[f]
            match = jnp.broadcast_to((kw.ords >= 0)[:, None, :],
                                     (d.g_pad, d.Q, d.n_pad))
            return jnp.where(match, jnp.float32(boost), 0.0), match
        return ("exists_kw", f, boost), dev
    if f in stack.text:
        pctx.use_field(f, "text")
        starts = np.zeros((stack.s_pad, stack.g_pad, 1, 1), np.int32)
        lens = np.zeros((stack.s_pad, stack.g_pad, 1, 1), np.int32)
        for si, rows in enumerate(stack.shard_rows):
            for gi, (_i, seg) in enumerate(rows):
                fx = seg.text.get(f)
                if fx is not None:
                    lens[si, gi, 0, 0] = fx.n_postings
        W = max(8, 1 << (max(int(lens.max()), 1) - 1).bit_length())
        pctx.emit(starts, _OP_S)
        pctx.emit(lens, _OP_S)

        def dev(d: _DevCtx):
            sf = d.fields[f]
            st, ln = d.pop(), d.pop()
            def one(di, st_, ln_):
                return bm25.term_match_mask(di, st_, ln_, W=W, n_pad=d.n_pad)
            hits = jax.vmap(one)(sf.doc_ids, st, ln)
            match = jnp.broadcast_to(hits, (d.g_pad, d.Q, d.n_pad))
            return jnp.where(match, jnp.float32(boost), 0.0), match
        return ("exists_text", f, boost, W), dev
    return (("exists_absent",), lambda d: (d.zeros(), d.false()))


def _p_ids(node: IdsNode, pctx: _PlanCtx):
    stack, Q = pctx.stack, pctx.Q
    boost = float(node.boost)
    mask = np.zeros((stack.s_pad, stack.g_pad, Q, stack.n_pad), bool)
    for si, rows in enumerate(stack.shard_rows):
        for gi, (_i, seg) in enumerate(rows):
            for qi, ids in enumerate(node.ids_per_query):
                for i in ids:
                    local = seg.id_to_local.get(i)
                    if local is not None:
                        mask[si, gi, qi, local] = True
    pctx.emit(mask, _OP_SQ)

    def dev(d: _DevCtx):
        match = d.pop()
        return jnp.where(match, jnp.float32(boost), 0.0), match
    return ("ids", boost), dev


def _p_match_all(node: MatchAllNode, pctx: _PlanCtx):
    boost = float(node.boost)
    return ("match_all", boost), (lambda d: (
        jnp.full((d.g_pad, d.Q, d.n_pad), boost, jnp.float32), d.true()))


def _p_match_none(node: MatchNoneNode, pctx: _PlanCtx):
    return ("match_none",), (lambda d: (d.zeros(), d.false()))


# -- structural -------------------------------------------------------------

def _p_bool(node: BoolNode, pctx: _PlanCtx):
    boost = float(node.boost)
    any_positive = bool(node.must or node.filter)
    musts = [_plan_exec(n, pctx) for n in node.must]
    filters = [_plan_exec(n, pctx) for n in node.filter]
    msm = node.minimum_should_match
    if node.should and msm is None:
        msm = 0 if any_positive else 1
    shoulds = [_plan_exec(n, pctx) for n in node.should]
    must_nots = [_plan_exec(n, pctx) for n in node.must_not]
    sig = ("bool", boost, msm, tuple(s for s, _ in musts),
           tuple(s for s, _ in filters), tuple(s for s, _ in shoulds),
           tuple(s for s, _ in must_nots))

    def dev(d: _DevCtx):
        scores = d.zeros()
        match = d.true()
        for _s, fn in musts:
            s, m = fn(d)
            scores = scores + s
            match = match & m
        for _s, fn in filters:
            _, m = fn(d)
            match = match & m
        if shoulds:
            should_count = jnp.zeros((d.g_pad, d.Q, d.n_pad), jnp.int32)
            for _s, fn in shoulds:
                s, m = fn(d)
                scores = scores + jnp.where(m, s, 0.0)
                should_count = should_count + m.astype(jnp.int32)
            if msm > 0:
                match = match & (should_count >= msm)
        for _s, fn in must_nots:
            _, m = fn(d)
            match = match & ~m
        return jnp.where(match, scores * boost, 0.0), match

    return sig, dev


def _pm_bool(node: BoolNode, pctx: _PlanCtx):
    pos = [_plan_match(n, pctx) for n in node.must + node.filter]
    msm = node.minimum_should_match
    if node.should and msm is None:
        msm = 0 if (node.must or node.filter) else 1
    # mirror stacked._m_bool: msm==0 shoulds don't gate the mask and are
    # never evaluated in match context
    shoulds = [_plan_match(n, pctx) for n in node.should] \
        if node.should and msm is not None and msm >= 1 else []
    must_nots = [_plan_match(n, pctx) for n in node.must_not]
    sig = ("m_bool", msm, tuple(s for s, _ in pos),
           tuple(s for s, _ in shoulds), tuple(s for s, _ in must_nots))

    def dev(d: _DevCtx):
        match = d.true()
        for _s, fn in pos:
            match = match & fn(d)
        if shoulds:
            if msm == 1:
                any_should = d.false()
                for _s, fn in shoulds:
                    any_should = any_should | fn(d)
                match = match & any_should
            else:
                cnt = jnp.zeros((d.g_pad, d.Q, d.n_pad), jnp.int32)
                for _s, fn in shoulds:
                    cnt = cnt + fn(d).astype(jnp.int32)
                match = match & (cnt >= msm)
        for _s, fn in must_nots:
            match = match & ~fn(d)
        return match

    return sig, dev


def _p_const(node: ConstantScoreNode, pctx: _PlanCtx):
    boost = float(node.boost)
    sig, fn = _plan_match(node.inner, pctx)

    def dev(d: _DevCtx):
        m = fn(d)
        return jnp.where(m, jnp.float32(boost), 0.0), m
    return ("const", boost, sig), dev


def _pm_const(node: ConstantScoreNode, pctx: _PlanCtx):
    sig, fn = _plan_match(node.inner, pctx)
    return ("m_const", sig), fn


def _p_dis_max(node: DisMaxNode, pctx: _PlanCtx):
    boost = float(node.boost)
    tie = float(node.tie_breaker)
    subs = [_plan_exec(n, pctx) for n in node.queries]
    sig = ("dis_max", boost, tie, tuple(s for s, _ in subs))

    def dev(d: _DevCtx):
        best = d.zeros()
        total = d.zeros()
        match = d.false()
        for _s, fn in subs:
            s, m = fn(d)
            s = jnp.where(m, s, 0.0)
            best = jnp.maximum(best, s)
            total = total + s
            match = match | m
        scores = best + tie * (total - best)
        return jnp.where(match, scores * boost, 0.0), match
    return sig, dev


def _p_boosting(node: BoostingNode, pctx: _PlanCtx):
    boost = float(node.boost)
    nb = float(node.negative_boost)
    psig, pfn = _plan_exec(node.positive, pctx)
    nsig, nfn = _plan_exec(node.negative, pctx)
    sig = ("boosting", boost, nb, psig, nsig)

    def dev(d: _DevCtx):
        s, m = pfn(d)
        _, nm = nfn(d)
        s = jnp.where(nm, s * nb, s)
        return jnp.where(m, s * boost, 0.0), m
    return sig, dev


_P_EXEC = {
    MatchAllNode: _p_match_all,
    MatchNoneNode: _p_match_none,
    MatchNode: _p_match,
    TermFilterNode: _p_term,
    RangeNode: _p_range,
    ExistsNode: _p_exists,
    IdsNode: _p_ids,
    BoolNode: _p_bool,
    ConstantScoreNode: _p_const,
    DisMaxNode: _p_dis_max,
    BoostingNode: _p_boosting,
}

_P_MATCH = {
    MatchNode: _pm_match,
    BoolNode: _pm_bool,
    ConstantScoreNode: _pm_const,
}


def _plan_exec(node: Node, pctx: _PlanCtx):
    h = _P_EXEC.get(type(node))
    if h is None:
        raise _Unsupported(type(node).__name__)
    return h(node, pctx)


def _plan_match(node: Node, pctx: _PlanCtx):
    h = _P_MATCH.get(type(node))
    if h is None:
        sig, fn = _plan_exec(node, pctx)
        return ("xm", sig), (lambda d: fn(d)[1])
    return h(node, pctx)


def plan_types_supported(node: Node) -> bool:
    """Cheap pre-flight: every node in the tree has a typed mesh handler
    (field-shape checks happen at plan time). False -> fan-out."""
    t = type(node)
    if t in (BoolNode,):
        return all(plan_types_supported(n) for n in
                   node.must + node.filter + node.should + node.must_not)
    if t is ConstantScoreNode:
        return plan_types_supported(node.inner)
    if t is DisMaxNode:
        return all(plan_types_supported(n) for n in node.queries)
    if t is BoostingNode:
        return plan_types_supported(node.positive) \
            and plan_types_supported(node.negative)
    return t in _P_EXEC


# ---------------------------------------------------------------------------
# Program assembly: jit(shard_map(per-shard exec + fused collective reduce))
# ---------------------------------------------------------------------------

_FIELD_TENSORS = {"text": 3, "keyword": 1, "numeric": 2}


def _build_program(mesh, devfn, field_kinds: tuple, op_kinds: tuple,
                   k: int, n_queries: int, agg_devfns: tuple = ()):
    def step(live, seg_ids, *flat):
        live = live[0]                        # [G, N]
        seg_ids = seg_ids[0]                  # [G]
        fields = {}
        i = 0
        for name, kind in field_kinds:
            if kind == "text":
                fields[name] = MeshTextField(
                    doc_ids=flat[i][0], tf=flat[i + 1][0],
                    doc_len=flat[i + 2][0])
                i += 3
            elif kind == "keyword":
                fields[name] = MeshKeywordField(ords=flat[i][0])
                i += 1
            else:
                fields[name] = MeshNumericField(
                    vals=flat[i][0], missing=flat[i + 1][0], dtype="")
                i += 2
        ops = []
        for kind in op_kinds:
            blk = flat[i]
            i += 1
            ops.append(blk[0] if kind in (_OP_S, _OP_SQ) else blk)
        d = _DevCtx(fields, ops, live.shape[0], live.shape[1], n_queries)
        scores, match = devfn(d)

        # per-shard stacked reduce — stacked.stacked_reduce's math verbatim
        m = match & live[:, None, :]
        total = jnp.sum(m, axis=(0, 2), dtype=jnp.int64)          # [Qb]
        masked = jnp.where(m, scores, -jnp.inf)
        mx = masked.max(axis=(0, 2))                              # [Qb]
        kk = min(k, masked.shape[2])
        top, idx = lax.top_k(masked, kk)                          # [G,Qb,kk]
        keys = jnp.where(top > -jnp.inf,
                         (seg_ids[:, None, None] << SEG_SHIFT)
                         | idx.astype(jnp.int64),
                         jnp.int64(-1))
        Qb = masked.shape[1]
        cand_s = jnp.moveaxis(top, 0, 1).reshape(Qb, -1)
        cand_k = jnp.moveaxis(keys, 0, 1).reshape(Qb, -1)
        ks = min(k, cand_s.shape[1])
        shard_s, pos = lax.top_k(cand_s, ks)                      # [Qb, ks]
        shard_k = jnp.take_along_axis(cand_k, pos, axis=1)

        # cross-shard reduce: candidate blocks gather in shard order, so
        # stable top_k reproduces the host merge's (score, shard, pos)
        # tie order exactly (controller.sort_docs)
        g_s = lax.all_gather(shard_s, SHARD_AXIS)                 # [S,Qb,ks]
        g_k = lax.all_gather(shard_k, SHARD_AXIS)
        S = g_s.shape[0]
        g_s = jnp.transpose(g_s, (1, 0, 2)).reshape(Qb, S * ks)
        g_k = jnp.transpose(g_k, (1, 0, 2)).reshape(Qb, S * ks)
        out_s, pos2 = lax.top_k(g_s, min(k, S * ks))
        out_k = jnp.take_along_axis(g_k, pos2, axis=1)
        valid = out_s > -jnp.inf
        out_shard = jnp.where(valid, (pos2 // ks).astype(jnp.int32),
                              jnp.int32(-1))
        out_k = jnp.where(valid, out_k, jnp.int64(-1))
        # totals/max stay PER SHARD in the output (all_gather, not psum):
        # exact-int totals sum to the same value anywhere, and the cluster
        # host reduce decomposes the merged list back into per-shard wire
        # results — which need each shard's own total/max
        total_g = lax.all_gather(total, SHARD_AXIS)       # [S, Qb]
        mx_g = lax.all_gather(mx, SHARD_AXIS)             # [S, Qb]
        # agg partials ride the SAME program + fetch: counts reduce as
        # exact integers; f64 metric rows merge host-side in segment
        # order (parallel/mesh_aggs.py)
        agg_outs = tuple(lax.all_gather(fn(d, m), SHARD_AXIS)
                         for fn in agg_devfns)
        return (out_k, out_shard, out_s, total_g, mx_g) + agg_outs

    field_specs = []
    for _name, kind in field_kinds:
        field_specs.extend([P(SHARD_AXIS)] * _FIELD_TENSORS[kind])
    op_specs = []
    for kind in op_kinds:
        if kind == _OP_S:
            op_specs.append(P(SHARD_AXIS))
        elif kind == _OP_SQ:
            op_specs.append(P(SHARD_AXIS, None, REPLICA_AXIS))
        elif kind == _OP_Q:
            op_specs.append(P(REPLICA_AXIS))
        else:
            op_specs.append(P())
    in_specs = tuple([P(SHARD_AXIS), P(SHARD_AXIS)]
                     + field_specs + op_specs)
    out_specs = (P(REPLICA_AXIS),) * 3 \
        + (P(None, REPLICA_AXIS),) * (2 + len(agg_devfns))
    return jax.jit(_shard_map(step, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs))


def _build_sorted_program(mesh, devfn, field_kinds: tuple, op_kinds: tuple,
                          nk: int, k: int, n_queries: int,
                          agg_devfns: tuple = ()):
    """jit(shard_map(per-shard sorted reduce + cross-shard sorted merge)):
    the sorted analog of _build_program (ISSUE 17). Per shard it is
    stacked.stacked_sorted_reduce's math verbatim over the encoded key
    columns (search/sort_encode.py); the cross-shard tail all_gathers the
    candidate operands and re-sorts with the shard index wedged between
    the user keys and the dockey, reproducing the host merge's
    (compare_key, shard_idx, pos) tie order bitwise."""
    def step(live, seg_ids, sort_keys, cursor, *flat):
        live = live[0]                        # [G, N]
        seg_ids = seg_ids[0]                  # [G]
        sk = sort_keys[0]                     # [nk, G, N]
        fields = {}
        i = 0
        for name, kind in field_kinds:
            if kind == "text":
                fields[name] = MeshTextField(
                    doc_ids=flat[i][0], tf=flat[i + 1][0],
                    doc_len=flat[i + 2][0])
                i += 3
            elif kind == "keyword":
                fields[name] = MeshKeywordField(ords=flat[i][0])
                i += 1
            else:
                fields[name] = MeshNumericField(
                    vals=flat[i][0], missing=flat[i + 1][0], dtype="")
                i += 2
        ops = []
        for kind in op_kinds:
            blk = flat[i]
            i += 1
            ops.append(blk[0] if kind in (_OP_S, _OP_SQ) else blk)
        d = _DevCtx(fields, ops, live.shape[0], live.shape[1], n_queries)
        scores, match = devfn(d)

        # per-shard sorted reduce — stacked_sorted_reduce's math verbatim
        m = match & live[:, None, :]
        total = jnp.sum(m, axis=(0, 2), dtype=jnp.int64)          # [Qb]
        masked = jnp.where(m, scores, -jnp.inf)
        mx = masked.max(axis=(0, 2))                              # [Qb]
        after = jnp.zeros(sk.shape[1:], bool)
        for ki in range(nk - 1, -1, -1):
            after = (sk[ki] > cursor[ki]) \
                | ((sk[ki] == cursor[ki]) & after)
        sel = m & after[:, None, :]
        G, Qb, N = match.shape
        dockey = (seg_ids[:, None] << SEG_SHIFT) \
            | jnp.arange(N, dtype=jnp.int64)[None, :]

        def flat2(x):                         # [G,Qb,N] -> [Qb,G*N]
            return jnp.moveaxis(x, 0, 1).reshape(Qb, -1)

        cand = [flat2(jnp.where(sel, sk[0][:, None, :], jnp.inf))]
        cand += [flat2(jnp.broadcast_to(sk[ki][:, None, :], (G, Qb, N)))
                 for ki in range(1, nk)]
        cand.append(flat2(jnp.broadcast_to(dockey[:, None, :], (G, Qb, N))))
        cand.append(flat2(masked))
        ks = min(k, G * N)
        shard_out = [o[:, :ks]
                     for o in lax.sort(tuple(cand), num_keys=nk + 1)]

        # cross-shard sorted merge: gather candidates in shard order and
        # re-sort with the shard index as the post-keys tiebreak
        g = [lax.all_gather(o, SHARD_AXIS) for o in shard_out]  # [S,Qb,ks]
        S = g[0].shape[0]
        shard_col = jnp.broadcast_to(
            jnp.arange(S, dtype=jnp.int64)[:, None, None], (S, Qb, ks))

        def gflat(x):                         # [S,Qb,ks] -> [Qb,S*ks]
            return jnp.transpose(x, (1, 0, 2)).reshape(Qb, S * ks)

        merged = lax.sort(
            tuple(gflat(o) for o in g[:nk])
            + (gflat(shard_col), gflat(g[nk]), gflat(g[nk + 1])),
            num_keys=nk + 2)
        kf = min(k, S * ks)
        valid = merged[0][:, :kf] < jnp.inf
        out_shard = jnp.where(valid, merged[nk][:, :kf].astype(jnp.int32),
                              jnp.int32(-1))
        out_k = jnp.where(valid, merged[nk + 1][:, :kf], jnp.int64(-1))
        out_s = jnp.where(valid, merged[nk + 2][:, :kf], -jnp.inf)
        total_g = lax.all_gather(total, SHARD_AXIS)       # [S, Qb]
        mx_g = lax.all_gather(mx, SHARD_AXIS)             # [S, Qb]
        agg_outs = tuple(lax.all_gather(fn(d, m), SHARD_AXIS)
                         for fn in agg_devfns)
        return (out_k, out_shard, out_s, total_g, mx_g) + agg_outs

    field_specs = []
    for _name, kind in field_kinds:
        field_specs.extend([P(SHARD_AXIS)] * _FIELD_TENSORS[kind])
    op_specs = []
    for kind in op_kinds:
        if kind == _OP_S:
            op_specs.append(P(SHARD_AXIS))
        elif kind == _OP_SQ:
            op_specs.append(P(SHARD_AXIS, None, REPLICA_AXIS))
        elif kind == _OP_Q:
            op_specs.append(P(REPLICA_AXIS))
        else:
            op_specs.append(P())
    in_specs = tuple([P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P()]
                     + field_specs + op_specs)
    out_specs = (P(REPLICA_AXIS),) * 3 \
        + (P(None, REPLICA_AXIS),) * (2 + len(agg_devfns))
    return jax.jit(_shard_map(step, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs))


def execute_sorted(stack: MeshStack, node: Node, stats, sort_specs,
                   search_after, *, k: int, Q: int = 1, agg_specs=None):
    """Sorted mesh execution (ISSUE 17): the whole multi-shard SORTED
    query phase as one collective program over the encoded key columns.

    -> (doc_keys i64[Q,k'], shard i32[Q,k'], scores [Q,k'],
    totals i64[S, Q], max f[S, Q], agg_partials) — execute()'s contract;
    hit order is the encoded-key order, bitwise-equal to the fan-out's
    host merge. None when the tree/aggs have no collective form OR the
    sort encoding declines (search/sort_encode.decline_reason — the
    caller's recorder carries the reason). May raise on execution
    failure; the caller degrades to the fan-out."""
    from ..common.device_stats import lane_decline
    from ..search import sort_encode

    global last_block_mode
    all_segs = [seg for rows in stack.shard_rows for _i, seg in rows]
    reason = sort_encode.decline_reason(sort_specs, all_segs)
    if reason is not None:
        lane_decline("coordinator.reduce", "mesh", reason)
        return None
    R = stack.n_replicas
    q_pad = -(-Q // R) * R
    last_block_mode = "materialized"
    pctx = _PlanCtx(stack, q_pad, stats)
    try:
        sig, devfn = _plan_exec(node, pctx)
    except _Unsupported:
        return None
    agg_plan = None
    if agg_specs:
        from . import mesh_aggs
        agg_plan = mesh_aggs.plan_aggs(agg_specs, pctx)
        if agg_plan is None:
            return None
    cols_dev, vocabs = sort_encode.mesh_key_cols(stack, sort_specs)
    cursor = sort_encode.encode_cursor(sort_specs, search_after, vocabs)
    nk = len(sort_specs)
    field_kinds = tuple(pctx.fields.items())
    op_kinds = tuple(kind for _a, kind in pctx.ops)
    key = ("sorted", _mesh_devkey(stack.mesh), stack.s_pad, R, q_pad, k,
           nk, sig, field_kinds,
           agg_plan.sig if agg_plan is not None else None)
    prog = _PROGRAMS.get(key)
    if prog is None:
        from ..common.device_stats import instrument
        prog = instrument(
            "mesh:sorted",
            _build_sorted_program(
                stack.mesh, devfn, field_kinds, op_kinds, nk, k,
                q_pad // R,
                agg_devfns=tuple(agg_plan.device_fns())
                if agg_plan is not None else ()),
            key=key)
        _PROGRAMS.put(key, prog, weight=1)
    args = []
    for name, kind in field_kinds:
        if kind == "text":
            ft = stack.text[name]
            args.extend([ft.doc_ids, ft.tf, ft.doc_len])
        elif kind == "keyword":
            args.append(stack.keywords[name].ords)
        else:
            nf = stack.numerics[name]
            args.extend([nf.vals, nf.missing])
    args.extend(a for a, _kind in pctx.ops)
    from ..common.metrics import (device_fetch, note_h2d,
                                  record_score_matrix_bytes)
    note_h2d(sum(int(a.nbytes) for a, _kind in pctx.ops) + cursor.nbytes)
    record_score_matrix_bytes(stack.g_pad * (q_pad // R) * stack.n_pad * 5)
    with exec_guard(stack.pool):
        outs = prog(stack.live_stack(), stack.seg_ids_dev, cols_dev,
                    jnp.asarray(cursor), *args)
        out_k, out_shard, out_s, total, mx = outs[:5]
        got = device_fetch({"keys": out_k, "shard": out_shard,
                            "scores": out_s, "total": total, "mx": mx,
                            "aggs": list(outs[5:])})
    agg_partials = None
    if agg_plan is not None:
        agg_partials = agg_plan.finish(
            [np.asarray(a)[: stack.s_count] for a in got["aggs"]],
            stack.s_count)
    return (np.asarray(got["keys"])[:Q], np.asarray(got["shard"])[:Q],
            np.asarray(got["scores"])[:Q],
            np.asarray(got["total"])[: stack.s_count, :Q],
            np.asarray(got["mx"])[: stack.s_count, :Q],
            agg_partials)


def _build_blockwise_program(mesh, bplan, *, k: int, n_queries: int,
                             kk: int, score_dtype):
    """jit(shard_map(blockwise scan + per-shard merge + cross-shard
    reduce)): the blockwise analog of _build_program. The scan body is
    search/blockwise.run_scan — the per-shard running top-k — and the
    merge tails are _build_program's verbatim, so results stay
    bitwise-identical to the materializing mesh program."""
    from ..search import blockwise as bw

    nf = bw.n_field_arrays(bplan.field_kinds)
    g_pad, block, nb = bplan.g_pad, bplan.block, bplan.nb

    def step(live, seg_ids, *flat):
        live = live[0]                        # [G, N]
        seg_ids = seg_ids[0]                  # [G]
        fields = bw.rebuild_fields(bplan.field_kinds,
                                   [a[0] for a in flat[:nf]])
        ops = []
        for kind, v in zip(bplan.op_kinds, flat[nf:]):
            ops.append(v[0] if kind in (bw.OP_X, bw.OP_SG, bw.OP_COL,
                                        bw.OP_COLQ) else v)
        top, gi, total, mx = bw.run_scan(
            bplan.devfn, fields, ops, bplan.op_kinds, live, g_pad=g_pad,
            block=block, nb=nb, n_queries=n_queries, kk=kk,
            score_dtype=score_dtype)

        # per-shard cross-segment merge — stacked_reduce's tail verbatim
        keys = jnp.where(top > -jnp.inf,
                         (seg_ids[:, None, None] << SEG_SHIFT)
                         | gi.astype(jnp.int64),
                         jnp.int64(-1))
        Qb = top.shape[1]
        cand_s = jnp.moveaxis(top, 0, 1).reshape(Qb, -1)
        cand_k = jnp.moveaxis(keys, 0, 1).reshape(Qb, -1)
        ks = min(k, cand_s.shape[1])
        shard_s, pos = lax.top_k(cand_s, ks)
        shard_k = jnp.take_along_axis(cand_k, pos, axis=1)

        # cross-shard reduce — _build_program's tail verbatim
        g_s = lax.all_gather(shard_s, SHARD_AXIS)
        g_k = lax.all_gather(shard_k, SHARD_AXIS)
        S = g_s.shape[0]
        g_s = jnp.transpose(g_s, (1, 0, 2)).reshape(Qb, S * ks)
        g_k = jnp.transpose(g_k, (1, 0, 2)).reshape(Qb, S * ks)
        out_s, pos2 = lax.top_k(g_s, min(k, S * ks))
        out_k = jnp.take_along_axis(g_k, pos2, axis=1)
        valid = out_s > -jnp.inf
        out_shard = jnp.where(valid, (pos2 // ks).astype(jnp.int32),
                              jnp.int32(-1))
        out_k = jnp.where(valid, out_k, jnp.int64(-1))
        total_g = lax.all_gather(total, SHARD_AXIS)       # [S, Qb]
        mx_g = lax.all_gather(mx, SHARD_AXIS)
        return out_k, out_shard, out_s, total_g, mx_g

    field_specs = []
    for _name, kind in bplan.field_kinds:
        field_specs.extend([P(SHARD_AXIS)] * _FIELD_TENSORS[kind])
    op_specs = []
    for kind in bplan.op_kinds:
        if kind == bw.OP_X:            # [S, NB, G, Q, ...]
            op_specs.append(P(SHARD_AXIS, None, None, REPLICA_AXIS))
        elif kind == bw.OP_SG:         # [S, G, Q, ...]
            op_specs.append(P(SHARD_AXIS, None, REPLICA_AXIS))
        elif kind == bw.OP_COLQ:       # [S, G, Q, N]
            op_specs.append(P(SHARD_AXIS, None, REPLICA_AXIS))
        elif kind == bw.OP_COL:        # [S, G, N]
            op_specs.append(P(SHARD_AXIS))
        elif kind == bw.OP_Q:          # [Q, ...]
            op_specs.append(P(REPLICA_AXIS))
        else:                          # scalar, replicated
            op_specs.append(P())
    in_specs = tuple([P(SHARD_AXIS), P(SHARD_AXIS)]
                     + field_specs + op_specs)
    out_specs = (P(REPLICA_AXIS),) * 3 + (P(None, REPLICA_AXIS),) * 2
    return jax.jit(_shard_map(step, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs))


def _try_blockwise(stack: MeshStack, node: Node, stats, *, k: int,
                   q_pad: int, R: int, block: int):
    """Plan + run the blockwise mesh program, or None when the tree/shape
    has no blockwise form (caller materializes). Output contract is
    execute()'s device 5-tuple."""
    from ..search import blockwise as bw

    env = bw.FieldEnv(set(stack.text), set(stack.keywords),
                      set(stack.numerics), stack.mixed,
                      lambda f: stack.numerics[f].dtype)
    shard_rows = tuple(tuple(seg for _i, seg in rows)
                       for rows in stack.shard_rows)
    bplan = bw.plan(node, shard_rows, env, g_pad=stack.g_pad,
                    n_pad=stack.n_pad, block=block, n_queries=q_pad,
                    stats=stats)
    if bplan is None:
        return None
    # dtype probe over SHAPES only (no device work): shard-local field
    # views are the mesh tensors minus their leading S axis
    probe_fields = {}
    for name, kind in bplan.field_kinds:
        if kind == "text":
            ft = stack.text[name]
            probe_fields[name] = bw.BTextField(
                jax.ShapeDtypeStruct(ft.doc_ids.shape[1:], ft.doc_ids.dtype),
                jax.ShapeDtypeStruct(ft.tf.shape[1:], ft.tf.dtype),
                jax.ShapeDtypeStruct(ft.doc_len.shape[1:], ft.doc_len.dtype))
        elif kind == "keyword":
            kw = stack.keywords[name]
            probe_fields[name] = bw.BKeywordField(
                jax.ShapeDtypeStruct(kw.ords.shape[1:], kw.ords.dtype))
        else:
            nf_ = stack.numerics[name]
            probe_fields[name] = bw.BNumericField(
                jax.ShapeDtypeStruct(nf_.vals.shape[1:], nf_.vals.dtype),
                jax.ShapeDtypeStruct(nf_.missing.shape[1:],
                                     nf_.missing.dtype))
    score_dtype = bw.probe_score_dtype(bplan, probe_fields)
    Qb = q_pad // R
    kk = min(k, stack.n_pad)
    key = ("bw", _mesh_devkey(stack.mesh), stack.s_pad, R, q_pad, k, kk,
           block, bplan.sig, bplan.field_kinds, bplan.op_kinds,
           str(score_dtype))
    prog = _PROGRAMS.get(key)
    if prog is None:
        from ..common.device_stats import instrument
        prog = instrument(
            "mesh:blockwise",
            _build_blockwise_program(stack.mesh, bplan, k=k,
                                     n_queries=Qb, kk=kk,
                                     score_dtype=score_dtype),
            key=key)
        _PROGRAMS.put(key, prog, weight=1)
    args = []
    for name, kind in bplan.field_kinds:
        if kind == "text":
            ft = stack.text[name]
            args.extend([ft.doc_ids, ft.tf, ft.doc_len])
        elif kind == "keyword":
            args.append(stack.keywords[name].ords)
        else:
            nf_ = stack.numerics[name]
            args.extend([nf_.vals, nf_.missing])
    args.extend(bplan.ops)
    from ..common.metrics import note_h2d, record_score_matrix_bytes
    note_h2d(sum(int(np.asarray(a).nbytes) for a in bplan.ops))
    record_score_matrix_bytes(stack.g_pad * Qb * block * 5)
    return prog(stack.live_stack(), stack.seg_ids_dev, *args)


def execute(stack: MeshStack, node: Node, stats, *, k: int, Q: int = 1,
            block_docs: int | None = None, agg_specs=None):
    """Run the parsed tree over the mesh stack as one program.

    -> (doc_keys i64[Q,k'], shard i32[Q,k'], scores [Q,k'],
    totals i64[S, Q], max f[S, Q], agg_partials) fetched in ONE device
    round-trip, or None when the plan has no collective form (caller falls
    back to the fan-out). Totals/max come back PER SHARD — callers sum/max
    them (exact: int totals, order-free max) or decompose them into
    per-shard wire results (the cluster host reduce). May raise on
    execution failure — the caller degrades to the fan-out there too.

    `agg_specs` (parsed AggSpec list) routes the agg tree through the same
    program (parallel/mesh_aggs.py); `agg_partials` is then one partial
    dict per shard — exactly the fan-out's per-shard collect output — or
    the whole call returns None when a spec has no mesh form.

    With `block_docs` set and the stack wider than one block, the DSL tree
    runs blockwise inside the shard_map body (search/blockwise.run_scan) —
    peak score memory O(Q × block) per device — before the same cross-shard
    collective reduce; trees without a blockwise plan (and agg bodies)
    materialize."""
    global last_block_mode
    R = stack.n_replicas
    q_pad = -(-Q // R) * R
    last_block_mode = "materialized"
    if not agg_specs and block_docs and stack.n_pad > block_docs \
            and stack.n_pad % block_docs == 0:
        with exec_guard(stack.pool):
            out_d = _try_blockwise(stack, node, stats, k=k, q_pad=q_pad,
                                   R=R, block=block_docs)
            if out_d is not None:
                from ..common.metrics import device_fetch
                out_k, out_shard, out_s, total, mx = out_d
                got = device_fetch({"keys": out_k, "shard": out_shard,
                                    "scores": out_s, "total": total,
                                    "mx": mx})
        if out_d is not None:
            last_block_mode = "blockwise"
            return (np.asarray(got["keys"])[:Q],
                    np.asarray(got["shard"])[:Q],
                    np.asarray(got["scores"])[:Q],
                    np.asarray(got["total"])[: stack.s_count, :Q],
                    np.asarray(got["mx"])[: stack.s_count, :Q],
                    None)
    pctx = _PlanCtx(stack, q_pad, stats)
    try:
        sig, devfn = _plan_exec(node, pctx)
    except _Unsupported:
        return None
    agg_plan = None
    if agg_specs:
        from . import mesh_aggs
        agg_plan = mesh_aggs.plan_aggs(agg_specs, pctx)
        if agg_plan is None:
            return None       # some agg has no mesh form -> fan-out
    field_kinds = tuple(pctx.fields.items())
    op_kinds = tuple(kind for _a, kind in pctx.ops)
    key = (_mesh_devkey(stack.mesh), stack.s_pad, R, q_pad, k, sig,
           field_kinds,
           agg_plan.sig if agg_plan is not None else None)
    prog = _PROGRAMS.get(key)
    if prog is None:
        from ..common.device_stats import instrument
        prog = instrument(
            "mesh:materialized",
            _build_program(
                stack.mesh, devfn, field_kinds, op_kinds, k, q_pad // R,
                agg_devfns=tuple(agg_plan.device_fns())
                if agg_plan is not None else ()),
            key=key)
        _PROGRAMS.put(key, prog, weight=1)
    args = []
    for name, kind in field_kinds:
        if kind == "text":
            ft = stack.text[name]
            args.extend([ft.doc_ids, ft.tf, ft.doc_len])
        elif kind == "keyword":
            args.append(stack.keywords[name].ords)
        else:
            nf = stack.numerics[name]
            args.extend([nf.vals, nf.missing])
    args.extend(a for a, _kind in pctx.ops)
    from ..common.metrics import (device_fetch, note_h2d,
                                  record_score_matrix_bytes)
    note_h2d(sum(int(a.nbytes) for a, _kind in pctx.ops))
    record_score_matrix_bytes(stack.g_pad * (q_pad // R) * stack.n_pad * 5)
    with exec_guard(stack.pool):
        outs = prog(stack.live_stack(), stack.seg_ids_dev, *args)
        out_k, out_shard, out_s, total, mx = outs[:5]
        # the whole multi-shard query phase — top-k reduce AND agg
        # partials — comes down in this ONE fetch
        got = device_fetch({"keys": out_k, "shard": out_shard,
                            "scores": out_s, "total": total, "mx": mx,
                            "aggs": list(outs[5:])})
    agg_partials = None
    if agg_plan is not None:
        agg_partials = agg_plan.finish(
            [np.asarray(a)[: stack.s_count] for a in got["aggs"]],
            stack.s_count)
    return (np.asarray(got["keys"])[:Q], np.asarray(got["shard"])[:Q],
            np.asarray(got["scores"])[:Q],
            np.asarray(got["total"])[: stack.s_count, :Q],
            np.asarray(got["mx"])[: stack.s_count, :Q],
            agg_partials)


def program_cache_stats() -> dict:
    return _PROGRAMS.stats()
