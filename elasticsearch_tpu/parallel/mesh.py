"""Device mesh construction for the search data plane.

Axes (the search-engine analog of an ML parallelism layout, SURVEY.md §2.10):
  * "shard"   — document partitions (data parallelism over the corpus);
                the index's stacked shard axis is sharded here.
  * "replica" — query-batch parallelism (replica groups serving QPS);
                the query batch is sharded here, the index is REPLICATED
                here — exactly the reference's "R copies per shard serve
                reads in parallel" (§2.10.2), but as a mesh axis instead
                of copied JVMs.

Cross-shard reduces (df psum, top-k all_gather) ride the "shard" axis —
on hardware these become ICI collectives; across pods XLA lowers them to
DCN automatically. The control plane (cluster state, doc transport) stays
host-side RPC, mirroring the reference's split (SURVEY.md §5.8).
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shard"
REPLICA_AXIS = "replica"


def make_mesh(n_shards: int | None = None, n_replicas: int = 1,
              devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if n_shards is None:
        n_shards = len(devices) // n_replicas
    need = n_shards * n_replicas
    if need > len(devices):
        raise ValueError(f"mesh {n_replicas}x{n_shards} needs {need} devices, "
                         f"have {len(devices)}")
    arr = np.asarray(devices[:need]).reshape(n_replicas, n_shards)
    return Mesh(arr, (REPLICA_AXIS, SHARD_AXIS))


def index_sharding(mesh: Mesh) -> NamedSharding:
    """Index tensors: leading shard axis split over "shard", replicated over
    "replica" (every replica group holds a full copy — the R-copies model)."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def query_sharding(mesh: Mesh) -> NamedSharding:
    """Per-shard query tensors [S, Q, ...]: S over "shard", Q over "replica"."""
    return NamedSharding(mesh, P(SHARD_AXIS, REPLICA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
