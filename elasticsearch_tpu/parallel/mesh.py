"""Device mesh construction for the search data plane.

Axes (the search-engine analog of an ML parallelism layout, SURVEY.md §2.10):
  * "shard"   — document partitions (data parallelism over the corpus);
                the index's stacked shard axis is sharded here.
  * "replica" — query-batch parallelism (replica groups serving QPS);
                the query batch is sharded here, the index is REPLICATED
                here — exactly the reference's "R copies per shard serve
                reads in parallel" (§2.10.2), but as a mesh axis instead
                of copied JVMs.

Cross-shard reduces (df psum, top-k all_gather) ride the "shard" axis —
on hardware these become ICI collectives; across pods XLA lowers them to
DCN automatically. The control plane (cluster state, doc transport) stays
host-side RPC, mirroring the reference's split (SURVEY.md §5.8).

Device ownership (ISSUE 19): each data node can OWN a disjoint device
subset (`node.devices` setting, or the harness's even split across
co-hosted nodes). A `DevicePool` carries that subset plus its OWN
dispatch lock, so collective programs from different nodes run
concurrently — the process-wide EXEC_LOCK remains only as the legacy
shared-pool fallback when no ownership is configured. The lock lives on
the POOL (not keyed by the raw device tuple) because two pools over
overlapping `devices[:need]` prefixes must never dispatch concurrently;
ownership resolution below only ever hands out disjoint subsets.
"""

from __future__ import annotations

import threading

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shard"
REPLICA_AXIS = "replica"

# The legacy process-wide dispatch lock (PR-11): serializes shard_map
# programs that run on the SHARED pool (all of jax.devices()). Per-node
# DevicePools carry their own lock and never touch this one — that is
# what takes EXEC_LOCK off the per-node hot path. mesh_exec re-exports
# this as EXEC_LOCK for back-compat.
SHARED_EXEC_LOCK = threading.Lock()


class DevicePool:
    """A node's owned device subset + its private dispatch lock.

    `devkey` (the sorted tuple of device ids) feeds compiled-program
    cache keys so two nodes never share a program, and labels the
    device-stats registry so attribution survives concurrent per-node
    dispatch.
    """

    def __init__(self, devices, name: str = "pool", lock=None):
        self.devices = tuple(devices)
        self.name = str(name)
        self.devkey = tuple(int(d.id) for d in self.devices)
        self.lock = lock if lock is not None else threading.Lock()
        # (n_replicas, s_pad) -> Mesh over this pool's devices; guarded
        # separately from `lock` — mesh construction must not serialize
        # behind a long-running device program.
        self._meshes: dict = {}
        self._mesh_build_lock = threading.Lock()

    @property
    def is_shared(self) -> bool:
        return self.lock is SHARED_EXEC_LOCK

    def mesh_for(self, n_shards: int, n_replicas: int = 1):
        """Smallest (replicas x padded-shards) mesh over this pool that
        fits `n_shards`, or None if the pool is too small / trivial.
        Mirrors the legacy mesh_exec.mesh_for contract:
        returns (mesh, s_pad, n_replicas)."""
        n_dev = len(self.devices)
        if n_dev < 2 or n_shards < 1:
            return None
        per = n_dev // n_replicas
        if per < 1:
            return None
        s_pad = 1
        while s_pad < n_shards:
            s_pad *= 2
        if s_pad > per:
            return None
        key = (n_replicas, s_pad)
        with self._mesh_build_lock:
            mesh = self._meshes.get(key)
            if mesh is None:
                mesh = make_mesh(s_pad, n_replicas, devices=self.devices)
                self._meshes[key] = mesh
        return mesh, s_pad, n_replicas

    def __repr__(self):  # pragma: no cover - debug aid
        return f"DevicePool({self.name}, devices={self.devkey})"


_SHARED_POOL = None
_SHARED_POOL_LOCK = threading.Lock()


def shared_pool() -> DevicePool:
    """The legacy whole-process pool over jax.devices(), guarded by
    SHARED_EXEC_LOCK. Rebuilt if the device count changes (tests that
    fork with different XLA_FLAGS)."""
    global _SHARED_POOL
    devs = jax.devices()
    with _SHARED_POOL_LOCK:
        if _SHARED_POOL is None or len(_SHARED_POOL.devices) != len(devs):
            _SHARED_POOL = DevicePool(devs, name="shared",
                                      lock=SHARED_EXEC_LOCK)
        return _SHARED_POOL


def resolve_device_pool(settings) -> DevicePool | None:
    """Parse the `node.devices` setting into an owned DevicePool.

    Accepted forms:
      * explicit indices — ``"0,1,2,3"`` or a list of ints — picks those
        positions out of jax.devices();
      * ``"auto:<i>/<n>"`` — the i-th slice of an even n-way split (the
        harness's co-hosted-nodes form).

    Returns None (→ legacy shared pool + EXEC_LOCK) when the setting is
    absent, malformed, or the split leaves this node without devices.
    """
    if settings is None:
        return None
    try:
        spec = settings.get("node.devices")
    except Exception:
        return None
    if spec is None or spec == "":
        return None
    devs = jax.devices()
    own = None
    if isinstance(spec, str) and spec.startswith("auto:"):
        try:
            i_s, n_s = spec[5:].split("/")
            i, n = int(i_s), int(n_s)
        except ValueError:
            return None
        if n < 1 or not (0 <= i < n):
            return None
        per = len(devs) // n
        if per < 1:
            return None
        own = devs[i * per:(i + 1) * per]
    else:
        try:
            if isinstance(spec, str):
                ids = [int(x) for x in spec.split(",") if x.strip()]
            else:
                ids = [int(x) for x in spec]
            own = [devs[i] for i in ids if 0 <= i < len(devs)]
            if len(own) != len(ids):
                return None
        except (TypeError, ValueError):
            return None
    if not own:
        return None
    name = "devices[" + ",".join(str(int(d.id)) for d in own) + "]"
    return DevicePool(own, name=name)


_DISTRIBUTED_INITED = False


def maybe_init_distributed(settings) -> bool:
    """`jax.distributed.initialize` when `cluster.mesh.coordinator` is
    set — the multi-host data plane's entry point (ICI within a host,
    DCN between; SURVEY §5.8). Idempotent; failures are swallowed so a
    node without the coordinator reachable still serves on its local
    devices (the ladder declines, it never errors)."""
    global _DISTRIBUTED_INITED
    if settings is None:
        return False
    try:
        coord = settings.get("cluster.mesh.coordinator")
    except Exception:
        return False
    if not coord:
        return False
    if _DISTRIBUTED_INITED:
        return True
    try:
        jax.distributed.initialize(
            coordinator_address=str(coord),
            num_processes=int(settings.get("cluster.mesh.num_processes", 1)),
            process_id=int(settings.get("cluster.mesh.process_id", 0)))
        _DISTRIBUTED_INITED = True
        return True
    except Exception:
        return False


def make_mesh(n_shards: int | None = None, n_replicas: int = 1,
              devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if n_shards is None:
        n_shards = len(devices) // n_replicas
    need = n_shards * n_replicas
    if need > len(devices):
        raise ValueError(f"mesh {n_replicas}x{n_shards} needs {need} devices, "
                         f"have {len(devices)}")
    arr = np.asarray(devices[:need]).reshape(n_replicas, n_shards)
    return Mesh(arr, (REPLICA_AXIS, SHARD_AXIS))


def index_sharding(mesh: Mesh) -> NamedSharding:
    """Index tensors: leading shard axis split over "shard", replicated over
    "replica" (every replica group holds a full copy — the R-copies model)."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def query_sharding(mesh: Mesh) -> NamedSharding:
    """Per-shard query tensors [S, Q, ...]: S over "shard", Q over "replica"."""
    return NamedSharding(mesh, P(SHARD_AXIS, REPLICA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
