"""Document→shard routing — the OperationRouting analog.

ref /root/reference/src/main/java/org/elasticsearch/cluster/routing/OperationRouting.java:48,60
(shard = hash(routing ?: id) % numShards) with the default DJB hash
(cluster/routing/DjbHashFunction.java:28). We keep the exact partition
function so a doc corpus routed by the reference lands on the same shard
numbers here — routing parity matters for cross-validating shard contents.
"""

from __future__ import annotations


def djb_hash(value: str) -> int:
    """DJB2 over UTF-16 code units, as the reference's DjbHashFunction:
    hash = 5381; hash = 33*hash + char; truncated to signed int32.
    Java's charAt iterates UTF-16 units (surrogate pairs count as two), so we
    hash utf-16 code units, not Python code points — non-BMP ids route
    identically to the reference."""
    h = 5381
    data = value.encode("utf-16-le")
    for i in range(0, len(data), 2):
        unit = data[i] | (data[i + 1] << 8)
        h = ((h * 33) + unit) & 0xFFFFFFFF
    # Java ints are signed 32-bit
    if h >= 0x80000000:
        h -= 0x100000000
    return h


def shard_id(doc_id: str, num_shards: int, routing: str | None = None) -> int:
    """MathUtils.mod(hash, numShards) — floor mod, NOT abs
    (ref OperationRouting.java shardId → common/math/MathUtils.java:28)."""
    h = djb_hash(routing if routing is not None else doc_id)
    return h % num_shards  # Python % is floor-mod, matching MathUtils.mod


def select_copy(shard: int, n_copies: int, preference: str | None = None,
                session_seed: int = 0) -> int:
    """Pick a shard copy for reads (ref OperationRouting.java:144-154 —
    round-robin/preference across primary+replicas)."""
    if preference == "_primary" or n_copies <= 1:
        return 0
    return (session_seed + shard) % n_copies
