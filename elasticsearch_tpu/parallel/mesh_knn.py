"""kNN vector search through the mesh program (ISSUE 11 tentpole (b)).

`ShardSearcher.execute_knn` runs per shard, per segment — one device
dispatch and one fetch per segment, then host merges, then (on a cluster)
one transport round-trip per shard. This module packs the shards' vector
columns onto the same ("replica", "shard") mesh the text lane uses
(parallel/mesh.py) and runs the WHOLE multi-shard kNN query phase as ONE
shard_map program with the cross-shard top-k reduce on device:

    exact : per-segment [Q, N] similarity matmuls (ops/knn._sim's math,
            vmapped over the segment axis) under the shard axis
    ivf   : per-segment centroid route + gathered cluster scan
            (ops/ann.ivf_search's two stages inlined, uniform static
            nlist/nprobe/W across segments; each segment's own slot
            budget W_own masks the tail, so the candidate set equals the
            per-segment kernel's exactly — postings_slots is prefix-
            stable in W)
    int8  : the ivf scan on quantized codes (ops/ann.ivf_search_int8's
            int8×int8 GEMM + full-precision rescore, ISSUE 12) when the
            index or request selects `quantization: int8` and every
            segment's QuantData is available
    pq    : the ivf scan on u8 sub-quantizer codes (ops/ann.
            ivf_search_pq's ADC stages, ISSUE 19): per-query LUTs built
            in-program from the replicated query operand against the
            shard-sharded per-segment codebooks, candidate work = m u8
            gathers + adds, then the same full-precision rescore tail

Bitwise parity with the per-shard fan-out holds because per-doc
similarities are contractions over D only (padding the doc axis never
changes them), candidates concatenate in (segment, shard) order, and
`lax.top_k` keeps the earlier candidate on ties — the same (score,
shard, pos) order `controller.sort_docs` produces.

The fallback ladder: mixed IVF/exact segment lanes, non-uniform nlist or
nprobe, filter plans without a mesh match form, undersized meshes and any
execution error return None and the caller runs the per-shard fan-out.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..index.segment import next_pow2
from ..ops import ann as ann_ops
from ..ops import bm25 as bm25_ops
from ..ops.topk import merge_running_topk
from .distributed_search import _shard_map
from .mesh import REPLICA_AXIS, SHARD_AXIS, index_sharding
from . import mesh_exec
from .mesh_exec import SEG_SHIFT, _DevCtx, _PlanCtx, _Unsupported


@dataclass
class _IvfPack:
    """Uniform-(nlist, nprobe) IVF operands stacked over (shard, segment)."""
    nlist: int
    nprobe_eff: int
    centroids: jax.Array             # f32[S, G, nlist, D]
    starts: jax.Array                # i32[S, G, nlist]
    sizes: jax.Array                 # i32[S, G, nlist]
    slot_docs: jax.Array             # i32[S, G, N]
    norms: jax.Array                 # f32[S, G, N]
    sizes_desc_cum: list             # per (s, g): np i64[nlist] | None
    n_docs: np.ndarray               # i64[S, G]
    nbytes: int = 0


@dataclass
class _QuantPack:
    """Quantized codes stacked over (shard, segment) — the mesh rider of
    the per-shard `ann_quant` tier (ISSUE 12). int8: the scan gathers
    1/4-size codes instead of the f32 stack. pq (ISSUE 19): u8 sub-
    quantizer codes + per-segment codebooks ride the shard axis, and the
    per-query ADC lookup tables are built IN-program from the replicated
    query operand (one einsum per segment) so the collective surface
    stays one u8 gather + adds per candidate. The rescore tail for both
    modes gathers f32 rows from the SAME packed vecs tensor."""
    mode: str                        # "int8" | "pq"
    codes: jax.Array                 # i8[S, G, N, D] | u8[S, G, N, m]
    scales: jax.Array | None = None  # f32[S, G, D]           (int8)
    codebooks: jax.Array | None = None  # f32[S, G, m, 256, dsub] (pq)
    m: int = 0                       # pq sub-quantizer count
    nbytes: int = 0


@dataclass
class MeshVectorStack:
    """Immutable packed view of one vector field across an index's shards
    on the device mesh. Rows mirror MeshStack.shard_rows (segments with
    live docs, in segment order) so a filter plan over the text mesh
    stack aligns row-for-row."""
    field: str
    shard_rows: tuple                # per shard: tuple[(orig_idx, Segment)]
    s_count: int
    s_pad: int
    g_pad: int
    n_pad: int
    dims: int
    mesh: jax.sharding.Mesh = None
    n_replicas: int = 1
    vecs: jax.Array | None = None    # f32[S, G, N, D]
    has_field: np.ndarray | None = None      # bool[S, G] host
    seg_ids_dev: jax.Array | None = None     # i64[S, G]
    nbytes: int = 0
    ivf_packs: dict = dc_field(default_factory=dict)   # nlist -> _IvfPack
    pool: object = None              # owning DevicePool (None = shared)

    def __post_init__(self):
        self._live_key = None
        self._live_dev = None

    def live_stack(self) -> jax.Array:
        """bool[S, G, N] root-doc liveness (tombstone-generation cached,
        padding all-False) — the same mask execute_knn gates on."""
        key = tuple(seg.live_gen for rows in self.shard_rows
                    for _i, seg in rows)
        if self._live_key != key or self._live_dev is None:
            arr = np.zeros((self.s_pad, self.g_pad, self.n_pad), bool)
            for si, rows in enumerate(self.shard_rows):
                for gi, (_i, seg) in enumerate(rows):
                    arr[si, gi, : seg.n_pad] = np.asarray(seg.root_live_host)
            self._live_dev = jax.device_put(arr, index_sharding(self.mesh))
            self._live_key = key
        return self._live_dev


def estimate_vector_stack_bytes(per_shard_segments, field: str) -> int:
    """Device bytes the packed vector mesh stack will occupy — the
    pre-build fielddata-breaker charge (mirrors build arithmetic)."""
    rows = [[s for s in segs if s.n_docs > 0] for segs in per_shard_segments]
    live = [s for r in rows for s in r]
    cols = [s.vectors.get(field) for s in live]
    cols = [c for c in cols if c is not None]
    if not cols:
        return 0
    s_pad = next_pow2(len(per_shard_segments), floor=1)
    g_pad = next_pow2(max(len(r) for r in rows), floor=1)
    n_pad = max(s.n_pad for s in live)
    dims = cols[0].dims
    return s_pad * g_pad * n_pad * (dims * 4 + 1) + s_pad * g_pad * 8


def build_vector_stack(per_shard_segments, field: str, mesh, s_pad: int,
                       n_replicas: int, pool=None) -> MeshVectorStack | None:
    """Pack every shard's live segments' `field` vector columns into
    mesh-sharded tensors. None when the field is absent everywhere or the
    columns disagree on dims (per-shard fan-out handles those)."""
    from ..common import tracing
    shard_rows = tuple(
        tuple((i, s) for i, s in enumerate(segs) if s.n_docs > 0)
        for segs in per_shard_segments)
    all_live = [seg for rows in shard_rows for _i, seg in rows]
    if not all_live:
        return None
    dims_set = {seg.vectors[field].dims for seg in all_live
                if field in seg.vectors}
    if len(dims_set) != 1:
        return None
    dims = dims_set.pop()
    g_pad = next_pow2(max(len(r) for r in shard_rows), floor=1)
    n_pad = max(s.n_pad for s in all_live)
    with tracing.span("mesh_vstack_build", field=field,
                      shards=len(per_shard_segments)):
        vecs = np.zeros((s_pad, g_pad, n_pad, dims), np.float32)
        has_field = np.zeros((s_pad, g_pad), bool)
        seg_ids = np.zeros((s_pad, g_pad), np.int64)
        for si, rows in enumerate(shard_rows):
            for gi, (orig, seg) in enumerate(rows):
                seg_ids[si, gi] = orig
                vc = seg.vectors.get(field)
                if vc is None:
                    continue
                v = np.asarray(vc.vecs)
                vecs[si, gi, : v.shape[0]] = v
                has_field[si, gi] = True
        sharding = index_sharding(mesh)
        nbytes = vecs.nbytes + s_pad * g_pad * (n_pad + 8)
        return MeshVectorStack(
            field=field, shard_rows=shard_rows,
            s_count=len(per_shard_segments), s_pad=s_pad, g_pad=g_pad,
            n_pad=n_pad, dims=dims, mesh=mesh, n_replicas=n_replicas,
            vecs=jax.device_put(vecs, sharding), has_field=has_field,
            seg_ids_dev=jax.device_put(seg_ids, sharding), nbytes=nbytes,
            pool=pool)


def _build_ivf_pack(vstack: MeshVectorStack, acquire_ivf) -> _IvfPack | str:
    """Stack per-(shard, segment) IVF structures — the SAME cached IvfData
    the per-shard lane uses (acquire_ivf callback), so centroids and CSR
    layouts are bit-identical. Returns an _IvfPack, or a reason string
    when the lanes are mixed / nlist is non-uniform (-> decline)."""
    per = {}
    nlists = set()
    nprobes = set()
    n_exact = 0
    for si, rows in enumerate(vstack.shard_rows):
        for gi, (_i, seg) in enumerate(rows):
            vc = seg.vectors.get(vstack.field)
            if vc is None:
                continue
            ivf, nprobe_eff = acquire_ivf(si, seg, vc)
            if ivf is None:
                n_exact += 1
                continue
            per[(si, gi)] = (ivf, nprobe_eff)
            nlists.add(int(ivf.nlist))
            nprobes.add(int(nprobe_eff))
    if not per:
        return "exact"                  # every segment on the exact lane
    if n_exact:
        return "mixed"                  # mixed lanes: fan-out decides per seg
    if len(nlists) != 1 or len(nprobes) != 1:
        return "nlist"                  # non-uniform clustering shape
    nlist = nlists.pop()
    s_pad, g_pad, n_pad = vstack.s_pad, vstack.g_pad, vstack.n_pad
    cents = np.zeros((s_pad, g_pad, nlist, vstack.dims), np.float32)
    starts = np.zeros((s_pad, g_pad, nlist), np.int32)
    sizes = np.zeros((s_pad, g_pad, nlist), np.int32)
    slot_docs = np.full((s_pad, g_pad, n_pad), n_pad - 1, np.int32)
    norms = np.zeros((s_pad, g_pad, n_pad), np.float32)
    sdc: list = [[None] * g_pad for _ in range(s_pad)]
    n_docs = np.zeros((s_pad, g_pad), np.int64)
    for (si, gi), (ivf, _np_eff) in per.items():
        cents[si, gi] = np.asarray(ivf.centroids)
        starts[si, gi] = np.asarray(ivf.starts)
        sizes[si, gi] = np.asarray(ivf.sizes)
        sd = np.asarray(ivf.slot_docs)
        slot_docs[si, gi, : sd.shape[0]] = sd
        nm = np.asarray(ivf.norms)
        norms[si, gi, : nm.shape[0]] = nm
        sdc[si][gi] = ivf.sizes_desc_cum
        n_docs[si, gi] = ivf.n_docs
    sharding = index_sharding(vstack.mesh)
    return _IvfPack(
        nlist=nlist, nprobe_eff=nprobes.pop(),
        centroids=jax.device_put(cents, sharding),
        starts=jax.device_put(starts, sharding),
        sizes=jax.device_put(sizes, sharding),
        slot_docs=jax.device_put(slot_docs, sharding),
        norms=jax.device_put(norms, sharding),
        sizes_desc_cum=sdc, n_docs=n_docs,
        nbytes=cents.nbytes + starts.nbytes + sizes.nbytes
        + slot_docs.nbytes + norms.nbytes)


def _build_quant_pack(vstack: MeshVectorStack, base: _IvfPack,
                      acquire_ivf, acquire_quant,
                      mode: str) -> "_QuantPack | str":
    """Stack per-(shard, segment) quantized codes — the SAME cached
    QuantData the per-shard lane uses (acquire_quant callback), so codes,
    scales and codebooks are bit-identical. Returns a _QuantPack, or a
    reason string when any segment declines quantization or (pq) the
    sub-quantizer counts disagree (-> the whole mesh lane declines and
    the per-shard fan-out honors the request's mode)."""
    s_pad, g_pad, n_pad = vstack.s_pad, vstack.g_pad, vstack.n_pad
    per = {}
    for si, rows in enumerate(vstack.shard_rows):
        for gi, (_i, seg) in enumerate(rows):
            vc = seg.vectors.get(vstack.field)
            if vc is None:
                continue
            ivf, _np_eff = acquire_ivf(si, seg, vc)    # cache hit
            if ivf is None:
                return "mixed"
            quant = acquire_quant(si, seg, vc, ivf, mode)
            if quant is None or quant.mode != mode:
                return "quant"
            per[(si, gi)] = quant
    sharding = index_sharding(vstack.mesh)
    if mode == "int8":
        codes = np.zeros((s_pad, g_pad, n_pad, vstack.dims), np.int8)
        scales = np.ones((s_pad, g_pad, vstack.dims), np.float32)
        for (si, gi), quant in per.items():
            c = np.asarray(quant.codes)
            codes[si, gi, : c.shape[0]] = c
            scales[si, gi] = np.asarray(quant.scales)
        return _QuantPack(
            mode=mode,
            codes=jax.device_put(codes, sharding),
            scales=jax.device_put(scales, sharding),
            nbytes=codes.nbytes + scales.nbytes)
    # pq: u8 codes [N, m] + per-segment codebooks [m, 256, dsub]; the
    # in-program ADC LUT einsum needs ONE static m across the stack
    ms = {int(q.m) for q in per.values()}
    if len(ms) != 1:
        return "pq_shape"
    m = ms.pop()
    if m < 1 or vstack.dims % m:
        return "pq_shape"
    dsub = vstack.dims // m
    codes = np.zeros((s_pad, g_pad, n_pad, m), np.uint8)
    books = np.zeros((s_pad, g_pad, m, ann_ops.PQ_CODES, dsub), np.float32)
    for (si, gi), quant in per.items():
        c = np.asarray(quant.codes)
        codes[si, gi, : c.shape[0]] = c
        books[si, gi] = np.asarray(quant.codebooks)
    return _QuantPack(
        mode=mode,
        codes=jax.device_put(codes, sharding),
        codebooks=jax.device_put(books, sharding),
        m=m, nbytes=codes.nbytes + books.nbytes)


def _plan_filter(filter_node, filter_stack, q_pad: int):
    """Mesh match plan for the kNN pre-filter over the text mesh stack.
    The match mask is stats-independent (presence booleans), so stats
    built from the stack's own segments are safe. None -> no mesh form."""
    from ..search.query_dsl import CollectionStats, contains_joins
    if filter_stack is None or contains_joins(filter_node):
        return None
    if not mesh_exec.plan_types_supported(filter_node):
        return None
    terms_by_field: dict[str, set] = {}
    filter_node.collect_terms(terms_by_field)
    segs = [seg for rows in filter_stack.shard_rows for _i, seg in rows]
    stats = CollectionStats.from_segments(segs, terms_by_field)
    pctx = _PlanCtx(filter_stack, q_pad, stats)
    try:
        sig, mfn = mesh_exec._plan_match(filter_node, pctx)
    except _Unsupported:
        return None
    return sig, mfn, pctx


def execute(vstack: MeshVectorStack, query_vectors, *, k: int, metric: str,
            knn_opts: dict, nprobe, exact: bool, acquire_ivf,
            acquire_quant=None, quantization: str | None = None,
            filter_node=None, filter_stack=None):
    """Run a kNN query batch over the vector mesh stack as one program.

    -> (doc_keys i64[Q,k'], shard i32[Q,k'], scores f32[Q,k'],
    totals i64[S,Q], max f32[S,Q], used_ivf, used_quant) in ONE device
    fetch, or None when the shape has no single-program form (caller
    fans out). May raise on execution failure — callers degrade the
    same way."""
    qv_np = np.asarray(query_vectors, np.float32)
    if qv_np.ndim == 1:
        qv_np = qv_np[None, :]
    Q = qv_np.shape[0]
    R = vstack.n_replicas
    q_pad = -(-Q // R) * R
    if qv_np.shape[0] < q_pad:
        qv_np = np.concatenate(
            [qv_np, np.zeros((q_pad - Q, qv_np.shape[1]), np.float32)])
    precision = knn_opts["precision"]
    qmode = (quantization if quantization is not None
             else knn_opts.get("quantization", "none"))
    qmode = str(qmode).strip().lower()
    if qmode not in ("int8", "pq"):
        qmode = "none"
    from ..common.device_stats import lane_decline
    # the mesh kNN lane serves the IVF path only: the exact per-segment
    # kernel runs EAGERLY on the per-shard path, and a fused collective
    # program cannot reproduce its GEMM rounding bit-for-bit — exact and
    # mixed lanes keep the per-shard fan-out (which can)
    pack, qpack = _build_or_get_pack(vstack, acquire_ivf, knn_opts, nprobe,
                                     exact, qmode, acquire_quant)
    if not isinstance(pack, _IvfPack):
        lane_decline("knn", "mesh_knn", "knn_lane")
        return None
    if qmode != "none" and not isinstance(qpack, _QuantPack):
        # a segment declined quantization: fan-out decides
        lane_decline("knn", "mesh_knn", "quant_declined")
        return None
    used_ivf = True
    used_quant = qpack.mode if isinstance(qpack, _QuantPack) else None
    ivf: _IvfPack = pack

    nlist = ivf.nlist
    nprobe_eff = ivf.nprobe_eff          # the per-segment lane's own value
    # per-segment slot budgets; the STATIC W is their max (pow2) and
    # each segment's own budget masks its slot tail — postings_slots
    # fills slots in cluster order, so the first W_own slots of the
    # W_max enumeration ARE the W_own enumeration (prefix property)
    w_own = np.zeros((vstack.s_pad, vstack.g_pad), np.int32)
    for si in range(vstack.s_count):
        for gi in range(len(vstack.shard_rows[si])):
            sdc = ivf.sizes_desc_cum[si][gi]
            if sdc is None:
                continue
            w_own[si, gi] = ann_ops.slot_budget(
                sdc, nprobe_eff, int(ivf.n_docs[si, gi]), nlist)
    W = int(next_pow2(int(w_own.max()), floor=8))
    block = ann_ops.scan_block_size(q_pad // R, vstack.dims, W)

    fplan = None
    if filter_node is not None:
        fplan = _plan_filter(filter_node, filter_stack, q_pad)
        if fplan is None:
            lane_decline("knn", "mesh_knn", "filter_shape")
            return None
        fsig, mfn, fpctx = fplan
        # the filter stack's rows must mirror the vector stack's rows so
        # the match mask aligns segment-for-segment
        v_ids = [[seg.seg_id for _i, seg in rows]
                 for rows in vstack.shard_rows]
        f_ids = [[seg.seg_id for _i, seg in rows]
                 for rows in filter_stack.shard_rows]
        if v_ids != f_ids:
            lane_decline("knn", "mesh_knn", "stack_rows_mismatch")
            return None

    kk = min(k, W) if used_ivf else min(k, vstack.n_pad)
    rw = 0
    if used_quant:
        rw = ann_ops.rescore_width(
            kk, int(knn_opts.get("rescore_window") or 0), W)
    # g_pad MUST key the program: it is a closure constant of step(), and
    # a merge can take an index from g_pad=2 back to g_pad=1 while every
    # other component matches (chaos-harness find: the cached program
    # then broadcast-errors on the new stack and the lane falls back)
    pq_m = qpack.m if isinstance(qpack, _QuantPack) else 0
    key = ("knn", mesh_exec._mesh_devkey(vstack.mesh),
           vstack.s_pad, vstack.g_pad, R, q_pad, k, kk,
           vstack.n_pad, vstack.dims,
           metric, precision, used_ivf, nprobe_eff, W, block,
           used_quant, rw, pq_m,
           (fplan[0], tuple(fplan[2].fields.items()),
            tuple(kind for _a, kind in fplan[2].ops))
           if fplan is not None else None)
    prog = mesh_exec._PROGRAMS.get(key)
    if prog is None:
        from ..common.device_stats import instrument
        prog = instrument(
            "mesh:knn",
            _build_knn_program(
                vstack, metric=metric, precision=precision, k=k, kk=kk,
                n_queries=q_pad // R, used_ivf=used_ivf, nprobe=nprobe_eff,
                W=W, block=block, nlist=ivf.nlist if used_ivf else 0,
                quant=used_quant, rw=rw, pq_m=pq_m, fplan=fplan),
            key=key)
        mesh_exec._PROGRAMS.put(key, prog, weight=1)

    args = [vstack.live_stack(), vstack.seg_ids_dev,
            jnp.asarray(vstack.has_field),
            vstack.vecs]
    if used_ivf:
        args.extend([ivf.centroids, ivf.starts, ivf.sizes, ivf.slot_docs,
                     ivf.norms, jnp.asarray(w_own)])
    if used_quant:
        args.extend([qpack.codes,
                     qpack.scales if used_quant == "int8"
                     else qpack.codebooks])
    if fplan is not None:
        _fsig, _mfn, fpctx = fplan
        for name, kind in fpctx.fields.items():
            if kind == "text":
                ft = filter_stack.text[name]
                args.extend([ft.doc_ids, ft.tf, ft.doc_len])
            elif kind == "keyword":
                args.append(filter_stack.keywords[name].ords)
            else:
                nf = filter_stack.numerics[name]
                args.extend([nf.vals, nf.missing])
        args.extend(a for a, _kind in fpctx.ops)
    args.append(jnp.asarray(qv_np))

    from ..common.metrics import device_fetch, note_h2d
    note_h2d(int(qv_np.nbytes))
    with mesh_exec.exec_guard(vstack.pool):
        out_k, out_shard, out_s, total, mx = prog(*args)
        got = device_fetch({"keys": out_k, "shard": out_shard,
                            "scores": out_s, "total": total, "mx": mx})
    return (np.asarray(got["keys"])[:Q], np.asarray(got["shard"])[:Q],
            np.asarray(got["scores"])[:Q],
            np.asarray(got["total"])[: vstack.s_count, :Q],
            np.asarray(got["mx"])[: vstack.s_count, :Q],
            used_ivf, used_quant)


def _build_or_get_pack(vstack, acquire_ivf, knn_opts, nprobe, exact,
                       qmode: str = "none", acquire_quant=None):
    """(ivf_pack, quant_pack) for this request shape, each memoized on
    the stack (the tensors are immutable alongside the segment set);
    either slot may instead hold a reason string ("exact"/"mixed"/
    "nlist"/"quant"). Exact-pinned requests skip IVF acquisition
    entirely."""
    if exact or not knn_opts.get("ivf_enable", True):
        return "exact", None
    ck = ("req", nprobe)
    cached = vstack.ivf_packs.get(ck)
    if cached is None:
        cached = vstack.ivf_packs[ck] = _build_ivf_pack(vstack, acquire_ivf)
    if qmode == "none" or not isinstance(cached, _IvfPack) \
            or acquire_quant is None:
        return cached, None
    qk = ("quant", nprobe, qmode)
    qp = vstack.ivf_packs.get(qk)
    if qp is None:
        qp = vstack.ivf_packs[qk] = _build_quant_pack(
            vstack, cached, acquire_ivf, acquire_quant, qmode)
    return cached, qp


def _build_knn_program(vstack, *, metric, precision, k, kk, n_queries,
                       used_ivf, nprobe, W, block, nlist, fplan,
                       quant=None, rw=0, pq_m=0):
    mesh = vstack.mesh
    n_pad = vstack.n_pad
    g_pad = vstack.g_pad
    nf_specs = []
    f_op_specs = []
    if fplan is not None:
        _fsig, _mfn, fpctx = fplan
        for _name, kind in fpctx.fields.items():
            nf_specs.extend([P(SHARD_AXIS)] * mesh_exec._FIELD_TENSORS[kind])
        for kind in fpctx.ops:
            kindv = kind[1]
            if kindv == mesh_exec._OP_S:
                f_op_specs.append(P(SHARD_AXIS))
            elif kindv == mesh_exec._OP_SQ:
                f_op_specs.append(P(SHARD_AXIS, None, REPLICA_AXIS))
            elif kindv == mesh_exec._OP_Q:
                f_op_specs.append(P(REPLICA_AXIS))
            else:
                f_op_specs.append(P())

    def step(live, seg_ids, has_f, vecs, *rest):
        live = live[0]                       # [G, N]
        seg_ids = seg_ids[0]                 # [G]
        has_f = has_f[0]                     # [G]
        vecs = vecs[0]                       # [G, N, D]
        i = 0
        rest = list(rest)
        if used_ivf:
            cents, starts, sizes, slot_docs, norms, w_own = \
                (r[0] for r in rest[:6])
            rest = rest[6:]
        if quant:
            q_codes, q_scales = (r[0] for r in rest[:2])
            rest = rest[2:]
        qv = rest[-1]                        # [Qb, D]
        Qb = qv.shape[0]

        # pre-filter mask over the text mesh stack (stats-independent)
        fmask = None
        if fplan is not None:
            _fsig, mfn, fpctx = fplan
            fields = {}
            j = 0
            for name, kind in fpctx.fields.items():
                if kind == "text":
                    fields[name] = mesh_exec.MeshTextField(
                        doc_ids=rest[j][0], tf=rest[j + 1][0],
                        doc_len=rest[j + 2][0])
                    j += 3
                elif kind == "keyword":
                    fields[name] = mesh_exec.MeshKeywordField(
                        ords=rest[j][0])
                    j += 1
                else:
                    fields[name] = mesh_exec.MeshNumericField(
                        vals=rest[j][0], missing=rest[j + 1][0], dtype="")
                    j += 2
            ops = []
            for kind in fpctx.ops:
                blk = rest[j]
                j += 1
                ops.append(blk[0] if kind[1] in (mesh_exec._OP_S,
                                                 mesh_exec._OP_SQ) else blk)
            d = _DevCtx(fields, ops, g_pad, n_pad, Qb)
            fmask = mfn(d)                   # [G, Qb, N]

        eff_live = live[:, None, :] & has_f[:, None, None]
        if fmask is not None:
            eff_live = eff_live & fmask      # [G, Qb, N]
        eff_live = jnp.broadcast_to(eff_live, (g_pad, Qb, n_pad))

        dt = jnp.bfloat16 if precision == "bf16" else jnp.float32
        qc = qv.astype(dt)

        if not used_ivf:
            # exact lane: ops/knn._sim's math. The [G, N, D] block flattens
            # into ONE [Qb, D] x [G*N, D] GEMM — a plain (unbatched)
            # contraction reproduces the per-segment kernel's per-element
            # rounding exactly, where a vmapped batch-GEMM does not
            flat = vecs.reshape(-1, vecs.shape[-1])          # [G*N, D]
            dots = lax.dot_general(
                qc, flat.astype(dt), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)          # [Qb, G*N]
            if metric == "cosine":
                qn = jnp.linalg.norm(qv, axis=1, keepdims=True)
                xn = jnp.linalg.norm(flat, axis=1)
                sims = dots / jnp.maximum(qn * xn[None, :], 1e-12)
            elif metric == "l2":
                qn2 = jnp.sum(qv * qv, axis=1, keepdims=True)
                xn2 = jnp.sum(flat * flat, axis=1)
                sims = -(qn2 + xn2[None, :] - 2.0 * dots)
            else:
                sims = dots
            sims = jnp.moveaxis(
                sims.reshape(Qb, g_pad, n_pad), 1, 0)        # [G, Qb, N]
            sims = jnp.where(eff_live, sims, -jnp.inf)
            top, idx = lax.top_k(sims, kk)                   # [G, Qb, kk]
        else:
            # IVF lane: ops/ann.ivf_search's two stages per segment
            qn_cos = jnp.linalg.norm(qv, axis=1, keepdims=True)
            qn2 = jnp.sum(qv * qv, axis=1, keepdims=True)
            nb = W // block

            scan_k = rw if quant else kk

            def one(v_g, c_g, st_g, sz_g, sd_g, nm_g, w_g, live_g,
                    *qops):
                cc = c_g.astype(dt)
                r_dot = lax.dot_general(
                    qc, cc, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)      # [Qb, nlist]
                if metric == "cosine":
                    cn = jnp.linalg.norm(c_g, axis=1)
                    route = r_dot / jnp.maximum(qn_cos * cn[None, :], 1e-12)
                elif metric == "l2":
                    cn2 = jnp.sum(c_g * c_g, axis=1)
                    route = 2.0 * r_dot - cn2[None, :]
                else:
                    route = r_dot
                _, probe = lax.top_k(route, nprobe)          # [Qb, nprobe]
                t_starts = st_g[probe]
                t_lens = sz_g[probe]
                sidx, t_slot, valid = bm25_ops.postings_slots(t_starts,
                                                              t_lens, W)
                # the segment's OWN budget masks the tail — candidate set
                # == the per-segment kernel's
                valid = valid & (jnp.arange(W, dtype=jnp.int32)[None, :]
                                 < w_g)
                sidx = jnp.clip(sidx, 0, n_pad - 1)
                docs = sd_g[sidx]
                docs = jnp.where(valid, docs, n_pad - 1)
                docs_s = docs.reshape(-1, nb, block).transpose(1, 0, 2)
                valid_s = valid.reshape(-1, nb, block).transpose(1, 0, 2)
                xs = (docs_s, valid_s)
                if quant == "int8":
                    # int8 scan + full-precision rescore: exactly
                    # ops/ann.ivf_search_int8's stages per segment
                    codes_g, scales_g = qops
                    q8, sq = ann_ops.quantize_query_int8(qv, scales_g)
                elif quant == "pq":
                    # ADC scan: exactly ops/ann.ivf_search_pq's stages
                    # per segment — each slot's RAW centroid dot is the
                    # base term, the per-query LUT comes from the
                    # REPLICATED query operand against this segment's
                    # codebooks (one einsum per segment)
                    codes_g, books_g = qops
                    cl = jnp.take_along_axis(
                        probe, jnp.clip(t_slot, 0, nprobe - 1),
                        axis=1)                              # [Qb, W]
                    c_dot = jnp.take_along_axis(r_dot, cl, axis=1)
                    cdot_s = c_dot.reshape(-1, nb, block).transpose(1, 0, 2)
                    xs = (docs_s, valid_s, cdot_s)
                    qsub = qv.reshape(qv.shape[0], pq_m, -1).astype(dt)
                    lut = jnp.einsum(
                        "qmd,mjd->qmj", qsub, books_g.astype(dt),
                        preferred_element_type=jnp.float32)  # [Qb, m, 256]

                def body(carry, x):
                    top_s, top_i = carry
                    if quant == "pq":
                        d_blk, v_blk, cd_blk = x
                        cb = codes_g[d_blk]                  # [Qb, B, m] u8
                        cmb = jnp.moveaxis(cb, 2, 1).astype(jnp.int32)
                        vals = jnp.take_along_axis(lut, cmb, axis=2)
                        sims_b = cd_blk + jnp.sum(vals, axis=1)
                    elif quant == "int8":
                        d_blk, v_blk = x
                        cand8 = codes_g[d_blk]               # [Qb, B, D] i8
                        idot = jnp.einsum(
                            "qd,qbd->qb", q8, cand8,
                            preferred_element_type=jnp.int32)
                        sims_b = sq * idot.astype(jnp.float32)
                    else:
                        d_blk, v_blk = x
                        cand = v_g[d_blk].astype(dt)         # [Qb, B, D]
                        sims_b = jnp.einsum(
                            "qd,qbd->qb", qc, cand,
                            preferred_element_type=jnp.float32)
                    if metric == "cosine":
                        cn_b = nm_g[d_blk]
                        sims_b = sims_b / jnp.maximum(qn_cos * cn_b, 1e-12)
                    elif metric == "l2":
                        xn2 = jnp.square(nm_g[d_blk])
                        sims_b = -(qn2 + xn2 - 2.0 * sims_b)
                    ok = v_blk & jnp.take_along_axis(live_g, d_blk, axis=1)
                    sims_b = jnp.where(ok, sims_b, -jnp.inf)
                    return merge_running_topk(top_s, top_i, sims_b, d_blk,
                                              k=scan_k), None

                carry = (jnp.full((qv.shape[0], scan_k), -jnp.inf,
                                  jnp.float32),
                         jnp.full((qv.shape[0], scan_k), -1, jnp.int32))
                (top_s, top_i), _ = lax.scan(body, carry, xs)
                top_i = jnp.where(jnp.isfinite(top_s), top_i, -1)
                if quant:
                    top_s, top_i = ann_ops.rescore_topk(
                        v_g, nm_g, qv, top_s, top_i, k=kk, metric=metric,
                        precision=precision)
                return top_s, top_i

            if quant:
                top, idx = jax.vmap(one)(vecs, cents, starts, sizes,
                                         slot_docs, norms, w_own, eff_live,
                                         q_codes, q_scales)
            else:
                top, idx = jax.vmap(one)(vecs, cents, starts, sizes,
                                         slot_docs, norms, w_own, eff_live)

        # per-shard merge in segment order (the host merge's stable
        # argsort over [prev, seg] keeps earlier on ties — so does this)
        keys = jnp.where(top > -jnp.inf,
                         (seg_ids[:, None, None] << SEG_SHIFT)
                         | jnp.maximum(idx, 0).astype(jnp.int64),
                         jnp.int64(-1))
        Qb2 = top.shape[1]
        cand_s = jnp.moveaxis(top, 0, 1).reshape(Qb2, -1)
        cand_k = jnp.moveaxis(keys, 0, 1).reshape(Qb2, -1)
        ks = min(k, cand_s.shape[1])
        shard_s, pos = lax.top_k(cand_s, ks)
        shard_k = jnp.take_along_axis(cand_k, pos, axis=1)

        # cross-shard reduce — mesh_exec._build_program's tail verbatim
        g_s = lax.all_gather(shard_s, SHARD_AXIS)
        g_k = lax.all_gather(shard_k, SHARD_AXIS)
        S = g_s.shape[0]
        g_s2 = jnp.transpose(g_s, (1, 0, 2)).reshape(Qb2, S * ks)
        g_k2 = jnp.transpose(g_k, (1, 0, 2)).reshape(Qb2, S * ks)
        out_s, pos2 = lax.top_k(g_s2, min(k, S * ks))
        out_k = jnp.take_along_axis(g_k2, pos2, axis=1)
        valid_o = out_s > -jnp.inf
        out_shard = jnp.where(valid_o, (pos2 // ks).astype(jnp.int32),
                              jnp.int32(-1))
        out_k = jnp.where(valid_o, out_k, jnp.int64(-1))
        total = jnp.sum(eff_live, axis=(0, 2), dtype=jnp.int64)   # [Qb]
        total_g = lax.all_gather(total, SHARD_AXIS)               # [S, Qb]
        mx_g = lax.all_gather(shard_s[:, 0], SHARD_AXIS)          # [S, Qb]
        return out_k, out_shard, out_s, total_g, mx_g

    in_specs = [P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                P(SHARD_AXIS)]
    if used_ivf:
        in_specs.extend([P(SHARD_AXIS)] * 6)
    if quant:
        in_specs.extend([P(SHARD_AXIS)] * 2)
    in_specs.extend(nf_specs)
    in_specs.extend(f_op_specs)
    in_specs.append(P(REPLICA_AXIS))         # qv
    out_specs = (P(REPLICA_AXIS),) * 3 + (P(None, REPLICA_AXIS),) * 2
    return jax.jit(_shard_map(step, mesh=mesh, in_specs=tuple(in_specs),
                              out_specs=out_specs))
