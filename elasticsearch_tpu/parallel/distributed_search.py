"""The SPMD distributed query phase: one compiled program replaces the
reference's scatter-gather network protocol.

Reference flow (SURVEY.md §3.2): coordinator fans per-shard RPCs
("indices:data/read/search[phase/query]"), each data node runs Lucene top-k,
coordinator merges via TopDocs.merge (SearchPhaseController.java:147,233).

TPU-native flow (this module): the whole fan-out/gather is ONE jitted
shard_map over a ("replica", "shard") mesh:

  1. DFS stats all-reduce — psum of per-shard df / doc_count / sum_dl over
     the "shard" axis gives exact global IDF (the reference's optional
     DFS_QUERY_THEN_FETCH phase, search/dfs/DfsPhase.java:57-81, made free:
     it's a tiny psum riding ICI, not an extra network round-trip).
  2. Per-shard batched BM25 via the sort-reduce kernel (ops/bm25_sparse —
     contiguous postings DMAs, no gather/scatter, no [Q, N] score matrix).
  3. Per-shard top-k keys tagged (shard << 32 | local).
  4. Cross-shard reduce — all_gather over "shard" + top_k, the collective
     analog of SearchPhaseController.sortDocs.

total_hits is a psum; max_score a pmax. Queries are sharded over "replica"
so R replica groups serve disjoint slices of the query batch concurrently —
the reference's replica load-balancing (§2.10.2) as an SPMD axis.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops import bm25 as bm25_ops
from ..ops.bm25_sparse import bm25_topk_sparse
from .mesh import SHARD_AXIS, REPLICA_AXIS
from .packed import PackedIndex

K1_DEFAULT = 1.2
B_DEFAULT = 0.75


def _shard_map(fn, *, mesh, in_specs, out_specs):
    """jax.shard_map across jax versions: top-level `check_vma` (new) vs
    experimental `check_rep` (0.4.x) — replica-consistency checks off
    either way (the query batch is INTENTIONALLY different per replica)."""
    try:
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    except (AttributeError, TypeError):
        from jax.experimental.shard_map import shard_map as _sm
        return _sm(fn, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_rep=False)


def _query_step(doc_ids, tf, dl, sum_dl, doc_counts,
                term_starts, term_lens, boosts, *, Wt: int, n_pad: int,
                k: int, k1: float, b: float):
    """Per-device block of the distributed query phase (runs under shard_map;
    leading shard axis of every block is 1 and squeezed here)."""
    doc_ids = doc_ids[0]          # i32[P]
    tf = tf[0]                    # f32[P]
    dl = dl[0]                    # f32[P]
    term_starts = term_starts[0]  # i32[Qb, T]
    term_lens = term_lens[0]      # i32[Qb, T]
    boosts = boosts[0]            # f32[Qb, T]

    # (1) DFS stats all-reduce: exact global IDF via psum over the shard axis
    df_global = lax.psum(term_lens, SHARD_AXIS)                 # i32[Qb, T]
    doc_count_g = lax.psum(doc_counts[0], SHARD_AXIS)           # i32
    sum_dl_g = lax.psum(sum_dl[0], SHARD_AXIS)                  # f32
    avgdl = sum_dl_g / jnp.maximum(doc_count_g.astype(jnp.float32), 1.0)
    weights = (bm25_ops.idf(df_global, doc_count_g) * (k1 + 1.0) * boosts
               ).astype(jnp.float32)

    # (2) per-shard sort-reduce BM25 top-k
    top, docs, hits = bm25_topk_sparse(
        doc_ids, tf, dl, term_starts, term_lens, weights,
        jnp.float32(k1), jnp.float32(b), avgdl,
        Wt=Wt, k=k, n_docs=n_pad)

    # (3) globally-addressable keys
    my_shard = lax.axis_index(SHARD_AXIS).astype(jnp.int64)
    keys = jnp.where(top > -jnp.inf,
                     (my_shard << 32) | docs.astype(jnp.int64),
                     jnp.int64(-1))

    # (4) cross-shard top-k reduce (SearchPhaseController.sortDocs as a
    # collective): all_gather candidate sets, reduce to global top-k
    g_scores = lax.all_gather(top, SHARD_AXIS)                  # [S, Qb, kk]
    g_keys = lax.all_gather(keys, SHARD_AXIS)
    S, Qb, kk = g_scores.shape
    g_scores = jnp.transpose(g_scores, (1, 0, 2)).reshape(Qb, S * kk)
    g_keys = jnp.transpose(g_keys, (1, 0, 2)).reshape(Qb, S * kk)
    out_scores, pos = lax.top_k(g_scores, min(k, S * kk))
    out_keys = jnp.take_along_axis(g_keys, pos, axis=-1)

    total = lax.psum(hits.astype(jnp.int64), SHARD_AXIS)
    max_score = lax.pmax(top[:, 0], SHARD_AXIS)
    return out_scores, out_keys, total, max_score


@dataclass
class DistributedSearcher:
    """Compiled distributed query phase over a packed index + mesh."""
    index: PackedIndex
    mesh: jax.sharding.Mesh

    def __post_init__(self):
        # jit caches by function identity — memoize compiled steps per
        # static config or every search would retrace + recompile.
        # A bounded common.cache.Cache, not a bare dict: step configs are
        # user-driven (k, Wt vary per request shape) and an unbounded memo
        # is a slow leak (tests/test_cache_lint.py tripwire)
        from ..common.cache import Cache
        self._step_cache = Cache("dist_steps", max_entries=64)

    def place(self):
        """Shard the packed index onto the mesh (one device_put per array;
        after this, queries run with zero host→device index traffic)."""
        from .mesh import index_sharding
        sh = index_sharding(self.mesh)
        self.index.live = jax.device_put(self.index.live, sh)
        self.index.doc_counts = jax.device_put(self.index.doc_counts, sh)
        for f in self.index.text.values():
            f.doc_ids = jax.device_put(f.doc_ids, sh)
            f.tf = jax.device_put(f.tf, sh)
            f.dl = jax.device_put(f.dl, sh)
            f.sum_dl = jax.device_put(f.sum_dl, sh)
        for v in (self.index.vectors or {}).values():
            v.vecs = jax.device_put(v.vecs, sh)
        return self

    def build_step(self, *, Wt: int, k: int,
                   k1: float = K1_DEFAULT, b: float = B_DEFAULT):
        """jit(shard_map) of the query step, memoized per static config."""
        key = (Wt, k, k1, b)
        cached = self._step_cache.get(key)
        if cached is not None:
            return cached
        n_pad = self.index.n_pad
        fn = functools.partial(_query_step, Wt=Wt, n_pad=n_pad, k=k,
                               k1=k1, b=b)
        shard_specs = P(SHARD_AXIS)
        query_specs = P(SHARD_AXIS, REPLICA_AXIS)
        out_specs = (P(REPLICA_AXIS), P(REPLICA_AXIS),
                     P(REPLICA_AXIS), P(REPLICA_AXIS))
        mapped = _shard_map(
            fn, mesh=self.mesh,
            in_specs=(shard_specs,) * 5 + (query_specs,) * 3,
            out_specs=out_specs)
        from ..common.device_stats import instrument
        step = instrument("dist:query_step", jax.jit(mapped), key=key)
        self._step_cache.put(key, step, weight=1)
        return step

    def build_knn_step(self, *, k: int, metric: str = "cosine"):
        """Distributed exact kNN: per-shard MXU matmul top-k + the same
        all_gather cross-shard reduce as text search. One compiled program
        for the whole mesh."""
        key = ("knn", k, metric)
        cached = self._step_cache.get(key)
        if cached is not None:
            return cached

        def knn_step(vecs, live, qv, q_valid):
            from ..ops import knn as knn_ops
            vecs = vecs[0]            # [N, D]
            live_b = live[0]          # [N]
            sims = knn_ops._sim(qv, vecs, metric)
            sims = jnp.where(live_b[None, :], sims, -jnp.inf)
            # replica-padding rows are all-zero query vectors: cosine on
            # them divides 0 by ~0, and a NaN lane would poison the
            # top-k/keys math below — mask pad rows INSIDE the step so
            # they contribute -inf (no hits), not NaN
            sims = jnp.where(q_valid[:, None], sims, -jnp.inf)
            top, idx = lax.top_k(sims, k)
            my_shard = lax.axis_index(SHARD_AXIS).astype(jnp.int64)
            keys = jnp.where(top > -jnp.inf,
                             (my_shard << 32) | idx.astype(jnp.int64),
                             jnp.int64(-1))
            g_s = lax.all_gather(top, SHARD_AXIS)
            g_k = lax.all_gather(keys, SHARD_AXIS)
            S, Qb, kk = g_s.shape
            g_s = jnp.transpose(g_s, (1, 0, 2)).reshape(Qb, S * kk)
            g_k = jnp.transpose(g_k, (1, 0, 2)).reshape(Qb, S * kk)
            out_s, pos = lax.top_k(g_s, min(k, S * kk))
            return out_s, jnp.take_along_axis(g_k, pos, axis=-1)

        from ..common.device_stats import instrument
        step = instrument(
            "dist:knn_step",
            jax.jit(_shard_map(
                knn_step, mesh=self.mesh,
                in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(REPLICA_AXIS),
                          P(REPLICA_AXIS)),
                out_specs=(P(REPLICA_AXIS), P(REPLICA_AXIS)))),
            key=key)
        self._step_cache.put(key, step, weight=1)
        return step

    def search_knn(self, field: str, query_vectors, *, k: int = 10,
                   metric: str = "cosine"):
        """-> (scores f32[Q,k], keys i64[Q,k])."""
        from ..common.metrics import current_profiler
        vf = self.index.vectors[field]
        n_rep = self.mesh.shape[REPLICA_AXIS]
        qv = np.asarray(query_vectors, np.float32)
        Q = qv.shape[0]
        q_pad = -(-Q // n_rep) * n_rep
        if q_pad != Q:
            qv = np.concatenate([qv, np.zeros((q_pad - Q, qv.shape[1]),
                                              np.float32)])
        q_valid = np.zeros((q_pad,), bool)
        q_valid[:Q] = True
        step = self.build_knn_step(k=k, metric=metric)
        prof = current_profiler()
        from ..common.metrics import note_h2d
        note_h2d(qv.nbytes)
        if prof is not None:
            with prof.phase("spmd_query"):
                scores, keys = step(vf.vecs, self.index.live,
                                    jnp.asarray(qv), jnp.asarray(q_valid))
                scores, keys = np.asarray(scores), np.asarray(keys)
            prof.note_dispatch()
            prof.note_d2h(scores.nbytes + keys.nbytes)
            return scores[:Q], keys[:Q]
        scores, keys = step(vf.vecs, self.index.live, jnp.asarray(qv),
                            jnp.asarray(q_valid))
        return np.asarray(scores)[:Q], np.asarray(keys)[:Q]

    def search_terms(self, field: str, queries: list[list[str]], *,
                     k: int = 10, boosts: np.ndarray | None = None,
                     k1: float = K1_DEFAULT, b: float = B_DEFAULT):
        """End-to-end: host query prep -> device SPMD step -> host results.

        Returns (scores f32[Q,k], keys i64[Q,k], total i64[Q], max f32[Q]).
        """
        fx = self.index.text[field]
        n_rep = self.mesh.shape[REPLICA_AXIS]
        Q = len(queries)
        q_pad = -(-Q // n_rep) * n_rep
        queries = queries + [[] for _ in range(q_pad - Q)]
        ts, tl = self.index.prepare_term_queries(field, queries)
        Wt = self.index.slot_budget(tl)
        if boosts is None:
            bsts = jnp.ones(ts.shape, jnp.float32)
        else:
            b_arr = np.ones((q_pad,) + boosts.shape[1:], np.float32)
            b_arr[:Q] = boosts
            bsts = jnp.broadcast_to(jnp.asarray(b_arr)[None], ts.shape)
        step = self.build_step(Wt=Wt, k=k, k1=k1, b=b)
        from ..common.metrics import current_profiler, note_h2d
        prof = current_profiler()
        # term tables + boosts are this request's host→device upload;
        # the SPMD program's result fetch is its device→host leg
        note_h2d(ts.nbytes + tl.nbytes + bsts.nbytes)
        if prof is not None:
            with prof.phase("spmd_query"):
                scores, keys, total, mx = step(
                    fx.doc_ids, fx.tf, fx.dl, fx.sum_dl,
                    self.index.doc_counts, ts, tl, bsts)
                scores, keys, total, mx = (np.asarray(scores),
                                           np.asarray(keys),
                                           np.asarray(total),
                                           np.asarray(mx))
            prof.note_dispatch()
            prof.note_d2h(scores.nbytes + keys.nbytes
                          + total.nbytes + mx.nbytes)
            return scores[:Q], keys[:Q], total[:Q], mx[:Q]
        scores, keys, total, mx = step(
            fx.doc_ids, fx.tf, fx.dl, fx.sum_dl, self.index.doc_counts,
            ts, tl, bsts)
        return (np.asarray(scores)[:Q], np.asarray(keys)[:Q],
                np.asarray(total)[:Q], np.asarray(mx)[:Q])
