"""Mesh parallelism: doc routing, packed shard tensors, SPMD search.

The data-plane replacement for the reference's scatter-gather RPC protocol
(SURVEY.md §2.10, §5.8): shards and replicas are mesh axes, reduces are XLA
collectives over ICI instead of coordinator merge loops.
"""

from .routing import djb_hash, shard_id, select_copy
from .mesh import make_mesh, index_sharding, query_sharding, replicated, \
    SHARD_AXIS, REPLICA_AXIS
from .packed import PackedIndex, PackedTextField
from .distributed_search import DistributedSearcher

__all__ = [
    "djb_hash", "shard_id", "select_copy",
    "make_mesh", "index_sharding", "query_sharding", "replicated",
    "SHARD_AXIS", "REPLICA_AXIS",
    "PackedIndex", "PackedTextField", "DistributedSearcher",
]
