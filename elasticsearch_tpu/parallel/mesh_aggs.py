"""Aggregation partials inside the mesh program (ISSUE 11 tentpole (b)).

Until now agg bodies declined the mesh lane: the coordinator fell back to
the per-shard fan-out, paid S device fetches and merged host-side wire
partials — exactly the flat-vs-linear reduce the device wins (ROADMAP
item 1). This module plans the SUPPORTED slice of the agg tree into device
closures that run inside the shard_map body of parallel/mesh_exec.py,
right after the query mask is computed:

    m = match & live                 # [G, Q, N] — the same mask the
                                     # per-shard collect gates on
    counts  = one-hot / affine-bucket contractions over m (exact ints)
    metrics = fused (count, sum, sum_sq, min, max) rows per segment

and `all_gather`s the per-shard partial tensors over the "shard" axis so
they ride the SAME single device fetch as the top-k reduce. Count tensors
are exact integers, so summing them on device (or host) reproduces the
per-shard dict merge bit-for-bit; f64 metric rows stay per-SEGMENT in the
gathered output and merge on host in segment order — float addition is
not associative, and the fan-out merges in exactly that order.

Supported: terms (keyword field), histogram / date_histogram (numeric,
fixed interval), range (non-date), and the metric family min / max / sum /
avg / value_count / stats / extended_stats (numeric) — all without
sub-aggregations. Anything else returns None and the caller falls down
the existing ladder (mesh -> fan-out -> per-segment loop).
"""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

# operand placement kinds — mirrors mesh_exec's _OP_S/_OP_Q/_OP_R values
# (imported lazily there; literals here avoid a circular module import)
_OP_S = "s"
_OP_Q = "q"
_OP_R = "r"

# bin caps: past these the per-shard fan-out's own device/host ladder is
# the better place to be (and the fan-out is what we decline to)
_MAX_TERMS_BINS = 1 << 12
_MAX_HIST_BINS = 1 << 14          # aggregators._MAX_DEVICE_BINS

_METRIC_TYPES = {"min", "max", "sum", "avg", "value_count", "stats",
                 "extended_stats"}


class AggMeshPlan:
    """One planned agg tree: `devfns` run inside the shard_map body (each
    returns a [Qb, ...] tensor that the program all_gathers to [S, Qb,
    ...]), `finish(outs, q_row)` turns the fetched host arrays back into
    per-shard partial dicts — the exact wire shapes the fan-out's
    `collect_shard` produces."""

    def __init__(self, specs, devfns, finishers, sig):
        self.specs = specs
        self.devfns = devfns          # list[callable(d, m) -> tensor]
        self.finishers = finishers    # list[callable(np_out, q) -> [dict]]
        self.sig = sig                # static program-key component

    def device_fns(self):
        """The closures that actually run on device (absent-field specs
        have none — their partials are constant)."""
        return [fn for fn in self.devfns if fn is not None]

    def finish(self, outs, s_count: int, q_row: int = 0) -> list[dict]:
        """outs: fetched np arrays aligned with device_fns() -> one partial
        dict per shard (index-aligned with the stack's shard rows)."""
        per_shard: list[dict] = [{} for _ in range(s_count)]
        it = iter(outs)
        for spec, dev, fin in zip(self.specs, self.devfns, self.finishers):
            out = next(it) if dev is not None else None
            parts = fin(out, q_row)
            for si in range(s_count):
                per_shard[si][spec.name] = parts[si]
        return per_shard


def _supported_type(spec) -> bool:
    return spec.type in ({"terms", "histogram", "date_histogram", "range"}
                         | _METRIC_TYPES)


def plan_aggs(specs, pctx) -> AggMeshPlan | None:
    """Plan the agg list against a mesh _PlanCtx (parallel/mesh_exec). The
    plan emits its operands through `pctx` AFTER the query tree has been
    planned, so the device op iterator pops query ops first, agg ops
    second. None = some spec has no mesh form -> the whole query falls
    back to the fan-out."""
    if not specs:
        return None
    devfns, finishers, sigs = [], [], []
    for spec in specs:
        if spec.subs or not _supported_type(spec):
            return None
        try:
            if spec.type == "terms":
                planned = _plan_terms(spec, pctx)
            elif spec.type in ("histogram", "date_histogram"):
                planned = _plan_histogram(spec, pctx)
            elif spec.type == "range":
                planned = _plan_range(spec, pctx)
            else:
                planned = _plan_metric(spec, pctx)
        except _Unsupported:
            return None
        sig, dev, fin = planned
        sigs.append(sig)
        devfns.append(dev)
        finishers.append(fin)
    return AggMeshPlan(specs, devfns, finishers, tuple(sigs))


class _Unsupported(Exception):
    pass


def _empty_terms():
    return {"buckets": {}, "other_doc_count": 0, "error_bound": 0}


def _plan_terms(spec, pctx):
    """terms on a keyword field: per-(shard, segment) ordinals remap onto a
    GLOBAL vocabulary (the host-built [S, G, Vpad] remap operand), counts
    are one one-hot contraction per segment row summed over the segment
    axis — exact integers, so the gathered [S, Q, n_bins] tensor equals
    the per-shard dict merge."""
    stack = pctx.stack
    field = spec.params.get("field")
    if not field or field in stack.mixed:
        raise _Unsupported(f"terms field [{field}]")
    if field not in stack.keywords:
        if field in stack.text or field in stack.numerics:
            # analyzed-text / numeric terms keep the host collect's
            # np.unique semantics — fan-out territory
            raise _Unsupported(f"terms over non-keyword [{field}]")
        # absent everywhere: every shard reports the empty partial
        sig = ("terms_absent",)
        return (sig, None,
                lambda out, q: [_empty_terms()
                                for _ in range(stack.s_count)])
    vocab: list[str] = sorted({v for rows in stack.shard_rows
                               for _i, seg in rows
                               for v in (seg.keywords.get(field).values
                                         if seg.keywords.get(field)
                                         else ())})
    n_bins = len(vocab)
    if n_bins == 0:
        sig = ("terms_absent",)
        return (sig, None,
                lambda out, q: [_empty_terms()
                                for _ in range(stack.s_count)])
    if n_bins > _MAX_TERMS_BINS:
        raise _Unsupported(f"terms vocab [{n_bins}]")
    bin_of = {v: i for i, v in enumerate(vocab)}
    v_pad = max(max((len(seg.keywords[field].values)
                     for rows in stack.shard_rows for _i, seg in rows
                     if field in seg.keywords), default=1), 1)
    remap = np.full((stack.s_pad, stack.g_pad, v_pad), n_bins, np.int32)
    for si, rows in enumerate(stack.shard_rows):
        for gi, (_i, seg) in enumerate(rows):
            kc = seg.keywords.get(field)
            if kc is None:
                continue
            for o, v in enumerate(kc.values):
                remap[si, gi, o] = bin_of[v]
    pctx.use_field(field, "keyword")
    pctx.emit(remap, _OP_S)
    sig = ("terms", field, n_bins, v_pad)

    def dev(d, m):
        rmp = d.pop()                            # [G, Vpad]
        ords = d.fields[field].ords              # [G, N]
        gid = jnp.where(
            ords >= 0,
            jnp.take_along_axis(rmp, jnp.maximum(ords, 0).astype(jnp.int32),
                                axis=1),
            jnp.int32(n_bins))                   # [G, N]

        def one(gid_g, m_g):                     # [N], [Qb, N]
            oh = (gid_g[:, None]
                  == jnp.arange(n_bins, dtype=jnp.int32)[None, :])
            return jax.lax.dot_general(
                m_g.astype(jnp.float32), oh.astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        return jax.vmap(one)(gid, m).sum(axis=0).astype(jnp.int32)

    from ..search.aggs.aggregators import terms_partial_from_counts

    def fin(out, q):                             # out: [S, Qb, n_bins]
        parts = []
        for si in range(stack.s_count):
            row = out[si, q]
            counts = {vocab[b]: int(row[b])
                      for b in np.nonzero(row)[0]}
            parts.append(terms_partial_from_counts(spec, counts))
        return parts

    return sig, dev, fin


def _plan_histogram(spec, pctx):
    """histogram / fixed-interval date_histogram: bucket id is an affine
    transform of the column per segment (per-segment base from the cached
    column min — exactly `_device_histogram`'s keys), counts stay
    per-SEGMENT in the output so each shard rebuilds the same key->count
    dicts the per-segment device collect produced."""
    from ..search.aggs.aggregators import (_col_minmax, _fixed_interval_ms)
    stack = pctx.stack
    field = spec.params.get("field")
    if not field or field in stack.mixed:
        raise _Unsupported(f"histogram field [{field}]")
    if spec.type == "date_histogram":
        interval = _fixed_interval_ms(spec.params.get("interval", "1d"))
        if interval is None:
            raise _Unsupported("calendar interval")
    else:
        interval = float(spec.params["interval"])
    if interval <= 0:
        raise _Unsupported("non-positive interval")
    if field not in stack.numerics:
        sig = ("hist_absent",)
        return (sig, None,
                lambda out, q: [{"buckets": {}}
                                for _ in range(stack.s_count)])
    pctx.use_field(field, "numeric")
    bases = np.zeros((stack.s_pad, stack.g_pad), np.float64)
    hvalid = np.zeros((stack.s_pad, stack.g_pad), bool)
    n_bins = 1
    for si, rows in enumerate(stack.shard_rows):
        for gi, (_i, seg) in enumerate(rows):
            nc = seg.numerics.get(field)
            if nc is None:
                continue
            mn, mx = _col_minmax(seg, field, nc)
            if not (np.isfinite(mn) and np.isfinite(mx)):
                continue              # empty column: zero contribution
            base = math.floor(mn / interval) * interval
            bins = int((mx - base) // interval) + 1
            if bins > _MAX_HIST_BINS:
                # the fan-out's own device collect declines this too; keep
                # the two lanes on the same ladder rung
                raise _Unsupported(f"histogram bins [{bins}]")
            bases[si, gi] = base
            hvalid[si, gi] = True
            n_bins = max(n_bins, bins)
    pctx.emit(bases, _OP_S)
    pctx.emit(hvalid, _OP_S)
    sig = (spec.type, field, float(interval), n_bins)

    def dev(d, m):
        base = d.pop()                           # [G]
        ok_g = d.pop()                           # [G]
        num = d.fields[field]
        idx = jnp.floor((num.vals.astype(jnp.float64)
                         - base[:, None]) / interval).astype(jnp.int32)
        ok = (~num.missing) & (idx >= 0) & (idx < n_bins) \
            & ok_g[:, None]                      # [G, N]

        def one(idx_g, ok_g2, m_g):              # [N], [N], [Qb, N]
            sel = m_g & ok_g2[None, :]
            safe = jnp.where(ok_g2, idx_g, n_bins)
            oh = (safe[:, None]
                  == jnp.arange(n_bins, dtype=jnp.int32)[None, :])
            return jax.lax.dot_general(
                sel.astype(jnp.float32), oh.astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        # [G, Qb, n_bins] -> [Qb, G, n_bins]: per-SEGMENT counts survive
        # so host keys rebuild from each segment's own base
        return jnp.moveaxis(jax.vmap(one)(idx, ok, m), 0, 1) \
            .astype(jnp.int32)

    def fin(out, q):                             # out: [S, Qb, G, n_bins]
        parts = []
        for si in range(stack.s_count):
            buckets: dict = {}
            for gi in range(len(stack.shard_rows[si])):
                if not hvalid[si, gi]:
                    continue
                row = out[si, q, gi]
                base = bases[si, gi]
                for i in np.nonzero(row)[0]:
                    key = float(base + i * interval)
                    ent = buckets.get(key)
                    if ent is None:
                        buckets[key] = {"doc_count": int(row[i])}
                    else:
                        ent["doc_count"] += int(row[i])
            parts.append({"buckets": buckets})
        return parts

    return sig, dev, fin


def _plan_range(spec, pctx):
    """range (non-date): bounds are query-derived and uniform across
    segments, so per-shard counts sum over the segment axis on device."""
    from ..search.aggs.aggregators import _range_bounds
    stack = pctx.stack
    field = spec.params.get("field")
    if not field or field in stack.mixed:
        raise _Unsupported(f"range field [{field}]")
    bounds = _range_bounds(spec.params, is_date=False)
    if bounds is None:
        raise _Unsupported("empty ranges")
    keys, los, his = bounds
    if field not in stack.numerics:
        sig = ("range_absent",)
        return (sig, None,
                lambda out, q: [{"buckets": {}}
                                for _ in range(stack.s_count)])
    pctx.use_field(field, "numeric")
    pctx.emit(los, _OP_R)   # request-global bounds: replicated operands
    pctx.emit(his, _OP_R)
    sig = ("range", field, len(keys))

    def dev(d, m):
        lo_b, hi_b = d.pop(), d.pop()            # [R]
        num = d.fields[field]
        v = num.vals.astype(jnp.float64)         # [G, N]
        inr = (~num.missing)[:, None, :] \
            & (v[:, None, :] >= lo_b[None, :, None]) \
            & (v[:, None, :] < hi_b[None, :, None])        # [G, R, N]
        # [G, Qb, R] summed over G and N -> [Qb, R]
        return jnp.einsum("gqn,grn->qr", m.astype(jnp.int64),
                          inr.astype(jnp.int64))

    def fin(out, q):                             # out: [S, Qb, R]
        parts = []
        for si in range(stack.s_count):
            row = out[si, q]
            parts.append({"buckets": {
                key: {"doc_count": int(row[ri]), "from": lo, "to": hi}
                for ri, (key, lo, hi) in enumerate(keys)}})
        return parts

    return sig, dev, fin


def _plan_metric(spec, pctx):
    """min/max/sum/avg/value_count/stats/extended_stats on a numeric
    column: fused per-(segment, query) 5-vectors — `masked_stats`'s exact
    math over the mesh-padded column (appended zero padding is exact under
    f64 accumulation) — merged on HOST in segment order, because float
    addition is order-sensitive and the fan-out merges in that order."""
    stack = pctx.stack
    field = spec.params.get("field")
    if not field or field in stack.mixed:
        raise _Unsupported(f"metric field [{field}]")

    def empty():
        return {"count": 0, "sum": 0.0, "min": math.inf,
                "max": -math.inf, "sum_sq": 0.0}

    if field not in stack.numerics:
        sig = ("metric_absent", spec.type)
        return (sig, None,
                lambda out, q: [empty() for _ in range(stack.s_count)])
    pctx.use_field(field, "numeric")
    sig = ("metric", field)

    def dev(d, m):
        num = d.fields[field]

        def one(vals_g, miss_g, m_g):            # [N], [N], [Qb, N]
            sel = m_g & ~miss_g[None, :]
            v = vals_g.astype(jnp.float64)[None, :]
            vz = jnp.where(sel, v, 0.0)
            cnt = sel.sum(axis=1).astype(jnp.float64)
            s = vz.sum(axis=1)
            ss = (vz * vz).sum(axis=1)
            mn = jnp.where(sel, v, jnp.inf).min(axis=1)
            mx = jnp.where(sel, v, -jnp.inf).max(axis=1)
            return jnp.stack([cnt, s, ss, mn, mx], axis=1)   # [Qb, 5]

        # [G, Qb, 5] -> [Qb, G, 5]
        return jnp.moveaxis(
            jax.vmap(one)(num.vals, num.missing, m), 0, 1)

    from ..search.aggs.aggregators import merge_partial

    def fin(out, q):                             # out: [S, Qb, G, 5]
        parts = []
        for si in range(stack.s_count):
            merged = None
            for gi in range(len(stack.shard_rows[si])):
                cnt, s, ss, mn, mx = out[si, q, gi]
                part = {"count": int(cnt), "sum": float(s),
                        "sum_sq": float(ss),
                        "min": float(mn) if cnt else math.inf,
                        "max": float(mx) if cnt else -math.inf}
                merged = part if merged is None \
                    else merge_partial(spec, merged, part)
            parts.append(merged if merged is not None else empty())
        return parts

    return sig, dev, fin
