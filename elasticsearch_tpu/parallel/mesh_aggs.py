"""Aggregation partials inside the mesh program (ISSUE 11 tentpole (b)).

Until now agg bodies declined the mesh lane: the coordinator fell back to
the per-shard fan-out, paid S device fetches and merged host-side wire
partials — exactly the flat-vs-linear reduce the device wins (ROADMAP
item 1). This module plans the SUPPORTED slice of the agg tree into device
closures that run inside the shard_map body of parallel/mesh_exec.py,
right after the query mask is computed:

    m = match & live                 # [G, Q, N] — the same mask the
                                     # per-shard collect gates on
    counts  = one-hot / affine-bucket contractions over m (exact ints)
    metrics = fused (count, sum, sum_sq, min, max) rows per segment

and `all_gather`s the per-shard partial tensors over the "shard" axis so
they ride the SAME single device fetch as the top-k reduce. Count tensors
are exact integers, so summing them on device (or host) reproduces the
per-shard dict merge bit-for-bit; f64 metric rows stay per-SEGMENT in the
gathered output and merge on host in segment order — float addition is
not associative, and the fan-out merges in exactly that order.

Supported: terms (keyword field), histogram / date_histogram (numeric,
fixed interval), range (non-date), and the metric family min / max / sum /
avg / value_count / stats / extended_stats (numeric). Sub-aggregation
TREES (ISSUE 17 tentpole (b)) flatten into composite bins on device:
a `date_histogram -> terms -> avg` tree becomes one per-doc composite
bin id (`parent_bin * child_bins + child_bin`), one exact-int bincount
per (segment, level) and one fused 5-vector stats row per (segment,
composite bin, metric leaf) — `finish` rebuilds the per-shard nested
partial dicts with the host collect's own truncation/merge code, so the
wire partials stay bit-identical to the fan-out. Trees that cannot be
reproduced bitwise decline with a stable reason (`calendar_interval`,
`float_histogram`, `subagg_bins`, `unsupported_child`) and the caller
falls down the existing ladder (mesh -> fan-out -> per-segment loop).
"""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

# operand placement kinds — mirrors mesh_exec's _OP_S/_OP_Q/_OP_R values
# (imported lazily there; literals here avoid a circular module import)
_OP_S = "s"
_OP_Q = "q"
_OP_R = "r"

# bin caps: past these the per-shard fan-out's own device/host ladder is
# the better place to be (and the fan-out is what we decline to)
_MAX_TERMS_BINS = 1 << 12
_MAX_HIST_BINS = 1 << 14          # aggregators._MAX_DEVICE_BINS

_METRIC_TYPES = {"min", "max", "sum", "avg", "value_count", "stats",
                 "extended_stats"}


class AggMeshPlan:
    """One planned agg tree: `devfns` run inside the shard_map body (each
    returns a [Qb, ...] tensor that the program all_gathers to [S, Qb,
    ...]), `finish(outs, q_row)` turns the fetched host arrays back into
    per-shard partial dicts — the exact wire shapes the fan-out's
    `collect_shard` produces."""

    def __init__(self, specs, devfns, finishers, sig):
        self.specs = specs
        self.devfns = devfns          # list[callable(d, m) -> tensor]
        self.finishers = finishers    # list[callable(np_out, q) -> [dict]]
        self.sig = sig                # static program-key component

    def device_fns(self):
        """The closures that actually run on device (absent-field specs
        have none — their partials are constant)."""
        return [fn for fn in self.devfns if fn is not None]

    def finish(self, outs, s_count: int, q_row: int = 0) -> list[dict]:
        """outs: fetched np arrays aligned with device_fns() -> one partial
        dict per shard (index-aligned with the stack's shard rows)."""
        per_shard: list[dict] = [{} for _ in range(s_count)]
        it = iter(outs)
        for spec, dev, fin in zip(self.specs, self.devfns, self.finishers):
            out = next(it) if dev is not None else None
            parts = fin(out, q_row)
            for si in range(s_count):
                per_shard[si][spec.name] = parts[si]
        return per_shard


def _supported_type(spec) -> bool:
    return spec.type in ({"terms", "histogram", "date_histogram", "range"}
                         | _METRIC_TYPES)


def plan_aggs(specs, pctx) -> AggMeshPlan | None:
    """Plan the agg list against a mesh _PlanCtx (parallel/mesh_exec). The
    plan emits its operands through `pctx` AFTER the query tree has been
    planned, so the device op iterator pops query ops first, agg ops
    second. None = some spec has no mesh form -> the whole query falls
    back to the fan-out."""
    if not specs:
        return None
    devfns, finishers, sigs = [], [], []
    for spec in specs:
        if not spec.subs and not _supported_type(spec):
            if spec.type == "composite":
                # composite paginates over the GLOBALLY merged bucket
                # space — a per-shard device tensor cannot carry the
                # after-key cursor, so the fan-out (whose host collect
                # factorizes key tuples per segment) is the documented
                # lane; named decline for the explain surface
                from ..common.device_stats import lane_decline
                lane_decline("coordinator.aggs", "mesh", "composite")
            return None
        try:
            if spec.subs:
                planned = _plan_subagg_tree(spec, pctx)
            elif spec.type == "terms":
                planned = _plan_terms(spec, pctx)
            elif spec.type in ("histogram", "date_histogram"):
                planned = _plan_histogram(spec, pctx)
            elif spec.type == "range":
                planned = _plan_range(spec, pctx)
            else:
                planned = _plan_metric(spec, pctx)
        except _Unsupported as e:
            if spec.subs:
                # stable decline reasons for the lane-explain surface —
                # the fan-out remains the documented fallback
                from ..common.device_stats import lane_decline
                lane_decline("coordinator.aggs", "mesh", e.reason)
            return None
        sig, dev, fin = planned
        sigs.append(sig)
        devfns.append(dev)
        finishers.append(fin)
    return AggMeshPlan(specs, devfns, finishers, tuple(sigs))


class _Unsupported(Exception):
    def __init__(self, msg: str = "", reason: str = "agg_shape"):
        super().__init__(msg)
        self.reason = reason


def _empty_terms():
    return {"buckets": {}, "other_doc_count": 0, "error_bound": 0}


def _plan_terms(spec, pctx):
    """terms on a keyword field: per-(shard, segment) ordinals remap onto a
    GLOBAL vocabulary (the host-built [S, G, Vpad] remap operand), counts
    are one one-hot contraction per segment row summed over the segment
    axis — exact integers, so the gathered [S, Q, n_bins] tensor equals
    the per-shard dict merge."""
    stack = pctx.stack
    field = spec.params.get("field")
    if not field or field in stack.mixed:
        raise _Unsupported(f"terms field [{field}]")
    if field not in stack.keywords:
        if field in stack.text or field in stack.numerics:
            # analyzed-text / numeric terms keep the host collect's
            # np.unique semantics — fan-out territory
            raise _Unsupported(f"terms over non-keyword [{field}]")
        # absent everywhere: every shard reports the empty partial
        sig = ("terms_absent",)
        return (sig, None,
                lambda out, q: [_empty_terms()
                                for _ in range(stack.s_count)])
    vocab: list[str] = sorted({v for rows in stack.shard_rows
                               for _i, seg in rows
                               for v in (seg.keywords.get(field).values
                                         if seg.keywords.get(field)
                                         else ())})
    n_bins = len(vocab)
    if n_bins == 0:
        sig = ("terms_absent",)
        return (sig, None,
                lambda out, q: [_empty_terms()
                                for _ in range(stack.s_count)])
    if n_bins > _MAX_TERMS_BINS:
        raise _Unsupported(f"terms vocab [{n_bins}]")
    bin_of = {v: i for i, v in enumerate(vocab)}
    v_pad = max(max((len(seg.keywords[field].values)
                     for rows in stack.shard_rows for _i, seg in rows
                     if field in seg.keywords), default=1), 1)
    remap = np.full((stack.s_pad, stack.g_pad, v_pad), n_bins, np.int32)
    for si, rows in enumerate(stack.shard_rows):
        for gi, (_i, seg) in enumerate(rows):
            kc = seg.keywords.get(field)
            if kc is None:
                continue
            for o, v in enumerate(kc.values):
                remap[si, gi, o] = bin_of[v]
    pctx.use_field(field, "keyword")
    pctx.emit(remap, _OP_S)
    sig = ("terms", field, n_bins, v_pad)

    def dev(d, m):
        rmp = d.pop()                            # [G, Vpad]
        ords = d.fields[field].ords              # [G, N]
        gid = jnp.where(
            ords >= 0,
            jnp.take_along_axis(rmp, jnp.maximum(ords, 0).astype(jnp.int32),
                                axis=1),
            jnp.int32(n_bins))                   # [G, N]

        def one(gid_g, m_g):                     # [N], [Qb, N]
            oh = (gid_g[:, None]
                  == jnp.arange(n_bins, dtype=jnp.int32)[None, :])
            return jax.lax.dot_general(
                m_g.astype(jnp.float32), oh.astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        return jax.vmap(one)(gid, m).sum(axis=0).astype(jnp.int32)

    from ..search.aggs.aggregators import terms_partial_from_counts

    def fin(out, q):                             # out: [S, Qb, n_bins]
        parts = []
        for si in range(stack.s_count):
            row = out[si, q]
            counts = {vocab[b]: int(row[b])
                      for b in np.nonzero(row)[0]}
            parts.append(terms_partial_from_counts(spec, counts))
        return parts

    return sig, dev, fin


def _plan_histogram(spec, pctx):
    """histogram / fixed-interval date_histogram: bucket id is an affine
    transform of the column per segment (per-segment base from the cached
    column min — exactly `_device_histogram`'s keys), counts stay
    per-SEGMENT in the output so each shard rebuilds the same key->count
    dicts the per-segment device collect produced."""
    from ..search.aggs.aggregators import (_col_minmax, _fixed_interval_ms)
    stack = pctx.stack
    field = spec.params.get("field")
    if not field or field in stack.mixed:
        raise _Unsupported(f"histogram field [{field}]")
    if spec.type == "date_histogram":
        interval = _fixed_interval_ms(spec.params.get("interval", "1d"))
        if interval is None:
            raise _Unsupported("calendar interval")
    else:
        interval = float(spec.params["interval"])
    if interval <= 0:
        raise _Unsupported("non-positive interval")
    if field not in stack.numerics:
        sig = ("hist_absent",)
        return (sig, None,
                lambda out, q: [{"buckets": {}}
                                for _ in range(stack.s_count)])
    pctx.use_field(field, "numeric")
    bases = np.zeros((stack.s_pad, stack.g_pad), np.float64)
    hvalid = np.zeros((stack.s_pad, stack.g_pad), bool)
    n_bins = 1
    for si, rows in enumerate(stack.shard_rows):
        for gi, (_i, seg) in enumerate(rows):
            nc = seg.numerics.get(field)
            if nc is None:
                continue
            mn, mx = _col_minmax(seg, field, nc)
            if not (np.isfinite(mn) and np.isfinite(mx)):
                continue              # empty column: zero contribution
            base = math.floor(mn / interval) * interval
            bins = int((mx - base) // interval) + 1
            if bins > _MAX_HIST_BINS:
                # the fan-out's own device collect declines this too; keep
                # the two lanes on the same ladder rung
                raise _Unsupported(f"histogram bins [{bins}]")
            bases[si, gi] = base
            hvalid[si, gi] = True
            n_bins = max(n_bins, bins)
    pctx.emit(bases, _OP_S)
    pctx.emit(hvalid, _OP_S)
    sig = (spec.type, field, float(interval), n_bins)

    def dev(d, m):
        base = d.pop()                           # [G]
        ok_g = d.pop()                           # [G]
        num = d.fields[field]
        idx = jnp.floor((num.vals.astype(jnp.float64)
                         - base[:, None]) / interval).astype(jnp.int32)
        ok = (~num.missing) & (idx >= 0) & (idx < n_bins) \
            & ok_g[:, None]                      # [G, N]

        def one(idx_g, ok_g2, m_g):              # [N], [N], [Qb, N]
            sel = m_g & ok_g2[None, :]
            safe = jnp.where(ok_g2, idx_g, n_bins)
            oh = (safe[:, None]
                  == jnp.arange(n_bins, dtype=jnp.int32)[None, :])
            return jax.lax.dot_general(
                sel.astype(jnp.float32), oh.astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        # [G, Qb, n_bins] -> [Qb, G, n_bins]: per-SEGMENT counts survive
        # so host keys rebuild from each segment's own base
        return jnp.moveaxis(jax.vmap(one)(idx, ok, m), 0, 1) \
            .astype(jnp.int32)

    def fin(out, q):                             # out: [S, Qb, G, n_bins]
        parts = []
        for si in range(stack.s_count):
            buckets: dict = {}
            for gi in range(len(stack.shard_rows[si])):
                if not hvalid[si, gi]:
                    continue
                row = out[si, q, gi]
                base = bases[si, gi]
                for i in np.nonzero(row)[0]:
                    key = float(base + i * interval)
                    ent = buckets.get(key)
                    if ent is None:
                        buckets[key] = {"doc_count": int(row[i])}
                    else:
                        ent["doc_count"] += int(row[i])
            parts.append({"buckets": buckets})
        return parts

    return sig, dev, fin


def _plan_range(spec, pctx):
    """range (non-date): bounds are query-derived and uniform across
    segments, so per-shard counts sum over the segment axis on device."""
    from ..search.aggs.aggregators import _range_bounds
    stack = pctx.stack
    field = spec.params.get("field")
    if not field or field in stack.mixed:
        raise _Unsupported(f"range field [{field}]")
    bounds = _range_bounds(spec.params, is_date=False)
    if bounds is None:
        raise _Unsupported("empty ranges")
    keys, los, his = bounds
    if field not in stack.numerics:
        sig = ("range_absent",)
        return (sig, None,
                lambda out, q: [{"buckets": {}}
                                for _ in range(stack.s_count)])
    pctx.use_field(field, "numeric")
    pctx.emit(los, _OP_R)   # request-global bounds: replicated operands
    pctx.emit(his, _OP_R)
    sig = ("range", field, len(keys))

    def dev(d, m):
        lo_b, hi_b = d.pop(), d.pop()            # [R]
        num = d.fields[field]
        v = num.vals.astype(jnp.float64)         # [G, N]
        inr = (~num.missing)[:, None, :] \
            & (v[:, None, :] >= lo_b[None, :, None]) \
            & (v[:, None, :] < hi_b[None, :, None])        # [G, R, N]
        # [G, Qb, R] summed over G and N -> [Qb, R]
        return jnp.einsum("gqn,grn->qr", m.astype(jnp.int64),
                          inr.astype(jnp.int64))

    def fin(out, q):                             # out: [S, Qb, R]
        parts = []
        for si in range(stack.s_count):
            row = out[si, q]
            parts.append({"buckets": {
                key: {"doc_count": int(row[ri]), "from": lo, "to": hi}
                for ri, (key, lo, hi) in enumerate(keys)}})
        return parts

    return sig, dev, fin


def _plan_metric(spec, pctx):
    """min/max/sum/avg/value_count/stats/extended_stats on a numeric
    column: fused per-(segment, query) 5-vectors — `masked_stats`'s exact
    math over the mesh-padded column (appended zero padding is exact under
    f64 accumulation) — merged on HOST in segment order, because float
    addition is order-sensitive and the fan-out merges in that order."""
    stack = pctx.stack
    field = spec.params.get("field")
    if not field or field in stack.mixed:
        raise _Unsupported(f"metric field [{field}]")

    def empty():
        return {"count": 0, "sum": 0.0, "min": math.inf,
                "max": -math.inf, "sum_sq": 0.0}

    if field not in stack.numerics:
        sig = ("metric_absent", spec.type)
        return (sig, None,
                lambda out, q: [empty() for _ in range(stack.s_count)])
    pctx.use_field(field, "numeric")
    sig = ("metric", field)

    def dev(d, m):
        num = d.fields[field]

        def one(vals_g, miss_g, m_g):            # [N], [N], [Qb, N]
            sel = m_g & ~miss_g[None, :]
            v = vals_g.astype(jnp.float64)[None, :]
            vz = jnp.where(sel, v, 0.0)
            cnt = sel.sum(axis=1).astype(jnp.float64)
            s = vz.sum(axis=1)
            ss = (vz * vz).sum(axis=1)
            mn = jnp.where(sel, v, jnp.inf).min(axis=1)
            mx = jnp.where(sel, v, -jnp.inf).max(axis=1)
            return jnp.stack([cnt, s, ss, mn, mx], axis=1)   # [Qb, 5]

        # [G, Qb, 5] -> [Qb, G, 5]
        return jnp.moveaxis(
            jax.vmap(one)(num.vals, num.missing, m), 0, 1)

    from ..search.aggs.aggregators import merge_partial

    def fin(out, q):                             # out: [S, Qb, G, 5]
        parts = []
        for si in range(stack.s_count):
            merged = None
            for gi in range(len(stack.shard_rows[si])):
                cnt, s, ss, mn, mx = out[si, q, gi]
                part = {"count": int(cnt), "sum": float(s),
                        "sum_sq": float(ss),
                        "min": float(mn) if cnt else math.inf,
                        "max": float(mx) if cnt else -math.inf}
                merged = part if merged is None \
                    else merge_partial(spec, merged, part)
            parts.append(merged if merged is not None else empty())
        return parts

    return sig, dev, fin


# ---------------------------------------------------------------------------
# Sub-aggregation trees (ISSUE 17 tentpole (b)): composite-bin flattening
# ---------------------------------------------------------------------------

# composite (parent x child) bins past this cap keep the fan-out's host
# collect (per-bucket python masks) — the cap bounds the per-segment
# [Qb, G, bins, 5] metric tensor, not correctness
_MAX_SUBAGG_BINS = 1 << 12

_SUBAGG_PARENTS = {"terms", "histogram", "date_histogram"}

# f64 bin keys are exact only while |value| < 2^53 (search/sort_encode
# applies the same ceiling to encoded sort keys)
_MAX_EXACT_I64 = float(2 ** 53)


class _Binner:
    """One bucket level of a sub-agg tree: `nb` real bins (id == nb is the
    spill for missing/out-of-bucket docs), `dev_ids(d)` the device closure
    producing i32[G, N] per-doc bin ids, `key_of(b)` the host bucket key —
    derived the same way the fan-out's host collect derives it, so the two
    lanes can never disagree on a key."""

    def __init__(self, nb, sig, dev_ids, key_of):
        self.nb = nb
        self.sig = sig
        self.dev_ids = dev_ids
        self.key_of = key_of


class _TreeNode:
    """Planned node of a sub-agg tree. `binner is None` means the bucket
    field is absent from the whole stack — the node contributes no device
    tensors and finishes to the host collect's constant empty partial."""

    def __init__(self, spec, binner):
        self.spec = spec
        self.binner = binner
        self.metrics = []     # [(AggSpec, present: bool)]
        self.children = []    # [_TreeNode]
        self.cnb = 0          # composite bin count at this level
        self.count_off = -1
        self.metric_offs = []


def _terms_binner(spec, pctx, reason: str):
    """Global-vocab terms level — _plan_terms' remap-operand construction
    shared across every segment AND shard, so one ordinal space covers the
    whole composite bin axis."""
    stack = pctx.stack
    field = spec.params.get("field")
    if not field or field in stack.mixed:
        raise _Unsupported(f"terms field [{field}]", reason=reason)
    if field not in stack.keywords:
        if field in stack.text or field in stack.numerics:
            # analyzed-text / numeric terms keep the host collect's
            # np.unique semantics — fan-out territory
            raise _Unsupported(f"terms over non-keyword [{field}]",
                               reason=reason)
        return None     # absent everywhere -> constant empty partial
    vocab: list[str] = sorted({v for rows in stack.shard_rows
                               for _i, seg in rows
                               for v in (seg.keywords.get(field).values
                                         if seg.keywords.get(field)
                                         else ())})
    nb = len(vocab)
    if nb == 0:
        return None
    if nb > _MAX_SUBAGG_BINS:
        raise _Unsupported(f"terms vocab [{nb}]", reason="subagg_bins")
    bin_of = {v: i for i, v in enumerate(vocab)}
    v_pad = max(max((len(seg.keywords[field].values)
                     for rows in stack.shard_rows for _i, seg in rows
                     if field in seg.keywords), default=1), 1)
    remap = np.full((stack.s_pad, stack.g_pad, v_pad), nb, np.int32)
    for si, rows in enumerate(stack.shard_rows):
        for gi, (_i, seg) in enumerate(rows):
            kc = seg.keywords.get(field)
            if kc is None:
                continue
            for o, v in enumerate(kc.values):
                remap[si, gi, o] = bin_of[v]
    pctx.use_field(field, "keyword")
    pctx.emit(remap, _OP_S)

    def dev_ids(d):
        rmp = d.pop()                            # [G, Vpad]
        ords = d.fields[field].ords              # [G, N]
        return jnp.where(
            ords >= 0,
            jnp.take_along_axis(rmp, jnp.maximum(ords, 0).astype(jnp.int32),
                                axis=1),
            jnp.int32(nb))

    b = _Binner(nb, ("terms", field, nb, v_pad), dev_ids,
                lambda i: vocab[i])
    b.vocab = vocab
    return b


def _int_hist_binner(spec, pctx, reason: str):
    """Exact-integer histogram / fixed-interval date_histogram level. The
    host collect's WITH-SUBS path buckets by `(vals // step) * step`
    (aggregators._bucket_segment), which f64 affine binning cannot
    reproduce bitwise for float columns / fractional intervals — those
    decline. For i64 columns + integer steps the device bin id is exact
    i64 floor-division against a GLOBAL base, so `base + i * step` equals
    the host's floor key for every segment and shard."""
    from ..search.aggs.aggregators import _col_minmax, _fixed_interval_ms
    stack = pctx.stack
    field = spec.params.get("field")
    if not field or field in stack.mixed:
        raise _Unsupported(f"histogram field [{field}]", reason=reason)
    if spec.type == "date_histogram":
        iv = _fixed_interval_ms(spec.params.get("interval", "1d"))
        if iv is None:
            raise _Unsupported("calendar interval",
                               reason="calendar_interval")
    else:
        iv = float(spec.params["interval"])
    if iv <= 0 or not float(iv).is_integer():
        raise _Unsupported(f"non-integer interval [{iv}]",
                           reason="float_histogram")
    step = int(iv)
    if field not in stack.numerics:
        return None     # absent everywhere -> {"buckets": {}}
    mn_g, mx_g = math.inf, -math.inf
    for rows in stack.shard_rows:
        for _i, seg in rows:
            nc = seg.numerics.get(field)
            if nc is None:
                continue
            if nc.dtype != "i64":
                # float column: host buckets by np.floor(v/interval) —
                # not bitwise-reachable from affine device bins
                raise _Unsupported(f"float column [{field}]",
                                   reason="float_histogram")
            mn, mx = _col_minmax(seg, field, nc)
            if np.isfinite(mn) and np.isfinite(mx):
                mn_g = min(mn_g, mn)
                mx_g = max(mx_g, mx)
    if not np.isfinite(mn_g):
        return None     # no present values anywhere
    if max(abs(mn_g), abs(mx_g)) >= _MAX_EXACT_I64:
        raise _Unsupported("i64 precision", reason="float_histogram")
    base = (int(mn_g) // step) * step
    nb = (int(mx_g) // step) - (base // step) + 1
    if nb > _MAX_SUBAGG_BINS:
        raise _Unsupported(f"histogram bins [{nb}]", reason="subagg_bins")
    pctx.use_field(field, "numeric")
    # base rides as a replicated data operand so a refresh that only
    # shifts the column range reuses the compiled program (no-retrace)
    pctx.emit(np.array([float(base)]), _OP_R)

    def dev_ids(d):
        b = d.pop()[0].astype(jnp.int64)         # scalar base
        num = d.fields[field]
        vi = num.vals.astype(jnp.int64)          # [G, N] exact (< 2^53)
        idx = (vi - b) // step
        ok = (~num.missing) & (idx >= 0) & (idx < nb)
        return jnp.where(ok, idx, nb).astype(jnp.int32)

    return _Binner(nb, (spec.type, field, step, nb), dev_ids,
                   lambda i: float(base + i * step))


def _plan_tree_node(spec, pctx, depth: int) -> _TreeNode:
    """Recursively plan one bucket level + its subs. Operands are emitted
    in traversal order (parent binner, then each bucket child), and the
    device closure pops in the same order."""
    reason = "unsupported_child" if depth else "agg_shape"
    if spec.type == "terms":
        binner = _terms_binner(spec, pctx, reason)
    elif spec.type in ("histogram", "date_histogram"):
        binner = _int_hist_binner(spec, pctx, reason)
    else:
        raise _Unsupported(f"subs under [{spec.type}]",
                           reason="unsupported_child")
    node = _TreeNode(spec, binner)
    stack = pctx.stack
    for s in spec.subs:
        if s.type in _METRIC_TYPES:
            field = s.params.get("field")
            if not field or field in stack.mixed:
                raise _Unsupported(f"metric field [{field}]",
                                   reason="unsupported_child")
            present = field in stack.numerics
            if present and binner is not None:
                pctx.use_field(field, "numeric")
            node.metrics.append((s, present))
        elif s.type in _SUBAGG_PARENTS and depth == 0:
            node.children.append(_plan_tree_node(s, pctx, depth + 1))
        else:
            raise _Unsupported(f"sub-agg [{s.type}] at depth {depth + 1}",
                               reason="unsupported_child")
    return node


def _assign_offsets(node: _TreeNode, g_pad: int, parent_nb: int | None,
                    tot: int) -> int:
    """Lay the tree's tensors out along one packed f64 axis: per-segment
    counts [G, cnb], then per-metric [G, cnb, 5], then children."""
    if node.binner is None:
        return tot
    node.cnb = node.binner.nb if parent_nb is None \
        else parent_nb * node.binner.nb
    if node.cnb > _MAX_SUBAGG_BINS:
        raise _Unsupported(f"composite bins [{node.cnb}]",
                           reason="subagg_bins")
    node.count_off = tot
    tot += g_pad * node.cnb
    node.metric_offs = []
    for _s, present in node.metrics:
        node.metric_offs.append(tot if present else None)
        if present:
            tot += g_pad * node.cnb * 5
    for ch in node.children:
        tot = _assign_offsets(ch, g_pad, node.cnb, tot)
    return tot


def _per_g_counts(ids, m, nb):
    """ids i32[G, N] (nb = spill), m bool[G, Qb, N] -> f64[Qb, G * nb]
    exact per-segment counts (integers below 2^31 are exact in f64)."""
    def one_g(ids_g, m_g):                       # [N], [Qb, N]
        idq = jnp.where(m_g, ids_g[None, :], nb)
        return jax.vmap(
            lambda ix: jnp.bincount(ix, length=nb + 1))(idq)[:, :nb]
    c = jnp.moveaxis(jax.vmap(one_g)(ids, m), 0, 1)      # [Qb, G, nb]
    return c.reshape(c.shape[0], -1).astype(jnp.float64)


def _per_g_stats(ids, m, num, nb):
    """Fused per-(segment, bin) metric rows: (count, sum, sum_sq, min,
    max) via segment reductions over the composite bin ids ->
    f64[Qb, G * nb * 5]. Rows with count 0 are ignored at finish time
    (min/max read as +/-inf there), so the reduction identities never
    leak into the wire partial."""
    v64 = num.vals.astype(jnp.float64)
    miss = num.missing

    def one_g(ids_g, v_g, miss_g, m_g):          # [N], [N], [N], [Qb, N]
        def one_q(m_q):
            sel = m_q & ~miss_g
            idq = jnp.where(sel, ids_g, nb)
            vz = jnp.where(sel, v_g, 0.0)
            cnt = jax.ops.segment_sum(sel.astype(jnp.float64), idq,
                                      num_segments=nb + 1)
            s = jax.ops.segment_sum(vz, idq, num_segments=nb + 1)
            ss = jax.ops.segment_sum(vz * vz, idq, num_segments=nb + 1)
            mn = jax.ops.segment_min(jnp.where(sel, v_g, jnp.inf), idq,
                                     num_segments=nb + 1)
            mx = jax.ops.segment_max(jnp.where(sel, v_g, -jnp.inf), idq,
                                     num_segments=nb + 1)
            return jnp.stack([cnt, s, ss, mn, mx], axis=1)[:nb]
        return jax.vmap(one_q)(m_g)              # [Qb, nb, 5]

    st = jnp.moveaxis(jax.vmap(one_g)(ids, v64, miss, m), 0, 1)
    return st.reshape(st.shape[0], -1)           # [Qb, G*nb*5]


def _metric_part_from_row(vec) -> dict:
    cnt = int(vec[0])
    return {"count": cnt, "sum": float(vec[1]), "sum_sq": float(vec[2]),
            "min": float(vec[3]) if cnt else math.inf,
            "max": float(vec[4]) if cnt else -math.inf}


_EMPTY_METRIC = {"count": 0, "sum": 0.0, "sum_sq": 0.0,
                 "min": math.inf, "max": -math.inf}


def _empty_bucket_partial(spec) -> dict:
    if spec.type == "terms":
        return _empty_terms()
    return {"buckets": {}}


def _plan_subagg_tree(spec, pctx):
    """Plan a bucket agg WITH sub-aggregations as ONE packed device tensor
    per shard: every level's per-segment composite-bin counts and every
    metric leaf's per-segment 5-vector rows, flattened and concatenated
    along one f64 axis (counts are exact integers in f64). `fin` slices
    the gathered [S, Qb, TOT] row back apart and rebuilds the nested
    partial dicts with the host collect's own truncation and merge code
    (terms_partial_from_counts / merge_partial), reproducing the fan-out
    shard partial bit-for-bit."""
    from ..search.aggs.aggregators import (_empty_partial, merge_partial,
                                           terms_partial_from_counts)
    stack = pctx.stack
    tree = _plan_tree_node(spec, pctx, 0)
    if tree.binner is None:
        # absent parent field: the host collect's constant empty partial
        sig = ("subtree_absent", spec.type)
        return (sig, None,
                lambda out, q: [_empty_bucket_partial(spec)
                                for _ in range(stack.s_count)])
    g_pad = stack.g_pad
    _assign_offsets(tree, g_pad, None, 0)

    def tree_sig(node):
        return (node.binner.sig if node.binner is not None else None,
                tuple((s.params.get("field"), present)
                      for s, present in node.metrics),
                tuple(tree_sig(ch) for ch in node.children))

    sig = ("subtree", tree_sig(tree))

    def dev(d, m):
        outs = []

        def emit_node(node, pids, pnb):
            b = node.binner
            if b is None:
                return
            ids = b.dev_ids(d)                   # [G, N]
            if pids is None:
                cids, cnb = ids, b.nb
            else:
                ok = (pids < pnb) & (ids < b.nb)
                cids = jnp.where(ok, pids * b.nb + ids,
                                 pnb * b.nb).astype(jnp.int32)
                cnb = pnb * b.nb
            outs.append(_per_g_counts(cids, m, cnb))
            for (ms, present) in node.metrics:
                if present:
                    outs.append(_per_g_stats(
                        cids, m, d.fields[ms.params["field"]], cnb))
            for ch in node.children:
                emit_node(ch, cids, cnb)

        emit_node(tree, None, None)
        return jnp.concatenate(outs, axis=1)     # [Qb, TOT]

    def counts_of(node, row):
        return row[node.count_off:
                   node.count_off + g_pad * node.cnb] \
            .reshape(g_pad, node.cnb)

    def stats_of(node, mi, row):
        off = node.metric_offs[mi]
        return row[off: off + g_pad * node.cnb * 5] \
            .reshape(g_pad, node.cnb, 5)

    def seg_subs(node, row, gi, comp) -> dict:
        """subs dict for ONE (segment, bucket) — what _bucket_entry /
        _collect_terms_shard pass 2 collects for that segment."""
        subs: dict = {}
        for mi, (ms, present) in enumerate(node.metrics):
            subs[ms.name] = _metric_part_from_row(
                stats_of(node, mi, row)[gi, comp]) if present \
                else dict(_EMPTY_METRIC)
        for ch in node.children:
            subs[ch.spec.name] = child_partial(ch, row, gi, comp)
        return subs

    def child_partial(node, row, gi, pcomp) -> dict:
        """One bucket-child partial for (segment gi, parent composite
        bin) — exactly _collect_one's per-segment result."""
        if node.binner is None:
            return _empty_bucket_partial(node.spec)
        nb = node.binner.nb
        crow = counts_of(node, row)[gi, pcomp * nb:(pcomp + 1) * nb]
        if node.spec.type == "terms":
            counts = {node.binner.vocab[j]: int(crow[j])
                      for j in np.nonzero(crow)[0]}
            if not node.spec.subs:
                return terms_partial_from_counts(node.spec, counts)
            # _collect_terms_shard([seg]) with subs, replicated: per-
            # SEGMENT truncation, then per-key metric leaves
            p = node.spec.params
            size = int(p.get("size", 10)) or len(counts) or 1
            shard_size = int(p.get("shard_size", size * 3 + 10))
            items = sorted(counts.items(),
                           key=lambda kv: (-kv[1], str(kv[0])))
            top = items[:shard_size]
            dropped = items[shard_size:]
            buckets: dict = {}
            for key, c in top:
                j = node.binner.vocab.index(key)
                buckets[key] = {
                    "doc_count": int(c),
                    "subs": seg_subs(node, row, gi, pcomp * nb + j)}
            return {"buckets": buckets,
                    "other_doc_count": int(sum(c for _k, c in dropped)),
                    "error_bound": int(top[-1][1]) if dropped else 0}
        # histogram / date_histogram child: nonzero bins ascending ==
        # the host's np.unique(keys[sel]) order
        buckets = {}
        for j in np.nonzero(crow)[0]:
            e: dict = {"doc_count": int(crow[j])}
            if node.spec.subs:
                e["subs"] = seg_subs(node, row, gi, pcomp * nb + int(j))
            buckets[node.binner.key_of(int(j))] = e
        return {"buckets": buckets}

    def finish_shard(row, si) -> dict:
        n_rows = len(stack.shard_rows[si])
        ct = counts_of(tree, row)
        if spec.type == "terms":
            # two-pass shard semantics: top keys from the MERGED counts,
            # subs per segment merged in segment order
            merged = ct[:n_rows].sum(axis=0)
            counts = {tree.binner.vocab[b]: int(merged[b])
                      for b in np.nonzero(merged)[0]}
            p = spec.params
            size = int(p.get("size", 10)) or len(counts) or 1
            shard_size = int(p.get("shard_size", size * 3 + 10))
            items = sorted(counts.items(),
                           key=lambda kv: (-kv[1], str(kv[0])))
            top = items[:shard_size]
            dropped = items[shard_size:]
            buckets: dict = {}
            for key, c in top:
                b = tree.binner.vocab.index(key)
                sub_parts: dict = {}
                for gi in range(n_rows):
                    for s_name, part in seg_subs(tree, row, gi,
                                                 b).items():
                        prev = sub_parts.get(s_name)
                        sub_parts[s_name] = part if prev is None \
                            else merge_partial(
                                next(s for s in spec.subs
                                     if s.name == s_name), prev, part)
                buckets[key] = {
                    "doc_count": int(c),
                    "subs": {s.name: sub_parts.get(s.name,
                                                   _empty_partial(s))
                             for s in spec.subs}}
            return {"buckets": buckets,
                    "other_doc_count": int(sum(c for _k, c in dropped)),
                    "error_bound": int(top[-1][1]) if dropped else 0}
        # histogram parent: per-segment partials merged in segment order
        # (collect_shard's merge), bucket keys ascending per segment
        merged_p = None
        for gi in range(n_rows):
            srow = ct[gi]
            buckets = {}
            for b in np.nonzero(srow)[0]:
                buckets[tree.binner.key_of(int(b))] = {
                    "doc_count": int(srow[b]),
                    "subs": seg_subs(tree, row, gi, int(b))}
            part = {"buckets": buckets}
            merged_p = part if merged_p is None \
                else merge_partial(spec, merged_p, part)
        return merged_p if merged_p is not None else {"buckets": {}}

    def fin(out, q):                             # out: [S, Qb, TOT]
        return [finish_shard(out[si, q], si)
                for si in range(stack.s_count)]

    return sig, dev, fin
