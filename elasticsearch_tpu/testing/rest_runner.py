"""YAML REST acceptance-suite runner.

Executes the reference's implementation-agnostic REST test suites
(/root/reference/rest-api-spec/test/*/*.yaml, format documented in
test/README.asciidoc; reference runner
src/test/java/org/elasticsearch/test/rest/ElasticsearchRestTests.java)
against a live HTTP endpoint. API calls are resolved data-driven from the
api specs (/root/reference/rest-api-spec/api/*.json): path templates,
required parts, methods — nothing endpoint-specific is hardcoded here, so
every suite the surface can satisfy runs unmodified.

Supported statements: do (with catch + stash substitution), match
(incl. /regex/ values and dotted paths with \\. escapes), length, is_true,
is_false, lt, gt, lte, gte, set, skip (version ranges against VERSION and
feature gates).
"""

from __future__ import annotations

import json
import re
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass, field

import yaml

VERSION = (2, 0, 0)                 # what we report to version skips
FEATURES = {"regex", "stash_in_path"}


@dataclass
class SectionResult:
    file: str
    section: str
    ok: bool
    skipped: bool = False
    error: str | None = None
    steps_run: int = 0


class _Failure(Exception):
    pass


class _Skip(Exception):
    pass


class YamlRestRunner:
    def __init__(self, base_url: str, api_dir: str):
        import os
        self.base_url = base_url.rstrip("/")
        self.apis: dict[str, dict] = {}
        for fn in os.listdir(api_dir):
            if fn.endswith(".json"):
                with open(os.path.join(api_dir, fn)) as f:
                    spec = json.load(f)
                name = fn[:-5]
                self.apis[name] = spec.get(name) or next(iter(spec.values()))

    # -- http --------------------------------------------------------------

    def _call(self, method: str, path: str, params: dict, body):
        url = self.base_url + path
        if params:
            def enc(v):
                if isinstance(v, bool):
                    return str(v).lower()
                if isinstance(v, list):
                    return ",".join(str(x) for x in v)   # ES list params
                return v
            url += "?" + urllib.parse.urlencode(
                {k: enc(v) for k, v in params.items()})
        data = None
        if body is not None:
            if isinstance(body, (dict, list)):
                data = json.dumps(body).encode()
            else:
                data = str(body).encode()
        if data is not None and method == "GET":
            method = "POST"         # urllib can't GET-with-body
        req = urllib.request.Request(url, data=data, method=method)
        try:
            with urllib.request.urlopen(req, timeout=120) as r:
                raw = r.read()
                status = r.status
        except urllib.error.HTTPError as e:
            raw = e.read()
            status = e.code
        if not raw:
            return status, ""
        if raw[:1] not in (b"{", b"["):
            return status, raw.decode(errors="replace")   # text (_cat etc.)
        try:
            parsed = json.loads(raw)
        except json.JSONDecodeError:
            parsed = raw.decode(errors="replace")
        return status, parsed

    # -- api resolution ----------------------------------------------------

    def _do_api(self, api_name: str, args: dict):
        if api_name == "create" and "create" not in self.apis:
            # the 2015 spec snapshot has no create.json: create == index
            # with op_type=create (ref RestIndexAction CREATE variant)
            api_name = "index"
            args = {**args, "op_type": "create"}
        spec = self.apis.get(api_name)
        if spec is None:
            raise _Failure(f"unknown api [{api_name}]")
        url = spec["url"]
        parts = dict(url.get("parts", {}))
        body = args.pop("body", None)
        if isinstance(body, str) and api_name != "bulk":
            # some suites embed the body as a loose-YAML string
            try:
                parsed = yaml.safe_load(body)
                if isinstance(parsed, (dict, list)):
                    body = parsed
            except yaml.YAMLError:
                pass
        path_args = {k: v for k, v in args.items() if k in parts}
        q_params = {k: v for k, v in args.items() if k not in parts}
        # choose the most specific path template all of whose parts we have
        best = None
        for tmpl in url.get("paths", [url.get("path", "/")]):
            needed = re.findall(r"\{(\w+)\}", tmpl)
            if all(n in path_args for n in needed):
                if best is None or len(needed) > len(re.findall(r"\{(\w+)\}",
                                                               best)):
                    best = tmpl
        if best is None:
            raise _Failure(
                f"no path of [{api_name}] satisfiable with {list(path_args)}")
        path = best
        for k, v in path_args.items():
            if isinstance(v, list):
                v = ",".join(str(x) for x in v)
            path = path.replace("{%s}" % k,
                                urllib.parse.quote(str(v), safe=",*"))
        methods = spec.get("methods", ["GET"])
        if body is not None and "POST" in methods:
            method = "POST"
        elif "GET" in methods:
            method = "GET"
        else:
            method = methods[0]
        if method == "HEAD":
            # exists-style APIs: the client maps 200 -> true, 404 -> false;
            # the REAL status flows through so `catch: request` can see
            # 4xx validation failures (the bool payload marks this shape)
            status, _ = self._call("HEAD", path, q_params, None)
            return status, status < 300
        if api_name.startswith("indices.put") or api_name in (
                "index", "create") and "PUT" in methods and "id" in path_args:
            method = "PUT"
        if body is not None and isinstance(body, list):
            # bulk-style ndjson bodies (items may be pre-serialized strings)
            body = "\n".join(
                x.strip() if isinstance(x, str) else json.dumps(x)
                for x in body) + "\n"
        return self._call(method, path, q_params, body)

    # -- value helpers -----------------------------------------------------

    @staticmethod
    def _split_path(path: str) -> list[str]:
        out, cur, i = [], "", 0
        while i < len(path):
            c = path[i]
            if c == "\\" and i + 1 < len(path) and path[i + 1] == ".":
                cur += "."
                i += 2
                continue
            if c == ".":
                out.append(cur)
                cur = ""
            else:
                cur += c
            i += 1
        out.append(cur)
        return [p for p in out if p != ""]

    def _lookup(self, response, path: str, stash: dict):
        if path == "$body" or path == "":
            return response
        val = response
        for part in self._split_path(path):
            part = self._stash(part, stash)
            if isinstance(val, dict):
                val = val.get(str(part))
            elif isinstance(val, list):
                try:
                    val = val[int(part)]
                except (ValueError, IndexError):
                    return None
            else:
                return None
        return val

    def _stash(self, v, stash: dict):
        if isinstance(v, str) and v.startswith("$"):
            return stash.get(v[1:], v)
        if isinstance(v, str) and "$" in v:
            return re.sub(r"\$\{?(\w+)\}?",
                          lambda m: str(stash.get(m.group(1), m.group(0))), v)
        if isinstance(v, dict):
            return {self._stash(k, stash): self._stash(x, stash)
                    for k, x in v.items()}
        if isinstance(v, list):
            return [self._stash(x, stash) for x in v]
        return v

    # -- assertions --------------------------------------------------------

    @staticmethod
    def _eq(got, want) -> bool:
        if isinstance(want, str) and len(want) > 1 and want.strip().startswith("/") \
                and want.strip().endswith("/"):
            pat = want.strip()[1:-1]
            return re.search(pat, str(got), re.VERBOSE | re.S) is not None
        if isinstance(want, (int, float)) and isinstance(got, (int, float)) \
                and not isinstance(want, bool) and not isinstance(got, bool):
            return float(got) == float(want)
        if isinstance(want, dict) and isinstance(got, dict):
            return got == want
        return got == want

    def _assert(self, kind: str, spec, response, stash: dict):
        if kind == "match":
            (path, want), = spec.items()
            got = self._lookup(response, path, stash)
            want = self._stash(want, stash)
            if not self._eq(got, want):
                raise _Failure(f"match {path}: got {got!r}, want {want!r}")
        elif kind in ("is_true", "is_false"):
            got = self._lookup(response, spec, stash)
            truthy = got not in (None, False, "", 0, "false")
            if truthy != (kind == "is_true"):
                raise _Failure(f"{kind} {spec}: got {got!r}")
        elif kind == "length":
            (path, want), = spec.items()
            got = self._lookup(response, path, stash)
            if got is None or len(got) != int(self._stash(want, stash)):
                raise _Failure(f"length {path}: got "
                               f"{None if got is None else len(got)}, "
                               f"want {want}")
        elif kind in ("lt", "gt", "lte", "gte"):
            (path, want), = spec.items()
            got = self._lookup(response, path, stash)
            if not isinstance(got, (int, float)) or isinstance(got, bool):
                raise _Failure(f"{kind} {path}: got non-numeric {got!r}")
            want = float(self._stash(want, stash))
            ok = {"lt": got < want, "gt": got > want,
                  "lte": got <= want, "gte": got >= want}[kind]
            if not ok:
                raise _Failure(f"{kind} {path}: got {got!r} vs {want!r}")
        else:
            raise _Failure(f"unsupported assertion [{kind}]")

    # -- skip --------------------------------------------------------------

    @staticmethod
    def _version_tuple(s: str):
        s = s.strip()
        if not s:
            return None
        nums = re.findall(r"\d+", s)
        return tuple(int(x) for x in nums[:3]) + (0,) * (3 - len(nums[:3]))

    def _should_skip(self, spec: dict) -> str | None:
        feats = spec.get("features")
        if feats:
            feats = feats if isinstance(feats, list) else [feats]
            missing = [f for f in feats if f not in FEATURES]
            if missing:
                return f"features {missing}"
        ver = spec.get("version")
        if ver:
            if str(ver).strip().lower() == "all":
                return "version all"
            m = re.match(r"^\s*(.*?)\s*-\s*(.*?)\s*$", str(ver))
            if m:
                lo = self._version_tuple(m.group(1)) or (0, 0, 0)
                hi = self._version_tuple(m.group(2)) or (99, 99, 99)
                if lo <= VERSION <= hi:
                    return f"version {ver}"
        return None

    # -- execution ---------------------------------------------------------

    def _run_steps(self, steps: list, stash: dict) -> int:
        n = 0
        response = {}
        for step in steps:
            (kind, spec), = step.items()
            if kind == "skip":
                why = self._should_skip(spec)
                if why:
                    raise _Skip(why)
                continue
            if kind == "do":
                spec = dict(spec)
                catch = spec.pop("catch", None)
                (api, args), = spec.items()
                args = self._stash(dict(args or {}), stash)
                ignore = args.pop("ignore", None)
                ignored = [int(x) for x in
                           (ignore if isinstance(ignore, list) else [ignore])
                           ] if ignore is not None else []
                try:
                    status, response = self._do_api(api.strip(), args)
                except _Failure:
                    if catch in ("param", "request"):
                        # client-side validation failure was EXPECTED
                        n += 1
                        continue
                    raise
                if status in ignored:
                    n += 1
                    continue
                if catch is None:
                    # bool responses are HEAD/exists results: a 404 means
                    # "false", not a failed step
                    if status >= 400 and not isinstance(response, bool):
                        raise _Failure(
                            f"do {api}: HTTP {status}: {response}")
                else:
                    expected = {"missing": (404,), "conflict": (409,),
                                "forbidden": (403,),
                                "request": tuple(range(400, 600)),
                                "param": tuple(range(400, 600)),
                                "unavailable": (503,)}.get(catch)
                    if expected is not None:
                        if status not in expected:
                            raise _Failure(
                                f"do {api}: expected {catch}, got "
                                f"HTTP {status}: {response}")
                    elif catch.startswith("/"):
                        # catch regexes match literally (spaces count) —
                        # only body `match:` regexes use COMMENTS mode
                        if status < 400 or not re.search(
                                catch.strip("/"), json.dumps(response),
                                re.S):
                            raise _Failure(
                                f"do {api}: error !~ {catch}: {response}")
                    else:
                        raise _Failure(f"unknown catch [{catch}]")
            elif kind == "set":
                (path, var), = spec.items()
                stash[var] = self._lookup(response, path, stash)
            else:
                self._assert(kind, spec, response, stash)
            n += 1
        return n

    def _teardown(self):
        """Delete all indices and all templates (per the suite contract in
        test/README.asciidoc)."""
        self._call("DELETE", "/_all", {}, None)
        self._call("DELETE", "/_template/*", {}, None)

    def run_file(self, path: str) -> list[SectionResult]:
        with open(path) as f:
            docs = list(yaml.safe_load_all(f))
        setup: list = []
        sections: list[tuple[str, list]] = []
        for doc in docs:
            if not doc:
                continue
            for name, steps in doc.items():
                if name == "setup":
                    setup = steps
                else:
                    sections.append((name, steps))
        results = []
        for name, steps in sections:
            stash: dict = {}
            try:
                self._teardown()
                self._run_steps(setup, stash)
                n = self._run_steps(steps, stash)
                results.append(SectionResult(path, name, ok=True,
                                             steps_run=n))
            except _Skip as s:
                results.append(SectionResult(path, name, ok=True,
                                             skipped=True, error=str(s)))
            except _Failure as e:
                results.append(SectionResult(path, name, ok=False,
                                             error=str(e)))
            except Exception as e:  # noqa: BLE001 — report, don't crash
                results.append(SectionResult(
                    path, name, ok=False,
                    error=f"{type(e).__name__}: {e}"))
        self._teardown()
        return results
