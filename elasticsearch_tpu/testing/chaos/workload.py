"""Seeded workload generator: reproducible docs and query bodies
spanning every execution-ladder rung (text scoring, filters, paging,
aggs, kNN across metrics, quantized kNN, msearch batches).

Everything derives from ONE `random.Random` owned by the caller — the
same seed replays the same docs in the same order and the same query
sample, which is what makes a chaos failure reproducible from a single
integer.
"""

from __future__ import annotations

import random

WORDS = ["quick", "brown", "fox", "jumps", "lazy", "dog", "sleeps",
         "swift", "river", "stone", "amber", "cloud"]

TAGS = ["t0", "t1", "t2"]


class SeededWorkload:
    __test__ = False        # not a pytest class

    def __init__(self, rng: random.Random, dims: int = 8):
        self.rng = rng
        self.dims = dims
        self._doc_seq = 0

    def mapping(self) -> dict:
        return {"properties": {
            "body": {"type": "string"},
            "tag": {"type": "string", "index": "not_analyzed"},
            "n": {"type": "long"},
            "price": {"type": "double"},
            "vec": {"type": "dense_vector", "dims": self.dims}}}

    # -- documents ----------------------------------------------------------

    def vector(self) -> list[float]:
        return [round(self.rng.gauss(0.0, 1.0), 6) for _ in range(self.dims)]

    def next_docs(self, count: int) -> list[tuple[str, dict]]:
        """The next `count` (doc_id, source) pairs. Ids are sequential so
        later rounds can deterministically target earlier docs for
        deletes/updates."""
        out = []
        for _ in range(count):
            i = self._doc_seq
            self._doc_seq += 1
            body = " ".join(self.rng.choice(WORDS)
                            for _ in range(self.rng.randint(3, 7)))
            out.append((str(i), {
                "body": body,
                "tag": self.rng.choice(TAGS),
                "n": i,
                "price": round(self.rng.uniform(0.5, 99.5), 2),
                "vec": self.vector()}))
        return out

    def victim_ids(self, count: int) -> list[str]:
        """Previously written doc ids to delete (deterministic sample)."""
        if self._doc_seq == 0 or count <= 0:
            return []
        pool = [str(i) for i in range(self._doc_seq)]
        return self.rng.sample(pool, min(count, len(pool)))

    # -- queries ------------------------------------------------------------

    def text_queries(self, count: int) -> list[dict]:
        """Bodies exercising the dense scoring ladder: match / bool /
        filters / term / range / paging / aggs — all shapes every lane
        (loop, stacked, blockwise, mesh) serves."""
        out = []
        for _ in range(count):
            kind = self.rng.randrange(6)
            w1, w2 = self.rng.choice(WORDS), self.rng.choice(WORDS)
            size = self.rng.choice([5, 10, 20])
            if kind == 0:
                body = {"size": size, "query": {"match": {"body": w1}}}
            elif kind == 1:
                body = {"size": size, "query": {"bool": {
                    "should": [{"match": {"body": w1}},
                               {"match": {"body": w2}}]}}}
            elif kind == 2:
                lo = self.rng.randrange(0, 100)
                body = {"size": size, "query": {"bool": {
                    "should": [{"match": {"body": w1}}],
                    "filter": [{"range": {"n": {"gte": lo,
                                                "lt": lo + 120}}}]}}}
            elif kind == 3:
                body = {"size": size, "query": {"bool": {
                    "must": [{"term": {"tag": self.rng.choice(TAGS)}}],
                    "must_not": [{"term": {"n": self.rng.randrange(50)}}]}}}
            elif kind == 4:
                body = {"size": size, "from": self.rng.choice([0, 3, 7]),
                        "query": {"match": {"body": f"{w1} {w2}"}}}
            else:
                body = {"size": 5, "query": {"match": {"body": w1}},
                        "aggs": {"tags": {"terms": {"field": "tag"}},
                                 "st": {"stats": {"field": "n"}}}}
            out.append(body)
        return out

    def sorted_queries(self, count: int) -> list[dict]:
        """Sorted bodies (ISSUE 17): numeric / keyword-then-numeric /
        duplicate-heavy keyword-only primaries, so the encoded-key
        device sort is exercised where ties force the (_shard, _doc)
        tie-break — the shapes the sorted lane must answer bitwise
        like the per-segment loop's materialized-value merge."""
        out = []
        for j in range(count):
            w = self.rng.choice(WORDS)
            size = self.rng.choice([5, 10])
            if j % 3 == 0:
                body = {"size": size, "query": {"match": {"body": w}},
                        "sort": [{"n": self.rng.choice(["asc", "desc"])}]}
            elif j % 3 == 1:
                body = {"size": size, "query": {"match_all": {}},
                        "sort": [{"tag": "asc"}, {"n": "desc"}]}
            else:
                # keyword-only sort: every hit ties within a tag, so
                # the hidden (_shard, _doc) order IS the result order
                body = {"size": size, "query": {"match_all": {}},
                        "sort": [{"tag": "desc"}]}
            out.append(body)
        return out

    def subagg_queries(self, count: int) -> list[dict]:
        """Sub-agg trees (ISSUE 17) with integer-exact leaf metrics
        (value_count / min / max over `n`) — float SUMS are excluded
        from the bitwise roster by design: the device's pairwise
        reduction and the host's sequential sum differ in the last
        ulp, which is documented, not a parity failure."""
        out = []
        for j in range(count):
            w = self.rng.choice(WORDS)
            interval = self.rng.choice([25, 50])
            if j % 3 == 0:
                tree = {"by_n": {
                    "histogram": {"field": "n", "interval": interval},
                    "aggs": {"tags": {
                        "terms": {"field": "tag"},
                        "aggs": {"hi": {"max": {"field": "n"}}}}}}}
            elif j % 3 == 1:
                tree = {"by_n": {
                    "histogram": {"field": "n", "interval": interval},
                    "aggs": {"lo": {"min": {"field": "n"}},
                             "cnt": {"value_count": {"field": "n"}}}}}
            else:
                tree = {"tags": {
                    "terms": {"field": "tag"},
                    "aggs": {"by_n": {
                        "histogram": {"field": "n",
                                      "interval": interval},
                        "aggs": {"cnt": {
                            "value_count": {"field": "n"}}}}}}}
            out.append({"size": 5, "query": {"match": {"body": w}},
                        "aggs": tree})
        return out

    def composite_queries(self, count: int) -> list[dict]:
        """Composite + pipeline bodies (ISSUE 20). The composite collect
        is host-side on every lane and the pipeline columns are applied
        at the central render over the bitwise device partials, so every
        twin must answer byte-equal; the mesh planner declines composite
        under its stable "composite" reason. Pipeline inputs stay
        integer-exact (counts / max over `n`) — the moving_avg division
        and bucket_script arithmetic run once, host-side, so they are
        bitwise too."""
        out = []
        for j in range(count):
            w = self.rng.choice(WORDS)
            interval = self.rng.choice([25, 50])
            if j % 3 == 0:
                aggs = {"pages": {"composite": {
                    "size": self.rng.choice([3, 5]),
                    "sources": [
                        {"tag": {"terms": {"field": "tag"}}},
                        {"bin": {"histogram": {"field": "n",
                                               "interval": interval}}}],
                }}}
            elif j % 3 == 1:
                aggs = {"by_n": {
                    "histogram": {"field": "n", "interval": interval},
                    "aggs": {
                        "cnt": {"value_count": {"field": "n"}},
                        "run": {"cumulative_sum": {"buckets_path": "cnt"}},
                        "rate": {"derivative": {"buckets_path": "_count"}},
                    }}}
            else:
                aggs = {"by_n": {
                    "histogram": {"field": "n", "interval": interval},
                    "aggs": {
                        "hi": {"max": {"field": "n"}},
                        "ma": {"moving_avg": {"buckets_path": "hi",
                                              "window": 3}},
                        "calc": {"bucket_script": {
                            "buckets_path": {"c": "_count", "h": "hi"},
                            "script": "c * 2.0 + h"}},
                    }}}
            out.append({"size": 5, "query": {"match": {"body": w}},
                        "aggs": aggs})
        return out

    def knn_queries(self, count: int) -> list[dict]:
        """kNN bodies cycling the metric roster; `k` stays small so the
        tiny chaos corpus keeps every candidate window meaningful."""
        out = []
        metrics = ["cosine", "dot", "l2"]
        for j in range(count):
            out.append({"size": 5, "knn": {
                "field": "vec", "query_vector": self.vector(),
                "k": self.rng.choice([5, 10]),
                "metric": metrics[j % len(metrics)]}})
        return out

    def filtered_knn_query(self) -> dict:
        return {"size": 5, "knn": {
            "field": "vec", "query_vector": self.vector(), "k": 10,
            "filter": {"term": {"tag": self.rng.choice(TAGS)}}}}

    def percolator_queries(self, count: int) -> list[dict]:
        """Registered-query bodies (ISSUE 18) spanning every channel of
        the dense percolate grid — text counts (match and/or/msm), term
        identity, numeric ranges, bool combinations, exists — plus a
        wildcard that the dense plan declines, so the dense+residual-loop
        merge is always part of the replay pair."""
        out = []
        for j in range(count):
            w1, w2 = self.rng.choice(WORDS), self.rng.choice(WORDS)
            kind = j % 7
            if kind == 0:
                out.append({"match": {"body": w1}})
            elif kind == 1:
                out.append({"match": {"body": {
                    "query": f"{w1} {w2}", "operator": "and"}}})
            elif kind == 2:
                out.append({"term": {"tag": self.rng.choice(TAGS)}})
            elif kind == 3:
                lo = self.rng.randrange(0, 150)
                out.append({"range": {"n": {"gte": lo, "lt": lo + 40}}})
            elif kind == 4:
                out.append({"bool": {
                    "must": [{"match": {"body": w1}}],
                    "must_not": [{"term": {"tag": self.rng.choice(TAGS)}}]}})
            elif kind == 5:
                out.append({"bool": {
                    "should": [{"match": {"body": w1}},
                               {"match": {"body": w2}},
                               {"exists": {"field": "price"}}],
                    "minimum_should_match": 2}})
            else:
                # residual rung: the dense plan declines term expansion
                out.append({"wildcard": {"body": w1[:2] + "*"}})
        return out

    def percolate_docs(self, count: int) -> list[dict]:
        """Doc sources to percolate (NOT indexed): same field roster as
        the corpus docs, with `price` sometimes absent so the exists /
        missing channels are live in every pair."""
        out = []
        for _ in range(count):
            src = {"body": " ".join(self.rng.choice(WORDS)
                                    for _ in range(self.rng.randint(2, 6))),
                   "tag": self.rng.choice(TAGS),
                   "n": self.rng.randrange(0, 200),
                   "price": round(self.rng.uniform(0.5, 99.5), 2)}
            if self.rng.random() < 0.3:
                del src["price"]
            out.append(src)
        return out

    def script_exprs(self, count: int) -> list[tuple[str, str, dict]]:
        """(match word, expression, params) triples for the compiled-vs-
        host script_score pair (ISSUE 18). Restricted BY DESIGN to the
        exact-IEEE op subset (+ - * min max abs floor ceil and _score):
        ** / transcendentals / % / division are documented carve-outs,
        not replay-pair material."""
        pool = [
            ("doc['n'].value * 2.0 + 1.0", {}),
            ("Math.max(doc['price'].value, 10.0) - doc['n'].value", {}),
            ("Math.abs(doc['price'].value - 50.0) + _score", {}),
            ("Math.floor(doc['price'].value)"
             " + Math.min(doc['n'].value, params.c)", {"c": 25}),
            ("Math.ceil(doc['price'].value) * params.w", {"w": 3}),
        ]
        out = []
        for _ in range(count):
            expr, params = pool[self.rng.randrange(len(pool))]
            out.append((self.rng.choice(WORDS), expr, params))
        return out
