"""Cross-lane parity oracle + invariant checkers.

Parity: the same query replayed down two lanes the engine documents as
bitwise-identical (loop vs stacked vs blockwise vs mesh, sorted and
search_after bodies through the encoded-key device sort vs the loop's
materialized-value merge, sub-agg trees through the composite-bin
device planner vs the host's recursive collect, solo vs
msearch-batched, IVF(nprobe>=nlist) vs exact, int8-mesh vs int8-fanout,
host-reduce vs per-shard transport merge) must produce byte-equal
responses after canonicalization (drop `took`, neutralize the twin
index's `_index` label). A mismatch is a real engine bug, never test
noise — which is exactly why only documented-bitwise pairs are compared
(quantized-vs-f32 is approximate by design and is NOT a parity pair).

Invariants: error classification (a client must never see an
unclassified 5xx — transport/unavailable errors are legitimate only
while a disruption is live), control-plane traffic classes never shed,
breaker accounting drains to zero at teardown, acked writes survive
partition healing.
"""

from __future__ import annotations

import copy


def canon(resp: dict) -> dict:
    """Canonicalize a search response for cross-lane comparison: drop
    wall-clock fields, collapse the index name (twin indices hold the
    same docs under different lane settings)."""
    r = copy.deepcopy(resp)
    r.pop("took", None)
    for sub in r.get("responses", []):        # msearch envelope
        if isinstance(sub, dict):
            sub.pop("took", None)
            for h in sub.get("hits", {}).get("hits", []):
                h.pop("_index", None)
    for h in r.get("hits", {}).get("hits", []):
        h.pop("_index", None)
    return r


class ParityMismatch:
    def __init__(self, label: str, body: dict, ref, got):
        self.label = label
        self.body = body
        self.ref = ref
        self.got = got

    def __repr__(self) -> str:
        return (f"parity mismatch [{self.label}] for {self.body!r}: "
                f"expected {self.ref!r} got {self.got!r}")


class ParityOracle:
    """Counts comparisons and collects mismatches; `inject_fault` makes
    the FIRST comparison fail deliberately — the harness's own tripwire
    that a broken lane actually surfaces as a seed-stamped failure."""

    def __init__(self, inject_fault: bool = False):
        self.checks = 0
        self.lane_checks = 0
        self.mismatches: list[ParityMismatch] = []
        self._inject = inject_fault

    def compare(self, label: str, body: dict, ref: dict, got: dict) -> bool:
        self.checks += 1
        a, b = canon(ref), canon(got)
        if self._inject:
            self._inject = False
            b = copy.deepcopy(b)
            b.setdefault("hits", {})["max_score"] = -1e30
        ok = a == b
        if not ok:
            self.mismatches.append(ParityMismatch(label, body, a, b))
        return ok

    def lane_check(self, label: str, rec, claimed) -> bool:
        """Replay-rode-the-lane assertion (ISSUE 16): each parity label
        CLAIMS which execution lane a side rode, and the lane-decision
        flight recorder (common/device_stats.record_lanes) proves it.
        This closes the oracle's silent failure mode — two replays that
        BOTH fall back to the same ladder rung compare byte-equal while
        the pair under test never actually ran. `claimed` is a lane name
        or a tuple of acceptable lanes (ladders whose rung legitimately
        depends on corpus state, e.g. quantized builds on young
        segments)."""
        self.lane_checks += 1
        lanes = tuple([claimed] if isinstance(claimed, str) else claimed)
        chosen = sorted({e["lane"] for e in rec.entries
                         if e["reason"] == "chosen"}) if rec else []
        if rec is not None and any(rec.chose(ln) for ln in lanes):
            return True
        self.mismatches.append(ParityMismatch(
            f"lane[{label}]", {"claimed_lane": list(lanes)},
            list(lanes), chosen))
        return False


# exception families a DISRUPTED cluster may legitimately surface: the
# caller's link to a copy (or the master) is the thing being broken
_DISRUPTION_OK = ("ConnectTransportException", "RemoteTransportException",
                  "UnavailableShardsException", "NoMasterException",
                  "TimeoutError")


def classify(exc: Exception, disrupted: bool) -> str | None:
    """None when the failure is acceptable, else a violation string.

    Acceptable = anything the REST boundary maps below 500 (breaker
    trips / sheds / rejections are 429s, validation is 4xx — the
    'never an unclassified 5xx' contract), plus transport/availability
    errors while a disruption is actively severing links."""
    from ...rest.http_server import _status_of
    if _status_of(exc) < 500:
        return None
    if disrupted and type(exc).__name__ in _DISRUPTION_OK:
        return None
    return (f"unclassified 5xx-class failure "
            f"({type(exc).__name__}: {exc}) "
            f"{'under disruption' if disrupted else 'with no fault active'}")


def control_plane_violations(nodes) -> list[str]:
    """state/ping traffic classes must never shed — overload shedding
    that takes out the control plane turns degradation into an outage."""
    out = []
    for n in nodes:
        qos = getattr(n, "qos", None)
        if qos is None:
            continue
        shed = qos.control_plane_shed()
        if shed:
            out.append(f"control-plane class shed {shed}x on "
                       f"[{getattr(n, 'node_id', 'node')}]")
    return out
