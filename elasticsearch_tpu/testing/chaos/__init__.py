"""Chaos harness (ISSUE 14): seeded randomized disruption, leak
detectors, and a cross-lane bitwise-parity oracle.

One `random.Random(seed)` drives everything — the workload, the
disruption schedule, and the query sample — so any failure reproduces
from the single `CHAOS_SEED` printed in its message (the
ESIntegTestCase `REPRODUCE WITH` line, collapsed to one integer).

    from elasticsearch_tpu.testing.chaos import ChaosOptions, ChaosRunner
    report = ChaosRunner(path, ChaosOptions(seed=7)).run()
"""

from .detectors import arm, armed, breaker_problems, disarm, seed_tag
from .runner import ChaosFailure, ChaosOptions, ChaosReport, ChaosRunner
from .scheme import DisruptionScheme
from .workload import SeededWorkload

__all__ = [
    "ChaosFailure", "ChaosOptions", "ChaosReport", "ChaosRunner",
    "DisruptionScheme", "SeededWorkload",
    "arm", "armed", "breaker_problems", "disarm", "seed_tag",
]
