"""ChaosRunner: seeded rounds of workload + disruption + parity sweep +
invariant checks, over a single-node twin-index ladder AND a live
multi-node cluster, with leak detectors armed throughout.

Every random choice flows from `ChaosOptions.seed`; the seed is
exported as `CHAOS_SEED` for the duration of the run so any assertion
raised anywhere underneath (including engine leak checks) carries the
reproducing integer in its message.
"""

from __future__ import annotations

import copy
import os
import random

from ...cluster.host_reduce import HOST_REDUCE_SETTING
from ...common.settings import Settings
from ...index.engine import SearcherLeakError
from . import detectors
from .oracle import ParityOracle, classify, control_plane_violations
from .scheme import DisruptionScheme
from .workload import SeededWorkload

# the twin-index ladder: same docs under every dense-lane configuration
# the engine documents as bitwise-equivalent (index-creation-time
# settings are the lane toggles)
_TWINS = [
    ("c-loop", {"index.search.stacked.enable": False,
                "index.search.blockwise.enable": False,
                "index.search.mesh.enable": False}),
    ("c-stacked", {"index.search.blockwise.enable": False,
                   "index.search.mesh.enable": False}),
    ("c-block", {"index.search.mesh.enable": False,
                 "index.search.block_docs": 64}),
    ("c-mesh", {}),
]

_KNN_SETTINGS = {"index.knn.ivf.nlist": 4, "index.knn.ivf.nprobe": 2,
                 "index.knn.ivf.min_docs": 16, "index.knn.precision": "f32"}


class ChaosFailure(AssertionError):
    """Any chaos-run failure: the message leads with the reproducing
    seed (the `REPRODUCE WITH` line of this harness)."""

    def __init__(self, seed: int, problems: list):
        detail = "\n  ".join(str(p) for p in problems)
        super().__init__(
            f"chaos run failed [CHAOS_SEED={seed}] — reproduce with "
            f"CHAOS_SEED={seed}:\n  {detail}")
        self.seed = seed
        self.problems = problems


class ChaosOptions:
    __test__ = False

    def __init__(self, seed: int, rounds: int = 3, docs_per_round: int = 48,
                 dims: int = 8, cluster_nodes: int = 3, shards: int = 4,
                 replicas: int = 1, transport: str = "local",
                 inject_parity_fault: bool = False,
                 raise_on_failure: bool = True,
                 extended_roster: bool = False, pods: int = 0):
        self.seed = seed
        self.rounds = rounds
        self.docs_per_round = docs_per_round
        self.dims = dims
        # 0 disables the cluster half (the cheap single-node-only mode
        # the bench leg uses)
        self.cluster_nodes = cluster_nodes
        self.shards = shards
        self.replicas = replicas
        self.transport = transport
        self.inject_parity_fault = inject_parity_fault
        self.raise_on_failure = raise_on_failure
        # opt-in kill/restart + clock-skew disruptions (scheme roster).
        # Off by default so pinned-seed schedules stay bit-identical.
        self.extended_roster = extended_roster
        # pod mode (ISSUE 19): every cluster node owns a disjoint device
        # slice and nodes spread over `pods` simulated hosts, so the
        # roster runs over the multi-host / per-node-pool transport
        self.pods = pods


class ChaosReport:
    __test__ = False

    def __init__(self, seed: int):
        self.seed = seed
        self.rounds = 0
        self.parity_checks = 0
        self.lane_checks = 0
        self.mismatches: list = []
        self.invariant_violations: list[str] = []
        self.disruptions: list[str] = []
        self.faults_injected = 0
        self.acked_writes = 0
        self.hedges_fired = 0

    def ok(self) -> bool:
        return not self.mismatches and not self.invariant_violations

    def as_dict(self) -> dict:
        return {"seed": self.seed, "rounds": self.rounds,
                "parity_checks": self.parity_checks,
                "lane_checks": self.lane_checks,
                "mismatches": len(self.mismatches),
                "invariant_violations": len(self.invariant_violations),
                "disruptions": list(self.disruptions),
                "faults_injected": self.faults_injected,
                "acked_writes": self.acked_writes}


class ChaosRunner:
    __test__ = False

    def __init__(self, path: str, options: ChaosOptions):
        self.path = str(path)
        self.opt = options
        self.rng = random.Random(options.seed)
        self.report = ChaosReport(options.seed)
        self.oracle = ParityOracle(options.inject_parity_fault)
        self.node = None
        self.cluster = None
        self.scheme = None
        self._acked: list[str] = []

    # -- lifecycle ----------------------------------------------------------

    def run(self) -> ChaosReport:
        prev_seed = os.environ.get("CHAOS_SEED")
        os.environ["CHAOS_SEED"] = str(self.opt.seed)
        detectors.arm()
        try:
            self._setup()
            for _ in range(self.opt.rounds):
                self._round()
                self.report.rounds += 1
            self._final_invariants()
        except Exception as e:
            # ANY unexpected failure must carry the reproducing seed
            raise ChaosFailure(self.opt.seed,
                               [f"{type(e).__name__}: {e}"]) from e
        finally:
            self._teardown()
            if prev_seed is None:
                os.environ.pop("CHAOS_SEED", None)
            else:
                os.environ["CHAOS_SEED"] = prev_seed
        self.report.parity_checks = self.oracle.checks
        self.report.lane_checks = self.oracle.lane_checks
        self.report.mismatches = list(self.oracle.mismatches)
        problems = self.report.mismatches + self.report.invariant_violations
        if problems and self.opt.raise_on_failure:
            raise ChaosFailure(self.opt.seed, problems)
        return self.report

    def _setup(self) -> None:
        from ...node import NodeService
        self.solo_work = SeededWorkload(
            random.Random(self.rng.randrange(2 ** 62)), self.opt.dims)
        self.node = NodeService(os.path.join(self.path, "solo"), Settings({
            # the chaos corpus is tiny; a latency-EWMA spike from first
            # compiles must not shed the parity sweep
            "node.search.qos.shed_latency_ms": 0}))
        mapping = self.solo_work.mapping()
        for name, extra in _TWINS:
            self.node.create_index(
                name, settings={"number_of_shards": 2,
                                **_KNN_SETTINGS, **extra},
                mappings={"_doc": mapping})
        if self.opt.cluster_nodes:
            from ...cluster.harness import TestCluster
            self.cluster_work = SeededWorkload(
                random.Random(self.rng.randrange(2 ** 62)), self.opt.dims)
            self.cluster = TestCluster(
                self.opt.cluster_nodes, os.path.join(self.path, "cluster"),
                transport=self.opt.transport, pods=self.opt.pods)
            client = self.cluster.client()
            client.create_index("docs", {
                "number_of_shards": self.opt.shards,
                "number_of_replicas": self.opt.replicas,
                **_KNN_SETTINGS})
            client.put_mapping("docs", "_doc", mapping)
            self.cluster.ensure_green()
            self.scheme = DisruptionScheme(
                self.cluster, random.Random(self.rng.randrange(2 ** 62)),
                extended_roster=self.opt.extended_roster)

    # -- one round ----------------------------------------------------------

    def _round(self) -> None:
        self._solo_writes()
        if self.cluster is not None:
            started = self.scheme.start_round()
            self.report.disruptions.extend(started)
            try:
                self._cluster_traffic_under_disruption()
            finally:
                self.scheme.heal()
        self._solo_parity_sweep()
        if self.cluster is not None:
            self._cluster_parity_sweep()
            if self.opt.pods:
                self._pod_invariants()
            self._acked_write_check()
            self.report.invariant_violations.extend(
                control_plane_violations(
                    [self.node, *self.cluster.nodes.values()]))
            self.report.faults_injected = self._cluster_faults()

    def _solo_writes(self) -> None:
        w = self.solo_work
        docs = w.next_docs(self.opt.docs_per_round)
        victims = w.victim_ids(self.rng.randint(2, 5))
        merge = self.rng.random() < 0.5
        # every twin sees the identical write/delete/merge sequence — the
        # precondition for cross-lane parity (stats included: a merge
        # purges deletes, so it must happen on ALL twins or none)
        for name, _ in _TWINS:
            for doc_id, src in docs:
                self.node.index_doc(name, doc_id, copy.deepcopy(src))
            for doc_id in victims:
                try:
                    self.node.delete_doc(name, doc_id)
                except Exception:
                    pass        # already deleted in an earlier round
            if merge:
                self.node.force_merge(name)
            self.node.refresh(name)

    def _search_lanes(self, index: str, body: dict):
        """Search with the lane-decision flight recorder armed (ISSUE
        16): returns (response, LaneRecorder) so the parity sweep can
        assert the replay actually rode the lane its label claims."""
        from ...common.device_stats import record_lanes
        with record_lanes() as rec:
            resp = self.node.search(index, copy.deepcopy(body))
        return resp, rec

    # lanes each twin may legitimately ride for the seeded text bodies:
    # the packed serve lane coalesces packed-servable plans even for solo
    # requests (on every twin), the sparse postings lane outranks the
    # dense ladder for pure-term shapes, and blockwise only engages when
    # the stack exceeds one block — so the claim is a set per twin, and
    # the check still catches the real failure (a twin silently riding
    # the LOOP lane because its configured dense lane declined)
    _TWIN_LANES = {
        "c-stacked": ("stacked", "stacked_blockwise", "sparse", "packed"),
        "c-block": ("stacked", "stacked_blockwise", "sparse", "packed"),
        "c-mesh": ("mesh", "sparse", "packed"),
    }

    def _solo_parity_sweep(self) -> None:
        texts = self.solo_work.text_queries(8)
        for body in texts:
            ref = self.node.search("c-loop", copy.deepcopy(body))
            for name, _ in _TWINS[1:]:
                got, rec = self._search_lanes(name, body)
                if self.oracle.compare(f"loop-vs-{name}", body, ref, got):
                    self.oracle.lane_check(f"loop-vs-{name}", rec,
                                           self._TWIN_LANES[name])
        # batched vs solo: the msearch lane coalesces compatible plans
        # into ONE Q>1 program; responses must equal the solo path's
        reqs = [({"index": "c-mesh"}, copy.deepcopy(b)) for b in texts[:4]]
        batch = self.node.msearch(reqs)
        for body, sub in zip(texts[:4], batch["responses"]):
            solo = self.node.search("c-mesh", copy.deepcopy(body))
            self.oracle.compare("batched-vs-solo", body, solo, sub)
        self._sorted_parity()
        self._subagg_parity()
        self._composite_parity()
        self._knn_parity()
        self._percolate_parity()
        self._script_parity()

    # sorted bodies ride the ISSUE 17 sorted device lanes (the sparse
    # postings lane never serves a sorted plan); the claim catches a
    # twin quietly answering sorted bodies through the per-segment loop
    _SORTED_TWIN_LANES = {
        "c-stacked": ("stacked", "stacked_blockwise", "packed"),
        "c-block": ("stacked", "stacked_blockwise", "packed"),
        "c-mesh": ("mesh", "packed"),
    }

    def _sorted_parity(self) -> None:
        """Sorted-query replay pairs (ISSUE 17): the encoded-key device
        sort on every dense twin vs the loop's materialized-value
        merge — documented bitwise — plus a search_after page-2 replay
        whose cursor is the reference page's last `sort`, so the
        duplicate-key (_shard, _doc) tie-break is part of the pair."""
        for body in self.solo_work.sorted_queries(4):
            ref = self.node.search("c-loop", copy.deepcopy(body))
            for name, _ in _TWINS[1:]:
                got, rec = self._search_lanes(name, body)
                if self.oracle.compare(f"sorted-loop-vs-{name}", body,
                                       ref, got):
                    self.oracle.lane_check(
                        f"sorted-loop-vs-{name}", rec,
                        self._SORTED_TWIN_LANES[name])
            hits = ref["hits"]["hits"]
            if not hits or "sort" not in hits[-1]:
                continue
            page2 = {**copy.deepcopy(body),
                     "search_after": copy.deepcopy(hits[-1]["sort"])}
            ref2 = self.node.search("c-loop", copy.deepcopy(page2))
            for name, _ in _TWINS[1:]:
                got, rec = self._search_lanes(name, page2)
                if self.oracle.compare(f"search-after-loop-vs-{name}",
                                       page2, ref2, got):
                    self.oracle.lane_check(
                        f"search-after-loop-vs-{name}", rec,
                        self._SORTED_TWIN_LANES[name])

    def _subagg_parity(self) -> None:
        """Sub-agg-tree replay pairs (ISSUE 17): the composite-bin
        device planner (histogram/terms parents, integer-exact leaf
        metrics) vs the host's recursive per-segment collect —
        documented bitwise on every twin."""
        for body in self.solo_work.subagg_queries(3):
            ref = self.node.search("c-loop", copy.deepcopy(body))
            for name, _ in _TWINS[1:]:
                got, rec = self._search_lanes(name, body)
                if self.oracle.compare(f"subagg-loop-vs-{name}", body,
                                       ref, got):
                    self.oracle.lane_check(f"subagg-loop-vs-{name}",
                                           rec, self._TWIN_LANES[name])

    def _composite_parity(self) -> None:
        """Composite + pipeline replay pairs (ISSUE 20): the composite
        collect and host-side pipeline render are lane-invariant by
        construction — every twin answers byte-equal to the loop, with
        an `after`-key page-2 replay so cursor pagination is part of
        the pair. On the mesh twin a composite body must decline the
        collective planner under its STABLE reason ("composite") — a
        renamed/dropped reason breaks the explain surface's contract."""
        for body in self.solo_work.composite_queries(3):
            ref = self.node.search("c-loop", copy.deepcopy(body))
            for name, _ in _TWINS[1:]:
                got, rec = self._search_lanes(name, body)
                self.oracle.compare(f"composite-loop-vs-{name}", body,
                                    ref, got)
                if name == "c-mesh" and "pages" in body["aggs"]:
                    want = ["composite"]
                    seen = sorted({e["reason"] for e in rec.entries
                                   if e["component"] == "coordinator.aggs"
                                   and e["lane"] == "mesh"
                                   and e["reason"] != "chosen"})
                    self.oracle.compare(
                        f"composite-decline-reason-{name}", body,
                        {"declines": want}, {"declines": seen})
            comp = (ref.get("aggregations") or {}).get("pages")
            if comp and comp.get("after_key"):
                page2 = copy.deepcopy(body)
                page2["aggs"]["pages"]["composite"]["after"] = \
                    copy.deepcopy(comp["after_key"])
                ref2 = self.node.search("c-loop", copy.deepcopy(page2))
                for name, _ in _TWINS[1:]:
                    got, _rec = self._search_lanes(name, page2)
                    self.oracle.compare(
                        f"composite-after-loop-vs-{name}", page2,
                        ref2, got)

    def _percolate_parity(self) -> None:
        """Reverse-search replay pairs (ISSUE 18): the dense doc×query
        matrix executor vs the per-doc loop reference over the SAME
        registry — documented bitwise, wildcard residuals merged through
        the loop rung on both sides. Queries register on EVERY twin (same
        writes on all twins is the cross-lane parity precondition — a
        one-twin registry would skew doc counts and idf), and re-register
        each round so the generation-keyed corpus cache turns over."""
        from ...common.device_stats import record_lanes
        from ...search import percolator as perc_mod

        queries = self.solo_work.percolator_queries(7)
        for name, _ in _TWINS:
            for qi, q in enumerate(queries):
                self.node.index_doc(name, f"pq-{qi}", {"query": q},
                                    type_name=".percolator")
            self.node.refresh(name)
        name = _TWINS[1][0]
        svc = self.node.indices[name]
        for doc in self.solo_work.percolate_docs(4):
            registry = perc_mod.parsed_registry(svc)
            _, seg, root = perc_mod.build_doc_segment(
                svc, copy.deepcopy(doc))
            ref_ids = sorted(perc_mod.loop_match(registry, seg, root))
            ref = {"total": len(ref_ids),
                   "matches": [{"_index": name, "_id": i}
                               for i in ref_ids]}
            with record_lanes() as rec:
                got = self.node.percolate(name, {"doc": copy.deepcopy(doc)})
            got_c = {"total": got["total"], "matches": got["matches"]}
            if self.oracle.compare("percolate-dense-vs-loop",
                                   {"doc": doc}, ref, got_c):
                self.oracle.lane_check("percolate-dense-vs-loop", rec,
                                       ("dense", "mesh"))

    def _script_parity(self) -> None:
        """Compiled script_score vs the host evaluator (ISSUE 18): the
        SAME expression, once compiled to the fused device op and once
        wrapped in a host-only no-op conditional (`(e) if true else 0.0`
        — an IfExp the compiler declines with a stable reason) so it
        rides the per-doc host evaluator. Both lanes evaluate in f64 and
        the expression pool sticks to the exact-IEEE subset, so scores
        must match bitwise."""
        for w, expr, params in self.solo_work.script_exprs(3):
            def body(src):
                return {"size": 10, "query": {"function_score": {
                    "query": {"match": {"body": w}},
                    "script_score": {"script": src,
                                     "params": dict(params)},
                    "boost_mode": "replace"}}}
            ref, _ref_rec = self._search_lanes(
                "c-stacked", body(f"({expr}) if true else 0.0"))
            got, rec = self._search_lanes("c-stacked", body(expr))
            if self.oracle.compare("script-compiled-vs-host",
                                   body(expr), ref, got):
                self.oracle.lane_check("script-compiled-vs-host", rec,
                                       "compiled")

    def _knn_parity(self) -> None:
        for body in self.solo_work.knn_queries(3):
            knn = body["knn"]
            exact = {**body, "knn": {**knn, "exact": True}}
            ref, ref_rec = self._search_lanes("c-loop", exact)
            self.oracle.lane_check("knn-exact-ref", ref_rec, "exact")
            # IVF with nprobe >= nlist routes to the exact kernel —
            # documented bitwise parity, same index
            full = {**body, "knn": {**knn, "nprobe": 64}}
            got, rec = self._search_lanes("c-loop", full)
            self.oracle.compare("ivf-full-vs-exact", body, ref, got)
            self.oracle.lane_check("ivf-full-vs-exact", rec, "exact")
            # the exact kernel across twins (mesh exact lane declines to
            # the fan-out; either way the result is the same program)
            got, rec = self._search_lanes("c-mesh", exact)
            self.oracle.compare("knn-exact-loop-vs-mesh", body, ref, got)
            self.oracle.lane_check("knn-exact-loop-vs-mesh", rec, "exact")
            # int8 through the mesh lane vs the per-shard fan-out — the
            # documented quantized bitwise pair (f32-vs-quantized is
            # approximate by design and is NOT compared). The lane claim
            # is conditional: whenever the fan-out side built the
            # quantized tier, the mesh side must have rode mesh_knn —
            # both sides quietly falling back to the same rung would
            # pass parity without testing the pair at all
            int8 = {**body, "knn": {**knn, "quantization": "int8"}}
            ref8, ref8_rec = self._search_lanes("c-loop", int8)
            got8, got8_rec = self._search_lanes("c-mesh", int8)
            self.oracle.compare("knn-int8-loop-vs-mesh", body, ref8, got8)
            if ref8_rec.chose("ann_quantized"):
                self.oracle.lane_check("knn-int8-loop-vs-mesh", got8_rec,
                                       "mesh_knn")
        fbody = self.solo_work.filtered_knn_query()
        fref, fref_rec = self._search_lanes("c-loop", fbody)
        fgot, fgot_rec = self._search_lanes("c-mesh", fbody)
        self.oracle.compare("knn-filtered-loop-vs-mesh", fbody, fref, fgot)
        if fref_rec.chose("ann"):
            self.oracle.lane_check("knn-filtered-loop-vs-mesh", fgot_rec,
                                   "mesh_knn")

    # -- cluster half -------------------------------------------------------

    def _client(self):
        return self.cluster.client()

    def _cluster_traffic_under_disruption(self) -> None:
        w = self.cluster_work
        client = self._client()
        # fault detection runs WITH the faults live — the master must
        # react (remove the isolated node / step down), never crash
        self.cluster.detect_once()
        for doc_id, src in w.next_docs(self.opt.docs_per_round // 2):
            try:
                client.index_doc("docs", doc_id, src)
                self._acked.append(doc_id)
                self.report.acked_writes += 1
            except Exception as e:
                v = classify(e, disrupted=True)
                if v:
                    self.report.invariant_violations.append(f"write: {v}")
        for body in w.text_queries(4):
            try:
                client.search("docs", body)
            except Exception as e:
                v = classify(e, disrupted=True)
                if v:
                    self.report.invariant_violations.append(f"search: {v}")
        for doc_id in w.victim_ids(2):
            try:
                client.get_doc("docs", doc_id)
            except Exception as e:
                v = classify(e, disrupted=True)
                if v:
                    self.report.invariant_violations.append(f"get: {v}")
        self.cluster.detect_once()

    def _cluster_parity_sweep(self) -> None:
        """Post-heal: host-reduce vs the per-shard transport merge on
        the SAME queries (the cluster's lane pair), toggled live via the
        cluster setting."""
        client = self._client()
        # recoveries stream on background threads: wait for every copy
        # to be STARTED before refreshing, or a replica can come up
        # BETWEEN the two compared searches serving a pre-refresh view
        self.cluster.ensure_green(20.0)
        client.refresh("docs")
        bodies = self.cluster_work.text_queries(4)
        bodies.append({"size": 5, "knn": {
            "field": "vec", "query_vector": self.cluster_work.vector(),
            "k": 5}})
        from ...common.device_stats import record_lanes
        for body in bodies:
            try:
                with record_lanes() as got_rec:
                    got = client.search("docs", copy.deepcopy(body))
                self._set_cluster_setting(
                    "cluster.search.host_reduce.enable", False)
                with record_lanes() as want_rec:
                    want = client.search("docs", copy.deepcopy(body))
                self.oracle.compare("host-reduce-vs-fanout", body, want, got)
                # lane claims (ISSUE 16): with the setting ON the
                # coordinator must at least CONSULT the host-reduce
                # ladder (a chosen lane or an explained decline —
                # contextvars ride the per-host fan-out threads); with it
                # OFF, riding host_reduce anyway means the toggle is dead
                if not any(e["lane"] == "host_reduce"
                           for e in got_rec.entries):
                    self.report.invariant_violations.append(
                        f"host-reduce ladder never consulted with "
                        f"{HOST_REDUCE_SETTING}=true for {body!r}")
                if want_rec.chose("host_reduce"):
                    self.report.invariant_violations.append(
                        f"host_reduce lane rode with "
                        f"{HOST_REDUCE_SETTING}=false for {body!r}")
            finally:
                self._set_cluster_setting(
                    "cluster.search.host_reduce.enable", True)

    def _pod_invariants(self) -> None:
        """Pod-mode invariants (ISSUE 19): every surviving node OWNS a
        disjoint device slice; on each node co-hosting >= 2 shards the
        host reduce rides that node's OWN mesh (a direct, deterministic
        per-node probe — the sweep's coordinator-side copy choice is
        adaptive); and the per-node data plane never touches the shared
        EXEC_LOCK."""
        from ...cluster.host_reduce import try_host_reduce
        from ...parallel.mesh_exec import exec_lock_stats
        viol = self.report.invariant_violations
        live = [n for n in self.cluster.nodes.values() if not n.closed]
        owner: dict[int, str] = {}
        for n in live:
            pool = getattr(n, "device_pool", None)
            if pool is None:
                viol.append(f"pod mode: {n.node_id} owns no device pool")
                continue
            for did in pool.devkey:
                if did in owner:
                    viol.append(f"pod mode: device {did} owned by both "
                                f"{owner[did]} and {n.node_id}")
                owner[did] = n.node_id
        shared0 = exec_lock_stats()["shared_acquisitions"]
        rode = 0
        for n in live:
            if getattr(n, "device_pool", None) is None:
                continue
            with n._shards_lock:
                sids = sorted(sid for (ix, sid), h in n._shards.items()
                              if ix == "docs" and h.engine is not None)
            if len(sids) < 2:
                continue
            # cap the group at what the node's slice can mesh (s_pad
            # must fit the pool) — the ride itself is what's asserted
            cap = len(n.device_pool.devices)
            out, reason = try_host_reduce(
                n, "docs", sids[:cap], {"query": {"match_all": {}}},
                10, None)
            if out is None:
                viol.append(f"pod mode: host reduce declined on "
                            f"{n.node_id} ({reason})")
            else:
                rode += 1
            self.oracle.lane_checks += 1
        if live and not rode:
            viol.append("pod mode: host reduce rode no node's mesh")
        shared1 = exec_lock_stats()["shared_acquisitions"]
        if shared1 != shared0:
            viol.append(
                f"pod mode: per-node reduce took the shared EXEC_LOCK "
                f"{shared1 - shared0}x — pools must dispatch lock-free")

    def _set_cluster_setting(self, key: str, val) -> None:
        master = self.cluster.master_node()

        def task(cur):
            st = cur.mutate()
            st.data.setdefault("settings", {})[key] = val
            return st
        master.cluster.submit_task(f"chaos-setting[{key}]", task)

    def _acked_write_check(self) -> None:
        """Every write acked on the quorum side must be retrievable
        after the partition heals (the split-brain acked-write
        invariant)."""
        client = self._client()
        sample = self._acked if len(self._acked) <= 20 \
            else self.rng.sample(self._acked, 20)
        for doc_id in sample:
            try:
                got = client.get_doc("docs", doc_id)
                found = bool(got.get("found"))
            except Exception as e:
                self.report.invariant_violations.append(
                    f"acked write [{doc_id}] unreadable after heal: {e!r}")
                continue
            if not found:
                self.report.invariant_violations.append(
                    f"acked write [{doc_id}] lost after heal")

    def _cluster_faults(self) -> int:
        fs = getattr(self.cluster.network, "fault_stats", None)
        return fs()["faults_injected_total"] if fs else 0

    # -- teardown invariants ------------------------------------------------

    def _final_invariants(self) -> None:
        if self.cluster is not None:
            hedged = sum(n.hedge_stats.get("fired", 0)
                         for n in self.cluster.nodes.values())
            self.report.hedges_fired = hedged

    def _teardown(self) -> None:
        viol = self.report.invariant_violations
        if self.cluster is not None:
            for n in self.cluster.nodes.values():
                try:
                    if not n.closed:
                        n.close()
                except SearcherLeakError as e:
                    viol.append(str(e))
            if hasattr(self.cluster.network, "close"):
                self.cluster.network.close()
            self.cluster = None
        if self.node is not None:
            caches, breakers = self.node.caches, self.node.breakers
            try:
                self.node.close()
            except SearcherLeakError as e:
                viol.append(str(e))
            # after close every cache owner is gone: residue in any tier
            # (or any non-drained breaker) is a real leak
            viol.extend(detectors.cache_problems(caches))
            viol.extend(detectors.breaker_problems(breakers))
            self.node = None
