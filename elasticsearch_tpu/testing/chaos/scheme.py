"""Seeded disruption scheme — the MockTransportService /
ServiceDisruptionScheme analog. Picks disruptions from one
`random.Random` and applies them through the transport fault seams both
transports share (`partition` / `disconnect` / `add_rule` /
`add_delay`), plus node kills through the harness.

A disruption object is `start()`-ed for a round and `stop()`-ed before
heal; `DisruptionScheme.heal()` clears every rule/link and drives fault
detection until the cluster converges, so rounds compose without
leaking faults into each other.
"""

from __future__ import annotations

import random

from ...cluster.node import A_GET, A_QUERY, A_WRITE_R_BULK

# action classes a drop rule may target: data traffic only — never the
# discovery/ping plane, which the partition disruption owns (dropping
# pings without a real partition would just flap fault detection)
DROPPABLE_PREFIXES = [
    A_WRITE_R_BULK,                       # replica bulk only
    "indices:data/read/search",           # the whole search family
    A_GET,                                # realtime gets
]


class Disruption:
    kind = "?"

    def start(self, cluster) -> None:
        raise NotImplementedError

    def stop(self, cluster) -> None:
        """Best-effort targeted teardown; DisruptionScheme.heal() is the
        backstop that clears everything regardless."""

    def describe(self) -> str:
        return self.kind


class IsolateNode(Disruption):
    """Two-way partition of one non-master node from the rest (the
    NetworkPartition minority side). The quorum side keeps a master and
    keeps acking writes; the isolated side must step down rather than
    ack writes it can no longer replicate."""

    kind = "isolate_node"

    def __init__(self, node_id: str):
        self.node_id = node_id

    def start(self, cluster) -> None:
        others = [nid for nid in cluster.nodes
                  if nid != self.node_id and not cluster.nodes[nid].closed]
        cluster.network.partition([self.node_id], others)

    def stop(self, cluster) -> None:
        cluster.network.heal()

    def describe(self) -> str:
        return f"isolate_node[{self.node_id}]"


class DropAction(Disruption):
    """Action-prefix-scoped drop rule: kills one traffic class into one
    node while everything else (fault-detection pings included) keeps
    flowing — the failure mode a full partition can't produce."""

    kind = "drop_action"

    def __init__(self, node_id: str, prefix: str):
        self.node_id = node_id
        self.prefix = prefix

    def start(self, cluster) -> None:
        cluster.network.add_rule(self.node_id, self.prefix)

    def stop(self, cluster) -> None:
        cluster.network.clear_rule(self.node_id, self.prefix)

    def describe(self) -> str:
        return f"drop_action[{self.node_id}, {self.prefix}]"


class SlowNode(Disruption):
    """Inject per-send latency on the query action into one node — the
    seam the hedged-read coordinator is built to cover."""

    kind = "slow_node"

    def __init__(self, node_id: str, delay_s: float):
        self.node_id = node_id
        self.delay_s = delay_s

    def start(self, cluster) -> None:
        cluster.network.add_delay(self.node_id, A_QUERY, self.delay_s)

    def stop(self, cluster) -> None:
        cluster.network.clear_delay(self.node_id, A_QUERY)

    def describe(self) -> str:
        return f"slow_node[{self.node_id}, {self.delay_s}s]"


class KillRestartNode(Disruption):
    """Abrupt process death of one non-master node for the duration of a
    round (ref InternalTestCluster.restartRandomDataNode). The master
    must fail the node's shards — including any recovery the node was
    mid-stream on, source or target side — and the restart must rejoin
    and recover without acked-write loss or leaked engines."""

    kind = "kill_restart"

    def __init__(self, node_id: str):
        self.node_id = node_id

    def start(self, cluster) -> None:
        cluster.kill_node(self.node_id)
        cluster.detect_once()

    def stop(self, cluster) -> None:
        cluster.restart_node(self.node_id)

    def describe(self) -> str:
        return f"kill_restart[{self.node_id}]"


class ClockSkew(Disruption):
    """Skew one node's *reported* wall clock. Correctness invariant: only
    wall-clock timestamps (e.g. _cat/recovery start_time_ms) may move —
    durations, throttling and timeouts are monotonic-based and must be
    unaffected, which the chaos tests assert."""

    kind = "clock_skew"

    def __init__(self, node_id: str, skew_s: float):
        self.node_id = node_id
        self.skew_s = skew_s

    def start(self, cluster) -> None:
        cluster.nodes[self.node_id].clock_skew_s = self.skew_s

    def stop(self, cluster) -> None:
        node = cluster.nodes.get(self.node_id)
        if node is not None:
            node.clock_skew_s = 0.0

    def describe(self) -> str:
        return f"clock_skew[{self.node_id}, {self.skew_s}s]"


class DisruptionScheme:
    def __init__(self, cluster, rng: random.Random,
                 extended_roster: bool = False):
        self.cluster = cluster
        self.rng = rng
        # opt-in: kill/restart + clock-skew join the draw. Default stays
        # the original three kinds so pinned-seed schedules (the tier-1
        # seed-1234 smoke) are bit-identical with the flag off.
        self.extended_roster = extended_roster
        self.active: list[Disruption] = []
        self.applied: list[str] = []      # full history, for the report

    def _non_master_ids(self) -> list[str]:
        master = self.cluster.master_node()
        mid = master.node_id if master is not None else None
        return sorted(nid for nid, n in self.cluster.nodes.items()
                      if not n.closed and nid != mid)

    def pick(self, max_n: int = 2) -> list[Disruption]:
        """Choose 1..max_n disruptions for a round. At most one
        link-level disruption (isolation) per round so a quorum always
        remains to ack writes."""
        victims = self._non_master_ids()
        if not victims:
            return []
        out: list[Disruption] = []
        kinds = ["isolate", "drop", "slow"]
        if self.extended_roster:
            kinds += ["kill", "skew"]
        self.rng.shuffle(kinds)
        node_level = 0      # at most one of isolate/kill per round
        for kind in kinds[:self.rng.randint(1, max_n)]:
            victim = self.rng.choice(victims)
            if kind == "isolate":
                if node_level:
                    continue
                node_level += 1
                out.append(IsolateNode(victim))
            elif kind == "kill":
                if node_level:
                    continue
                node_level += 1
                out.append(KillRestartNode(victim))
            elif kind == "skew":
                out.append(ClockSkew(
                    victim, round(self.rng.uniform(-120.0, 120.0), 1)))
            elif kind == "drop":
                out.append(DropAction(
                    victim, self.rng.choice(DROPPABLE_PREFIXES)))
            else:
                out.append(SlowNode(victim,
                                    round(self.rng.uniform(0.05, 0.2), 3)))
        return out

    def start_round(self, max_n: int = 2) -> list[str]:
        assert not self.active, "previous round not healed"
        self.active = self.pick(max_n)
        for d in self.active:
            d.start(self.cluster)
            self.applied.append(d.describe())
        return [d.describe() for d in self.active]

    def heal(self, timeout: float = 20.0) -> None:
        # clear link faults FIRST: a KillRestartNode.stop() rejoins the
        # master over the network, which must not race a still-active
        # partition against the same node id
        self.cluster.network.heal()
        for d in self.active:
            d.stop(self.cluster)
        self.active = []
        self.cluster.network.heal()
        # converge: fault detection notices rejoins/step-downs, the
        # allocator re-assigns, replicas re-sync
        self.cluster.detect_once()
        self.cluster.ensure_yellow_or_green(timeout)
