"""Leak detectors (the AssertingSearcher / MockEngine analog).

`arm()` flips `index.engine.LEAK_CHECK`: every `Engine.close()` then
asserts its searcher refcounts drained, its per-site breaker ledger is
balanced, and no fielddata cache entries survived the engine — raising
`SearcherLeakError` naming the acquire/charge SITE plus the
`CHAOS_SEED` tag when one is exported. The conftest arms this for the
whole suite, so an engine leaked by ANY test fails loudly instead of
silently inflating the parent breaker for the tests behind it.

This module owns the flag flip (rather than tests importing engine
internals) so the engine module never imports testing code — the
production tree stays one-directional.
"""

from __future__ import annotations

from ...index import engine as _engine


def arm() -> None:
    _engine.LEAK_CHECK = True


def disarm() -> None:
    _engine.LEAK_CHECK = False


def armed() -> bool:
    return bool(_engine.LEAK_CHECK)


def seed_tag() -> str:
    """' [CHAOS_SEED=n]' when a chaos run is active, else ''."""
    return _engine._seed_tag()


def breaker_problems(breakers) -> list[str]:
    """Non-drained circuit breakers: every byte charged during a run
    must be released once the engines and caches holding it are closed —
    a residue means an add_estimate without its release (the invariant
    the per-site engine ledger localizes to an acquire site)."""
    problems = []
    for name, st in breakers.stats().items():
        used = st.get("estimated_size_in_bytes", 0)
        if used:
            problems.append(
                f"breaker [{name}] holds {used} bytes after close"
                + seed_tag())
    return problems


def cache_problems(caches) -> list[str]:
    """Cache tiers holding bytes after a full clear (see
    IndicesCacheService.leak_report)."""
    return [p + seed_tag() for p in caches.leak_report()]
