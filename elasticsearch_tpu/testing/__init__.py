"""Test harnesses: the implementation-agnostic REST YAML suite runner
(ref rest-api-spec/test/README.asciidoc + the reference's
test/rest/ElasticsearchRestTests.java runner)."""

from .rest_runner import YamlRestRunner, SectionResult

__all__ = ["YamlRestRunner", "SectionResult"]
