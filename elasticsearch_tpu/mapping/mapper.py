"""Schema layer: field types, document parsing, dynamic mapping.

TPU-native analog of the reference mapper package
(/root/reference/src/main/java/org/elasticsearch/index/mapper/DocumentMapper.java:786,
MapperService.java:993, core/*FieldMapper.java; SURVEY.md §2.4 "Mapper"):
a JSON document is parsed against a (possibly dynamically growing) schema into
typed channels that the tensor segment builder consumes:

  text fields    -> analyzed token lists  -> CSR postings tensors
  keyword fields -> raw strings           -> ordinal columns (global-ords analog)
  numeric fields -> float64/int64         -> dense columns (fielddata analog)
  date fields    -> epoch millis int64    -> dense columns
  boolean fields -> 0/1                   -> dense columns
  dense_vector   -> float list            -> [N, dim] matrix for kNN

Differences from the reference, by design:
  * Object fields flatten to dot-paths (same as reference); `nested` objects
    parse into per-element sub-documents (ParsedDocument.nested) that the
    segment builder lays out as ADJACENT ROWS before their root document
    with a parent-pointer column — the tensor analog of Lucene's block join
    (ref index/mapper/object/ObjectMapper.java nested mode).
  * `_parent` (ref index/mapper/internal/ParentFieldMapper) becomes a
    keyword column `_parent` on child documents; the parent id doubles as
    routing so parent and children share a shard.
  * `string` fields are mapped to text (analyzed) unless
    `"index": "not_analyzed"` (ES 2.x) — and modern `text`/`keyword` types are
    accepted directly.
  * Every text field also records its first 256 chars as a keyword ordinal so
    sorting/aggregating on an analyzed field degrades gracefully.
"""

from __future__ import annotations

import datetime as _dt
import re
from dataclasses import dataclass
from typing import Any

from ..analysis.analyzers import AnalysisService, Analyzer

# ---------------------------------------------------------------------------
# Field types
# ---------------------------------------------------------------------------

TEXT = "text"
KEYWORD = "keyword"
LONG = "long"
INTEGER = "integer"
SHORT = "short"
BYTE = "byte"
DOUBLE = "double"
FLOAT = "float"
DATE = "date"
BOOLEAN = "boolean"
IP = "ip"
DENSE_VECTOR = "dense_vector"
GEO_POINT = "geo_point"
OBJECT = "object"
NESTED = "nested"

# keyword column recording which nested path a sub-document row belongs to
NESTED_PATH_FIELD = "_nested_path"
# keyword column holding a child document's parent id (_parent mapping)
PARENT_FIELD = "_parent"

_INT_TYPES = {LONG, INTEGER, SHORT, BYTE}
_FLOAT_TYPES = {DOUBLE, FLOAT}
NUMERIC_TYPES = _INT_TYPES | _FLOAT_TYPES


@dataclass
class FieldType:
    name: str                      # full dot path
    type: str
    analyzer: str = "standard"
    search_analyzer: str | None = None
    index: bool = True             # indexed (searchable)
    doc_values: bool = True        # columnar fielddata
    store: bool = False
    dims: int = 0                  # dense_vector dimension
    format: str | None = None      # date format
    boost: float = 1.0
    similarity: str | None = None  # named similarity (index/similarity.py)

    def to_dict(self) -> dict:
        """Render in the reference's wire vocabulary: analyzed and
        not-analyzed strings are both "string" (ES 2.x, ref
        index/mapper/core/StringFieldMapper) — _merge_props parses that
        form back losslessly, so the mapping round-trips."""
        if self.type == TEXT:
            out: dict[str, Any] = {"type": "string"}
            if self.analyzer != "standard":
                out["analyzer"] = self.analyzer
            if not self.index:
                out["index"] = "no"
            if self.similarity:
                out["similarity"] = self.similarity
            return out
        if self.type == KEYWORD:
            return {"type": "string", "index": "not_analyzed"}
        out = {"type": self.type}
        if self.type == DENSE_VECTOR:
            out["dims"] = self.dims
        if not self.index:
            out["index"] = False
        return out


class MapperParsingException(Exception):
    pass


class MergeMappingException(Exception):
    pass


class RoutingMissingException(Exception):
    """Child-type doc indexed without a parent/routing value
    (ref action/RoutingMissingException — a 400, caught by YAML suites
    with /RoutingMissingException/)."""


class AlreadyExpiredException(Exception):
    """_ttl + timestamp lies in the past (ref index/AlreadyExpiredException)."""


def parse_ttl_ms(v) -> int:
    """'100000' | 100000 | '20s' | '1d' -> milliseconds."""
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return int(v)
    s = str(v).strip()
    m = re.match(r"^(\d+(?:\.\d+)?)(ms|s|m|h|d|w)?$", s)
    if not m:
        raise MapperParsingException(f"failed to parse TTL [{v}]")
    n = float(m.group(1))
    mult = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000,
            "d": 86_400_000, "w": 7 * 86_400_000, None: 1}[m.group(2)]
    return int(n * mult)


# ---------------------------------------------------------------------------
# Date parsing (ref: common/joda + core/DateFieldMapper)
# ---------------------------------------------------------------------------

_DATE_PATTERNS = [
    "%Y-%m-%dT%H:%M:%S.%f%z", "%Y-%m-%dT%H:%M:%S%z",
    "%Y-%m-%dT%H:%M:%S.%f", "%Y-%m-%dT%H:%M:%S",
    "%Y-%m-%dT%H:%M", "%Y-%m-%d %H:%M:%S", "%Y-%m-%d", "%Y/%m/%d",
    "%Y-%m", "%Y",
]
_ISO_DATE_RE = re.compile(r"^\d{4}(-\d{2}(-\d{2}([T ]\d{2}:\d{2}(:\d{2}(\.\d+)?)?(Z|[+-]\d{2}:?\d{2})?)?)?)?$")


def parse_date_millis(value: Any) -> int:
    """Parse a date value into epoch millis (UTC)."""
    if isinstance(value, bool):
        raise MapperParsingException(f"cannot parse date from boolean [{value}]")
    if isinstance(value, (int, float)):
        return int(value)  # epoch_millis
    s = str(value).strip()
    if re.fullmatch(r"-?\d{10,}", s):
        return int(s)
    z = s.replace("Z", "+0000").replace("z", "+0000")
    # normalize +hh:mm to +hhmm for strptime
    z = re.sub(r"([+-]\d{2}):(\d{2})$", r"\1\2", z)
    for pat in _DATE_PATTERNS:
        try:
            dt = _dt.datetime.strptime(z, pat)
            if dt.tzinfo is None:
                dt = dt.replace(tzinfo=_dt.timezone.utc)
            return int(dt.timestamp() * 1000)
        except ValueError:
            continue
    raise MapperParsingException(f"failed to parse date field [{value}]")


def looks_like_date(s: str) -> bool:
    return bool(_ISO_DATE_RE.match(s.strip()))


def format_date_millis(millis: int) -> str:
    dt = _dt.datetime.fromtimestamp(millis / 1000.0, tz=_dt.timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{dt.microsecond // 1000:03d}Z"


def parse_ip(value: Any) -> int:
    """IPv4 dotted-quad -> uint32 (ref: index/mapper/ip/IpFieldMapper.java)."""
    parts = str(value).split(".")
    if len(parts) != 4:
        raise MapperParsingException(f"failed to parse ip [{value}]")
    n = 0
    for p in parts:
        b = int(p)
        if not 0 <= b <= 255:
            raise MapperParsingException(f"failed to parse ip [{value}]")
        n = (n << 8) | b
    return n


# ---------------------------------------------------------------------------
# Parsed document — the "Lucene Document" analog
# ---------------------------------------------------------------------------

class ParsedDocument:
    """Channel -> field -> values. A __slots__ class, not a dataclass: one
    instance is built per indexed document, and the generated kwargs
    __init__ costs ~5µs — a measurable slice of the 20k+ docs/s ingest
    budget (ISSUE 7)."""

    __slots__ = ("doc_id", "routing", "source", "tokens", "keywords",
                 "numerics", "longs", "vectors", "geo", "nested",
                 "token_enc")

    def __init__(self, doc_id: str, routing: str | None = None,
                 source: dict | None = None):
        self.doc_id = doc_id
        self.routing = routing
        self.source = source
        # optional batched-ingest side channel: field -> [(vocab, ids)]
        # integer encodings of self.tokens (index/bulk_ingest.TextBatcher
        # fills it; SegmentBuilder.add_batch consumes it to skip per-token
        # re-encoding at refresh). None on the per-doc path.
        self.token_enc: dict | None = None
        self.tokens: dict[str, list[str]] = {}    # text: analyzed tokens
        self.keywords: dict[str, list[str]] = {}  # keyword: raw values
        self.numerics: dict[str, list[float]] = {}  # double/float
        self.longs: dict[str, list[int]] = {}     # long/int/date/ip/bool
        self.vectors: dict[str, list[float]] = {}  # dense_vector
        self.geo: dict[str, tuple[float, float]] = {}  # (lat, lon)
        # nested sub-documents: (path, sub-doc) in source order — the
        # builder lays them out as adjacent rows BEFORE this root doc
        # (block join order)
        self.nested: list = []


# ---------------------------------------------------------------------------
# DocumentMapper
# ---------------------------------------------------------------------------

_TYPE_ALIASES = {"string": TEXT, "half_float": FLOAT, "scaled_float": DOUBLE}


def _truthy(v) -> bool:
    return v is True or v == 1 or str(v).lower() in ("true", "yes", "on", "1")


class DocumentMapper:
    """Parses source documents against a schema; grows it dynamically.

    ref: index/mapper/DocumentMapper.java (parse),
         index/mapper/object/ObjectMapper.java (dot-path flattening),
         index/mapper/DocumentMapperParser.java (mapping JSON).
    """

    def __init__(self, type_name: str, analysis: AnalysisService,
                 mapping: dict | None = None, dynamic: bool = True,
                 date_detection: bool = True):
        self.type_name = type_name
        self.analysis = analysis
        self.fields: dict[str, FieldType] = {}
        # parent path -> explicit multi-field sub-paths (indexed alongside)
        self.multi_fields: dict[str, list[str]] = {}
        # completion field path -> context spec ({name: {type, default,
        # path, precision}}; ref suggest/context/ContextMapping)
        self.completion_contexts: dict[str, dict] = {}
        self.dynamic = dynamic
        self.date_detection = date_detection
        self._mapping_version = 0
        # nested object paths -> {"include_in_parent": bool, "include_in_root": bool}
        self.nested_paths: dict[str, dict] = {}
        # _parent mapping: the parent TYPE this type's docs join to
        self.parent_type: str | None = None
        # _timestamp / _ttl metadata mappings (ref internal/Timestamp-
        # FieldMapper, TTLFieldMapper): index time + expiry as i64 columns
        self.ts_enabled = False
        self.ttl_enabled = False
        self.ttl_default_ms: int | None = None
        if mapping:
            self.merge_mapping(mapping)

    # -- mapping management ------------------------------------------------

    def merge_mapping(self, mapping: dict) -> bool:
        """Merge a mapping dict ({"properties": {...}}). Returns True if the
        schema changed. Raises MergeMappingException on type conflicts
        (ref: MapperService.merge / DocumentMapper.merge)."""
        props = mapping.get("properties")
        if props is None:
            # bare property map: strip meta fields (_parent, _all, ...)
            props = {k: v for k, v in mapping.items()
                     if not k.startswith("_")
                     and k not in ("dynamic", "date_detection")}
        if "dynamic" in mapping:
            dyn = mapping["dynamic"]
            self.dynamic = dyn is True or str(dyn).lower() == "true"
        changed = False
        pt = mapping.get("_parent", {}).get("type") \
            if isinstance(mapping.get("_parent"), dict) else None
        if pt is not None:
            if self.parent_type is not None and self.parent_type != pt:
                raise MergeMappingException(
                    f"The _parent field's type option can't be changed: "
                    f"[{self.parent_type}]->[{pt}]")
            if self.parent_type is None:
                self.parent_type = pt
                changed = True
        ts = mapping.get("_timestamp")
        if isinstance(ts, dict) and _truthy(ts.get("enabled")) \
                and not self.ts_enabled:
            self.ts_enabled = True
            changed = True
        ttl = mapping.get("_ttl")
        if isinstance(ttl, dict) and _truthy(ttl.get("enabled")) \
                and not self.ttl_enabled:
            self.ttl_enabled = True
            if ttl.get("default") is not None:
                self.ttl_default_ms = parse_ttl_ms(ttl["default"])
            changed = True
        changed |= self._merge_props("", props)
        if changed:
            self._mapping_version += 1
        return changed

    def _merge_props(self, prefix: str, props: dict) -> bool:
        changed = False
        for name, spec in props.items():
            if not isinstance(spec, dict):
                raise MapperParsingException(f"invalid mapping for field [{name}]")
            path = f"{prefix}{name}"
            if spec.get("type") == "multi_field":
                # legacy multi_field (ref mapper/multifield): the sub-field
                # sharing the parent's name IS the parent mapping; the rest
                # become ordinary multi-fields
                subs = dict(spec.get("fields") or {})
                own = subs.pop(name, None) or {"type": "string"}
                spec = {**own, "fields": subs}
            if "properties" in spec and "type" not in spec:
                changed |= self._merge_props(path + ".", spec["properties"])
                continue
            ftype = _TYPE_ALIASES.get(spec.get("type", OBJECT), spec.get("type", OBJECT))
            if ftype == NESTED:
                if path not in self.nested_paths:
                    self.nested_paths[path] = {
                        "include_in_parent": bool(spec.get("include_in_parent")),
                        "include_in_root": bool(spec.get("include_in_root"))}
                    changed = True
                changed |= self._merge_props(path + ".", spec.get("properties", {}))
                continue
            if ftype == OBJECT:
                changed |= self._merge_props(path + ".", spec.get("properties", {}))
                continue
            # ES 2.x: {"type": "string", "index": "not_analyzed"} == keyword
            if ftype == TEXT and spec.get("index") == "not_analyzed":
                ftype = KEYWORD
            if ftype == "completion" and spec.get("context"):
                self.completion_contexts[path] = dict(spec["context"])
            ft = FieldType(
                name=path, type=ftype,
                analyzer=spec.get("analyzer", "standard"),
                search_analyzer=spec.get("search_analyzer"),
                index=spec.get("index", True) not in (False, "no", "false"),
                doc_values=spec.get("doc_values", True),
                store=spec.get("store", False),
                dims=int(spec.get("dims", 0)),
                format=spec.get("format"),
                boost=float(spec.get("boost", 1.0)),
                similarity=spec.get("similarity"),
            )
            existing = self.fields.get(path)
            if existing is None:
                self.fields[path] = ft
                changed = True
            elif existing.type != ft.type:
                raise MergeMappingException(
                    f"mapper [{path}] of different type, current_type [{existing.type}], "
                    f"merged_type [{ft.type}]")
            # sub-fields ("fields": {"raw": {...}})
            for sub, subspec in spec.get("fields", {}).items():
                subpath = f"{path}.{sub}"
                stype = _TYPE_ALIASES.get(subspec.get("type", KEYWORD), subspec.get("type", KEYWORD))
                if stype == TEXT and subspec.get("index") == "not_analyzed":
                    stype = KEYWORD
                if subpath not in self.fields:
                    self.fields[subpath] = FieldType(name=subpath, type=stype,
                                                    analyzer=subspec.get("analyzer", "standard"))
                    if subpath != path + ".keyword":
                        self.multi_fields.setdefault(path, []).append(subpath)
                    changed = True
        return changed

    def mapping_dict(self) -> dict:
        """Render the schema back as a nested mapping dict (GET _mapping)."""
        root: dict[str, Any] = {}
        mf_children = {sub for subs in self.multi_fields.values()
                       for sub in subs}
        for path, ft in sorted(self.fields.items()):
            if path in mf_children:
                continue     # rendered under the parent's "fields" below
            parts = path.split(".")
            node = root
            for p in parts[:-1]:
                node = node.setdefault(p, {}).setdefault("properties", {})
            node[parts[-1]] = ft.to_dict()
        for parent, subs in self.multi_fields.items():
            parts = parent.split(".")
            node = root
            for p in parts[:-1]:
                node = node.setdefault(p, {}).setdefault("properties", {})
            pnode = node.get(parts[-1])
            if not isinstance(pnode, dict):
                continue
            for sub in subs:
                sft = self.fields.get(sub)
                if sft is not None:
                    pnode.setdefault("fields", {})[
                        sub.split(".")[-1]] = sft.to_dict()
        for path, opts in self.nested_paths.items():
            parts = path.split(".")
            node = root
            for p in parts[:-1]:
                node = node.setdefault(p, {}).setdefault("properties", {})
            leaf = node.setdefault(parts[-1], {})
            leaf["type"] = NESTED
            for k in ("include_in_parent", "include_in_root"):
                if opts.get(k):
                    leaf[k] = True
        out: dict[str, Any] = {"properties": root}
        if self.parent_type:
            out["_parent"] = {"type": self.parent_type}
        if self.ts_enabled:
            out["_timestamp"] = {"enabled": True}
        if self.ttl_enabled:
            ttl_out: dict[str, Any] = {"enabled": True}
            if self.ttl_default_ms is not None:
                ttl_out["default"] = self.ttl_default_ms
            out["_ttl"] = ttl_out
        return out

    # -- document parsing --------------------------------------------------

    def parse(self, source: dict, doc_id: str, routing: str | None = None,
              parent: str | None = None, timestamp=None,
              ttl=None, text_collector=None) -> ParsedDocument:
        """text_collector: optional `(analyzer, field, text, doc)` sink the
        batched ingest lane (index/bulk_ingest.py) installs — text values
        are COLLECTED instead of analyzed inline, then tokenized across the
        whole bulk request in one batch pass. Everything else (dynamic
        mapping, per-item validation errors) behaves identically."""
        doc = ParsedDocument(doc_id=doc_id, routing=routing, source=source)
        new_fields: dict[str, FieldType] = {}
        if parent is not None:
            if self.parent_type is None:
                raise MapperParsingException(
                    f"can't specify parent if no parent field has been "
                    f"configured for type [{self.type_name}]")
            doc.keywords[PARENT_FIELD] = [str(parent)]
        elif self.parent_type is not None:
            raise RoutingMissingException(
                f"routing is required for [{self.type_name}] documents "
                f"with a _parent mapping")
        # int fast path: the batch lane stamps epoch-ms ints — skip the
        # parse_date_millis dispatch on the per-doc hot path
        ts_ms = timestamp if timestamp.__class__ is int \
            else parse_date_millis(timestamp) if timestamp is not None \
            else int(_dt.datetime.now(_dt.timezone.utc).timestamp() * 1000)
        if self.ts_enabled:
            doc.longs["_timestamp"] = [ts_ms]
        ttl_ms = parse_ttl_ms(ttl) if ttl is not None else self.ttl_default_ms
        if self.ttl_enabled and ttl_ms is not None:
            expiry = ts_ms + ttl_ms
            now = int(_dt.datetime.now(_dt.timezone.utc).timestamp() * 1000)
            if expiry <= now:
                raise AlreadyExpiredException(
                    f"already expired [{doc_id}]: expiry [{expiry}] <= "
                    f"now [{now}]")
            doc.longs["_ttl_expiry"] = [expiry]
        self._parse_obj("", source, doc, new_fields,
                        text_collector=text_collector)
        if new_fields:
            if not self.dynamic:
                # dynamic=false: unmapped fields are ignored (not indexed)
                pass
            else:
                self.fields.update(new_fields)
                self._mapping_version += 1
        # _uid term for realtime get / versioning handled by the engine
        return doc

    def dynamic_new_fields(self) -> int:
        return self._mapping_version

    # -- compiled per-field parse plan (ISSUE 7) ---------------------------
    # A mapping-version-keyed dict of `path -> handler(value, doc,
    # text_collector)` closures for SIMPLE scalar fields: the handler has
    # its analyzer, keyword sub-field and error message pre-bound, so the
    # per-value work is one dict get + one call instead of the generic
    # path-building / field-resolution / type-dispatch chain. Structural
    # values (dict/list), nested paths, unknown fields and exotic types
    # (vector, geo, completion, shapes, multi-fields) take the generic
    # branch unchanged.

    def _parser_plan(self) -> dict:
        if getattr(self, "_plan_ver", None) == self._mapping_version:
            return self._plan
        plan: dict = {}
        for path, ft in self.fields.items():
            if self.nested_paths and path in self.nested_paths:
                continue
            if path in self.multi_fields:     # multi-field parents: generic
                continue
            h = self._make_handler(ft)
            if h is not None:
                plan[path] = h
        self._plan = plan
        self._plan_ver = self._mapping_version
        return plan

    def _make_handler(self, ft: FieldType):
        name = ft.name
        t = ft.type
        if t == TEXT:
            analyzer = self._analyzer_for(ft)
            kw = self.fields.get(name + ".keyword")
            kw_name = kw.name if kw is not None and kw.type == KEYWORD \
                else None

            def h_text(v, doc, coll):
                s = v if v.__class__ is str else str(v)
                if coll is not None:
                    coll(analyzer, name, s, doc)
                else:
                    toks = doc.tokens.get(name)
                    if toks is None:
                        toks = doc.tokens[name] = []
                    toks.extend(analyzer(s))
                if kw_name is not None:
                    kws = doc.keywords.get(kw_name)
                    if kws is None:
                        kws = doc.keywords[kw_name] = []
                    kws.append(s[:256])
            return h_text
        if t == KEYWORD:
            def h_kw(v, doc, coll):
                kws = doc.keywords.get(name)
                if kws is None:
                    kws = doc.keywords[name] = []
                kws.append(str(v))
            return h_kw
        if t in _INT_TYPES or t == DATE or t == BOOLEAN or t == IP:
            if t in _INT_TYPES:
                conv = int
            elif t == DATE:
                conv = parse_date_millis
            elif t == IP:
                conv = parse_ip
            else:
                conv = (lambda v: 1 if (v if isinstance(v, bool)
                                        else str(v).lower()
                                        in ("true", "1", "on")) else 0)

            def h_long(v, doc, coll):
                try:
                    iv = conv(v)
                except (ValueError, TypeError) as e:
                    raise MapperParsingException(
                        f"failed to parse [{name}]: {e}") from e
                l = doc.longs.get(name)
                if l is None:
                    l = doc.longs[name] = []
                l.append(iv)
            return h_long
        if t in _FLOAT_TYPES:
            def h_dbl(v, doc, coll):
                try:
                    fv = float(v)
                except (ValueError, TypeError) as e:
                    raise MapperParsingException(
                        f"failed to parse [{name}]: {e}") from e
                l = doc.numerics.get(name)
                if l is None:
                    l = doc.numerics[name] = []
                l.append(fv)
            return h_dbl
        return None                     # exotic types: generic path

    def _parse_obj(self, prefix: str, obj: dict, doc: ParsedDocument,
                   new_fields: dict[str, FieldType],
                   text_collector=None) -> None:
        # hoisted lookups: this loop runs once per field per document and
        # dominates host-side ingest cost (ISSUE 7) — scalar values on
        # known fields take the early path below the structural dispatch
        nested_paths = self.nested_paths
        fields_get = self.fields.get
        new_get = new_fields.get
        multi_fields = self.multi_fields
        plan_get = self._parser_plan().get
        for name, value in obj.items():
            if value is None:
                continue
            path = prefix + name if prefix else name
            scalar = not isinstance(value, (dict, list))
            if scalar:
                h = plan_get(path)
                if h is not None:
                    h(value, doc, text_collector)
                    continue
            if scalar and not (nested_paths and path in nested_paths):
                # -- scalar fast path (no container dispatch, no [v] wrap)
                ft = fields_get(path) or new_get(path)
                if ft is None:
                    if not self.dynamic:
                        continue
                    ft = self._infer_type(path, value)
                    if ft is None:
                        continue
                    new_fields[path] = ft
                    # text fields get a raw keyword sub-field for aggs/sort
                    if ft.type == TEXT:
                        new_fields[path + ".keyword"] = FieldType(
                            name=path + ".keyword", type=KEYWORD)
                if ft.type == TEXT and text_collector is not None:
                    # inlined _index_value TEXT branch: the collector call
                    # is the per-text-value hot spot of batched ingest
                    text_collector(self._analyzer_for(ft), ft.name,
                                   value if value.__class__ is str
                                   else str(value), doc)
                else:
                    self._index_value(ft, value, doc,
                                      text_collector=text_collector)
                if ft.type == TEXT:
                    kw = fields_get(path + ".keyword") \
                        or new_get(path + ".keyword")
                    if kw is not None:
                        doc.keywords.setdefault(kw.name, []).append(
                            str(value)[:256])
                if multi_fields:
                    for sub in multi_fields.get(path, ()):
                        sft = fields_get(sub)
                        if sft is None:
                            continue
                        if sft.type == "completion":
                            doc.keywords.setdefault(sub, []).append(
                                str(value)[:256])
                        else:
                            self._index_value(sft, value, doc,
                                              text_collector=text_collector)
                continue
            if nested_paths and path in nested_paths:
                # nested object: each element becomes a sub-document row in
                # the block (ref ObjectMapper nested mode — one Lucene doc
                # per element, root doc last in the block)
                opts = self.nested_paths[path]
                elems = value if isinstance(value, list) else [value]
                for elem in elems:
                    if not isinstance(elem, dict):
                        raise MapperParsingException(
                            f"object mapping for [{path}] tried to parse "
                            f"field as object, but found a concrete value")
                    sub = ParsedDocument(doc_id=doc.doc_id, routing=None,
                                         source=elem)
                    self._parse_obj(path + ".", elem, sub, new_fields,
                                    text_collector=text_collector)
                    doc.nested.append((path, sub))
                    if opts.get("include_in_parent") \
                            or opts.get("include_in_root"):
                        # ALSO flatten into the root doc (ES option)
                        self._parse_obj(path + ".", elem, doc, new_fields,
                                        text_collector=text_collector)
                continue
            if isinstance(value, dict):
                ft = self.fields.get(path)
                if ft is not None and ft.type == GEO_POINT:
                    self._index_value(ft, value, doc)
                elif ft is not None and ft.type in ("completion",
                                                   "geo_shape"):
                    self._index_value(ft, value, doc)
                else:
                    self._parse_obj(path + ".", value, doc, new_fields,
                                    text_collector=text_collector)
                continue
            ft = self.fields.get(path) or new_fields.get(path)
            # a list IS the value for vectors and [lon, lat] geo points
            if isinstance(value, list) and ft is not None and ft.type in (DENSE_VECTOR, GEO_POINT):
                self._index_value(ft, value, doc)
                continue
            values = value if isinstance(value, list) else [value]
            if not values:
                continue
            if ft is None:
                if not self.dynamic:
                    continue
                ft = self._infer_type(path, values[0])
                if ft is None:
                    continue
                new_fields[path] = ft
                # text fields get a raw keyword sub-field for aggs/sort
                if ft.type == TEXT:
                    new_fields[path + ".keyword"] = FieldType(name=path + ".keyword", type=KEYWORD)
            for v in values:
                self._index_value(ft, v, doc, text_collector=text_collector)
            if ft.type == TEXT:
                kw = self.fields.get(path + ".keyword") or new_fields.get(path + ".keyword")
                if kw is not None:
                    for v in values:
                        doc.keywords.setdefault(kw.name, []).append(str(v)[:256])
            # explicit multi-fields index the SAME value under their own
            # type (ref mapper/core/AbstractFieldMapper multiFields);
            # completion sub-fields land in the keyword column the
            # completion suggester reads
            for sub in self.multi_fields.get(path, ()):
                sft = self.fields.get(sub)
                if sft is None:
                    continue
                if sft.type == "completion":
                    for v in values:
                        doc.keywords.setdefault(sub, []).append(str(v)[:256])
                else:
                    for v in values:
                        self._index_value(sft, v, doc,
                                          text_collector=text_collector)

    def _infer_type(self, path: str, v: Any) -> FieldType | None:
        """Dynamic type inference (ref: index/mapper/DocumentParser dynamic
        templates & type guessing)."""
        if isinstance(v, bool):
            return FieldType(name=path, type=BOOLEAN)
        if isinstance(v, int):
            return FieldType(name=path, type=LONG)
        if isinstance(v, float):
            return FieldType(name=path, type=DOUBLE)
        if isinstance(v, str):
            if self.date_detection and looks_like_date(v):
                try:
                    parse_date_millis(v)
                    return FieldType(name=path, type=DATE)
                except MapperParsingException:
                    pass
            return FieldType(name=path, type=TEXT)
        return None

    def _analyzer_for(self, ft: FieldType) -> Analyzer:
        # per-FieldType memo (one mapper == one AnalysisService, so the
        # resolution can never change identity under a given ft)
        a = getattr(ft, "_resolved_analyzer", None)
        if a is None:
            a = self.analysis.analyzer(ft.analyzer)
            ft._resolved_analyzer = a
        return a

    def search_analyzer_for(self, field_name: str) -> Analyzer:
        ft = self.fields.get(field_name)
        if ft is None or ft.type != TEXT:
            return self.analysis.analyzer("keyword")
        return self.analysis.analyzer(ft.search_analyzer or ft.analyzer)

    COMPLETION_CTX_SEP = "\x1f"

    @staticmethod
    def shape_bbox(shape: dict) -> tuple[float, float, float, float] | None:
        """GeoJSON-ish shape -> (minlat, maxlat, minlon, maxlon).
        Supports point / envelope / polygon / multipolygon / linestring /
        circle (ref common/geo/builders/ShapeBuilder). The bbox is the
        segment's INDEXED representation — the tensor-native analog of the
        reference's prefix-tree grid approximation (geo_shape queries are
        approximate there too; exact only for points/envelopes here)."""
        t = str(shape.get("type", "")).lower()
        coords = shape.get("coordinates")

        def flat(c):
            # leaves are [lon, lat] pairs at arbitrary nesting depth
            if not isinstance(c, (list, tuple)) or not c:
                raise ValueError(f"malformed coordinates {c!r}")
            if isinstance(c[0], (int, float)) \
                    and not isinstance(c[0], bool):
                if len(c) < 2 or not isinstance(c[1], (int, float)):
                    raise ValueError(f"malformed coordinate pair {c!r}")
                return [c]
            out = []
            for x in c:
                out.extend(flat(x))
            return out
        if coords is None:
            return None
        if t == "circle":
            lon, lat = float(coords[0]), float(coords[1])
            from ..search.geo import parse_distance
            import math as _m
            r = parse_distance(shape.get("radius", "0m"))
            dlat = r / 111_320.0
            dlon = r / (111_320.0 * max(_m.cos(_m.radians(lat)), 1e-6))
            return (lat - dlat, lat + dlat, lon - dlon, lon + dlon)
        if t == "envelope":
            (lon1, lat1), (lon2, lat2) = coords[0], coords[1]
            return (min(lat1, lat2), max(lat1, lat2),
                    min(lon1, lon2), max(lon1, lon2))
        pts = flat(coords)
        lons = [float(p[0]) for p in pts]
        lats = [float(p[1]) for p in pts]
        return (min(lats), max(lats), min(lons), max(lons))

    def _index_completion(self, ft: FieldType, value: Any,
                          doc: ParsedDocument) -> None:
        """Completion field entries land in the keyword column, each input
        PREFIX-ENCODED with its context keys (category value or geohash) —
        the same trick the reference's ContextMapping plays inside the FST
        (ref suggest/completion + suggest/context/ContextMapping)."""
        if isinstance(value, str):
            inputs, ctx_map, weight = [value], {}, 1
        elif isinstance(value, list):
            inputs, ctx_map, weight = [str(x) for x in value], {}, 1
        else:
            inputs = value.get("input") or []
            inputs = [inputs] if isinstance(inputs, str) else list(inputs)
            if value.get("output"):
                inputs = inputs or [str(value["output"])]
            ctx_map = value.get("context") or {}
            weight = int(value.get("weight", 1))
        ctx_spec = self.completion_contexts.get(ft.name)
        keys = [""]
        if ctx_spec:
            keys = []
            for cname, cspec in ctx_spec.items():
                vals = ctx_map.get(cname)
                if str(cspec.get("type")) == "geo":
                    from ..search.geo import (encode_geohash,
                                              geohash_length_for,
                                              parse_geo_point)
                    if vals is None:
                        continue
                    lat, lon = parse_geo_point(vals)
                    ln = geohash_length_for(cspec.get("precision", "1km"))
                    keys.append(encode_geohash(lat, lon, ln))
                    continue
                if vals is None:
                    pth = cspec.get("path")
                    if pth is not None and doc.source.get(pth) is not None:
                        vals = doc.source[pth]
                    elif "default" in cspec:
                        vals = cspec["default"]
                if vals is None:
                    continue
                vals = vals if isinstance(vals, list) else [vals]
                keys.extend(str(v) for v in vals)
        sep = self.COMPLETION_CTX_SEP
        for inp in inputs:
            for key in keys:
                entry = f"{key}{sep}{inp}" if ctx_spec else str(inp)
                for _ in range(max(weight, 1)):
                    doc.keywords.setdefault(ft.name, []).append(entry)

    def _index_value(self, ft: FieldType, v: Any, doc: ParsedDocument,
                     text_collector=None) -> None:
        t = ft.type
        if t == "completion":
            self._index_completion(ft, v, doc)
            return
        if t == "geo_shape":
            # bbox columns <field>.minlat/.maxlat/.minlon/.maxlon — the
            # indexed form geo_shape queries evaluate against. Multi-valued
            # fields UNION into one bbox (the segment keeps one value per
            # doc per column), widening coverage instead of dropping shapes
            if isinstance(v, dict):
                try:
                    box = self.shape_bbox(v)
                except (ValueError, TypeError, KeyError, IndexError) as e:
                    raise MapperParsingException(
                        f"failed to parse geo_shape [{ft.name}]: {e}") \
                        from e
                if box is not None:
                    combine = (min, max, min, max)
                    for suffix, val, comb in zip(
                            (".minlat", ".maxlat", ".minlon", ".maxlon"),
                            box, combine):
                        cur = doc.numerics.setdefault(ft.name + suffix, [])
                        if cur:
                            cur[0] = comb(cur[0], float(val))
                        else:
                            cur.append(float(val))
            return
        try:
            if t == TEXT:
                if text_collector is not None:
                    # batched lane: defer tokenization — the collector runs
                    # the analyzer over the whole bulk request at once
                    text_collector(self._analyzer_for(ft), ft.name,
                                   str(v), doc)
                else:
                    doc.tokens.setdefault(ft.name, []).extend(
                        self._analyzer_for(ft)(str(v)))
            elif t == KEYWORD:
                doc.keywords.setdefault(ft.name, []).append(str(v))
            elif t in _INT_TYPES:
                doc.longs.setdefault(ft.name, []).append(int(v))
            elif t in _FLOAT_TYPES:
                doc.numerics.setdefault(ft.name, []).append(float(v))
            elif t == DATE:
                doc.longs.setdefault(ft.name, []).append(parse_date_millis(v))
            elif t == BOOLEAN:
                b = v if isinstance(v, bool) else str(v).lower() in ("true", "1", "on")
                doc.longs.setdefault(ft.name, []).append(1 if b else 0)
            elif t == IP:
                doc.longs.setdefault(ft.name, []).append(parse_ip(v))
            elif t == DENSE_VECTOR:
                vec = [float(x) for x in (v if isinstance(v, list) else [v])]
                if ft.dims and len(vec) != ft.dims:
                    raise MapperParsingException(
                        f"vector length {len(vec)} != dims {ft.dims} for [{ft.name}]")
                doc.vectors[ft.name] = vec
            elif t == GEO_POINT:
                if isinstance(v, dict):
                    doc.geo[ft.name] = (float(v["lat"]), float(v["lon"]))
                elif isinstance(v, str):
                    lat, lon = v.split(",")
                    doc.geo[ft.name] = (float(lat), float(lon))
                elif isinstance(v, list) and len(v) == 2:  # [lon, lat] GeoJSON order
                    doc.geo[ft.name] = (float(v[1]), float(v[0]))
        except (ValueError, TypeError) as e:
            raise MapperParsingException(f"failed to parse [{ft.name}]: {e}") from e

    def field_type(self, name: str) -> FieldType | None:
        return self.fields.get(name)


class MapperService:
    """Per-index registry of DocumentMappers by type name
    (ref: index/mapper/MapperService.java:993)."""

    def __init__(self, analysis: AnalysisService | None = None,
                 mappings: dict | None = None, dynamic: bool = True):
        self.analysis = analysis or AnalysisService()
        self._mappers: dict[str, DocumentMapper] = {}
        self.dynamic = dynamic
        for type_name, mapping in (mappings or {}).items():
            self._mappers[type_name] = DocumentMapper(
                type_name, self.analysis, mapping, dynamic=dynamic)

    def document_mapper(self, type_name: str, create: bool = True) -> DocumentMapper | None:
        m = self._mappers.get(type_name)
        if m is None and create:
            m = DocumentMapper(type_name, self.analysis, dynamic=self.dynamic)
            self._mappers[type_name] = m
        return m

    def merge(self, type_name: str, mapping: dict) -> bool:
        return self.document_mapper(type_name).merge_mapping(mapping)

    def types(self) -> list[str]:
        return list(self._mappers)

    def mappings_dict(self) -> dict:
        return {t: m.mapping_dict() for t, m in self._mappers.items()}

    def field_type(self, name: str) -> FieldType | None:
        """Resolve a field across types (types share a field namespace in the
        reference too)."""
        for m in self._mappers.values():
            ft = m.fields.get(name)
            if ft is not None:
                return ft
        return None

    def nested_path(self, path: str) -> bool:
        """True if any type maps `path` as a nested object."""
        return any(path in m.nested_paths for m in self._mappers.values())

    def parent_type_of(self, child_type: str) -> str | None:
        m = self._mappers.get(child_type)
        return m.parent_type if m is not None else None

    def mapping_version(self) -> int:
        return sum(m._mapping_version for m in self._mappers.values())
