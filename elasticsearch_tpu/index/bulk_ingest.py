"""Vectorized bulk-ingest lane: batched analysis for whole `_bulk` requests.

The reference processes a bulk request as ONE shard-level batch
(ref action/bulk/TransportShardBulkAction.java:133 — every op of the
request applies under one engine pass, with one translog fsync per
request). The per-doc lane here instead paid the full Python analysis
chain, a translog append and a version-map round trip PER DOCUMENT
(~5k docs/s). This module supplies the batch lane's host-side pieces:

  * `batch_tokenize` — one C-level regex sweep per source string (no
    per-token Python) for the standard/whitespace/letter/keyword
    tokenizers; anything else declines and the analyzer falls back.
  * `analyze_batch` — applies a chain of PER-TOKEN filters (see
    analyzers.per_token) over the batch's *unique* vocabulary once
    instead of per occurrence: a zipf-shaped corpus has ~50x fewer
    uniques than occurrences, so lowercase/stop/porter run ~50x less.
    Chains with cross-token filters (shingle, synonym, decompounder,
    unique) return None and the caller analyzes per value — semantics
    never change, only speed.
  * `TextBatcher` — the `text_collector` sink DocumentMapper.parse
    accepts: text values are collected during parsing (dynamic mapping
    and per-item 400s keep their per-doc behavior) and tokenized in
    grouped batch passes afterwards.
  * `BulkOp` — the op envelope node.bulk hands to
    IndexService.bulk_ingest / Engine.index_batch.

Segment construction for batched docs is columnar too — see
SegmentBuilder.add_batch (index/segment.py); the translog group-commit
is Translog.add_batch (index/translog.py).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..analysis.analyzers import (_WORD_RE, Analyzer, keyword_tokenizer,
                                  letter_tokenizer, standard_tokenizer,
                                  whitespace_tokenizer)


class BulkOp:
    """One operation of a `_bulk` request, normalized for the batch lane.
    A hand-rolled __slots__ class, not a dataclass: the generated kwargs
    __init__ costs ~4µs/op — real money at 100k ops/request."""

    __slots__ = ("action", "doc_id", "source", "type_name", "routing",
                 "parent", "timestamp", "ttl", "version", "version_type",
                 "raw_len")

    def __init__(self, action, doc_id, source=None, type_name="_doc",
                 routing=None, parent=None, timestamp=None, ttl=None,
                 version=None, version_type="internal", raw_len=0):
        self.action = action          # "index" | "create" | "delete"
        self.doc_id = doc_id
        self.source = source
        self.type_name = type_name
        self.routing = routing
        self.parent = parent
        self.timestamp = timestamp
        self.ttl = ttl
        self.version = version
        self.version_type = version_type
        # raw JSON source line length (REST lane) — the engine's buffered
        # -bytes estimate uses it to skip re-walking the source dict
        self.raw_len = raw_len


# ---------------------------------------------------------------------------
# Batched tokenization
# ---------------------------------------------------------------------------

def batch_tokenize(tokenizer, texts: list[str]) -> list[list[str]] | None:
    """Tokenize a batch of sources with at most one C-level regex/split
    call per source (no per-token Python). Returns None when `tokenizer`
    has no batch form — the caller falls back to per-value analysis.

    Output is EXACTLY `[tokenizer(t) for t in texts]`: the standard
    tokenizer's apostrophe handling (’ fold, possessive strip) only
    fires on sources that contain an apostrophe, so apostrophe-free
    sources — the overwhelming majority — take the pure `findall` path.
    """
    if tokenizer is standard_tokenizer:
        findall = _WORD_RE.findall
        return [standard_tokenizer(text) if "'" in text or "’" in text
                else findall(text)
                for text in texts]
    if tokenizer is whitespace_tokenizer:
        return [t.split() for t in texts]
    if tokenizer is letter_tokenizer:
        return [letter_tokenizer(t) for t in texts]   # already one findall
    if tokenizer is keyword_tokenizer:
        return [[t] if t else [] for t in texts]
    return None


class _BatchCache:
    """Per-analyzer memo shared across bulk requests: the filter-chain
    result and output-token encoding per UNIQUE input token. Zipf-shaped
    corpora re-send the same head tokens in every request, so the chain
    runs once per token per process, not once per request. Attached to
    the Analyzer object (same lifetime; a dead index's analyzers take
    their cache with them). Guarded by a lock — concurrent bulks on one
    analyzer must not interleave the vocab/id appends."""

    __slots__ = ("lock", "mapping", "vocab", "vid", "enc1", "encN",
                 "nonident")
    MAX_TOKENS = 1 << 20              # reset backstop for adversarial vocab

    def __init__(self):
        import threading
        self.lock = threading.Lock()
        self.mapping: dict[str, Any] = {}   # input -> output str | list
        self.vocab: list[str] = []          # output id -> output token
        self.vid: dict[str, int] = {}       # output token -> id
        self.enc1: dict[str, int] = {}      # 1->1 input -> output id
        self.encN: dict[str, list[int]] = {}  # 0/N input -> output ids
        # False while EVERY cached mapping is the identity (already-
        # lowercase corpora): output rows can reuse the tokenizer's lists
        # verbatim instead of mapping token by token
        self.nonident = False

    def out_id(self, tok: str) -> int:
        i = self.vid.get(tok)
        if i is None:
            i = self.vid[tok] = len(self.vocab)
            self.vocab.append(tok)
        return i


def analyze_batch(analyzer: Analyzer, texts: list[str],
                  encode: bool = False):
    """Run `analyzer` over a batch of sources, applying the filter chain
    once per UNIQUE NEW token (per-analyzer memo) instead of once per
    occurrence. Returns None when the chain is not batchable (unknown
    tokenizer, or any filter without the per_token contract) — never a
    wrong answer.

    Per-token filters distribute over concatenation, so
    `chain(tokens) == concat(chain([t]) for t in tokens)` and the result
    is bitwise-identical to `[analyzer.analyze(t) for t in texts]`.

    encode=False -> list of per-source token lists.
    encode=True  -> (rows, vocab, ids): additionally an i32 id array per
    source over the analyzer's shared output `vocab` list —
    SegmentBuilder.add_batch consumes these so refresh never re-encodes
    tokens one by one (and, vocab being shared, needs ONE remap table
    per field for the whole buffer)."""
    tok_lists = batch_tokenize(analyzer.tokenizer, texts)
    if tok_lists is None:
        return None
    filters = analyzer.filters
    if not all(getattr(f, "per_token", False) for f in filters):
        return None
    cache = getattr(analyzer, "_batch_cache", None)
    if cache is None:
        cache = analyzer._batch_cache = _BatchCache()
    with cache.lock:
        if len(cache.mapping) > cache.MAX_TOKENS:
            # in-place reset under the lock; docs holding (vocab, ids)
            # pairs keep their references to the retired vocab list
            cache.mapping = {}
            cache.vocab = []
            cache.vid = {}
            cache.enc1 = {}
            cache.encN = {}
            cache.nonident = False
        return _analyze_with_cache(cache, filters, tok_lists, encode)


def _analyze_with_cache(cache: _BatchCache, filters, tok_lists, encode):
    uniq: set[str] = set()
    for toks in tok_lists:
        uniq.update(toks)
    mapping = cache.mapping
    new = [t for t in uniq if t not in mapping] if mapping \
        else list(uniq)
    irregular_new = False
    for t in new:
        out = [t]
        for f in filters:
            out = f(out)
            if not out:
                break                 # f([]) == [] for per-token filters
        if len(out) == 1:
            # encodings fill unconditionally: a later encode=True call
            # must find every cached token's ids
            m = mapping[t] = out[0]
            cache.enc1[t] = cache.out_id(m)
            if m != t:
                cache.nonident = True
        else:                         # dropped (stop/elision) or expanded
            mapping[t] = out
            irregular_new = True
            cache.nonident = True
            cache.encN[t] = [cache.out_id(o) for o in out]
    # irregular if ANY token of THIS batch maps 0/N ways (cached included)
    encN = cache.encN
    irregular = irregular_new or (bool(encN)
                                  and any(t in encN for t in uniq))
    if not irregular:
        # identity corpora (already-lowercase tokens, no drops): the
        # tokenizer's fresh lists ARE the output rows — skip the per-
        # occurrence remap entirely. Equality-keyed, so a content-equal
        # token list is exactly what the remap would have produced.
        if not cache.nonident:
            rows = tok_lists
        else:
            get = mapping.__getitem__
            rows = [list(map(get, toks)) for toks in tok_lists]
        if not encode:
            return rows
        # one flat fromiter for the whole batch, then per-doc views: a
        # fromiter call per doc costs more than the encode itself
        from itertools import chain
        eget = cache.enc1.__getitem__
        total = sum(map(len, tok_lists))
        flat = np.fromiter(map(eget, chain.from_iterable(tok_lists)),
                           np.int32, count=total)
        ids = []
        append = ids.append
        s = 0
        for toks in tok_lists:
            e = s + len(toks)
            append(flat[s:e])
            s = e
        return rows, cache.vocab, ids
    enc1 = cache.enc1
    rows = []
    enc_rows: list = []
    for toks in tok_lists:
        row: list[str] = []
        append, extend = row.append, row.extend
        id_row: list[int] = []
        for t in toks:
            m = mapping[t]
            if type(m) is str:
                append(m)
                if encode:
                    id_row.append(enc1[t])
            else:
                extend(m)
                if encode:
                    id_row.extend(encN[t])
        rows.append(row)
        if encode:
            enc_rows.append(np.asarray(id_row, np.int32))
    if not encode:
        return rows
    return rows, cache.vocab, enc_rows


# ---------------------------------------------------------------------------
# Deferred-analysis collector (plugs into DocumentMapper.parse)
# ---------------------------------------------------------------------------

class TextBatcher:
    """Collects (analyzer, field, text, doc) tuples during a chunk's
    parses, then `flush()` runs each analyzer's group as one batch pass
    and extends the docs' token lists in collection (== parse) order."""

    def __init__(self):
        # id(analyzer) -> (analyzer, [(doc, field, text), ...])
        self._groups: dict[int, tuple] = {}
        self.batched_values = 0
        self.fallback_values = 0

    def __call__(self, analyzer, field, text, doc) -> None:
        # pre-create the key so doc.tokens preserves the per-doc field
        # insertion order the inline path would have produced
        doc.tokens.setdefault(field, [])
        g = self._groups.get(id(analyzer))
        if g is None:
            g = self._groups[id(analyzer)] = (analyzer, [])
        g[1].append((doc, field, text))

    def flush(self) -> dict[int, Exception]:
        """Run all collected analysis. Returns {id(doc): error} for docs
        whose (fallback) analysis raised — the engine turns those into
        per-item 400s before any engine state mutates."""
        failed: dict[int, Exception] = {}
        for analyzer, entries in self._groups.values():
            texts = [e[2] for e in entries]
            out = None
            try:
                out = analyze_batch(analyzer, texts, encode=True)
            except Exception:  # noqa: BLE001 — fall back, never corrupt
                out = None
            if out is not None:
                rows, vocab, ids = out
                self.batched_values += len(texts)
                for (doc, field, _), toks, id_arr in zip(entries, rows,
                                                         ids):
                    tl = doc.tokens
                    cur = tl[field]
                    if cur:                      # multi-value field: append
                        cur.extend(toks)
                    else:   # fresh rows list from analyze_batch — hand it
                        tl[field] = toks         # over instead of copying
                    enc = doc.token_enc
                    if enc is None:
                        enc = doc.token_enc = {}
                    enc.setdefault(field, []).append((vocab, id_arr))
                continue
            self.fallback_values += len(texts)
            for doc, field, text in entries:
                try:
                    doc.tokens[field].extend(analyzer.analyze(text))
                except Exception as e:  # noqa: BLE001 — per-item contract
                    failed[id(doc)] = e
        self._groups.clear()
        return failed
