"""Per-shard write-ahead log with checksummed records.

The analog of the reference translog
(/root/reference/src/main/java/org/elasticsearch/index/translog/Translog.java:106,
fs/FsTranslog.java, ChecksummedTranslogStream.java): every engine operation is
appended (and optionally fsynced) before it is acknowledged; a crash replays
the log into a fresh engine (SURVEY.md §5.4(a)).

Record format (binary, little-endian):
    u32 length | u32 crc32(payload) | payload (JSON utf-8)

Generations: `translog-N.log`. A commit ("flush" in ES terms) rolls to a new
generation and deletes the old ones once segment state is durable.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Any, Iterator

_HEADER = struct.Struct("<II")


class TranslogCorruptedException(Exception):
    pass


class Translog:
    def __init__(self, directory: str, durability: str = "request"):
        """durability: 'request' = fsync every op (ES 'request'),
        'async' = fsync on flush/interval only."""
        self.dir = directory
        self.durability = durability
        os.makedirs(directory, exist_ok=True)
        self.generation = self._latest_generation()
        self._file = open(self._path(self.generation), "ab")
        self.ops_since_commit = 0
        self.size_bytes = self._file.tell()

    def _path(self, gen: int) -> str:
        return os.path.join(self.dir, f"translog-{gen}.log")

    def _latest_generation(self) -> int:
        gens = [int(f.split("-")[1].split(".")[0])
                for f in os.listdir(self.dir)
                if f.startswith("translog-") and f.endswith(".log")]
        return max(gens, default=0)

    # -- write path --------------------------------------------------------

    def add(self, op: dict[str, Any], sync: bool | None = None) -> int:
        """Append one operation; returns its location offset
        (ref Translog.java add -> Location).

        sync: None = honor the durability mode; False = defer the fsync —
        the bulk path appends a whole request then calls sync() ONCE, which
        is exactly the reference's 'request' durability (fsync per request,
        not per op)."""
        payload = json.dumps(op, separators=(",", ":")).encode("utf-8")
        rec = _HEADER.pack(len(payload), zlib.crc32(payload)) + payload
        loc = self._file.tell()
        self._file.write(rec)
        if sync is None:
            sync = self.durability == "request"
        if sync:
            self._file.flush()
            os.fsync(self._file.fileno())
        self.ops_since_commit += 1
        self.size_bytes = loc + len(rec)
        return loc

    def add_batch(self, ops: list[dict[str, Any]],
                  sync: bool | None = False) -> int:
        """Group commit (ref Translog.java add called under
        TransportShardBulkAction's single shard pass): ALL ops of a bulk
        request serialize as ONE checksummed batch record (`{"b": [...]}`,
        one json.dumps + one buffered write instead of one per op) — and,
        when sync is requested, exactly ONE fsync for the whole batch.
        snapshot() expands batch records back into individual ops, so
        recovery is shape-agnostic. Returns the record's location offset."""
        if not ops:
            return self._file.tell()
        payload = json.dumps({"b": ops},
                             separators=(",", ":")).encode("utf-8")
        rec = _HEADER.pack(len(payload), zlib.crc32(payload)) + payload
        loc = self._file.tell()
        self._file.write(rec)
        if sync is None:
            sync = self.durability == "request"
        if sync:
            self._file.flush()
            os.fsync(self._file.fileno())
        self.ops_since_commit += len(ops)
        self.size_bytes = loc + len(rec)
        return loc

    def sync(self) -> None:
        self._file.flush()
        os.fsync(self._file.fileno())

    # -- recovery / commit -------------------------------------------------

    def snapshot(self, from_generation: int = 0) -> Iterator[dict]:
        """Replay all ops from all live generations (ref Translog.snapshot)."""
        self._file.flush()
        for gen in sorted(self._generations()):
            if gen < from_generation:
                continue
            with open(self._path(gen), "rb") as f:
                while True:
                    head = f.read(_HEADER.size)
                    if not head:
                        break
                    if len(head) < _HEADER.size:
                        raise TranslogCorruptedException("truncated record header")
                    length, crc = _HEADER.unpack(head)
                    payload = f.read(length)
                    if len(payload) < length:
                        raise TranslogCorruptedException("truncated record payload")
                    if zlib.crc32(payload) != crc:
                        raise TranslogCorruptedException("checksum mismatch")
                    rec = json.loads(payload.decode("utf-8"))
                    if "b" in rec and "op" not in rec:
                        yield from rec["b"]     # group-commit batch record
                    else:
                        yield rec

    def _generations(self) -> list[int]:
        return [int(f.split("-")[1].split(".")[0])
                for f in os.listdir(self.dir)
                if f.startswith("translog-") and f.endswith(".log")]

    def roll(self) -> int:
        """Start a new generation (called at commit start); old generations
        stay until `trim` confirms the commit is durable."""
        self.sync()
        self._file.close()
        self.generation += 1
        self._file = open(self._path(self.generation), "ab")
        self.ops_since_commit = 0
        return self.generation

    def trim(self, below_generation: int) -> None:
        """Delete generations < below_generation after a durable commit."""
        for gen in self._generations():
            if gen < below_generation:
                os.remove(self._path(gen))

    def close(self) -> None:
        self.sync()
        self._file.close()

    def stats(self) -> dict:
        return {"operations": self.ops_since_commit,
                "size_in_bytes": self.size_bytes,
                "generation": self.generation}
