"""Binary segment store: write-once segment files + checksummed commit point.

The analog of the reference's Store/commit machinery
(/root/reference/src/main/java/org/elasticsearch/index/store/Store.java —
per-file checksums, VerifyingIndexOutput; gateway persistence SURVEY.md §5.4b).
Round 1 persisted commits as an O(corpus) JSON rewrite of every live doc on
every flush and re-tokenized the whole corpus on reopen; this store makes
flush cost O(new segments):

  seg_<id>.npz        CSR postings tensors, columns, vectors, ids/types/
                      versions — written ONCE when a frozen segment is first
                      committed, immutable after (Lucene segment-file model)
  seg_<id>.docs.jsonl stored _source, one JSON per line (stored-fields file)
  commit.json         the commit point: segment file list + crc32c-style
                      checksums + per-segment tombstone ("dead") lists +
                      deleted-doc versions; atomically replaced

Recovery = verify checksums + np.load (no re-analysis). A flipped byte in any
segment file fails the checksum and raises CorruptIndexException — the
detection contract Store.java enforces on recovery.
"""

from __future__ import annotations

import json
import os
import zlib

import numpy as np
import jax.numpy as jnp

from .segment import (KeywordColumn, NumericColumn, Segment, TextFieldIndex,
                      VectorColumn)

MANIFEST = "commit.json"
FORMAT = 2


class CorruptIndexException(Exception):
    pass


def _crc(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
    return crc


class SegmentStore:
    """Per-shard segment persistence with a single atomic commit point."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        # seg id -> (crc, docs_crc) for files already on disk; cached so
        # commit never re-reads unchanged write-once files (flush must stay
        # O(new segments), not O(index bytes))
        self.persisted: dict[int, tuple[int, int]] = {}

    # -- write -------------------------------------------------------------

    def write_segment(self, seg: Segment) -> None:
        """Write the immutable files for one frozen segment (idempotent)."""
        if seg.seg_id in self.persisted:
            return
        npz_path = os.path.join(self.path, f"seg_{seg.seg_id}.npz")
        docs_path = os.path.join(self.path,
                                 f"seg_{seg.seg_id}.docs.jsonl.gz")

        arrays: dict[str, np.ndarray] = {
            "ids": np.asarray(seg.ids, dtype=np.str_),
            "types": np.asarray(seg.types, dtype=np.str_),
            "versions": np.asarray(seg.versions, np.int64),
            "routings": np.asarray(
                [r if r is not None else "" for r in
                 (seg.routings or [None] * seg.n_docs)], dtype=np.str_),
        }
        if seg.parent_of is not None:
            arrays["parent_of"] = np.asarray(seg.parent_of, np.int32)
        schema: dict = {"n_docs": seg.n_docs, "n_pad": seg.n_pad,
                        "text": {}, "keywords": [], "numerics": {},
                        "vectors": {}}
        for fi, (f, fx) in enumerate(sorted(seg.text.items())):
            schema["text"][f] = {"i": fi, "sum_dl": fx.sum_dl,
                                 "n_postings": fx.n_postings,
                                 "max_df": fx.max_df}
            arrays[f"t{fi}_terms"] = np.asarray(list(fx.terms), dtype=np.str_)
            arrays[f"t{fi}_starts"] = np.asarray(fx.term_starts, np.int32)
            arrays[f"t{fi}_lens"] = np.asarray(fx.term_lens, np.int32)
            arrays[f"t{fi}_doc_ids"] = np.asarray(fx.doc_ids)
            arrays[f"t{fi}_tf"] = np.asarray(fx.tf)
            arrays[f"t{fi}_doc_len"] = np.asarray(fx.doc_len)
            arrays[f"t{fi}_dl"] = np.asarray(fx.dl)
            if fx.positions is not None:
                arrays[f"t{fi}_pos_starts"] = fx.pos_starts
                arrays[f"t{fi}_pos_lens"] = fx.pos_lens
                arrays[f"t{fi}_positions"] = fx.positions
        for fi, (f, kc) in enumerate(sorted(seg.keywords.items())):
            schema["keywords"].append(f)
            arrays[f"k{fi}_values"] = np.asarray(kc.values, dtype=np.str_)
            arrays[f"k{fi}_ords"] = np.asarray(kc.ords)
        for fi, (f, nc) in enumerate(sorted(seg.numerics.items())):
            schema["numerics"][f] = {"i": fi, "dtype": nc.dtype}
            arrays[f"n{fi}_vals"] = np.asarray(nc.vals)
            arrays[f"n{fi}_missing"] = np.asarray(nc.missing)
        for fi, (f, vc) in enumerate(sorted(seg.vectors.items())):
            schema["vectors"][f] = {"i": fi, "dims": vc.dims}
            arrays[f"v{fi}_vecs"] = np.asarray(vc.vecs)
        arrays["schema"] = np.asarray(json.dumps(schema))

        tmp = npz_path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, npz_path)

        # stored fields compress on disk (the reference's Lucene stored
        # fields are LZ4-compressed by default; gzip level 1 is the
        # stdlib analog — ~4-6x smaller, negligible CPU at flush)
        import gzip
        tmp = docs_path + ".tmp"
        with open(tmp, "wb") as raw:
            with gzip.GzipFile(fileobj=raw, mode="wb",
                               compresslevel=1, mtime=0) as f:
                for src in seg.stored:
                    f.write((json.dumps(src, separators=(",", ":"))
                             + "\n").encode("utf-8"))
            raw.flush()
            os.fsync(raw.fileno())
        os.replace(tmp, docs_path)
        self.persisted[seg.seg_id] = (_crc(npz_path), _crc(docs_path))

    def commit(self, segments: list[Segment],
               tombstones: dict[str, int]) -> None:
        """Write new segment files, atomically replace the commit point,
        GC segment files no longer referenced. Cost: O(new segments +
        deletes), never O(corpus)."""
        for seg in segments:
            self.write_segment(seg)
        manifest = {"format": FORMAT, "segments": [], "tombstones": tombstones}
        for seg in segments:
            crc, docs_crc = self.persisted[seg.seg_id]
            dead = [int(i) for i in range(seg.n_docs)
                    if not seg.live_host[i]]
            manifest["segments"].append({
                "seg_id": seg.seg_id,
                "file": f"seg_{seg.seg_id}.npz",
                "docs_file": self.docs_name(seg.seg_id),
                "crc": crc, "docs_crc": docs_crc, "dead": dead})
        tmp = os.path.join(self.path, MANIFEST + ".tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.path, MANIFEST))
        self._gc({s.seg_id for s in segments})

    def docs_name(self, seg_id: int) -> str:
        """The stored-fields filename actually ON DISK for a segment —
        pre-compression segments keep their plain .jsonl name (they are
        never rewritten: write_segment skips persisted ids), new ones use
        the compressed form."""
        plain = f"seg_{seg_id}.docs.jsonl"
        if os.path.exists(os.path.join(self.path, plain)):
            return plain
        return plain + ".gz"

    def _gc(self, keep: set[int]) -> None:
        import re
        for fn in os.listdir(self.path):
            m = re.match(r"^seg_(\d+)\.(npz|docs\.jsonl(\.gz)?)$", fn)
            if m and int(m.group(1)) not in keep:
                try:
                    os.remove(os.path.join(self.path, fn))
                except OSError:
                    pass
                self.persisted.pop(int(m.group(1)), None)

    # -- read --------------------------------------------------------------

    def load(self) -> tuple[list[Segment], dict[str, int]]:
        """Load the commit point: (segments, tombstone versions). Empty if
        no commit exists. Raises CorruptIndexException on checksum mismatch."""
        mpath = os.path.join(self.path, MANIFEST)
        if not os.path.exists(mpath):
            return [], {}
        with open(mpath) as f:
            manifest = json.load(f)
        if manifest.get("format") != FORMAT:
            # refusing loudly beats silently serving an empty index: the
            # translog was trimmed at the old flush, so ignoring the commit
            # would lose every doc older than it
            raise CorruptIndexException(
                f"unrecognized commit format "
                f"[{manifest.get('format')!r}] in {mpath}")
        segments = []
        for entry in manifest["segments"]:
            npz_path = os.path.join(self.path, entry["file"])
            docs_path = os.path.join(self.path, entry["docs_file"])
            for p, want in ((npz_path, entry["crc"]),
                            (docs_path, entry["docs_crc"])):
                if not os.path.exists(p):
                    raise CorruptIndexException(f"missing segment file {p}")
                got = _crc(p)
                if got != want:
                    raise CorruptIndexException(
                        f"checksum mismatch for {p}: "
                        f"expected {want:#010x}, got {got:#010x}")
            segments.append(self._read_segment(entry, npz_path, docs_path))
            self.persisted[entry["seg_id"]] = (entry["crc"],
                                               entry["docs_crc"])
        return segments, dict(manifest.get("tombstones", {}))

    def _read_segment(self, entry: dict, npz_path: str,
                      docs_path: str) -> Segment:
        data = np.load(npz_path, allow_pickle=False)
        schema = json.loads(str(data["schema"]))
        n_docs = schema["n_docs"]
        n_pad = schema["n_pad"]

        text = {}
        for f, meta in schema["text"].items():
            fi = meta["i"]
            terms = {t: i for i, t in enumerate(data[f"t{fi}_terms"])}
            np_doc_ids = data[f"t{fi}_doc_ids"]
            has_pos = f"t{fi}_positions" in data
            text[f] = TextFieldIndex(
                terms=terms,
                term_starts=data[f"t{fi}_starts"],
                term_lens=data[f"t{fi}_lens"],
                doc_ids=jnp.asarray(np_doc_ids),
                tf=jnp.asarray(data[f"t{fi}_tf"]),
                doc_len=jnp.asarray(data[f"t{fi}_doc_len"]),
                dl=jnp.asarray(data[f"t{fi}_dl"]),
                sum_dl=meta["sum_dl"], n_postings=meta["n_postings"],
                max_df=meta["max_df"],
                doc_ids_host=np_doc_ids[:meta["n_postings"]],
                pos_starts=data[f"t{fi}_pos_starts"] if has_pos else None,
                pos_lens=data[f"t{fi}_pos_lens"] if has_pos else None,
                positions=data[f"t{fi}_positions"] if has_pos else None)
        keywords = {}
        for fi, f in enumerate(schema["keywords"]):
            values = [str(v) for v in data[f"k{fi}_values"]]
            keywords[f] = KeywordColumn(
                ord_map={v: i for i, v in enumerate(values)}, values=values,
                ords=jnp.asarray(data[f"k{fi}_ords"]))
        numerics = {}
        for f, meta in schema["numerics"].items():
            fi = meta["i"]
            numerics[f] = NumericColumn(
                vals=jnp.asarray(data[f"n{fi}_vals"]),
                missing=jnp.asarray(data[f"n{fi}_missing"]),
                dtype=meta["dtype"])
        vectors = {}
        for f, meta in schema["vectors"].items():
            vectors[f] = VectorColumn(
                vecs=jnp.asarray(data[f"v{meta['i']}_vecs"]),
                dims=meta["dims"])

        ids = [str(i) for i in data["ids"]]
        types = [str(t) for t in data["types"]]
        versions = [int(v) for v in data["versions"]]
        routings = [str(r) if str(r) else None for r in data["routings"]] \
            if "routings" in data else [None] * n_docs
        import gzip
        opener = (lambda: gzip.open(docs_path, "rt")) \
            if docs_path.endswith(".gz") else (lambda: open(docs_path))
        with opener() as f:
            stored = [json.loads(ln) for ln in f if ln.strip()]
        if len(stored) != n_docs:
            raise CorruptIndexException(
                f"{docs_path}: expected {n_docs} docs, got {len(stored)}")
        live = np.zeros(n_pad, bool)
        live[:n_docs] = True
        for dead in entry.get("dead", []):
            live[dead] = False
        return Segment(
            seg_id=entry["seg_id"], n_docs=n_docs, n_pad=n_pad, text=text,
            keywords=keywords, numerics=numerics, vectors=vectors,
            stored=stored, ids=ids, types=types,
            # nested placeholder rows (type "__<path>") are not addressable
            id_to_local={d: i for i, d in enumerate(ids)
                         if not types[i].startswith("__")},
            live_host=live, versions=versions, routings=routings,
            parent_of=np.asarray(data["parent_of"], np.int32)
            if "parent_of" in data else None)
