"""Per-index service: N shard engines + mappers + routing.

Analog of the reference's IndexService (indices/IndicesService.java creates
one per index, holding IndexShard instances; SURVEY.md §2.5). Shards here are
independent Engines on disjoint doc partitions, routed by the reference's
exact hash function (parallel/routing.py).
"""

from __future__ import annotations

import itertools
import os
import shutil
from typing import Any

# monotonic index-incarnation ids (request-cache keys include one)
_INCARNATIONS = itertools.count(1)

from ..common.settings import Settings, EMPTY as EMPTY_SETTINGS
from ..mapping.mapper import MapperService
from ..parallel.routing import shard_id as route_shard
from ..search.shard_searcher import ShardSearcher
from .engine import Engine, EngineResult, GetResult


class IndexService:
    def __init__(self, name: str, path: str, settings: Settings | None = None,
                 mappings: dict | None = None, breakers=None, caches=None):
        self.name = name
        self.path = path
        self.caches = caches               # IndicesCacheService | None
        self.settings = settings if settings is not None else EMPTY_SETTINGS
        get = lambda k, d: self.settings.get(  # noqa: E731 — "index." optional
            f"index.{k}", self.settings.get(k, d))
        ns = get("number_of_shards", 1)
        nr = get("number_of_replicas", 1)   # 0 is a VALID replica count
        self.n_shards = int(ns) if ns is not None and str(ns) != "" else 1
        self.n_replicas = int(nr) if nr is not None and str(nr) != "" else 1
        # alias name -> properties ({filter, index_routing, search_routing})
        self.aliases: dict[str, dict] = {}
        self.breakers = breakers           # CircuitBreakerService | None
        fd = breakers.breaker("fielddata") if breakers is not None else None
        # custom analyzer/filter/tokenizer chains come from INDEX settings
        # (ref AnalysisService built per-index from its Settings)
        from ..analysis.analyzers import AnalysisService
        self.mappers = MapperService(
            analysis=AnalysisService(self.settings),
            mappings=mappings or {})
        # per-field similarity registry (named configs from index settings,
        # resolved via the mapping's "similarity" property) — attached to
        # the mapper service so QueryParser sees it everywhere
        from .similarity import SimilarityService
        self.mappers.similarity = SimilarityService(self.settings)
        # the vectorized bulk-ingest lane (index/bulk_ingest.py) is on
        # unless the index opts out (`index.bulk.vectorized.enable: false`
        # — the equivalence suite and bench use it to pin the per-doc path)
        raw_vec = get("bulk.vectorized.enable", True)
        self._bulk_vectorized = str(raw_vec).strip().lower() \
            not in ("false", "0", "no")
        self.shards: list[Engine] = [
            Engine(os.path.join(path, str(s)), self.mappers, breaker=fd,
                   fielddata_cache=caches.fielddata
                   if caches is not None else None,
                   ann_cache=caches.ann_indexes
                   if caches is not None else None,
                   index_name=name, vectorized=self._bulk_vectorized)
            for s in range(self.n_shards)]
        self.creation_date = None
        # searcher cache: rebuilt per shard only when its segment set changes
        # (the NRT "acquire searcher" analog — ref SearcherManager); device
        # query-path counters live here so they survive across requests
        self._searcher_cache: dict[int, tuple[tuple, ShardSearcher]] = {}
        self.search_stats = {"sparse": 0, "dense": 0, "packed": 0,
                             "stacked": 0, "mesh": 0}
        # the stacked dense lane is on unless the index opts out
        # (`index.search.stacked.enable: false` — bench uses it to measure
        # the per-segment loop it replaces)
        raw_stacked = get("search.stacked.enable", True)
        self._stacked_enabled = str(raw_stacked).strip().lower() \
            not in ("false", "0", "no")
        # the mesh-sharded query lane (parallel/mesh_exec) engages for
        # multi-shard unsorted queries unless the index opts out
        # (`index.search.mesh.enable: false` — bench uses it to measure
        # the thread-pool fan-out it replaces)
        raw_mesh = get("search.mesh.enable", True)
        self._mesh_enabled = str(raw_mesh).strip().lower() \
            not in ("false", "0", "no")
        # streaming blockwise dense execution (search/blockwise.py):
        # segments/stacks wider than `index.search.block_docs` run the DSL
        # tree per pow2 doc block under a running on-device top-k — peak
        # score memory O(Q × block) instead of O(Q × n_pad). Opt out with
        # `index.search.blockwise.enable: false` (the equivalence suite and
        # bench use it to pin the materializing executor).
        raw_blk = get("search.blockwise.enable", True)
        self._blockwise_enabled = str(raw_blk).strip().lower() \
            not in ("false", "0", "no")
        from ..search.blockwise import DEFAULT_BLOCK_DOCS
        raw_bd = get("search.block_docs", DEFAULT_BLOCK_DOCS)
        try:
            self._block_docs = int(raw_bd)
        except (TypeError, ValueError):
            self._block_docs = DEFAULT_BLOCK_DOCS
        # IVF-clustered ANN kNN lane (ops/ann.py): knn queries over
        # columns past `index.knn.ivf.min_docs` route through a trained
        # cluster index instead of the full [Q, N] matmul. Opt out with
        # `index.knn.ivf.enable: false`; nlist/nprobe default to
        # ~sqrt(N) / nlist/8 when 0. `index.knn.precision` pins the
        # matmul dtype (bf16 default, f32 for exact-parity workloads).
        self._knn_opts = knn_options_from(get)
        # op counters surfaced by _stats (ref index/shard stats holders:
        # IndexingStats w/ per-type breakdown, SearchStats w/ groups, GetStats)
        self.indexing_stats: dict = {"index_total": 0, "delete_total": 0,
                                     "types": {}}
        self.search_groups: dict[str, int] = {}
        self.query_total = 0
        self.get_total = 0
        # windowed op rates (1m/5m/15m EWMA) — `*_rate` in `_stats`,
        # `_cat/indices` and the /_metrics scrape; every op-count bump
        # below also marks its meter
        from ..common.metrics import Meter
        self.meters: dict[str, Meter] = {"search": Meter(),
                                         "indexing": Meter(),
                                         "get": Meter()}
        # shard request cache counters (ref indices/cache/request/
        # IndicesRequestCache — size-0 responses keyed by reader version)
        self.request_cache_hits = 0
        self.request_cache_misses = 0
        # unique per index INCARNATION: delete+recreate under the same name
        # must never hit the old incarnation's cache entries
        self._incarnation = next(_INCARNATIONS)
        # fused serving view over all shards' segments (serving/packed_view):
        # rebuilt only when the segment set changes; tombstone-only changes
        # refresh its liveness row in place. A single-entry common.cache
        # Cache so its bytes/evictions surface uniformly; the removal
        # listener releases the "request" breaker charge on every exit
        from ..common.cache import Cache
        self._packed_view_cache = Cache(
            "packed_view", max_entries=1,
            weigher=lambda v: getattr(v[1], "memory_bytes", 0),
            removal_listener=self._on_packed_removed)
        if caches is not None:
            caches.register(f"packed_view[{name}]", self._packed_view_cache)

    def reader_generation(self) -> tuple:
        """Changes whenever a refresh/merge/delete changes what a searcher
        can see — the request-cache key component (the reference keys on
        the IndexReader version the same way)."""
        return tuple((e.refresh_count, e.merge_count,
                      sum(s.live_gen for s in e.segments),
                      len(e._buffer_docs))
                     for e in self.shards)

    # -- routing -----------------------------------------------------------

    def shard_for(self, doc_id: str, routing: str | None = None) -> Engine:
        return self.shards[route_shard(doc_id, self.n_shards, routing)]

    # -- document ops (ref index/shard/IndexShard.java:444-523) ------------

    def index_doc(self, doc_id: str, source: dict, type_name: str = "_doc",
                  routing: str | None = None, parent: str | None = None,
                  **kw) -> EngineResult:
        # _parent doubles as routing so parent and children co-locate
        # (ref index/mapper/internal/ParentFieldMapper routing contract)
        if parent is not None and routing is None:
            routing = parent
        res = self.shard_for(doc_id, routing).index(
            doc_id, source, type_name=type_name, routing=routing,
            parent=parent, **kw)
        self.indexing_stats["index_total"] += 1
        self.meters["indexing"].mark()
        tmap = self.indexing_stats["types"]
        tmap[type_name] = tmap.get(type_name, 0) + 1
        return res

    def get_doc(self, doc_id: str, routing: str | None = None,
                realtime: bool = True,
                parent: str | None = None) -> GetResult:
        if parent is not None and routing is None:
            routing = parent
        self.get_total += 1
        self.meters["get"].mark()
        return self.shard_for(doc_id, routing).get(doc_id, realtime=realtime)

    def delete_doc(self, doc_id: str, routing: str | None = None,
                   parent: str | None = None, **kw) -> EngineResult:
        if parent is not None and routing is None:
            routing = parent
        res = self.shard_for(doc_id, routing).delete(doc_id, **kw)
        self.indexing_stats["delete_total"] += 1
        self.meters["indexing"].mark()
        return res

    def bulk_ingest(self, ops: list) -> list:
        """Vectorized bulk lane: route a run of BulkOps to their shards and
        apply each shard's slice as ONE Engine.index_batch pass (batched
        analysis + columnar buffer + group-commit translog). Preserves
        per-shard op order (same-id ops always route to the same shard, so
        cross-shard order is immaterial). Translog fsyncs are deferred —
        the caller ends the request with sync_translogs(). Returns results
        aligned with `ops` (EngineResult or the per-item exception)."""
        for op in ops:
            if op.routing is None and op.parent is not None:
                op.routing = op.parent  # _parent doubles as routing
        if self.n_shards == 1:
            # single-shard indices (the bench shape) skip the per-op
            # routing hash entirely
            results = self.shards[0].index_batch(ops, sync=False)
        else:
            by_shard: dict[int, tuple[list[int], list]] = {}
            for pos, op in enumerate(ops):
                sid = route_shard(op.doc_id, self.n_shards, op.routing)
                slot = by_shard.setdefault(sid, ([], []))
                slot[0].append(pos)
                slot[1].append(op)
            results = [None] * len(ops)
            for sid, (positions, shard_ops) in by_shard.items():
                out = self.shards[sid].index_batch(shard_ops, sync=False)
                for pos, res in zip(positions, out):
                    results[pos] = res
        # op counters mirror the per-doc path: successes only, per type
        n_index = n_delete = 0
        tmap = self.indexing_stats["types"]
        for op, res in zip(ops, results):
            if not isinstance(res, EngineResult):
                continue
            if op.action == "delete":
                n_delete += 1
            else:
                n_index += 1
                tmap[op.type_name] = tmap.get(op.type_name, 0) + 1
        self.indexing_stats["index_total"] += n_index
        self.indexing_stats["delete_total"] += n_delete
        if n_index or n_delete:
            self.meters["indexing"].mark(n_index + n_delete)
        return results

    def sync_translogs(self) -> None:
        """One fsync per shard — the tail of a deferred-sync bulk request
        (ref 'request' durability: fsync per request, not per op)."""
        for e in self.shards:
            e.translog.sync()

    # -- lifecycle ---------------------------------------------------------

    def refresh(self) -> None:
        for e in self.shards:
            e.refresh()
        self._drop_stale_stacks()

    def flush(self) -> None:
        for e in self.shards:
            e.flush()
        self._drop_stale_stacks()

    def force_merge(self, max_num_segments: int = 1) -> None:
        for e in self.shards:
            e.force_merge(max_num_segments)
        self._drop_stale_stacks()

    def _drop_stale_stacks(self) -> None:
        """A refresh/merge changed some shard's segment set: free stale
        packed segment stacks NOW (their removal listener hands the device
        bytes back to the fielddata breaker) instead of waiting for the
        next query's put to displace them."""
        if self.caches is None:
            return
        valid = {(si, tuple(s.seg_id for s in e.segments if s.n_docs > 0))
                 for si, e in enumerate(self.shards)}
        self.caches.segment_stacks.drop_stale(self.name, valid)
        self.caches.mesh_stacks.drop_stale(self.name, valid)
        self.caches.mesh_vector_stacks.drop_stale(self.name, valid)

    def _on_packed_removed(self, _key, value, _reason) -> None:
        """Packed-view cache removal: hand the view's duplicate-postings
        bytes back to the `request` breaker (the view charged them at
        build time)."""
        _k, view = value
        if self.breakers is not None and view is not None:
            self.breakers.breaker("request").release(view.memory_bytes)

    def close(self) -> None:
        for cached in self._searcher_cache.values():
            cached[2].release()     # before engine close: the leak
        self._searcher_cache.clear()  # detector asserts refcounts drained
        for e in self.shards:
            e.close()
        self._packed_view_cache.clear()
        if self.caches is not None:
            self.caches.segment_stacks.clear([self.name])
            self.caches.mesh_stacks.clear([self.name])
            self.caches.ann_indexes.clear([self.name])

    def delete_files(self) -> None:
        shutil.rmtree(self.path, ignore_errors=True)

    # -- search ------------------------------------------------------------

    def searchers(self) -> list[ShardSearcher]:
        out = []
        for si, e in enumerate(self.shards):
            key = tuple(s.seg_id for s in e.segments)
            cached = self._searcher_cache.get(si)
            if cached is None or cached[0] != key:
                if cached is not None:
                    # rotation releases the stale searcher's refcount —
                    # the leak detector (ISSUE 14) pins this symmetry
                    cached[2].release()
                handle = e.acquire_searcher(
                    site=f"index[{self.name}]/shard[{si}]/searchers")
                cached = (key, ShardSearcher(
                    si, e.segments, self.mappers, stats=self.search_stats,
                    stack_cache=self.caches.segment_stacks
                    if self.caches is not None else None,
                    index_name=self.name, incarnation=self._incarnation,
                    stacked=self._stacked_enabled,
                    blockwise=self._blockwise_enabled,
                    block_docs=self._block_docs,
                    request_breaker=self.breakers.breaker("request")
                    if self.breakers is not None else None,
                    knn_opts=self._knn_opts), handle)
                self._searcher_cache[si] = cached
            out.append(cached[1])
        return out

    def packed_view(self):
        """The one-device-program serving view for this index (all shards'
        segments fused). None when the index is empty, or when the "request"
        breaker refuses the view's duplicate postings (the packed view
        roughly doubles device residency for text fields — breach degrades
        to the per-segment lane, it never raises).

        NRT-friendly: when the segment set only GREW (refresh without a
        merge), the new view EXTENDS the cached one — appended segments'
        postings concatenate on device; cost is O(new postings), not
        O(index) (advisor r3 medium). Any removal (merge) rebuilds."""
        from ..serving.packed_view import PackedIndexView
        live: dict[tuple, object] = {}
        for si, e in enumerate(self.shards):
            for seg in e.segments:
                live[(si, seg.seg_id)] = seg
        if not live:
            return None
        key = tuple(sorted(live))
        cached = self._packed_view_cache.get("view")
        if cached is not None and cached[0] == key:
            return cached[1]
        req = self.breakers.breaker("request") \
            if self.breakers is not None else None
        old = cached[1] if cached is not None else None
        base = None
        entries = None
        if old is not None:
            old_keys = [(si, seg.seg_id) for si, seg in old.entries]
            if all(k in live and live[k] is seg
                   for k, (_, seg) in zip(old_keys, old.entries)) \
                    and len(old_keys) == len(set(old_keys)):
                appended = [(si, seg) for (si, sid), seg in live.items()
                            if (si, sid) not in set(old_keys)]
                appended.sort(key=lambda x: (x[0], x[1].seg_id))
                base = old
                entries = list(old.entries) + appended
        if entries is None:
            entries = [(si, seg) for si, e in enumerate(self.shards)
                       for seg in e.segments]
        if old is not None:
            # release the stale view's charge (removal listener) BEFORE
            # building — the new view needs the breaker headroom
            self._packed_view_cache.invalidate("view")
        view = PackedIndexView(entries, breaker=req, base=base)
        self._packed_view_cache.put("view", (key, view))
        return view

    # -- introspection -----------------------------------------------------

    def doc_count(self) -> int:
        return sum(e.doc_count() for e in self.shards)

    def stats(self) -> dict:
        seg = [e.segment_stats() for e in self.shards]
        return {
            "docs": {"count": self.doc_count(),
                     "deleted": sum(s["deleted"] for s in seg)},
            "segments": {"count": sum(s["count"] for s in seg),
                         "memory_in_bytes": sum(s["memory_in_bytes"] for s in seg)},
            "translog": {"operations": sum(e.translog.ops_since_commit
                                           for e in self.shards)},
            "shards": {"total": self.n_shards * (1 + self.n_replicas),
                       "primaries": self.n_shards},
            "packed_view_cache": self._packed_view_cache.stats(),
        }

    def mappings_dict(self) -> dict:
        return self.mappers.mappings_dict()


def knn_options_from(get) -> dict:
    """Read the kNN/ANN settings roster through an `(key, default)`
    getter (index Settings here; cluster-state dicts in cluster/node.py
    read the same keys for searcher parity)."""
    def as_bool(v, default=True):
        if v is None:
            return default
        return str(v).strip().lower() not in ("false", "0", "no")

    def as_int(v, default=0):
        try:
            return int(v)
        except (TypeError, ValueError):
            return default

    precision = str(get("knn.precision", "bf16")).strip().lower()
    if precision not in ("bf16", "f32"):
        precision = "bf16"
    # quantized ANN tier (ISSUE 12): int8 / IVF-PQ cluster scan with a
    # full-precision rescore of the top `rescore_window` survivors;
    # anything unrecognized degrades to the f32 IVF lane
    quant = str(get("knn.quantization", "none")).strip().lower()
    if quant not in ("none", "int8", "pq"):
        quant = "none"
    from ..ops.ann import DEFAULT_PQ_M
    return {
        "ivf_enable": as_bool(get("knn.ivf.enable", True)),
        "nlist": as_int(get("knn.ivf.nlist", 0)),
        "nprobe": as_int(get("knn.ivf.nprobe", 0)),
        "min_docs": as_int(get("knn.ivf.min_docs", 4096), 4096),
        "precision": precision,
        "quantization": quant,
        "pq_m": as_int(get("knn.pq.m", DEFAULT_PQ_M), DEFAULT_PQ_M),
        "rescore_window": as_int(get("knn.rescore_window", 0)),
    }
